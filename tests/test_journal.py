"""Write-ahead bind journal + fencing-epoch unit tests (HA failover PR)."""

import json
import os

import pytest

from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.journal import (
    BindJournal,
    EpochFence,
    FileJournalStore,
    JournalWriteError,
    MemoryJournalStore,
    StaleEpochError,
)


def _bind(uid, node, req=(1000.0, 2048.0)):
    return {
        "uid": uid,
        "node": node,
        "req": list(req),
        "est": list(req),
        "prod": False,
        "nom": 0.0,
        "conf": True,
        "quota": None,
    }


# ---------------------------------------------------------------------------
# EpochFence
# ---------------------------------------------------------------------------


def test_fence_advance_adopt_check():
    f = EpochFence()
    assert f.current() == 0
    assert f.advance() == 1
    f.check(1)
    with pytest.raises(StaleEpochError):
        f.check(0)
    assert f.adopt(3) == 3
    with pytest.raises(StaleEpochError):
        f.adopt(2)  # fencing tokens never move backwards
    with pytest.raises(StaleEpochError):
        f.check(1)


def test_fence_revoked_sentinel_always_stale():
    f = EpochFence()
    with pytest.raises(StaleEpochError):
        f.check(-1)


# ---------------------------------------------------------------------------
# BindJournal core protocol
# ---------------------------------------------------------------------------


def test_bind_then_forget_replay():
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0"), ("b", "n1")])
    j.append_bind(1, 0, [_bind("a", "n0"), _bind("b", "n1")])
    j.append_forget(1, 3, ["a"])
    rep = j.replay()
    assert set(rep.live) == {"b"}
    assert rep.live["b"]["node"] == "n1"
    assert rep.binds == 1 and rep.forgets == 1 and rep.open_intents == 0


def test_crash_mid_commit_intent_is_void():
    """An intent with no matching bind/abort (the process died between
    journal-intent and journal-bind) contributes nothing to replay: the
    dying process's host mutations died with it."""
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0")])
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_intent(1, 1, [("b", "n0")])  # crash here
    rep = j.replay()
    assert set(rep.live) == {"a"}
    assert rep.open_intents == 1


def test_abort_voids_intent():
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0")])
    j.append_abort(1, 0, "rolled back")
    rep = j.replay()
    assert rep.live == {} and rep.aborts == 1 and rep.open_intents == 0


def test_rebind_last_write_wins():
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(1, 4, [_bind("a", "n2")])
    assert j.replay().live["a"]["node"] == "n2"


def test_journal_epoch_fencing_refuses_stale_writer():
    """The journal is the fencing backstop at the storage boundary: once
    epoch 2 has written, an epoch-1 straggler is refused."""
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(2, 0, [_bind("b", "n1")])
    with pytest.raises(StaleEpochError):
        j.append_bind(1, 1, [_bind("c", "n2")])
    # the refused write left no record
    assert set(j.replay().live) == {"a", "b"}
    assert j.epoch_high == 2


def test_compact_preserves_live_set():
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0"), _bind("b", "n1")])
    j.append_forget(1, 1, ["a"])
    j.compact()
    recs = j.records()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"
    assert set(j.replay().live) == {"b"}
    # appends continue after compaction, seq still monotonic
    j.append_bind(1, 2, [_bind("c", "n0")])
    assert set(j.replay().live) == {"b", "c"}


def test_chaos_write_fail_raises_and_counts():
    chaos = FaultInjector(seed=0)
    chaos.arm("journal.write_fail", times=1)
    j = BindJournal(chaos=chaos)
    with pytest.raises(JournalWriteError):
        j.append_intent(1, 0, [("a", "n0")])
    # nothing landed; the next write (fault exhausted) succeeds
    assert j.records() == []
    j.append_intent(1, 0, [("a", "n0")])
    assert len(j.records()) == 1


# ---------------------------------------------------------------------------
# FileJournalStore durability
# ---------------------------------------------------------------------------


def test_file_store_roundtrip_and_reopen(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_intent(1, 0, [("a", "n0")])
    j.append_bind(1, 0, [_bind("a", "n0")])
    # a fresh journal over the same file resumes seq + epoch_high
    j2 = BindJournal(FileJournalStore(path))
    assert j2.epoch_high == 1
    rep = j2.replay()
    assert set(rep.live) == {"a"}
    j2.append_forget(1, 1, ["a"])
    assert BindJournal(FileJournalStore(path)).replay().live == {}


def test_file_store_tolerates_torn_tail(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    with open(path, "a") as f:
        f.write('{"seq": 99, "epoch": 1, "op": "bi')  # crash mid-append
    rep = BindJournal(FileJournalStore(path)).replay()
    assert set(rep.live) == {"a"}
    assert rep.seq_high == 1


def test_file_store_appends_cleanly_after_torn_tail(tmp_path):
    """Reopening after a crash mid-append must TRUNCATE the partial
    line first — otherwise the next append merges into it, producing
    one unparseable record that load() stops at and silently discards
    every post-restart append behind it."""
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    with open(path, "a") as f:
        f.write('{"seq": 99, "epoch": 1, "op": "bi')  # crash mid-append
    j2 = BindJournal(FileJournalStore(path))
    j2.append_bind(1, 1, [_bind("b", "n1")])
    j2.append_forget(1, 2, ["a"])
    rep = BindJournal(FileJournalStore(path)).replay()
    assert set(rep.live) == {"b"}
    assert rep.binds == 2 and rep.forgets == 1


def test_file_store_records_are_json_lines(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(3, 7, [_bind("a", "n0")])
    with open(path) as f:
        rec = json.loads(f.readline())
    assert rec["op"] == "bind" and rec["epoch"] == 3 and rec["cycle"] == 7


def test_memory_store_survives_scheduler_death():
    """The store object outliving its journal/scheduler is the simulated
    crash: a second journal over the same store sees everything."""
    store = MemoryJournalStore()
    BindJournal(store).append_bind(1, 0, [_bind("a", "n0")])
    assert set(BindJournal(store).replay().live) == {"a"}


# ---------------------------------------------------------------------------
# Periodic compaction + crash-mid-compaction (PR 6 satellite)
# ---------------------------------------------------------------------------


def test_maybe_compact_threshold():
    j = BindJournal()
    for i in range(4):
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    assert j.maybe_compact(min_records=10) is None  # below threshold
    assert len(j.records()) == 4
    rep = j.maybe_compact(min_records=4)
    assert rep is not None and set(rep.live) == {"p0", "p1", "p2", "p3"}
    recs = j.records()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"
    # the counter reset: immediately re-running is below threshold again
    assert j.maybe_compact(min_records=1) is None  # 0 since reset
    # replay through the checkpoint + later appends
    j.append_forget(1, 9, ["p0"])
    assert set(j.replay().live) == {"p1", "p2", "p3"}


def test_compact_refuses_stale_epoch():
    j = BindJournal()
    j.append_bind(5, 0, [_bind("a", "n0")])
    with pytest.raises(StaleEpochError):
        j.compact(epoch=3)  # a deposed leader must not rewrite the log


def test_compact_crash_chaos_leaves_live_log_intact(tmp_path):
    """``journal.compact_crash``: the process dies mid-rewrite — only a
    torn TEMP file is left (atomic-rename discipline), the live log is
    untouched, and a fresh open ignores/repairs the orphan and replays
    the full pre-crash history."""
    path = os.fspath(tmp_path / "journal.jsonl")
    chaos = FaultInjector(seed=0)
    chaos.arm("journal.compact_crash", times=1)
    j = BindJournal(FileJournalStore(path), chaos=chaos)
    for i in range(3):
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    j.append_forget(1, 3, ["p1"])
    with pytest.raises(JournalWriteError):
        j.compact()
    assert os.path.exists(path + ".tmp")  # the torn rewrite artifact
    # "process restart": a fresh store repairs/ignores the torn tmp and
    # the journal replays exactly the pre-crash world
    j2 = BindJournal(FileJournalStore(path))
    rep = j2.replay()
    assert set(rep.live) == {"p0", "p2"}
    assert not os.path.exists(path + ".tmp")
    # the journal still appends and compacts cleanly afterwards
    j2.append_bind(1, 4, [_bind("p4", "n1")])
    rep2 = j2.compact()
    assert set(rep2.live) == {"p0", "p2", "p4"}
    recs = j2.records()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"


def test_scheduler_run_loop_compacts(tmp_path):
    """BatchScheduler(journal_compact_records=N) compacts from the run
    loop once N records accumulate, and the compacted journal still
    replays the full live set."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    snap = ClusterSnapshot()
    for i in range(4):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 32000.0,
                        ext.RES_MEMORY: 131072.0,
                    }
                ),
            )
        )
    store = MemoryJournalStore()
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=8,
        journal=BindJournal(store),
        journal_compact_records=6,
    )
    sched.extender.monitor.stop_background()
    bound = []
    for c in range(4):
        pods = [
            Pod(
                meta=ObjectMeta(name=f"p{c}-{k}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 500.0, ext.RES_MEMORY: 1024.0}
                ),
            )
            for k in range(3)
        ]
        out = sched.schedule(pods)
        bound.extend(p.meta.uid for p, _n in out.bound)
    assert (
        sched.extender.registry.get("journal_compactions_total").value()
        >= 1.0
    )
    # the log shrank to checkpoint + post-checkpoint tail, and replay
    # still reconstructs every acknowledged bind
    rep = BindJournal(store).replay()
    assert set(rep.live) == set(bound)
    assert any(r["op"] == "checkpoint" for r in store.load())


# ---------------------------------------------------------------------------
# Shard stamping + cross-shard single-winner claims (PR 6)
# ---------------------------------------------------------------------------


def test_shard_stamped_records():
    store = MemoryJournalStore()
    BindJournal(store, shard=3).append_bind(1, 0, [_bind("a", "n0")])
    assert store.load()[0]["shard"] == 3


def test_claim_table_single_winner():
    from koordinator_tpu.core.journal import ClaimTable

    t = ClaimTable()
    assert t.claim("pod-1", shard=0, epoch=1)
    assert t.claim("pod-1", shard=0, epoch=1)      # idempotent for winner
    assert not t.claim("pod-1", shard=2, epoch=1)  # loser shard
    assert t.winner("pod-1") == 0


def test_claim_table_epoch_fenced_per_shard():
    from koordinator_tpu.core.journal import ClaimTable

    t = ClaimTable()
    assert t.claim("a", shard=0, epoch=2)
    with pytest.raises(StaleEpochError):
        t.claim("b", shard=0, epoch=1)  # deposed shard-0 owner
    assert t.claim("c", shard=1, epoch=1)  # shard 1's history independent
    with pytest.raises(StaleEpochError):
        t.claim("d", shard=1, epoch=-1)  # revoked sentinel always stale


def test_claim_table_reload_and_release():
    from koordinator_tpu.core.journal import ClaimTable

    store = MemoryJournalStore()
    t = ClaimTable(store)
    t.claim("a", shard=1, epoch=1)
    t.claim("b", shard=0, epoch=1)
    t.release("a")
    t2 = ClaimTable(store)  # reload from the durable record stream
    assert t2.winner("a") is None
    assert t2.winner("b") == 0
    with pytest.raises(StaleEpochError):
        t2.claim("fresh", shard=0, epoch=0)  # epoch high survived reload


def test_claim_table_release_tombstones_uid():
    """A released (pod-GC'd) claim must never be re-claimable: a stale
    fanned-out copy of the pod can sit in a backlogged shard's queue
    past the pod's completion and deletion — a post-release claim must
    LOSE (the copy is dropped), or that shard re-schedules a dead pod,
    exactly the double-bind the ClaimTable exists to prevent."""
    from koordinator_tpu.core.journal import ClaimTable

    store = MemoryJournalStore()
    t = ClaimTable(store)
    assert t.claim("p", shard=0, epoch=1)
    t.release("p")  # the pod was bound, completed, and GC'd
    assert not t.claim("p", shard=1, epoch=1)  # backlogged copy loses
    assert not t.claim("p", shard=0, epoch=1)  # even the old winner
    t2 = ClaimTable(store)  # the tombstone survives a reload
    assert not t2.claim("p", shard=1, epoch=1)
    # a never-claimed uid is NOT tombstoned (no fan-out copy can exist)
    t.release("never-claimed")
    assert t.claim("never-claimed", shard=2, epoch=1)


def test_compact_folds_sibling_instance_appends():
    """compact() must fold records a SIBLING BindJournal instance wrote
    over the same store (the standby-forget pattern journals through a
    fresh view during ownerless gaps): the read-rewrite runs under the
    store lock and re-derives seq from the replay, so an interleaved
    acknowledged forget is neither erased by the rewrite nor sorted
    after the checkpoint."""
    store = MemoryJournalStore()
    a = BindJournal(store)
    a.append_bind(1, 0, [_bind("x", "n0"), _bind("y", "n1")])
    # a standby's fresh view journals a fence-exempt forget that the
    # compacting instance never observed in-memory
    BindJournal(store).append_forget(None, 1, ["x"])
    rep = a.compact()
    assert "x" not in rep.live and "y" in rep.live
    recs = store.load()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"
    assert "x" not in recs[0]["live"] and "y" in recs[0]["live"]
    # the checkpoint's seq sorts AFTER the sibling's append, and the
    # compacting instance's next append after it in turn
    assert recs[0]["seq"] >= 2
    nxt = a.append_bind(1, 2, [_bind("z", "n2")])
    assert nxt["seq"] > recs[0]["seq"]
    fresh = BindJournal(store).replay()
    assert set(fresh.live) == {"y", "z"}
