"""Write-ahead bind journal + fencing-epoch unit tests (HA failover PR)."""

import json
import os

import pytest

from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.journal import (
    BindJournal,
    EpochFence,
    FileJournalStore,
    JournalWriteError,
    MemoryJournalStore,
    StaleEpochError,
)


def _bind(uid, node, req=(1000.0, 2048.0)):
    return {
        "uid": uid,
        "node": node,
        "req": list(req),
        "est": list(req),
        "prod": False,
        "nom": 0.0,
        "conf": True,
        "quota": None,
    }


# ---------------------------------------------------------------------------
# EpochFence
# ---------------------------------------------------------------------------


def test_fence_advance_adopt_check():
    f = EpochFence()
    assert f.current() == 0
    assert f.advance() == 1
    f.check(1)
    with pytest.raises(StaleEpochError):
        f.check(0)
    assert f.adopt(3) == 3
    with pytest.raises(StaleEpochError):
        f.adopt(2)  # fencing tokens never move backwards
    with pytest.raises(StaleEpochError):
        f.check(1)


def test_fence_revoked_sentinel_always_stale():
    f = EpochFence()
    with pytest.raises(StaleEpochError):
        f.check(-1)


# ---------------------------------------------------------------------------
# BindJournal core protocol
# ---------------------------------------------------------------------------


def test_bind_then_forget_replay():
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0"), ("b", "n1")])
    j.append_bind(1, 0, [_bind("a", "n0"), _bind("b", "n1")])
    j.append_forget(1, 3, ["a"])
    rep = j.replay()
    assert set(rep.live) == {"b"}
    assert rep.live["b"]["node"] == "n1"
    assert rep.binds == 1 and rep.forgets == 1 and rep.open_intents == 0


def test_crash_mid_commit_intent_is_void():
    """An intent with no matching bind/abort (the process died between
    journal-intent and journal-bind) contributes nothing to replay: the
    dying process's host mutations died with it."""
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0")])
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_intent(1, 1, [("b", "n0")])  # crash here
    rep = j.replay()
    assert set(rep.live) == {"a"}
    assert rep.open_intents == 1


def test_abort_voids_intent():
    j = BindJournal()
    j.append_intent(1, 0, [("a", "n0")])
    j.append_abort(1, 0, "rolled back")
    rep = j.replay()
    assert rep.live == {} and rep.aborts == 1 and rep.open_intents == 0


def test_rebind_last_write_wins():
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(1, 4, [_bind("a", "n2")])
    assert j.replay().live["a"]["node"] == "n2"


def test_journal_epoch_fencing_refuses_stale_writer():
    """The journal is the fencing backstop at the storage boundary: once
    epoch 2 has written, an epoch-1 straggler is refused."""
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(2, 0, [_bind("b", "n1")])
    with pytest.raises(StaleEpochError):
        j.append_bind(1, 1, [_bind("c", "n2")])
    # the refused write left no record
    assert set(j.replay().live) == {"a", "b"}
    assert j.epoch_high == 2


def test_compact_preserves_live_set():
    j = BindJournal()
    j.append_bind(1, 0, [_bind("a", "n0"), _bind("b", "n1")])
    j.append_forget(1, 1, ["a"])
    j.compact()
    recs = j.records()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"
    assert set(j.replay().live) == {"b"}
    # appends continue after compaction, seq still monotonic
    j.append_bind(1, 2, [_bind("c", "n0")])
    assert set(j.replay().live) == {"b", "c"}


def test_chaos_write_fail_raises_and_counts():
    chaos = FaultInjector(seed=0)
    chaos.arm("journal.write_fail", times=1)
    j = BindJournal(chaos=chaos)
    with pytest.raises(JournalWriteError):
        j.append_intent(1, 0, [("a", "n0")])
    # nothing landed; the next write (fault exhausted) succeeds
    assert j.records() == []
    j.append_intent(1, 0, [("a", "n0")])
    assert len(j.records()) == 1


# ---------------------------------------------------------------------------
# FileJournalStore durability
# ---------------------------------------------------------------------------


def test_file_store_roundtrip_and_reopen(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_intent(1, 0, [("a", "n0")])
    j.append_bind(1, 0, [_bind("a", "n0")])
    # a fresh journal over the same file resumes seq + epoch_high
    j2 = BindJournal(FileJournalStore(path))
    assert j2.epoch_high == 1
    rep = j2.replay()
    assert set(rep.live) == {"a"}
    j2.append_forget(1, 1, ["a"])
    assert BindJournal(FileJournalStore(path)).replay().live == {}


def test_file_store_tolerates_torn_tail(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    with open(path, "a") as f:
        f.write('{"seq": 99, "epoch": 1, "op": "bi')  # crash mid-append
    rep = BindJournal(FileJournalStore(path)).replay()
    assert set(rep.live) == {"a"}
    assert rep.seq_high == 1


def test_file_store_appends_cleanly_after_torn_tail(tmp_path):
    """Reopening after a crash mid-append must TRUNCATE the partial
    line first — otherwise the next append merges into it, producing
    one unparseable record that load() stops at and silently discards
    every post-restart append behind it."""
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    with open(path, "a") as f:
        f.write('{"seq": 99, "epoch": 1, "op": "bi')  # crash mid-append
    j2 = BindJournal(FileJournalStore(path))
    j2.append_bind(1, 1, [_bind("b", "n1")])
    j2.append_forget(1, 2, ["a"])
    rep = BindJournal(FileJournalStore(path)).replay()
    assert set(rep.live) == {"b"}
    assert rep.binds == 2 and rep.forgets == 1


def test_file_store_records_are_json_lines(tmp_path):
    path = os.fspath(tmp_path / "journal.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(3, 7, [_bind("a", "n0")])
    with open(path) as f:
        rec = json.loads(f.readline())
    assert rec["op"] == "bind" and rec["epoch"] == 3 and rec["cycle"] == 7


def test_memory_store_survives_scheduler_death():
    """The store object outliving its journal/scheduler is the simulated
    crash: a second journal over the same store sees everything."""
    store = MemoryJournalStore()
    BindJournal(store).append_bind(1, 0, [_bind("a", "n0")])
    assert set(BindJournal(store).replay().live) == {"a"}
