"""Full runtime-hook inventory + qosmanager reconcile strategies
(reference pkg/koordlet/runtimehooks/hooks/* — 10 plugins — and
pkg/koordlet/qosmanager plugins cgreconcile/resctrl/blkio/sysreconcile)."""

import json

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    BlkIOStrategy,
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
    ResctrlStrategy,
    SystemStrategy,
)
from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.koordlet import qosmanager as qos
from koordinator_tpu.koordlet import resourceexecutor as rex
from koordinator_tpu.koordlet import runtimehooks as hooks


def mkpod(name, qos_label="LS", annotations=None, requests=None, limits=None):
    return Pod(
        meta=ObjectMeta(
            name=name,
            uid=name,
            labels={ext.LABEL_POD_QOS: qos_label},
            annotations=annotations or {},
        ),
        spec=PodSpec(requests=requests or {}, limits=limits or {}),
    )


class TestNewCgroupHooks:
    def test_cpu_normalization_scales_quota(self):
        pod = mkpod("p", limits={ext.RES_CPU: 2000.0})
        plan = hooks.cpu_normalization_plan(pod, ratio=1.25)
        assert plan == [
            (hooks.pod_cgroup(pod), rex.CPU_CFS_QUOTA, str(int(2000 / 1.25 / 1000 * 100_000)))
        ]
        assert hooks.cpu_normalization_plan(pod, ratio=1.0) == []

    def test_resctrl_group_by_qos(self):
        assert hooks.resctrl_group_plan(mkpod("a", "LSR"))[0][2] == "LSR"
        assert hooks.resctrl_group_plan(mkpod("b", "BE"))[0][2] == "BE"

    def test_tc_classid(self):
        assert hooks.tc_plan(mkpod("a", "LS"))[0][2] == str(0x10002)
        assert hooks.tc_plan(mkpod("b", "BE"))[0][2] == str(0x10004)

    def test_terway_qos_from_annotation(self):
        pod = mkpod(
            "p",
            annotations={
                ext.ANNOTATION_NETWORK_QOS: json.dumps(
                    {"IngressLimit": 1048576, "EgressLimit": 2097152}
                )
            },
        )
        plan = hooks.terway_qos_plan(pod)
        assert (hooks.pod_cgroup(pod), "net_qos.ingress_bps", "1048576") in plan
        assert (hooks.pod_cgroup(pod), "net_qos.egress_bps", "2097152") in plan
        assert hooks.terway_qos_plan(mkpod("q")) == []


class TestMutationHooks:
    def test_gpu_mutation_env_and_devices(self):
        alloc = {"gpu": [{"minor": 0, "resources": {}}, {"minor": 3, "resources": {}}]}
        pod = mkpod(
            "p", annotations={ext.ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc)}
        )
        m = hooks.gpu_mutation(pod)
        assert m.env["KOORD_VISIBLE_DEVICES"] == "0,3"
        assert m.env["NVIDIA_VISIBLE_DEVICES"] == "0,3"
        assert m.devices == ["/dev/accel0", "/dev/accel3"]

    def test_rdma_mutation(self):
        alloc = {"rdma": [{"minor": 1}]}
        pod = mkpod(
            "p", annotations={ext.ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc)}
        )
        assert hooks.rdma_mutation(pod).devices == ["/dev/infiniband/uverbs1"]

    def test_no_allocation_is_empty(self):
        m = hooks.pod_mutation(mkpod("p"))
        assert m.env == {} and m.devices == []


class TestNRIServer:
    def test_lifecycle_paths(self, tmp_path):
        executor = rex.ResourceExecutor(str(tmp_path))
        srv = hooks.NRIServer(executor)
        pod = mkpod(
            "p",
            "BE",
            requests={ext.RES_BATCH_CPU: 2000.0, ext.RES_BATCH_MEMORY: 1024.0},
            annotations={
                ext.ANNOTATION_DEVICE_ALLOCATED: json.dumps({"gpu": [{"minor": 0}]})
            },
        )
        writes = srv.run_pod_sandbox(pod)
        assert writes > 0
        # bvt applied for BE
        assert executor.read(hooks.pod_cgroup(pod), rex.CPU_BVT) == "-1"
        mut = srv.create_container(pod)
        assert mut.env["KOORD_VISIBLE_DEVICES"] == "0"
        assert srv.update_container_resources(pod) == 0  # steady state: no-op

    def test_audit_records_nri_reason(self, tmp_path):
        executor = rex.ResourceExecutor(str(tmp_path))
        hooks.NRIServer(executor).run_pod_sandbox(mkpod("p", "BE"))
        reasons = {e.reason for e in executor.auditor.query()}
        assert "nri:RunPodSandbox" in reasons


class TestQoSReconcileStrategies:
    def test_cg_reconcile_baseline(self, tmp_path):
        executor = rex.ResourceExecutor(str(tmp_path))
        executor.apply(qos.cg_reconcile_plan(total_cpus=8), reason="cgreconcile")
        assert executor.read("kubepods", rex.CPU_SHARES) == str(8 * 1024)
        assert executor.read("kubepods/besteffort", rex.CPU_SHARES) == "2"

    def test_resctrl_schemata_masks(self):
        strategy = ResctrlStrategy(
            enable=True,
            llc_percent={QoSClass.LSR: 100.0, QoSClass.LS: 100.0, QoSClass.BE: 30.0},
            mba_percent={QoSClass.LSR: 100.0, QoSClass.LS: 100.0, QoSClass.BE: 50.0},
        )
        plan = qos.resctrl_schemata_plan(strategy, cache_ways=10, n_l3_domains=2)
        by_group = {g: v for g, _f, v in plan}
        # BE: ceil(10*0.3)=3 ways -> 0x7; two domains
        assert by_group["resctrl/BE"] == "L3:0=7;1=7\nMB:0=50;1=50"
        assert by_group["resctrl/LS"].startswith("L3:0=3ff")

    def test_llc_mask_minimum_one_way(self):
        assert qos._llc_mask(0.0, 11) == "1"

    def test_blkio_plan(self):
        strategy = BlkIOStrategy(enable=True, be_read_bps=1 << 20, be_write_iops=100)
        plan = qos.blkio_plan(strategy, device="253:0")
        assert (qos.BE_GROUP, "blkio.throttle.read_bps_device", "253:0 1048576") in plan
        assert (qos.BE_GROUP, "blkio.throttle.write_iops_device", "253:0 100") in plan
        assert len(plan) == 2

    def test_sys_reconcile_plan(self):
        strategy = SystemStrategy(
            enable=True, min_free_kbytes_factor=100.0, watermark_scale_factor=150.0
        )
        plan = qos.sys_reconcile_plan(strategy, node_memory_capacity_mib=1024.0)
        assert ("proc/sys/vm", "min_free_kbytes", str(int(1024 * 1024 * 100 / 10000))) in plan
        assert ("proc/sys/vm", "watermark_scale_factor", "150") in plan

    def test_run_once_applies_enabled_strategies(self, tmp_path):
        executor = rex.ResourceExecutor(str(tmp_path))
        mgr = qos.QoSManager(
            executor,
            total_cpus=8,
            node_allocatable_milli=8000.0,
            node_memory_capacity_mib=1024.0,
        )
        slo = NodeSLO(meta=ObjectMeta(name="n"))
        slo.resctrl.enable = True
        slo.system.enable = True
        slo.blkio.enable = True
        slo.blkio.be_read_bps = 1000
        mgr.run_once(slo, node_used_milli=0, be_used_milli=0, node_memory_used_mib=0)
        reasons = {e.reason for e in executor.auditor.query()}
        assert {"cgreconcile", "resctrl", "blkio", "sysreconcile"} <= reasons
