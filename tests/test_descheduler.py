"""Descheduler + Reservation tests: LowNodeLoad classification/victims,
reservation lifecycle, reservation-first migration e2e
(reference ``pkg/descheduler`` + ``pkg/scheduler/plugins/reservation``)."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    MigrationPhase,
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
    ReservationOwner,
    ReservationPhase,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.descheduler.low_node_load import LowNodeLoad, LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import (
    Arbitrator,
    ArbitratorArgs,
    MigrationController,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.reservation import ReservationManager


def mknode(name, cpu=64000, mem=262144):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
    )


def set_util(snap, name, cpu_pct, mem_pct=None):
    idx = snap.node_id(name)
    alloc = snap.nodes.allocatable[idx]
    mem_pct = mem_pct if mem_pct is not None else cpu_pct
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name=name),
            node_usage=ResourceMetric(
                usage={
                    ext.RES_CPU: alloc[0] * cpu_pct / 100,
                    ext.RES_MEMORY: alloc[1] * mem_pct / 100,
                }
            ),
            update_time=1000.0,
        ),
        now=1010.0,
    )


def bound_pod(name, node, cpu=4000, prio=5500, labels=None):
    return Pod(
        meta=ObjectMeta(name=name, labels=labels or {}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu},
            priority=prio,
            node_name=node,
        ),
    )


def make_cluster(utils):
    snap = ClusterSnapshot()
    for i, u in enumerate(utils):
        snap.upsert_node(mknode(f"n{i}"))
        set_util(snap, f"n{i}", u)
    return snap


def test_classification_with_debounce():
    snap = make_cluster([90, 30, 55])
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=2))
    c1 = lnl.classify()
    assert c1.raw_high[0] and not c1.high[0]   # debounced on first sight
    assert c1.low[1] and not c1.low[0]
    c2 = lnl.classify()
    assert c2.high[0]                           # second consecutive round
    # node recovers -> counter resets
    set_util(snap, "n0", 30)
    c3 = lnl.classify()
    assert not c3.raw_high[0] and not c3.high[0]
    set_util(snap, "n0", 90)
    assert not lnl.classify().high[0]           # needs 2 rounds again


def test_victim_selection_prefers_batch_pods():
    snap = make_cluster([90, 20])
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=1))
    pods = [
        bound_pod("prod-1", "n0", prio=9500),
        bound_pod("batch-1", "n0", prio=5500),
        bound_pod("batch-2", "n0", prio=5500),
    ]
    victims = lnl.select_victims(pods)
    assert victims, "overutilized node must yield victims"
    assert victims[0].meta.name.startswith("batch")
    assert all(v.meta.name != "prod-1" for v in victims[:2])


def test_no_victims_without_low_nodes():
    snap = make_cluster([90, 85])
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=1))
    assert lnl.select_victims([bound_pod("b", "n0")]) == []


# ---- reservation lifecycle ----


def test_reservation_hold_and_consume():
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=16000, mem=16000))
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="r1"),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000},
            owners=[ReservationOwner(label_selector={"app": "web"})],
            allocate_once=True,
        )
    )
    assert rm.schedule_pending() == 1
    r = rm.get("r1")
    assert r.phase == ReservationPhase.AVAILABLE and r.node_name == "n0"
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx][0] == 8000   # hold in place

    # a non-matching pod cannot use the hold; node has 8000 free
    filler = bound_pod("filler", None, cpu=10000, prio=9000)
    filler.spec.node_name = None
    out = sched.schedule([filler])
    assert out.bound == []                        # 10000 > 8000 free

    # matching pod commits against the reservation directly
    owner_pod = Pod(
        meta=ObjectMeta(name="web-1", labels={"app": "web"}),
        spec=PodSpec(
            requests={ext.RES_CPU: 6000, ext.RES_MEMORY: 6000}, priority=9000
        ),
    )
    out2 = sched.schedule([owner_pod])
    assert [(p.meta.name, n) for p, n in out2.bound] == [("web-1", "n0")]
    # AllocateOnce: remainder released; node now holds only the pod
    assert snap.nodes.requested[idx][0] == 6000
    assert r.phase == ReservationPhase.SUCCEEDED


def test_reservation_expiry_releases_hold():
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=8000, mem=8000))
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="r1"),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000},
            owners=[ReservationOwner(label_selector={"app": "x"})],
        )
    )
    rm.schedule_pending()
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx][0] == 8000
    assert rm.expire_reservation("r1")
    assert snap.nodes.requested[idx][0] == 0
    assert rm.get("r1").phase == ReservationPhase.FAILED


# ---- arbitrator ----


def test_arbitrator_limits_and_order():
    args = ArbitratorArgs(max_migrating_global=3, max_migrating_per_namespace=1)
    arb = Arbitrator(args)
    pods = {}
    jobs = []
    from koordinator_tpu.api.types import PodMigrationJob

    for i, (ns, prio) in enumerate(
        [("a", 9500), ("a", 5500), ("b", 5500), ("c", 7500)]
    ):
        pod = Pod(
            meta=ObjectMeta(name=f"p{i}", namespace=ns),
            spec=PodSpec(priority=prio),
        )
        pods[pod.meta.uid] = pod
        jobs.append(
            PodMigrationJob(meta=ObjectMeta(name=f"j{i}"), pod_uid=pod.meta.uid)
        )
    picked = arb.arbitrate(jobs, pods, in_flight=0)
    names = [j.meta.name for j in picked]
    # batch pods first; ns 'a' capped at 1 so j0 (prod, same ns) dropped
    assert names == ["j1", "j2", "j3"]


# ---- reservation-first migration e2e ----


def test_reservation_first_migration_e2e():
    """Overloaded node -> victim -> reservation on a low node -> evict."""
    snap = make_cluster([92, 15])
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=1))

    victim = bound_pod("batch-victim", "n0", cpu=8000, prio=5500, labels={"job": "spark"})
    evicted = []

    def evict(pod, reason):
        evicted.append(pod.meta.name)
        snap.forget_pod(pod.meta.uid)
        return True

    ctrl = MigrationController(rm, evict)
    victims = lnl.select_victims([victim])
    assert victims
    for v in victims:
        ctrl.submit(v)
    ctrl.reconcile()
    job = next(iter(ctrl.jobs.values()))
    assert job.phase == MigrationPhase.SUCCEEDED, job
    assert evicted == ["batch-victim"]
    r = rm.get(job.reservation_name)
    assert r.phase == ReservationPhase.AVAILABLE
    assert r.node_name == "n1"  # replacement capacity on the low node

    # the replacement pod (same labels) consumes the reservation
    replacement = Pod(
        meta=ObjectMeta(name="batch-replacement", labels={"job": "spark"}),
        spec=PodSpec(
            requests=dict(victim.spec.requests), priority=5500
        ),
    )
    out = sched.schedule([replacement])
    assert [(p.meta.name, n) for p, n in out.bound] == [
        ("batch-replacement", "n1")
    ]


def test_victims_share_low_node_capacity():
    """Two overloaded nodes must not both count the same low-node free
    capacity when selecting victims."""
    snap = make_cluster([90, 91, 20])
    # low node n2 can absorb ~40k cpu of victims (45% low threshold)
    idx = snap.node_id("n2")
    snap.nodes.requested[idx][0] = 60_000  # only 40k requested-free
    snap.nodes.requested[idx][1] = 60_000
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=1))
    pods = [
        bound_pod(f"a{i}", "n0", cpu=20_000, prio=5500) for i in range(3)
    ] + [bound_pod(f"b{i}", "n1", cpu=20_000, prio=5500) for i in range(3)]
    victims = lnl.select_victims(pods)
    # 40k free => at most 2 x 20k victims total across BOTH high nodes
    assert len(victims) <= 2, [v.meta.name for v in victims]


def test_unlabeled_victim_falls_back_to_direct_eviction():
    snap = make_cluster([92, 15])
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    victim = bound_pod("plain", "n0", cpu=8000, prio=5500)  # no labels
    evicted = []
    ctrl = MigrationController(rm, lambda p, r: evicted.append(p.meta.name) or True)
    ctrl.submit(victim)
    ctrl.reconcile()
    job = next(iter(ctrl.jobs.values()))
    assert job.phase == MigrationPhase.SUCCEEDED
    assert job.reservation_name is None  # no promiscuous reservation created
    assert evicted == ["plain"]


def test_stuck_migration_times_out():
    snap = make_cluster([92, 90])  # nowhere to reserve a replacement
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    victim = bound_pod("stuck", "n0", cpu=90_000, prio=5500, labels={"j": "x"})
    ctrl = MigrationController(rm, lambda p, r: True, job_timeout_s=10.0)
    ctrl.submit(victim)
    ctrl.reconcile(now=victim and 1000.0)
    job = next(iter(ctrl.jobs.values()))
    job.create_time = 0.0
    ctrl.reconcile(now=1000.0)
    assert job.phase == MigrationPhase.FAILED
    assert "timed out" in job.reason


def test_running_migrations_count_toward_namespace_cap():
    from koordinator_tpu.api.types import PodMigrationJob
    from koordinator_tpu.descheduler.migration import Arbitrator, ArbitratorArgs

    arb = Arbitrator(ArbitratorArgs(max_migrating_per_namespace=2))
    pods, jobs = {}, []
    for i in range(3):
        pod = Pod(meta=ObjectMeta(name=f"p{i}", namespace="a"), spec=PodSpec(priority=5500))
        pods[pod.meta.uid] = pod
        jobs.append(PodMigrationJob(meta=ObjectMeta(name=f"j{i}"), pod_uid=pod.meta.uid))
    picked = arb.arbitrate(jobs, pods, in_flight=2, running_per_ns={"a": 2})
    assert picked == []  # namespace already at cap


def test_reservation_ttl_expiry():
    snap = make_cluster([20, 20])
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="ttl-res"),
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1000},
            owners=[ReservationOwner(label_selector={"a": "b"})],
            ttl_s=60.0,
        )
    )
    rm.schedule_pending()
    r = rm.get("ttl-res")
    assert r.phase == ReservationPhase.AVAILABLE
    assert rm.expire(now=r.available_time + 30) == []      # not yet
    assert rm.expire(now=r.available_time + 90) == ["ttl-res"]
    assert r.phase == ReservationPhase.FAILED


def test_deviation_thresholds_track_cluster_average():
    """UseDeviationThresholds (low_node_load.go getNodeThresholds): the
    high/low lines float around the cluster-average utilization, so a
    node is 'high' for standing out, not for an absolute level."""
    from koordinator_tpu.descheduler.low_node_load import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    snap = make_cluster([40.0] * 7 + [70.0])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            high_thresholds={ext.RES_CPU: 15.0},
            low_thresholds={ext.RES_CPU: 5.0},
            use_deviation_thresholds=True,
            anomaly_condition_count=1,
        ),
    )
    cls = lnl.classify()
    names = [snap.node_id(f"n{i}") for i in range(8)]
    assert cls.high[names[7]]
    assert not cls.high[names[:7]].any()
    # low band: avg - 5 ≈ 38.75; the 40% nodes are NOT low, and with an
    # absolute interpretation they all would be (40 < 80)
    assert not cls.low[names[7]]


def test_reservation_affinity_required_semantics():
    """ReservationAffinity (apis/extension/reservation.go:51-78): a pod
    carrying the annotation may ONLY allocate from a matching reservation —
    by name or reservation labels — and is unschedulable when none
    matches, never falling through to normal node scheduling."""
    import json

    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.scheduler.plugins.reservation import ReservationPhase

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=32000, mem=32000))
    sched = BatchScheduler(snap)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="gold-res", labels={"tier": "gold"}),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000},
            owners=[ReservationOwner(label_selector={"app": "web"})],
            allocate_once=False,
        )
    )
    assert rm.schedule_pending() == 1

    def web_pod(name, affinity=None):
        annotations = {}
        if affinity is not None:
            annotations[ext.ANNOTATION_RESERVATION_AFFINITY] = json.dumps(affinity)
        return Pod(
            meta=ObjectMeta(
                name=name, labels={"app": "web"}, annotations=annotations
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 2000, ext.RES_MEMORY: 2000}, priority=9000
            ),
        )

    # by-name affinity binds through the reservation
    out = sched.schedule([web_pod("by-name", {"name": "gold-res"})])
    assert [(p.meta.name, n) for p, n in out.bound] == [("by-name", "n0")]
    # selector affinity matches the reservation's labels
    out = sched.schedule(
        [web_pod("by-selector", {"reservationSelector": {"tier": "gold"}})]
    )
    assert len(out.bound) == 1
    # non-matching required affinity: unschedulable even with node capacity
    out = sched.schedule(
        [web_pod("no-match", {"reservationSelector": {"tier": "silver"}})]
    )
    assert out.bound == [] and len(out.unschedulable) == 1
    # without affinity, normal scheduling still works
    out = sched.schedule([web_pod("plain")])
    assert len(out.bound) == 1


def test_arbitrator_workload_level_limits():
    """filterMaxMigratingOrUnavailablePerWorkload: per-workload in-flight
    caps (int or percent of replicas) and the unavailable budget gate
    candidate selection; bare pods (no controller) skip both."""
    from koordinator_tpu.api.types import MigrationPhase, PodMigrationJob

    args = ArbitratorArgs(
        max_migrating_global=10,
        max_migrating_per_namespace=10,
        max_migrating_per_workload="20%",     # of replicas
        max_unavailable_per_workload=3,
    )
    arb = Arbitrator(args)

    def wpod(name, owner, prio=5000):
        p = Pod(
            meta=ObjectMeta(name=name, namespace="w"),
            spec=PodSpec(requests={}, priority=prio),
        )
        p.meta.owner_uid = owner
        return p

    pods = {f"w/m{i}": wpod(f"m{i}", "deploy-a") for i in range(5)}
    pods["w/bare"] = wpod("bare", "")
    jobs = [
        PodMigrationJob(meta=ObjectMeta(name=f"j{i}"), pod_uid=f"w/m{i}")
        for i in range(5)
    ] + [PodMigrationJob(meta=ObjectMeta(name="jb"), pod_uid="w/bare")]

    # deploy-a has 10 replicas -> 20% cap = 2 migrating at once
    picked = arb.arbitrate(
        jobs,
        pods,
        in_flight=0,
        replicas_by_owner={"deploy-a": 10},
        unavailable_by_owner={"deploy-a": 0},
    )
    a_picked = [j for j in picked if j.pod_uid != "w/bare"]
    assert len(a_picked) == 2
    assert any(j.pod_uid == "w/bare" for j in picked)  # bare pod unlimited

    # already one running migration for the workload: only one more
    picked2 = arb.arbitrate(
        jobs,
        pods,
        in_flight=1,
        running_per_workload={"deploy-a": 1},
        replicas_by_owner={"deploy-a": 10},
    )
    assert len([j for j in picked2 if j.pod_uid != "w/bare"]) == 1

    # unavailable budget: 2 pods already down + cap 3 -> one slot left...
    # but migrating cap (2) still applies; with 3 down, nothing fits
    picked3 = arb.arbitrate(
        jobs,
        pods,
        in_flight=0,
        replicas_by_owner={"deploy-a": 10},
        unavailable_by_owner={"deploy-a": 3},
    )
    assert [j for j in picked3 if j.pod_uid != "w/bare"] == []


def test_migration_controller_workload_info_fn():
    """The controllerFinder analog feeds per-workload limits end to end."""
    from koordinator_tpu.api.types import MigrationPhase
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    evicted = []
    mc = MigrationController(
        rm,
        evict_fn=lambda pod, reason: evicted.append(pod) or True,
        arbitrator=Arbitrator(
            ArbitratorArgs(max_migrating_per_workload=1)
        ),
        workload_info_fn=lambda owner: (4, 0),
    )
    victims = []
    for i in range(3):
        v = Pod(
            meta=ObjectMeta(name=f"v{i}", labels={"app": "x"}),
            spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024}),
        )
        v.meta.owner_uid = "rs-1"
        victims.append(v)
        mc.submit(v)
    mc.reconcile(now=1000.0)
    # only ONE of the three same-workload victims may migrate at a time
    # (the single arbitrated job completes within the pass — its
    # replacement reservation went Available immediately)
    started = [
        j
        for j in mc.jobs.values()
        if j.phase is not MigrationPhase.PENDING
    ]
    assert len(started) == 1
    assert len(evicted) == 1


def test_workload_percent_cap_without_replica_info_allows():
    """A percent cap must not resolve against replicas=0 when no
    controller-finder is wired — owned pods would be blocked forever."""
    from koordinator_tpu.api.types import PodMigrationJob

    arb = Arbitrator(
        ArbitratorArgs(
            max_migrating_global=10,
            max_migrating_per_namespace=10,
            max_migrating_per_workload="20%",
        )
    )
    p = Pod(meta=ObjectMeta(name="m0", namespace="w"), spec=PodSpec(requests={}))
    p.meta.owner_uid = "deploy-x"
    jobs = [PodMigrationJob(meta=ObjectMeta(name="j0"), pod_uid=p.meta.uid)]
    picked = arb.arbitrate(jobs, {p.meta.uid: p}, in_flight=0)
    assert len(picked) == 1


# ---- NodePools / ResourceWeights / NodeFit (types_loadaware.go:60-122) ----


def test_resource_weights_order_victims_by_overused_dim():
    """sortPodsOnOneOverloadedNode: only dims the node overuses count, at
    their configured weights — a memory-hog pod outranks a CPU-hog when
    only memory exceeds the threshold."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    snap.upsert_node(mknode("n1"))
    set_util(snap, "n0", 30, mem_pct=90)   # only memory overused
    set_util(snap, "n1", 10)
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            anomaly_condition_count=1, max_evictions_per_node=1
        ),
    )
    cpu_hog = Pod(
        meta=ObjectMeta(name="cpu-hog"),
        spec=PodSpec(
            requests={ext.RES_CPU: 20000, ext.RES_MEMORY: 1024},
            priority=5500, node_name="n0",
        ),
    )
    mem_hog = Pod(
        meta=ObjectMeta(name="mem-hog"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 120000},
            priority=5500, node_name="n0",
        ),
    )
    victims = lnl.select_victims([cpu_hog, mem_hog])
    assert [v.meta.name for v in victims] == ["mem-hog"]


def test_node_fit_false_skips_target_check():
    """NodeFit=false (types_loadaware.go:60-62): victims are picked even
    with no low node that fits them."""
    snap = make_cluster([90, 85])  # no low nodes at all
    args = LowNodeLoadArgs(anomaly_condition_count=1)
    assert LowNodeLoad(snap, args).select_victims([bound_pod("b", "n0")]) == []
    args_nofit = LowNodeLoadArgs(anomaly_condition_count=1, node_fit=False)
    lnl = LowNodeLoad(snap, args_nofit)
    cls = lnl.classify()
    cls.low[1] = True  # balance still requires a low node to exist
    assert lnl.select_victims([bound_pod("b", "n0")], cls)


def test_node_pools_independent_thresholds():
    """NodePools (types_loadaware.go:93-122): each pool classifies only
    its selected nodes against its own thresholds."""
    from koordinator_tpu.descheduler.low_node_load import (
        LowNodeLoadBalance,
        NodePool,
    )

    snap = ClusterSnapshot()
    for name, labels in [
        ("gp-0", {"pool": "general"}),
        ("gp-1", {"pool": "general"}),
        ("batch-0", {"pool": "batch"}),
        ("batch-1", {"pool": "batch"}),
    ]:
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name, labels=labels),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                ),
            )
        )
    set_util(snap, "gp-0", 70)     # over general's 65 but under batch's 90
    set_util(snap, "gp-1", 10)
    set_util(snap, "batch-0", 70)  # fine for the batch pool
    set_util(snap, "batch-1", 10)
    pools = [
        NodePool(
            name="general",
            node_selector={"pool": "general"},
            args=LowNodeLoadArgs(
                high_thresholds={ext.RES_CPU: 65, ext.RES_MEMORY: 80},
                anomaly_condition_count=1,
            ),
        ),
        NodePool(
            name="batch",
            node_selector={"pool": "batch"},
            args=LowNodeLoadArgs(
                high_thresholds={ext.RES_CPU: 90, ext.RES_MEMORY: 95},
                anomaly_condition_count=1,
            ),
        ),
    ]
    balance = LowNodeLoadBalance(LowNodeLoad(snap), pools=pools)
    evicted = []

    class Ctx:
        pods = [bound_pod("on-gp0", "gp-0"), bound_pod("on-batch0", "batch-0")]

        def evict(self, pod, reason, plugin):
            evicted.append((pod.meta.name, reason))
            return True

    n = balance.balance(Ctx())
    assert n == 1
    assert evicted[0][0] == "on-gp0"
    assert "pool general" in evicted[0][1]


def test_eviction_cost_orders_and_protects():
    """descheduling.go: lower eviction cost evicted first within a band;
    MaxInt32 = never evict."""
    from koordinator_tpu.descheduler.evictor import PodEvictionPolicy

    assert ext.parse_eviction_cost({}) == 0
    assert ext.parse_eviction_cost({ext.ANNOTATION_EVICTION_COST: "-10"}) == -10
    assert ext.parse_eviction_cost({ext.ANNOTATION_EVICTION_COST: "+10"}) == 0
    assert ext.parse_eviction_cost({ext.ANNOTATION_EVICTION_COST: "008"}) == 0

    snap = make_cluster([90, 20])
    lnl = LowNodeLoad(snap, LowNodeLoadArgs(anomaly_condition_count=1))
    cheap = bound_pod("cheap", "n0", prio=5500)
    cheap.meta.annotations[ext.ANNOTATION_EVICTION_COST] = "-5"
    costly = bound_pod("costly", "n0", prio=5500)
    costly.meta.annotations[ext.ANNOTATION_EVICTION_COST] = "100"
    victims = lnl.select_victims([costly, cheap])
    assert victims[0].meta.name == "cheap"

    protected = bound_pod("protected", "n0", prio=5500, labels={"owner-kind": "rs"})
    protected.meta.annotations[ext.ANNOTATION_EVICTION_COST] = str(
        ext.EVICTION_COST_MAX
    )
    assert not PodEvictionPolicy(evict_ownerless=True).evictable(protected)


def test_never_evict_pod_not_selected():
    """Code-review regression: MaxInt32-cost pods are filtered out of
    victim SELECTION (not just evictability), so they never consume the
    per-node eviction budget."""
    snap = make_cluster([90, 20])
    lnl = LowNodeLoad(
        snap, LowNodeLoadArgs(anomaly_condition_count=1, max_evictions_per_node=1)
    )
    protected = bound_pod("protected", "n0", prio=5500)
    protected.meta.annotations[ext.ANNOTATION_EVICTION_COST] = str(
        ext.EVICTION_COST_MAX
    )
    normal = bound_pod("normal", "n0", prio=9000)  # higher band
    victims = lnl.select_victims([protected, normal])
    assert [v.meta.name for v in victims] == ["normal"]


# ---- reservation controller sweep (plugins/reservation/controller/) ----


def test_reservation_owner_drift_refunds_and_reholds():
    """syncStatus (controller.go:221-260): a vanished owner pod refunds
    its allocation and the freed remainder is re-held by the ghost."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="hold"),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192},
            owners=[ReservationOwner(label_selector={"app": "a"})],
            allocate_once=False,
        )
    )
    assert rm.schedule_pending() == 1
    owner = bound_pod("owner-0", None, cpu=4000, prio=9000, labels={"app": "a"})
    owner.spec.node_name = None
    out = sched.schedule([owner])
    assert len(out.bound) == 1
    r = rm.get("hold")
    assert r.allocated.get(ext.RES_CPU) == 4000
    assert len(r.current_owners) == 1
    # owner pod dies: forget it, then the controller sweep reconciles
    snap.forget_pod(out.bound[0][0].meta.uid)
    report = rm.sync()
    assert report["drifted"] == ["hold"]
    assert r.allocated.get(ext.RES_CPU, 0.0) == 0.0
    assert r.current_owners == []
    # freed capacity is re-held: node requested carries the full ghost
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 8000.0


def test_reservation_gc_after_duration():
    """garbage_collection.go: terminal reservations older than gcDuration
    are deleted."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched, gc_duration_s=60.0)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="dead"),
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024},
            owners=[ReservationOwner(label_selector={"app": "x"})],
        )
    )
    assert rm.schedule_pending() == 1
    rm.expire_reservation("dead")
    assert rm.get("dead").phase == ReservationPhase.FAILED
    import time

    assert rm.sync(now=time.time() + 30)["deleted"] == []   # too young
    assert rm.sync(now=time.time() + 120)["deleted"] == ["dead"]
    assert rm.get("dead") is None


def test_operating_mode_pod_as_reservation():
    """operating_pod.go: a pod labeled operating-mode=Reservation acts as
    a reservation — its own assume is the capacity hold, owners consume
    it through the fast path, and the current-owner annotation lands on
    the operating pod."""
    import json as _json

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="placeholder-0",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
            annotations={
                ext.ANNOTATION_RESERVATION_OWNERS: _json.dumps(
                    [{"labelSelector": {"matchLabels": {"app": "svc"}}}]
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192}, priority=9500
        ),
    )
    # the operating pod schedules like any pod...
    out = sched.schedule([op])
    assert len(out.bound) == 1
    op.spec.node_name = out.bound[0][1]
    # ...and its bind turns it into an Available reservation
    r = rm.ingest_operating_pod(op)
    assert r is not None and r.phase == ReservationPhase.AVAILABLE
    assert r.node_name == "n0"
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 8000.0  # the pod IS the hold
    # an owner consumes it through the fast path (AllocateOnce)
    owner = bound_pod("svc-0", None, cpu=8000, prio=9500, labels={"app": "svc"})
    owner.spec.node_name = None
    out2 = sched.schedule([owner])
    assert [(p.meta.name, n) for p, n in out2.bound] == [("svc-0", "n0")]
    assert r.phase == ReservationPhase.SUCCEEDED
    # capacity swapped on the CPU dim (owner covers it exactly); the
    # placeholder keeps the uncovered memory remainder (8192 − 8000 MiB)
    # charged under its own uid until the pod itself is deleted
    assert snap.nodes.requested[idx, 0] == 8000.0
    assert snap.nodes.requested[idx, 1] == 8192.0
    assert snap.is_assumed(op.meta.uid)
    cur = _json.loads(
        op.meta.annotations[ext.ANNOTATION_RESERVATION_CURRENT_OWNER]
    )
    assert cur["name"] == "svc-0"
    # a non-operating pod is ignored
    assert rm.ingest_operating_pod(bound_pod("x", "n0")) is None


def test_pending_operating_pod_gets_no_ghost():
    """Code-review regression: schedule_pending must not schedule a ghost
    for an operating-pod-backed reservation — the pod itself is the unit
    of scheduling."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="pending-op",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
        ),
        spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096}),
    )
    r = rm.ingest_operating_pod(op)  # still pending (no node)
    assert r.phase == ReservationPhase.PENDING
    assert rm.schedule_pending() == 0  # no ghost scheduled
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 0.0


def test_consumed_operating_pod_reingest_stays_succeeded():
    """Code-review regression: re-ingesting an operating pod that carries
    the current-owner annotation (restart / post-GC resync) must register
    it Succeeded, never as fresh Available capacity."""
    import json as _json

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="used-op",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
            annotations={
                ext.ANNOTATION_RESERVATION_OWNERS: _json.dumps(
                    [{"labelSelector": {"matchLabels": {"app": "svc"}}}]
                ),
                ext.ANNOTATION_RESERVATION_CURRENT_OWNER: _json.dumps(
                    {"namespace": "default", "name": "svc-old"}
                ),
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192},
            priority=9500,
            node_name="n0",
        ),
    )
    r = rm.ingest_operating_pod(op)
    assert r.phase == ReservationPhase.SUCCEEDED
    owner = bound_pod("svc-new", None, cpu=4000, prio=9500, labels={"app": "svc"})
    owner.spec.node_name = None
    assert rm.match(owner) is None  # never offered as capacity


def test_expire_pod_backed_reservation_keeps_charge():
    """Code-review regression: expiring a pod-backed reservation must not
    forget the still-running placeholder pod's charge."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="ph-0",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
        ),
        spec=PodSpec(requests={ext.RES_CPU: 6000, ext.RES_MEMORY: 4096}),
    )
    out = sched.schedule([op])
    op.spec.node_name = out.bound[0][1]
    rm.ingest_operating_pod(op)
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 6000.0
    assert rm.expire_reservation("ph-0")
    # the placeholder still runs: its charge stays until the pod goes
    assert snap.nodes.requested[idx, 0] == 6000.0
    assert snap.is_assumed(op.meta.uid)


def test_operating_pod_partial_consumption_keeps_remainder_charge():
    """Advisor r2 (medium) regression: a 4000m owner consuming an 8000m
    pod-backed reservation must NOT free 4000m of phantom capacity — the
    still-RUNNING placeholder physically occupies it. The node stays
    charged max(placeholder, owner); the remainder frees only when the
    placeholder pod itself is forgotten (deleted)."""
    import json as _json

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="big-ph",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
            annotations={
                ext.ANNOTATION_RESERVATION_OWNERS: _json.dumps(
                    [{"labelSelector": {"matchLabels": {"app": "svc"}}}]
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192}, priority=9500
        ),
    )
    out = sched.schedule([op])
    assert len(out.bound) == 1
    op.spec.node_name = out.bound[0][1]
    rm.ingest_operating_pod(op)
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 8000.0
    # a HALF-size owner consumes the reservation
    owner = bound_pod("svc-0", None, cpu=4000, prio=9500, labels={"app": "svc"})
    owner.spec.node_name = None
    out2 = sched.schedule([owner])
    assert [(p.meta.name, n) for p, n in out2.bound] == [("svc-0", "n0")]
    # node stays charged the FULL placeholder size: owner 4000 + remainder
    # 4000 still held under the placeholder's uid
    assert snap.nodes.requested[idx, 0] == 8000.0
    assert snap.is_assumed(op.meta.uid)
    assert snap.is_assumed(owner.meta.uid)
    # only when the placeholder pod itself is deleted does the remainder go
    snap.forget_pod(op.meta.uid)
    assert snap.nodes.requested[idx, 0] == 4000.0


def test_operating_pod_owner_dies_first_reexpands_charge():
    """Reviewer r3 regression: the owner pod dying BEFORE the still-running
    placeholder must re-expand the placeholder's charge to its full
    footprint at the next controller sweep; deleting the placeholder
    itself (remove_operating_pod) then drops everything."""
    import json as _json

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0"))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    op = Pod(
        meta=ObjectMeta(
            name="ph-exp",
            labels={
                ext.LABEL_POD_OPERATING_MODE: ext.POD_OPERATING_MODE_RESERVATION
            },
            annotations={
                ext.ANNOTATION_RESERVATION_OWNERS: _json.dumps(
                    [{"labelSelector": {"matchLabels": {"app": "svc"}}}]
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192}, priority=9500
        ),
    )
    out = sched.schedule([op])
    op.spec.node_name = out.bound[0][1]
    rm.ingest_operating_pod(op)
    idx = snap.node_id("n0")
    owner = bound_pod("svc-1", None, cpu=4000, prio=9500, labels={"app": "svc"})
    owner.spec.node_name = None
    out2 = sched.schedule([owner])
    assert len(out2.bound) == 1
    assert snap.nodes.requested[idx, 0] == 8000.0
    # owner dies first: forget its assume, sweep re-expands the placeholder
    snap.forget_pod(owner.meta.uid)
    assert snap.nodes.requested[idx, 0] == 4000.0  # transiently degraded
    report = rm.sync()
    assert "ph-exp" in report["drifted"]
    assert snap.nodes.requested[idx, 0] == 8000.0  # full footprint restored
    # placeholder deletion drops the remaining charge
    rm.remove_operating_pod("ph-exp")
    assert snap.nodes.requested[idx, 0] == 0.0
    # idempotent / no resurrection at the next sweep
    rm.sync()
    assert snap.nodes.requested[idx, 0] == 0.0


def test_reservation_aligned_policy_spills_to_node():
    """reservation_types.go:86-90 Aligned: the owner allocates from the
    reservation FIRST and spills the rest to node free capacity. A
    6000m owner on a 4000m reservation consumes the full 4000m credit
    and charges only the 2000m spill beyond the ghost swap."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=16000, mem=16000))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="r-al"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4000},
            owners=[ReservationOwner(label_selector={"app": "al"})],
            allocate_once=False,
            allocate_policy="Aligned",
        )
    )
    assert rm.schedule_pending() == 1
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 4000.0
    owner = bound_pod("al-0", None, cpu=6000, prio=9500, labels={"app": "al"})
    owner.spec.node_name = None
    out = sched.schedule([owner])
    assert [(p.meta.name, n) for p, n in out.bound] == [("al-0", "n0")]
    r = rm.get("r-al")
    # the reservation credit is fully consumed; the ledger records what
    # came FROM the reservation (4000), not the pod's full request
    assert r.allocated[ext.RES_CPU] == 4000.0
    assert rm.owner_ledger("r-al")[owner.meta.uid][ext.RES_CPU] == 4000.0
    # node charge: owner 6000 (no remainder ghost left on the cpu dim)
    assert snap.nodes.requested[idx, 0] == 6000.0


def test_reservation_aligned_spill_needs_node_headroom():
    """An Aligned owner whose spill exceeds node free capacity must NOT
    commit through the reservation fast path (it falls through to the
    solver and stays unschedulable on a full node)."""
    from koordinator_tpu.scheduler.batch_solver import LoadAwareArgs

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=8000, mem=8000))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(
        snap, LoadAwareArgs(usage_thresholds={}), batch_bucket=64
    )
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="r-full"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4000},
            owners=[ReservationOwner(label_selector={"app": "al"})],
            allocate_policy="Aligned",
        )
    )
    assert rm.schedule_pending() == 1
    # fill the rest of the node so the spill cannot fit
    filler = bound_pod("filler", None, cpu=4000, prio=9000)
    filler.spec.node_name = None
    assert len(sched.schedule([filler]).bound) == 1
    owner = bound_pod("al-1", None, cpu=6000, prio=9500, labels={"app": "al"})
    owner.spec.node_name = None
    out = sched.schedule([owner])
    assert out.bound == []          # spill 2000 > 0 free: rejected
    assert rm.get("r-full").phase == ReservationPhase.AVAILABLE


def test_reservation_restricted_policy_requires_reservation_capacity():
    """reservation_types.go:91-97 Restricted: dims the reservation
    declares may ONLY come from the reservation — an owner exceeding the
    declared remaining does not match; undeclared dims still allocate
    from the node."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=32000, mem=32000))
    set_util(snap, "n0", 10)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="r-res"),
            requests={ext.RES_CPU: 4000},    # memory NOT declared
            owners=[ReservationOwner(label_selector={"app": "rs"})],
            allocate_once=False,
            allocate_policy="Restricted",
        )
    )
    assert rm.schedule_pending() == 1
    # over-declared-dim owner: no match (binds via the solver instead,
    # consuming nothing from the reservation)
    big = bound_pod("rs-big", None, cpu=6000, prio=9500, labels={"app": "rs"})
    big.spec.node_name = None
    assert rm.match(big) is None
    # fitting owner with an UNDECLARED memory dim: matches; memory comes
    # from the node
    ok = Pod(
        meta=ObjectMeta(name="rs-ok", labels={"app": "rs"}),
        spec=PodSpec(
            requests={ext.RES_CPU: 3000, ext.RES_MEMORY: 2048},
            priority=9500,
        ),
    )
    assert rm.match(ok) is not None
    out = sched.schedule([ok])
    assert [(p.meta.name, n) for p, n in out.bound] == [("rs-ok", "n0")]
    assert rm.get("r-res").allocated[ext.RES_CPU] == 3000.0
    assert ext.RES_MEMORY not in rm.get("r-res").allocated


def test_drained_preferred_reservation_does_not_shadow_feasible_one():
    """Reviewer r3 regression: an Aligned reservation whose spill cannot
    fit its node must be SKIPPED at match time so a lower-preference but
    feasible reservation (holding exactly the reserved capacity) wins."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n0", cpu=8000, mem=8000))
    snap.upsert_node(mknode("n1", cpu=8000, mem=8000))
    set_util(snap, "n0", 10)
    set_util(snap, "n1", 10)
    from koordinator_tpu.scheduler.batch_solver import LoadAwareArgs

    sched = BatchScheduler(
        snap, LoadAwareArgs(usage_thresholds={}), batch_bucket=64
    )
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    # preferred (ordered) reservation: fully drained AND its node full
    pref = Reservation(
        meta=ObjectMeta(
            name="pref",
            labels={ext.LABEL_RESERVATION_ORDER: "1"},
        ),
        requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000},
        owners=[ReservationOwner(label_selector={"app": "x"})],
        allocate_once=False,
        allocate_policy="Aligned",
    )
    pref.phase = ReservationPhase.AVAILABLE
    pref.node_name = "n0"
    pref.allocated = {ext.RES_CPU: 8000, ext.RES_MEMORY: 8000}
    # charge n0 full so any spill is infeasible there
    blocker = bound_pod("blk", "n0", cpu=8000)
    snap.assume_pod(blocker, "n0")
    rm.add(pref)
    # feasible unordered reservation with remaining capacity on n1
    rm.add(
        Reservation(
            meta=ObjectMeta(name="feas"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4000},
            owners=[ReservationOwner(label_selector={"app": "x"})],
            allocate_once=False,
            allocate_policy="Aligned",
        )
    )
    assert rm.schedule_pending() == 1
    pod = bound_pod("x-0", None, cpu=4000, prio=9500, labels={"app": "x"})
    pod.spec.node_name = None
    got = rm.match(pod)
    assert got is not None and got.meta.name == "feas"
    out = sched.schedule([pod])
    assert [(p.meta.name, n) for p, n in out.bound] == [("x-0", "n1")]


# ---------------------------------------------------------------------------
# SLO-driven migration pressure (devprof PR satellite: first consumer of
# the /slo layer — a burning shard tightens LowNodeLoad's high thresholds)
# ---------------------------------------------------------------------------


def _burning_slo(shard=0, n_bad=64):
    from koordinator_tpu.obs.slo import SloTracker

    class _Tick:
        t = 0.0

        def __call__(self):
            self.t += 0.001
            return self.t

    slo = SloTracker(clock=_Tick())
    for _ in range(n_bad):
        slo.observe_latency(shard, 10.0)  # >> 1.0 s target: budget burns
    return slo


def _healthy_slo(shard=0, n=64):
    from koordinator_tpu.obs.slo import SloTracker

    class _Tick:
        t = 0.0

        def __call__(self):
            self.t += 0.001
            return self.t

    slo = SloTracker(clock=_Tick())
    for _ in range(n):
        slo.observe_latency(shard, 0.01)
    return slo


def test_slo_pressure_flag_off_changes_nothing():
    snap = make_cluster([55, 20])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(anomaly_condition_count=1),  # flag defaults off
        slo=_burning_slo(),
        shard=0,
    )
    assert lnl.slo_pressure_factor() == 1.0
    cls = lnl.classify()
    assert not cls.raw_high[0]  # 55% < the 65% high threshold


def test_burning_shard_raises_migration_pressure():
    """A shard burning its latency error budget tightens the high
    thresholds: a 55%-utilized node (under the 65% threshold when
    healthy) becomes actionable, and victims flow to the low node."""
    snap = make_cluster([55, 20])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(anomaly_condition_count=1, slo_pressure=True),
        slo=_burning_slo(shard=0),
        shard=0,
    )
    factor = lnl.slo_pressure_factor()
    assert factor > 1.0
    cls = lnl.classify()
    assert cls.raw_high[0] and cls.high[0]
    assert cls.low[1]
    victims = lnl.select_victims(
        [bound_pod(f"v{i}", "n0", cpu=8000) for i in range(4)], cls
    )
    assert victims  # pressure actually produced migration work


def test_healthy_shard_keeps_baseline_thresholds():
    snap = make_cluster([55, 20])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(anomaly_condition_count=1, slo_pressure=True),
        slo=_healthy_slo(shard=0),
        shard=0,
    )
    assert lnl.slo_pressure_factor() == 1.0
    cls = lnl.classify()
    assert not cls.raw_high[0]
    assert not lnl.select_victims(
        [bound_pod(f"v{i}", "n0", cpu=8000) for i in range(4)], cls
    )


def test_slo_pressure_is_capped():
    snap = make_cluster([55, 20])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            anomaly_condition_count=1,
            slo_pressure=True,
            slo_pressure_cap=2.0,
        ),
        slo=_burning_slo(shard=0),
        shard=0,
    )
    assert lnl.slo_pressure_factor() == 2.0


def test_other_shards_burn_does_not_leak_pressure():
    # the tracker burns on shard 3; this plugin rebalances shard 0
    snap = make_cluster([55, 20])
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(anomaly_condition_count=1, slo_pressure=True),
        slo=_burning_slo(shard=3),
        shard=0,
    )
    assert lnl.slo_pressure_factor() == 1.0
    assert not lnl.classify().raw_high[0]
