"""frameworkext: transformers, monitor, error dispatch, debug, services,
metrics (reference pkg/scheduler/frameworkext/framework_extender_test.go
exercises the same seams)."""

import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.frameworkext import (
    ErrorHandlerDispatcher,
    FrameworkExtender,
    SchedulerMonitor,
)
from koordinator_tpu.utils.metrics import Registry


def mkpod(name, cpu=1000, mem=1 << 30, priority=9500):
    return Pod(
        meta=ObjectMeta(name=name, uid=name),
        spec=PodSpec(
            requests={ext.RES_CPU: float(cpu), ext.RES_MEMORY: float(mem)},
            priority=priority,
        ),
    )


@pytest.fixture
def sched():
    s = BatchScheduler()
    for i in range(4):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"node-{i}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 32000.0,
                        ext.RES_MEMORY: float(64 << 30),
                    }
                ),
            )
        )
    return s


class TestTransformers:
    def test_pod_transformer_rewrites_before_lowering(self, sched):
        # BeforePreFilter analog: double the CPU request.
        def double_cpu(pod):
            pod.spec.requests[ext.RES_CPU] *= 2
            return pod

        sched.extender.register_pod_transformer(double_cpu)
        pod = mkpod("p1", cpu=1000)
        out = sched.schedule([pod])
        assert len(out.bound) == 1
        assert pod.spec.requests[ext.RES_CPU] == 2000.0

    def test_pod_transformer_drop_marks_unschedulable(self, sched):
        sched.extender.register_pod_transformer(
            lambda pod: None if pod.meta.name == "bad" else pod
        )
        out = sched.schedule([mkpod("bad"), mkpod("ok")])
        assert [p.meta.name for p, _ in out.bound] == ["ok"]
        assert [p.meta.name for p in out.unschedulable] == ["bad"]
        assert sched.extender.errors.failures[0][0] == "bad"

    def test_batch_transformer_sees_device_arrays(self, sched):
        seen = {}

        def spy(pods, nodes):
            seen["p"] = int(pods.requests.shape[0])
            return pods, nodes

        sched.extender.register_batch_transformer(spy)
        sched.schedule([mkpod("p1")])
        assert seen["p"] >= 1

    def test_cost_transformer_steers_choice(self, sched):
        # Make node 0 infinitely expensive: nothing lands there (the solver
        # treats non-finite cost as infeasible, like a BeforeScore veto).
        def avoid_node0(cost):
            return jnp.where(
                (jnp.arange(cost.shape[1]) == 0)[None, :], jnp.inf, cost
            )

        sched.extender.register_cost_transformer(avoid_node0)
        out = sched.schedule([mkpod(f"p{i}") for i in range(8)])
        assert len(out.bound) == 8
        assert all(node != "node-0" for _, node in out.bound)


class TestMonitor:
    def test_timeout_sweep(self):
        reg = Registry(namespace="koord_scheduler")
        reg.counter("scheduling_timeout_total", "")
        mon = SchedulerMonitor(registry=reg, period_s=10.0, timeout_s=30.0)
        pod = mkpod("slow")
        mon.start_monitor(pod, now=0.0)
        # inside period: no sweep
        assert mon.sweep(now=5.0) == []
        mon._last_sweep = 0.0
        # past period but inside timeout
        assert mon.sweep(now=11.0) == []
        mon._last_sweep = 0.0
        assert mon.sweep(now=31.0) == ["slow"]
        assert reg.get("scheduling_timeout_total").value() == 1

    def test_complete_clears(self):
        mon = SchedulerMonitor(period_s=0.0, timeout_s=0.0)
        pod = mkpod("fast")
        mon.start_monitor(pod, now=0.0)
        mon.complete(pod)
        assert mon.sweep(now=100.0) == []


class TestErrorDispatcher:
    def test_pre_handler_consumes(self):
        d = ErrorHandlerDispatcher()
        calls = []
        d.register_pre(lambda p, m: calls.append(("pre", p.meta.name)) or True)
        d.set_default(lambda p, m: calls.append(("default", p.meta.name)) or False)
        d.handle(mkpod("x"), "boom")
        assert calls == [("pre", "x")]

    def test_falls_through_to_default_and_post(self):
        d = ErrorHandlerDispatcher()
        calls = []
        d.register_pre(lambda p, m: False)
        d.set_default(lambda p, m: calls.append("default") or False)
        d.register_post(lambda p, m: calls.append("post") or False)
        d.handle(mkpod("x"), "boom")
        assert calls == ["default", "post"]


class TestDebugAndServices:
    def test_score_dump_via_services(self, sched):
        eng = sched.extender.services
        code, body = eng.dispatch("POST", "/debug/scores", "3")
        assert (code, body) == (200, "3")
        out = sched.schedule([mkpod("p1")])
        assert len(out.bound) == 1
        code, body = eng.dispatch("GET", "/debug/scores")
        assert code == 200 and "p1" in body and "topScores" in body

    def test_metrics_exposition(self, sched):
        sched.schedule([mkpod("p1")])
        code, body = sched.extender.services.dispatch("GET", "/metrics")
        assert code == 200
        assert "koord_scheduler_scheduled_pods_total 1" in body
        assert "koord_scheduler_solver_batch_latency_seconds_count 1" in body

    def test_plugin_endpoint_install_and_http(self, sched):
        eng = sched.extender.services
        eng.install("loadaware", "/estimate", lambda body: (200, "ok:" + body))
        code, body = eng.dispatch("POST", "/apis/v1/loadaware/estimate", "x")
        assert (code, body) == (200, "ok:x")
        port = eng.serve()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                assert resp.status == 200
                assert b"koord_scheduler" in resp.read()
        finally:
            eng.shutdown()

    def test_unknown_route_404(self, sched):
        assert sched.extender.services.dispatch("GET", "/nope")[0] == 404


class TestRegistryPrimitives:
    def test_counter_gauge_histogram(self):
        reg = Registry(namespace="t")
        c = reg.counter("c", "help", labels=("a",))
        c.labels(a="x").inc(2)
        assert c.value(a="x") == 2
        g = reg.gauge("g", "help")
        g.set(7.5)
        assert g.value() == 7.5
        h = reg.histogram("h", "help")
        for v in (0.002, 0.002, 0.2, 3.0):
            h.observe(v)
        assert h.quantile(0.5) == pytest.approx(0.0025)
        text = reg.expose()
        assert "t_c" in text and "t_h_bucket" in text and 't_h_count 4' in text


class TestSchedulingQueueAndAdapter:
    """Reference frameworkext/scheduler_adapter.go:85-190 semantics."""

    def test_queue_lifecycle(self):
        from koordinator_tpu.scheduler.frameworkext import SchedulingQueue

        q = SchedulingQueue(backoff_s=10.0)
        a, b, c = mkpod("a"), mkpod("b"), mkpod("c")
        for p in (a, b, c):
            q.add(p)
        q.mark_backoff(b, now=100.0)
        q.mark_unschedulable(c)
        # backoff not yet expired: only the active pod drains
        assert [p.meta.name for p in q.drain_active(now=105.0)] == ["a"]
        # activate pulls the unschedulable pod back by name
        assert q.activate(["c"]) == 1
        assert [p.meta.name for p in q.drain_active(now=105.0)] == ["c"]
        # backoff expiry returns the pods (b from earlier, a just added)
        q.add(a)
        q.mark_backoff(a, now=100.0)
        drained = q.drain_active(now=111.0)
        assert {p.meta.name for p in drained} == {"a", "b"}

    def test_pools_are_exclusive(self):
        """Re-adding a backed-off pod must not leave a stale backoff entry
        that drains it a second time."""
        from koordinator_tpu.scheduler.frameworkext import SchedulingQueue

        q = SchedulingQueue(backoff_s=5.0)
        p = mkpod("dup")
        q.add(p)
        q.mark_backoff(p, now=0.0)
        q.add(p)  # pod update / forget_pod re-queues it
        assert [x.meta.name for x in q.drain_active(now=1.0)] == ["dup"]
        # past the old backoff deadline: nothing left to drain
        assert q.drain_active(now=10.0) == []

    def test_move_all_on_cluster_event(self):
        from koordinator_tpu.scheduler.frameworkext import SchedulingQueue

        q = SchedulingQueue()
        for i in range(3):
            p = mkpod(f"u{i}")
            q.add(p)
            q.mark_unschedulable(p)
        assert q.pending_counts["unschedulable"] == 3
        assert q.move_all_to_active_or_backoff() == 3
        assert len(q.drain_active()) == 3

    def test_adapter_cache_ops(self, sched):
        from koordinator_tpu.scheduler.frameworkext import SchedulerAdapter

        adapter = SchedulerAdapter(sched.snapshot)
        pod = mkpod("assumed")
        idx = sched.snapshot.node_id("node-0")
        before = sched.snapshot.nodes.requested[idx].copy()
        adapter.assume_pod(pod, "node-0")
        assert sched.snapshot.nodes.requested[idx][0] > before[0]
        adapter.forget_pod(pod)
        np.testing.assert_allclose(
            sched.snapshot.nodes.requested[idx], before, atol=1e-3
        )
        # forget re-queues the pod
        assert [p.meta.name for p in adapter.queue.drain_active()] == ["assumed"]
        # invalidation drops metric freshness (masks degrade like expiry)
        sched.snapshot.nodes.metric_fresh[idx] = True
        adapter.invalidate_node("node-0")
        assert not sched.snapshot.nodes.metric_fresh[idx]


# ---- informer pod transformers (pkg/util/transformer/pod_transformer.go) ----


def test_pod_transformers_chain():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.scheduler import transformers as tf
    from koordinator_tpu.utils.features import SCHEDULER_GATES

    pod = Pod(
        meta=ObjectMeta(
            name="p",
            labels={
                tf.LABEL_SCHEDULER_NAME: "my-sched",
                ext.LABEL_POD_PRIORITY: "9500",
            },
        ),
        spec=PodSpec(
            requests={
                f"{ext.DOMAIN}/batch-cpu": 4000,
                "kubernetes.io/gpu": 1,
                ext.RES_MEMORY: 1024,
            },
            priority=5000,
        ),
    )
    out = tf.transform_pod(pod)
    # deprecated names rename in place
    assert out.spec.requests[ext.RES_BATCH_CPU] == 4000
    assert out.spec.requests[ext.RES_GPU] == 1
    assert f"{ext.DOMAIN}/batch-cpu" not in out.spec.requests
    # scheduler-name label overrides spec
    assert out.spec.scheduler_name == "my-sched"
    # priority label only applies behind the gate
    assert out.spec.priority == 5000
    with SCHEDULER_GATES.override("PriorityTransformer", True):
        assert tf.transform_pod(pod).spec.priority == 9500
    # a current name already present wins over its deprecated alias
    pod2 = Pod(
        meta=ObjectMeta(name="q"),
        spec=PodSpec(
            requests={f"{ext.DOMAIN}/batch-cpu": 1000, ext.RES_BATCH_CPU: 2000}
        ),
    )
    assert tf.transform_pod(pod2).spec.requests[ext.RES_BATCH_CPU] == 2000


def test_pod_transformers_install_on_extender():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.scheduler import transformers as tf
    from koordinator_tpu.scheduler.frameworkext import FrameworkExtender

    fwext = FrameworkExtender()
    fwext.monitor.stop_background()
    tf.install(fwext)
    pod = Pod(
        meta=ObjectMeta(name="p"),
        spec=PodSpec(requests={f"{ext.DOMAIN}/batch-memory": 2048}),
    )
    kept, dropped = fwext.run_pre_batch_transformers([pod])
    assert dropped == []
    assert kept[0].spec.requests == {ext.RES_BATCH_MEMORY: 2048}
