"""End-to-end colocation flow — BASELINE config #1 (the reference's
``examples/spark-jobs`` demo) run through the whole §3.3 feedback loop:

  admission webhook (ClusterColocationProfile) mutates Spark pods to BE
  → noderesource controller computes kubernetes.io/batch-* from prod peak
  → scheduler places the BE pods against batch resources
  → koordlet runtimehooks derive the on-node cgroup plan (bvt, shares)
  → prod load rises → batch capacity shrinks, qosmanager suppresses BE,
    descheduler LowNodeLoad selects BE victims and a migration job starts.

One test per arrow would hide integration seams; this file drives the whole
loop over a shared cluster state exactly like the reference e2e suite does
over kind (``test/e2e/slocontroller``).
"""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.api.types import (
    ClusterColocationProfile,
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.descheduler.low_node_load import LowNodeLoad, LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import MigrationController
from koordinator_tpu.koordlet import qosmanager, runtimehooks
from koordinator_tpu.manager.noderesource import (
    ColocationStrategy,
    NodeResourceController,
)
from koordinator_tpu.manager.profile import ProfileMutator
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

N_NODES = 8
ALLOC_CPU = 64_000.0
ALLOC_MEM = 256 * 1024.0


def build_cluster(prod_util=0.3):
    snap = ClusterSnapshot()
    for i in range(N_NODES):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: ALLOC_CPU, ext.RES_MEMORY: ALLOC_MEM}
                ),
            )
        )
        report_usage(snap, f"n{i}", prod_util, now=1000.0)
    return snap


def report_usage(snap, node, prod_util, now):
    usage = {
        ext.RES_CPU: ALLOC_CPU * prod_util,
        ext.RES_MEMORY: ALLOC_MEM * prod_util * 0.8,
    }
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name=node),
            node_usage=ResourceMetric(usage=dict(usage)),
            prod_usage=ResourceMetric(usage=dict(usage)),
            update_time=now - 1,
        ),
        now=now,
    )


def spark_profile():
    return ClusterColocationProfile(
        meta=ObjectMeta(name="colocation-spark"),
        selector={"koordinator.sh/enable-colocation": "true"},
        qos_class=QoSClass.BE,
        priority=5500,
        scheduler_name="koord-scheduler",
        labels={"mutated-by": "colocation-profile"},
        resource_translation={
            ext.RES_CPU: ext.RES_BATCH_CPU,
            ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
        },
    )


def spark_pod(i):
    return Pod(
        meta=ObjectMeta(
            name=f"spark-executor-{i}",
            namespace="spark",
            labels={"koordinator.sh/enable-colocation": "true", "app": "spark"},
        ),
        spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}),
    )


def test_full_colocation_loop():
    snap = build_cluster(prod_util=0.3)

    # ---- 1. admission: profile turns Spark pods into BE batch pods ----
    mutator = ProfileMutator()
    mutator.upsert(spark_profile())
    pods = [mutator.mutate(spark_pod(i)) for i in range(16)]
    for p in pods:
        assert p.qos is QoSClass.BE
        assert p.spec.priority == 5500
        assert ext.RES_BATCH_CPU in p.spec.requests
        assert ext.RES_CPU not in p.spec.requests
        assert p.meta.labels["mutated-by"] == "colocation-profile"

    # ---- 2. slo-controller: batch capacity from prod peak ----
    ctrl = NodeResourceController(snap, ColocationStrategy(reserve_ratio=0.1))
    published = ctrl.reconcile()
    bc = snap.config.resources.index(ext.RES_BATCH_CPU)
    rows = [snap.node_id(f"n{i}") for i in range(N_NODES)]
    # batch = alloc*(1-reserve) - prod_peak = 64000*0.9 - 19200 = 38400
    np.testing.assert_allclose(
        snap.nodes.allocatable[rows, bc], 38400.0, rtol=1e-5
    )
    assert published["n0"][ext.RES_BATCH_CPU] > 0

    # ---- 3. scheduler: BE pods land against batch resources ----
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=64)
    sched.extender.monitor.stop_background()
    out = sched.schedule(pods)
    assert len(out.bound) == 16
    assert len({n for _, n in out.bound}) > 1  # spread, not piled

    # batch consumption is visible in the snapshot's requested tensor
    assert snap.nodes.requested[rows, bc].sum() == 16 * 4000

    # ---- 4. koordlet: cgroup plan for a bound BE pod ----
    bound_pod, node = out.bound[0]
    plan = runtimehooks.pod_plan(bound_pod)
    # group identity: BE pods get the lowest bvt tier; batchresource: shares
    rendered = str(plan)
    assert "bvt" in rendered
    assert "cpu" in rendered

    # ---- 5. prod load rises: batch shrinks, BE suppressed, victims ----
    for i in range(2):  # two hot nodes
        report_usage(snap, f"n{i}", prod_util=0.85, now=2000.0)
    ctrl.reconcile()
    hot = snap.node_id("n0")
    # batch capacity collapsed on the hot node (0.9*64000 - 0.85*64000)
    assert snap.nodes.allocatable[hot, bc] < 4000

    # qosmanager: BE allowance shrinks to the suppression leftovers
    dec = qosmanager.cpu_suppress(
        node_allocatable_milli=ALLOC_CPU,
        node_used_milli=0.85 * ALLOC_CPU + 8000,
        be_used_milli=8000,
        threshold_percent=65.0,
    )
    assert dec.be_allowance_milli < 8000  # squeezed below current BE usage

    # descheduler: hot nodes flagged (after debounce), BE pods are victims
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            high_thresholds={ext.RES_CPU: 70.0},
            low_thresholds={ext.RES_CPU: 45.0},
            anomaly_condition_count=2,
        ),
    )
    lnl.classify()               # debounce tick 1
    cls = lnl.classify()         # tick 2: sticky-high now
    assert cls.high[hot]
    for p, n in out.bound:     # Bind writes spec.nodeName
        p.spec.node_name = n
    hot_bound = [p for p, n in out.bound if n in ("n0", "n1")]
    victims = lnl.select_victims(hot_bound)
    assert victims, "no victims selected from overloaded nodes"
    assert all(v.qos is QoSClass.BE for v in victims)

    # migration: reservation-first job submitted and driven — a
    # Reservation for the replacement goes Available, then the victim is
    # evicted (ReservationFirst mode, reference controllers/migration)
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    evicted = []
    rm = ReservationManager(sched)
    mc = MigrationController(rm, evict_fn=lambda pod, reason: evicted.append(pod))
    job = mc.submit(victims[0])
    assert job is not None
    mc.reconcile(now=3000.0)
    mc.reconcile(now=3001.0)
    assert evicted and evicted[0].meta.uid == victims[0].meta.uid


def test_nodeslo_config_channel_drives_qos(tmp_path):
    """The §3.3 dynamic-config path: slo-controller-config with a
    node-label override renders a per-node NodeSLO, koordlet adopts it via
    the statesinformer callback, and the next QoS tick enforces the
    overridden suppression threshold in cgroup writes."""
    import dataclasses as dc

    from koordinator_tpu.api.types import ResourceThresholdStrategy
    from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig
    from koordinator_tpu.koordlet import resourceexecutor as rex
    from koordinator_tpu.manager.nodeslo import NodeSLOController, SLOControllerConfig

    ctrl = NodeSLOController(
        SLOControllerConfig(
            threshold=ResourceThresholdStrategy(
                enable=True, cpu_suppress_threshold_percent=65.0
            ),
            node_overrides={
                "node-pool=gold": ResourceThresholdStrategy(
                    enable=True, cpu_suppress_threshold_percent=40.0
                )
            },
        )
    )
    slo = ctrl.render("test-node", node_labels={"node-pool": "gold"})
    assert slo.threshold.cpu_suppress_threshold_percent == 40.0

    agent = Koordlet(
        KoordletConfig(
            node_name="test-node",
            cgroup_root=str(tmp_path),
            n_cpus=64,
            node_allocatable_milli=64_000,
            node_memory_capacity_mib=1 << 18,
        )
    )
    agent.update_node_slo(slo)
    # prod usage 30C, BE 8C: override budget 40% x 64C = 25.6C; leftover
    # 25.6 - 22 (non-BE) = 3.6C allowance
    from koordinator_tpu.koordlet import metriccache as mcache

    agent.metric_cache.append(mcache.NODE_CPU_USAGE, "node", 1000.0, 30_000.0)
    agent.metric_cache.append(mcache.BE_CPU_USAGE, "node", 1000.0, 8_000.0)
    agent.qos_tick(now=1001.0)
    quota = agent.executor.read("kubepods/besteffort", rex.CPU_CFS_QUOTA)
    assert quota is not None
    # allowance = 0.40*64000 - (30000-8000) = 3600m -> quota 360000us
    assert int(quota) == int(3600 / 1000 * 100_000)
