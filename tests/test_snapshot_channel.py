"""gRPC snapshot/delta channel tests: the control-plane↔solver contract
(SURVEY §2.8, §7 step 1) over a real loopback server."""

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
from koordinator_tpu.runtime.snapshot_channel import (
    SolverClient,
    SolverService,
    serve,
)


@pytest.fixture()
def channel():
    service = SolverService()
    server, port = serve(service)
    client = SolverClient(f"127.0.0.1:{port}")
    yield service, client
    client.close()
    server.stop(grace=None)


def cpu_mem_vec(cfg, cpu, mem):
    values = []
    for r in cfg.resources:
        if r == ext.RES_CPU:
            values.append(float(cpu))
        elif r == ext.RES_MEMORY:
            values.append(float(mem))
        else:
            values.append(0.0)
    return pb.ResourceVector(values=values)


def test_sync_applies_nodes_and_metrics(channel):
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(revision=7, now=1000.0)
    for i in range(4):
        delta.node_upserts.add(
            name=f"n{i}", allocatable=cpu_mem_vec(cfg, 32000, 128 * 1024)
        )
        delta.metric_updates.add(
            name=f"n{i}",
            usage=cpu_mem_vec(cfg, 3200, 12 * 1024),
            update_time=999.0,
        )
    ack = client.sync(delta)
    assert ack.applied_revision == 7
    assert ack.node_count == 4
    assert service.snapshot.node_count == 4


def test_nominate_round_trip(channel):
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    for i in range(8):
        delta.node_upserts.add(
            name=f"n{i}", allocatable=cpu_mem_vec(cfg, 64000, 256 * 1024)
        )
        delta.metric_updates.add(
            name=f"n{i}", usage=cpu_mem_vec(cfg, 6000, 24 * 1024), update_time=999.0
        )
    client.sync(delta)

    req = pb.NominateRequest()
    for i in range(32):
        req.pods.add(
            uid=f"pod-{i}",
            requests=cpu_mem_vec(cfg, 1000, 4096),
            priority=9000,
            is_prod=True,
        )
    resp = client.nominate(req)
    assert len(resp.nominations) == 32
    placed = [n for n in resp.nominations if n.node]
    assert len(placed) == 32
    # spread over several nodes, and every named node exists
    assert len({n.node for n in placed}) > 1
    assert all(n.node.startswith("n") for n in placed)
    assert resp.solve_ms > 0


def test_nominations_consume_capacity_across_calls(channel):
    """Nominate → control plane Reserves (pod_assumed delta) → next
    Nominate sees the reduced capacity: the feedback loop of §3.3."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 64 * 1024))
    delta.metric_updates.add(
        name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0
    )
    client.sync(delta)

    req = pb.NominateRequest()
    req.pods.add(uid="big-1", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    resp = client.nominate(req)
    assert resp.nominations[0].node == "only"

    # control plane commits the assumption back over the channel
    commit = pb.SnapshotDelta(now=1001.0)
    commit.pod_assumed.add(
        uid="big-1", node="only", requests=cpu_mem_vec(cfg, 6000, 1024)
    )
    client.sync(commit)

    req2 = pb.NominateRequest()
    req2.pods.add(uid="big-2", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    resp2 = client.nominate(req2)
    assert resp2.nominations[0].node == ""  # no longer fits

    # forget releases it again
    release = pb.SnapshotDelta(now=1002.0)
    release.pod_forgotten.append("big-1")
    client.sync(release)
    resp3 = client.nominate(req2)
    assert resp3.nominations[0].node == "only"


def test_node_remove_over_channel(channel):
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="gone", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    client.sync(delta)
    assert service.snapshot.node_count == 1
    rm = pb.SnapshotDelta(now=1001.0)
    rm.node_removes.append("gone")
    ack = client.sync(rm)
    assert ack.node_count == 0


def test_get_config_exposes_dimension_order(channel):
    service, client = channel
    cfg = client.get_config()
    assert list(cfg.resources) == list(service.snapshot.config.resources)
    assert len(cfg.usage_thresholds.values) == len(cfg.resources)
    # prod thresholds travel too — both sides of the channel must agree on
    # the prod-usage gate, not just the total-usage one
    assert len(cfg.prod_thresholds.values) == len(cfg.resources)


def test_get_config_round_trips_prod_thresholds():
    from koordinator_tpu.scheduler.batch_solver import LoadAwareArgs

    service = SolverService(
        args=LoadAwareArgs(prod_usage_thresholds={ext.RES_CPU: 65.0})
    )
    server, port = serve(service)
    client = SolverClient(f"127.0.0.1:{port}")
    try:
        cfg = client.get_config()
        cpu_i = list(cfg.resources).index(ext.RES_CPU)
        assert cfg.prod_thresholds.values[cpu_i] == 65.0
    finally:
        client.close()
        server.stop(grace=None)


def test_assume_on_unknown_node_is_skipped_not_fatal(channel):
    """A pod_assumed racing a node delete (same delta or out-of-order
    deltas) must not wedge the channel: the entry is skipped, counted in
    the ack, and the rest of the delta still applies."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="a", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.node_removes.append("ghost")
    delta.pod_assumed.add(
        uid="p-on-ghost", node="ghost", requests=cpu_mem_vec(cfg, 1000, 1024)
    )
    delta.pod_assumed.add(
        uid="p-on-a", node="a", requests=cpu_mem_vec(cfg, 1000, 1024)
    )
    ack = client.sync(delta)
    assert ack.assumes_skipped == 1
    assert ack.node_count == 1
    idx = service.snapshot.node_id("a")
    cpu_i = list(cfg.resources).index(ext.RES_CPU)
    assert service.snapshot.nodes.requested[idx][cpu_i] == 1000.0
    # retrying the same delta stays idempotent and keeps succeeding
    ack2 = client.sync(delta)
    assert ack2.assumes_skipped == 1


def test_nominate_honors_estimated_field(channel):
    """PendingPod.estimated overrides the estimator's request scaling: an
    overcommitted batch pod with a small measured estimate must pack more
    densely than its raw requests would allow (usage thresholds gate on the
    estimate, reference estimator framework)."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(
        name="n0", usage=cpu_mem_vec(cfg, 5800, 0), update_time=999.0
    )
    client.sync(delta)
    # node at 58% cpu; threshold 65% leaves 700m of estimate headroom.
    # raw request 2000m (scaled est 1700m) would breach; explicit
    # estimated 500m fits.
    req = pb.NominateRequest()
    req.pods.add(
        uid="measured",
        requests=cpu_mem_vec(cfg, 2000, 1024),
        estimated=cpu_mem_vec(cfg, 500, 512),
        priority=9000,
    )
    resp = client.nominate(req)
    assert resp.nominations[0].node == "n0"
    req2 = pb.NominateRequest()
    req2.pods.add(
        uid="unmeasured", requests=cpu_mem_vec(cfg, 2000, 1024), priority=9000
    )
    resp2 = client.nominate(req2)
    assert resp2.nominations[0].node == ""


def test_reassume_of_absorbed_pod_stays_absorbed(channel):
    """A metric report absorbs the pod's pending estimate; a later commit
    for the same uid must not re-add it (double count)."""
    service, client = channel
    cfg = service.snapshot.config
    snap = service.snapshot
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.metric_updates.add(name="n0", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    delta.pod_assumed.add(uid="p1", node="n0", requests=cpu_mem_vec(cfg, 4000, 8192))
    client.sync(delta)
    idx = snap.node_id("n0")
    pend0 = snap.nodes.assigned_pending[idx].copy()
    assert pend0.sum() > 0

    # fresh metric AFTER the assume time absorbs the pending estimate
    absorb = pb.SnapshotDelta(now=1100.0)
    absorb.metric_updates.add(
        name="n0", usage=cpu_mem_vec(cfg, 4000, 8192), update_time=1050.0
    )
    client.sync(absorb)
    assert snap.nodes.assigned_pending[idx].sum() == 0

    # idempotent recommit: still absorbed, pending must stay zero
    recommit = pb.SnapshotDelta(now=1101.0)
    recommit.pod_assumed.add(uid="p1", node="n0", requests=cpu_mem_vec(cfg, 4000, 8192))
    client.sync(recommit)
    assert snap.nodes.assigned_pending[idx].sum() == 0
    # requested stays single-counted
    req_cpu = snap.nodes.requested[idx][list(cfg.resources).index(ext.RES_CPU)]
    assert req_cpu == 4000.0


def test_pod_assumed_priority_charges_prod_pending(channel):
    """A committed PROD pod must raise the prod pending charge so the
    prod_usage_thresholds gate sees it before the next NodeMetric report
    (assigned_pending_prod accounting, reference pod_assign_cache)."""
    service, client = channel
    cfg = service.snapshot.config
    snap = service.snapshot
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.metric_updates.add(name="n0", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    delta.pod_assumed.add(
        uid="prod-p",
        node="n0",
        requests=cpu_mem_vec(cfg, 4000, 8192),
        priority=9500,
    )
    delta.pod_assumed.add(
        uid="batch-p",
        node="n0",
        requests=cpu_mem_vec(cfg, 4000, 8192),
        priority=5500,
    )
    client.sync(delta)
    idx = snap.node_id("n0")
    assert snap.nodes.assigned_pending_prod[idx].sum() > 0
    # only the prod pod is charged to the prod tier
    assert (
        snap.nodes.assigned_pending_prod[idx].sum()
        < snap.nodes.assigned_pending[idx].sum()
    )


def test_unconfirmed_nomination_expires(channel):
    """A nominate-side optimistic assume the control plane never confirms
    must expire after assume_ttl (kube-scheduler assumed-pod expiration) —
    a rejected-then-deleted nomination cannot leak capacity forever."""
    import time as _t

    service, client = channel
    service.assume_ttl = 0.05
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    client.sync(delta)

    req = pb.NominateRequest()
    req.pods.add(uid="big-1", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req).nominations[0].node == "only"

    # immediately: optimistic charge still present, a second big pod is out
    req2 = pb.NominateRequest()
    req2.pods.add(uid="big-2", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req2).nominations[0].node == ""

    # after ttl with no pod_assumed confirmation the charge evaporates
    _t.sleep(0.06)
    assert client.nominate(req2).nominations[0].node == "only"


def test_confirmed_assume_never_expires(channel):
    import time as _t

    service, client = channel
    service.assume_ttl = 0.05
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    # confirmed via Sync (the control plane reserved it)
    delta.pod_assumed.add(
        uid="held", node="only", requests=cpu_mem_vec(cfg, 6000, 1024)
    )
    client.sync(delta)
    _t.sleep(0.06)
    req = pb.NominateRequest()
    req.pods.add(uid="big", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req).nominations[0].node == ""


def test_concurrent_sync_and_nominate_consistency():
    """The sidecar's lock must keep interleaved Sync/Nominate consistent:
    hammer both from threads, then verify the final snapshot accounting
    equals the serial expectation (no torn deltas, no lost assumes)."""
    import threading

    service = SolverService()
    server, port = serve(service, max_workers=8)
    client = SolverClient(f"127.0.0.1:{port}")
    try:
        cfg = service.snapshot.config
        base = pb.SnapshotDelta(now=1000.0)
        for i in range(16):
            base.node_upserts.add(
                name=f"n{i}", allocatable=cpu_mem_vec(cfg, 64000, 1 << 18)
            )
            base.metric_updates.add(
                name=f"n{i}", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0
            )
        client.sync(base)

        errors = []

        def syncer(tid):
            try:
                for k in range(20):
                    d = pb.SnapshotDelta(now=1001.0 + k)
                    d.pod_assumed.add(
                        uid=f"t{tid}-p{k}",
                        node=f"n{(tid * 7 + k) % 16}",
                        requests=cpu_mem_vec(cfg, 100, 64),
                    )
                    client.sync(d)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def nominator():
            try:
                for k in range(5):
                    req = pb.NominateRequest()
                    req.pods.add(
                        uid=f"nom-{k}",
                        requests=cpu_mem_vec(cfg, 500, 256),
                        priority=9000,
                    )
                    client.nominate(req)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=syncer, args=(t,)) for t in range(4)]
        threads.append(threading.Thread(target=nominator))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        # forget the nominate-side optimistic assumes so only synced pods
        # remain, then check exact accounting: 4 threads x 20 pods x 100m
        service.snapshot.expire_assumed(now=float("inf"), ttl=0.0)
        na = service.snapshot.nodes
        cpu_i = list(cfg.resources).index(ext.RES_CPU)
        total_cpu = sum(
            na.requested[service.snapshot.node_id(f"n{i}")][cpu_i]
            for i in range(16)
        )
        assert total_cpu == 4 * 20 * 100
    finally:
        client.close()
        server.stop(grace=None)
