"""gRPC snapshot/delta channel tests: the control-plane↔solver contract
(SURVEY §2.8, §7 step 1) over a real loopback server."""

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
from koordinator_tpu.runtime.snapshot_channel import (
    SolverClient,
    SolverService,
    serve,
)


@pytest.fixture()
def channel():
    service = SolverService()
    server, port = serve(service)
    client = SolverClient(f"127.0.0.1:{port}")
    yield service, client
    client.close()
    server.stop(grace=None)


def cpu_mem_vec(cfg, cpu, mem):
    values = []
    for r in cfg.resources:
        if r == ext.RES_CPU:
            values.append(float(cpu))
        elif r == ext.RES_MEMORY:
            values.append(float(mem))
        else:
            values.append(0.0)
    return pb.ResourceVector(values=values)


def test_sync_applies_nodes_and_metrics(channel):
    service, client = channel
    cfg = service.snapshot.config
    # first contact at a mid-stream revision must be a full re-list (a
    # fresh solver cannot adopt one incremental delta as its world)
    delta = pb.SnapshotDelta(revision=7, now=1000.0, full=True)
    for i in range(4):
        delta.node_upserts.add(
            name=f"n{i}", allocatable=cpu_mem_vec(cfg, 32000, 128 * 1024)
        )
        delta.metric_updates.add(
            name=f"n{i}",
            usage=cpu_mem_vec(cfg, 3200, 12 * 1024),
            update_time=999.0,
        )
    ack = client.sync(delta)
    assert ack.applied_revision == 7
    assert ack.node_count == 4
    assert service.snapshot.node_count == 4


def test_nominate_round_trip(channel):
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    for i in range(8):
        delta.node_upserts.add(
            name=f"n{i}", allocatable=cpu_mem_vec(cfg, 64000, 256 * 1024)
        )
        delta.metric_updates.add(
            name=f"n{i}", usage=cpu_mem_vec(cfg, 6000, 24 * 1024), update_time=999.0
        )
    client.sync(delta)

    req = pb.NominateRequest()
    for i in range(32):
        req.pods.add(
            uid=f"pod-{i}",
            requests=cpu_mem_vec(cfg, 1000, 4096),
            priority=9000,
            is_prod=True,
        )
    resp = client.nominate(req)
    assert len(resp.nominations) == 32
    placed = [n for n in resp.nominations if n.node]
    assert len(placed) == 32
    # spread over several nodes, and every named node exists
    assert len({n.node for n in placed}) > 1
    assert all(n.node.startswith("n") for n in placed)
    assert resp.solve_ms > 0


def test_nominations_consume_capacity_across_calls(channel):
    """Nominate → control plane Reserves (pod_assumed delta) → next
    Nominate sees the reduced capacity: the feedback loop of §3.3."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 64 * 1024))
    delta.metric_updates.add(
        name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0
    )
    client.sync(delta)

    req = pb.NominateRequest()
    req.pods.add(uid="big-1", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    resp = client.nominate(req)
    assert resp.nominations[0].node == "only"

    # control plane commits the assumption back over the channel
    commit = pb.SnapshotDelta(now=1001.0)
    commit.pod_assumed.add(
        uid="big-1", node="only", requests=cpu_mem_vec(cfg, 6000, 1024)
    )
    client.sync(commit)

    req2 = pb.NominateRequest()
    req2.pods.add(uid="big-2", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    resp2 = client.nominate(req2)
    assert resp2.nominations[0].node == ""  # no longer fits

    # forget releases it again
    release = pb.SnapshotDelta(now=1002.0)
    release.pod_forgotten.append("big-1")
    client.sync(release)
    resp3 = client.nominate(req2)
    assert resp3.nominations[0].node == "only"


def test_node_remove_over_channel(channel):
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="gone", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    client.sync(delta)
    assert service.snapshot.node_count == 1
    rm = pb.SnapshotDelta(now=1001.0)
    rm.node_removes.append("gone")
    ack = client.sync(rm)
    assert ack.node_count == 0


def test_get_config_exposes_dimension_order(channel):
    service, client = channel
    cfg = client.get_config()
    assert list(cfg.resources) == list(service.snapshot.config.resources)
    assert len(cfg.usage_thresholds.values) == len(cfg.resources)
    # prod thresholds travel too — both sides of the channel must agree on
    # the prod-usage gate, not just the total-usage one
    assert len(cfg.prod_thresholds.values) == len(cfg.resources)


def test_get_config_round_trips_prod_thresholds():
    from koordinator_tpu.scheduler.batch_solver import LoadAwareArgs

    service = SolverService(
        args=LoadAwareArgs(prod_usage_thresholds={ext.RES_CPU: 65.0})
    )
    server, port = serve(service)
    client = SolverClient(f"127.0.0.1:{port}")
    try:
        cfg = client.get_config()
        cpu_i = list(cfg.resources).index(ext.RES_CPU)
        assert cfg.prod_thresholds.values[cpu_i] == 65.0
    finally:
        client.close()
        server.stop(grace=None)


def test_assume_on_unknown_node_is_skipped_not_fatal(channel):
    """A pod_assumed racing a node delete (same delta or out-of-order
    deltas) must not wedge the channel: the entry is skipped, counted in
    the ack, and the rest of the delta still applies."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="a", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.node_removes.append("ghost")
    delta.pod_assumed.add(
        uid="p-on-ghost", node="ghost", requests=cpu_mem_vec(cfg, 1000, 1024)
    )
    delta.pod_assumed.add(
        uid="p-on-a", node="a", requests=cpu_mem_vec(cfg, 1000, 1024)
    )
    ack = client.sync(delta)
    assert ack.assumes_skipped == 1
    assert ack.node_count == 1
    idx = service.snapshot.node_id("a")
    cpu_i = list(cfg.resources).index(ext.RES_CPU)
    assert service.snapshot.nodes.requested[idx][cpu_i] == 1000.0
    # retrying the same delta stays idempotent and keeps succeeding
    ack2 = client.sync(delta)
    assert ack2.assumes_skipped == 1


def test_nominate_honors_estimated_field(channel):
    """PendingPod.estimated overrides the estimator's request scaling: an
    overcommitted batch pod with a small measured estimate must pack more
    densely than its raw requests would allow (usage thresholds gate on the
    estimate, reference estimator framework)."""
    service, client = channel
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(
        name="n0", usage=cpu_mem_vec(cfg, 5800, 0), update_time=999.0
    )
    client.sync(delta)
    # node at 58% cpu; threshold 65% leaves 700m of estimate headroom.
    # raw request 2000m (scaled est 1700m) would breach; explicit
    # estimated 500m fits.
    req = pb.NominateRequest()
    req.pods.add(
        uid="measured",
        requests=cpu_mem_vec(cfg, 2000, 1024),
        estimated=cpu_mem_vec(cfg, 500, 512),
        priority=9000,
    )
    resp = client.nominate(req)
    assert resp.nominations[0].node == "n0"
    req2 = pb.NominateRequest()
    req2.pods.add(
        uid="unmeasured", requests=cpu_mem_vec(cfg, 2000, 1024), priority=9000
    )
    resp2 = client.nominate(req2)
    assert resp2.nominations[0].node == ""


def test_reassume_of_absorbed_pod_stays_absorbed(channel):
    """A metric report absorbs the pod's pending estimate; a later commit
    for the same uid must not re-add it (double count)."""
    service, client = channel
    cfg = service.snapshot.config
    snap = service.snapshot
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.metric_updates.add(name="n0", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    delta.pod_assumed.add(uid="p1", node="n0", requests=cpu_mem_vec(cfg, 4000, 8192))
    client.sync(delta)
    idx = snap.node_id("n0")
    pend0 = snap.nodes.assigned_pending[idx].copy()
    assert pend0.sum() > 0

    # fresh metric AFTER the assume time absorbs the pending estimate
    absorb = pb.SnapshotDelta(now=1100.0)
    absorb.metric_updates.add(
        name="n0", usage=cpu_mem_vec(cfg, 4000, 8192), update_time=1050.0
    )
    client.sync(absorb)
    assert snap.nodes.assigned_pending[idx].sum() == 0

    # idempotent recommit: still absorbed, pending must stay zero
    recommit = pb.SnapshotDelta(now=1101.0)
    recommit.pod_assumed.add(uid="p1", node="n0", requests=cpu_mem_vec(cfg, 4000, 8192))
    client.sync(recommit)
    assert snap.nodes.assigned_pending[idx].sum() == 0
    # requested stays single-counted
    req_cpu = snap.nodes.requested[idx][list(cfg.resources).index(ext.RES_CPU)]
    assert req_cpu == 4000.0


def test_pod_assumed_priority_charges_prod_pending(channel):
    """A committed PROD pod must raise the prod pending charge so the
    prod_usage_thresholds gate sees it before the next NodeMetric report
    (assigned_pending_prod accounting, reference pod_assign_cache)."""
    service, client = channel
    cfg = service.snapshot.config
    snap = service.snapshot
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="n0", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17))
    delta.metric_updates.add(name="n0", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    delta.pod_assumed.add(
        uid="prod-p",
        node="n0",
        requests=cpu_mem_vec(cfg, 4000, 8192),
        priority=9500,
    )
    delta.pod_assumed.add(
        uid="batch-p",
        node="n0",
        requests=cpu_mem_vec(cfg, 4000, 8192),
        priority=5500,
    )
    client.sync(delta)
    idx = snap.node_id("n0")
    assert snap.nodes.assigned_pending_prod[idx].sum() > 0
    # only the prod pod is charged to the prod tier
    assert (
        snap.nodes.assigned_pending_prod[idx].sum()
        < snap.nodes.assigned_pending[idx].sum()
    )


def test_unconfirmed_nomination_expires(channel):
    """A nominate-side optimistic assume the control plane never confirms
    must expire after assume_ttl (kube-scheduler assumed-pod expiration) —
    a rejected-then-deleted nomination cannot leak capacity forever."""
    import time as _t

    service, client = channel
    service.assume_ttl = 0.05
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    client.sync(delta)

    req = pb.NominateRequest()
    req.pods.add(uid="big-1", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req).nominations[0].node == "only"

    # immediately: optimistic charge still present, a second big pod is out
    req2 = pb.NominateRequest()
    req2.pods.add(uid="big-2", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req2).nominations[0].node == ""

    # after ttl with no pod_assumed confirmation the charge evaporates
    _t.sleep(0.06)
    assert client.nominate(req2).nominations[0].node == "only"


def test_confirmed_assume_never_expires(channel):
    import time as _t

    service, client = channel
    service.assume_ttl = 0.05
    cfg = service.snapshot.config
    delta = pb.SnapshotDelta(now=1000.0)
    delta.node_upserts.add(name="only", allocatable=cpu_mem_vec(cfg, 10000, 1 << 16))
    delta.metric_updates.add(name="only", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0)
    # confirmed via Sync (the control plane reserved it)
    delta.pod_assumed.add(
        uid="held", node="only", requests=cpu_mem_vec(cfg, 6000, 1024)
    )
    client.sync(delta)
    _t.sleep(0.06)
    req = pb.NominateRequest()
    req.pods.add(uid="big", requests=cpu_mem_vec(cfg, 6000, 1024), priority=9000)
    assert client.nominate(req).nominations[0].node == ""


def test_concurrent_sync_and_nominate_consistency():
    """The sidecar's lock must keep interleaved Sync/Nominate consistent:
    hammer both from threads, then verify the final snapshot accounting
    equals the serial expectation (no torn deltas, no lost assumes)."""
    import threading

    service = SolverService()
    server, port = serve(service, max_workers=8)
    client = SolverClient(f"127.0.0.1:{port}")
    try:
        cfg = service.snapshot.config
        base = pb.SnapshotDelta(now=1000.0)
        for i in range(16):
            base.node_upserts.add(
                name=f"n{i}", allocatable=cpu_mem_vec(cfg, 64000, 1 << 18)
            )
            base.metric_updates.add(
                name=f"n{i}", usage=cpu_mem_vec(cfg, 0, 0), update_time=999.0
            )
        client.sync(base)

        errors = []

        def syncer(tid):
            try:
                for k in range(20):
                    d = pb.SnapshotDelta(now=1001.0 + k)
                    d.pod_assumed.add(
                        uid=f"t{tid}-p{k}",
                        node=f"n{(tid * 7 + k) % 16}",
                        requests=cpu_mem_vec(cfg, 100, 64),
                    )
                    client.sync(d)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def nominator():
            try:
                for k in range(5):
                    req = pb.NominateRequest()
                    req.pods.add(
                        uid=f"nom-{k}",
                        requests=cpu_mem_vec(cfg, 500, 256),
                        priority=9000,
                    )
                    client.nominate(req)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=syncer, args=(t,)) for t in range(4)]
        threads.append(threading.Thread(target=nominator))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        # forget the nominate-side optimistic assumes so only synced pods
        # remain, then check exact accounting: 4 threads x 20 pods x 100m
        service.snapshot.expire_assumed(now=float("inf"), ttl=0.0)
        na = service.snapshot.nodes
        cpu_i = list(cfg.resources).index(ext.RES_CPU)
        total_cpu = sum(
            na.requested[service.snapshot.node_id(f"n{i}")][cpu_i]
            for i in range(16)
        )
        assert total_cpu == 4 * 20 * 100
    finally:
        client.close()
        server.stop(grace=None)


# ---- resync protocol: generation gaps force a full re-list ----


def _world_deltas(cfg, n_nodes=4):
    """An ordered sequence of deltas building a world state, plus a
    function rendering the CURRENT full state (what the control plane's
    cache would re-list)."""
    deltas = []
    d1 = pb.SnapshotDelta(revision=1, now=1000.0)
    for i in range(n_nodes):
        d1.node_upserts.add(
            name=f"n{i}", allocatable=cpu_mem_vec(cfg, 32000, 128 * 1024)
        )
    deltas.append(d1)
    d2 = pb.SnapshotDelta(revision=2, now=1001.0)
    d2.node_removes.append("n0")
    d2.pod_assumed.add(
        uid="p-a", node="n1", requests=cpu_mem_vec(cfg, 4000, 4096)
    )
    deltas.append(d2)
    d3 = pb.SnapshotDelta(revision=3, now=1002.0)
    d3.metric_updates.add(
        name="n1", usage=cpu_mem_vec(cfg, 8000, 9000), update_time=1002.0
    )
    deltas.append(d3)

    def full_state():
        full = pb.SnapshotDelta(now=1002.0)
        for i in range(1, n_nodes):
            full.node_upserts.add(
                name=f"n{i}", allocatable=cpu_mem_vec(cfg, 32000, 128 * 1024)
            )
        full.pod_assumed.add(
            uid="p-a", node="n1", requests=cpu_mem_vec(cfg, 4000, 4096)
        )
        full.metric_updates.add(
            name="n1", usage=cpu_mem_vec(cfg, 8000, 9000), update_time=1002.0
        )
        return full

    return deltas, full_state


def test_dropped_delta_triggers_resync_and_converges(channel):
    """Drop delta 2 entirely: delta 3 must be REJECTED (not applied), and
    the full re-list converges the solver to the true world state."""
    service, client = channel
    cfg = service.snapshot.config
    deltas, full_state = _world_deltas(cfg)
    client.sync(deltas[0])
    # delta 2 lost in transit; delta 3 arrives
    ack = client.sync(deltas[2])
    assert ack.resync_required and ack.expected_revision == 2
    # the rejected delta changed nothing: n0 still present, no metric
    assert service.snapshot.node_count == 4
    # control plane answers with a full re-list
    ack2 = client.sync_with_resync(deltas[2], full_state)
    assert not ack2.resync_required
    assert ack2.applied_revision == 3
    snap = service.snapshot
    assert snap.node_count == 3 and snap.node_id("n0") is None
    idx = snap.node_id("n1")
    assert snap.nodes.requested[idx][0] == 4000.0
    assert snap.nodes.usage_avg[idx][0] == 8000.0


def test_reordered_delta_rejected(channel):
    """Deltas arriving out of order must not be applied out of order."""
    service, client = channel
    cfg = service.snapshot.config
    deltas, full_state = _world_deltas(cfg)
    client.sync(deltas[0])
    ack3 = client.sync(deltas[2])          # rev 3 before rev 2
    assert ack3.resync_required
    ack2 = client.sync(deltas[1])          # rev 2 arrives late: in order
    assert not ack2.resync_required and ack2.applied_revision == 2
    # rev 3 can now apply normally
    ack3b = client.sync(deltas[2])
    assert not ack3b.resync_required and ack3b.applied_revision == 3
    snap = service.snapshot
    assert snap.node_count == 3
    assert snap.nodes.usage_avg[snap.node_id("n1")][0] == 8000.0


def test_fresh_solver_rejects_midstream_delta(channel):
    """A restarted solver (revision 0) receiving an incremental delta at a
    mid-stream revision must demand a resync — silently adopting it as the
    whole world is the divergence this protocol exists to prevent."""
    service, client = channel
    cfg = service.snapshot.config
    mid = pb.SnapshotDelta(revision=1001, now=1000.0)
    mid.metric_updates.add(
        name="n1", usage=cpu_mem_vec(cfg, 1000, 1000), update_time=1000.0
    )
    ack = client.sync(mid)
    assert ack.resync_required
    assert service.snapshot.node_count == 0  # nothing was applied
    # a stream head (revision 1) is fine for a fresh solver
    head = pb.SnapshotDelta(revision=1, now=1000.0)
    head.node_upserts.add(name="n1", allocatable=cpu_mem_vec(cfg, 32000, 1024))
    assert not client.sync(head).resync_required


def test_full_resync_replaces_divergent_state(channel):
    """A full delta replaces whatever the solver believed — stale nodes
    and assumed pods vanish."""
    service, client = channel
    cfg = service.snapshot.config
    deltas, full_state = _world_deltas(cfg)
    for d in deltas[:2]:
        client.sync(d)
    # solver believes p-a is assumed on n1; control plane re-lists a world
    # where only n9 exists
    full = pb.SnapshotDelta(revision=9, full=True, now=2000.0)
    full.node_upserts.add(
        name="n9", allocatable=cpu_mem_vec(cfg, 64000, 256 * 1024)
    )
    ack = client.sync(full)
    assert not ack.resync_required
    assert ack.applied_revision == 9 and ack.node_count == 1
    snap = service.snapshot
    assert snap.node_id("n1") is None and snap.node_id("n9") is not None
    assert not snap._assumed
