"""Inotify PLEG (VERDICT r4 #5, reference
``pkg/koordlet/pleg/watcher_linux.go:25-30``): kernel-latency lifecycle
events via ctypes inotify, with the polling diff as resync/fallback."""

import os
import threading
import time

import pytest

from koordinator_tpu.koordlet.pleg import (
    Event,
    EventType,
    InotifyPleg,
    Pleg,
    TIER_DIRS,
)


def _mk_root(tmp_path):
    for tier in TIER_DIRS:
        os.makedirs(tmp_path / tier, exist_ok=True)
    return str(tmp_path)


@pytest.fixture
def watcher(tmp_path):
    p = InotifyPleg(_mk_root(tmp_path))
    started = p.start()
    if not started:
        pytest.skip("inotify unavailable on this platform")
    yield p, tmp_path
    p.stop()


def _wait_for(events, pred, timeout=2.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if any(pred(e) for e in list(events)):
            return True
        time.sleep(0.01)
    return False


def test_event_latency_is_sub_interval(watcher):
    """A pod cgroup dir appearing is reported well under any polling
    interval — the inotify thread fires without a tick."""
    p, tmp = watcher
    events = []
    lock = threading.Lock()

    def handler(e: Event):
        with lock:
            events.append((e, time.time()))

    p.register_handler(handler)
    t0 = time.time()
    os.makedirs(tmp / "kubepods" / "podabc")
    assert _wait_for(
        events, lambda et: et[0].type == EventType.POD_ADDED
    ), events
    _e, t_seen = next(
        et for et in events if et[0].type == EventType.POD_ADDED
    )
    # sub-interval: a 1 s poller would average 500 ms; inotify lands in
    # tens of milliseconds even on a loaded host
    assert t_seen - t0 < 0.5, f"event latency {t_seen - t0:.3f}s"


def test_container_and_delete_events(watcher):
    p, tmp = watcher
    events = []
    p.register_handler(lambda e: events.append(e))
    os.makedirs(tmp / "kubepods" / "podx")
    assert _wait_for(events, lambda e: e.type == EventType.POD_ADDED)
    os.makedirs(tmp / "kubepods" / "podx" / "c1")
    assert _wait_for(
        events,
        lambda e: e.type == EventType.CONTAINER_ADDED and e.container_id == "c1",
    ), events
    os.rmdir(tmp / "kubepods" / "podx" / "c1")
    assert _wait_for(
        events,
        lambda e: e.type == EventType.CONTAINER_DELETED
        and e.container_id == "c1",
    ), events
    os.rmdir(tmp / "kubepods" / "podx")
    assert _wait_for(events, lambda e: e.type == EventType.POD_DELETED), events


def test_polling_resync_coexists(watcher):
    """tick() remains a safe resync: after inotify has consumed events,
    a tick fires nothing new; state stays consistent."""
    p, tmp = watcher
    events = []
    p.register_handler(lambda e: events.append(e))
    os.makedirs(tmp / "kubepods" / "burstable" / "podr")
    assert _wait_for(events, lambda e: e.type == EventType.POD_ADDED)
    n_before = len(events)
    assert p.tick() == []
    assert len(events) == n_before


def test_polling_fallback_still_works(tmp_path):
    """The base Pleg (and an InotifyPleg that was never started) keeps
    the documented tick semantics."""
    root = _mk_root(tmp_path)
    p = Pleg(root)
    assert p.tick() == []
    os.makedirs(tmp_path / "kubepods" / "podz" / "c9")
    evs = p.tick()
    assert [e.type for e in evs] == [
        EventType.POD_ADDED,
        EventType.CONTAINER_ADDED,
    ]
