"""QoS-manager strategy edge behavior (reference
``pkg/koordlet/qosmanager/plugins/``): suppression floors/clamps,
eviction ordering and watermark math, satisfaction-gap eviction, the
burst token bucket, and the resctrl schemata renderer."""

import pytest

from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.api.types import ResctrlStrategy
from koordinator_tpu.koordlet.qosmanager import (
    BurstLimiter,
    _llc_mask,
    cpu_evict,
    cpu_suppress,
    memory_evict,
    resctrl_schemata_plan,
)

# ---- cpu_suppress (calculateBESuppressCPU, cpu_suppress.go:136-170) ----


def test_cpu_suppress_formula_and_flag():
    # budget 65% of 16 cores = 10400m; non-BE used = 8000m → 2400m for BE
    d = cpu_suppress(
        node_allocatable_milli=16000.0,
        node_used_milli=10000.0,
        be_used_milli=2000.0,
        threshold_percent=65.0,
    )
    assert d.be_allowance_milli == pytest.approx(2400.0)
    assert d.be_cpuset_cpus == 3        # ceil(2.4)
    assert d.suppressed


def test_cpu_suppress_reserved_floor_applies():
    """max(system.Used, node.reserved): the larger of the two is
    subtracted, never both."""
    lo = cpu_suppress(
        node_allocatable_milli=16000.0,
        node_used_milli=6000.0,
        be_used_milli=2000.0,
        threshold_percent=65.0,
        sys_used_milli=1000.0,
        node_reserved_milli=3000.0,
    )
    # pod(non-BE) = 6000-2000-1000 = 3000; minus max(1000, 3000)=3000
    assert lo.be_allowance_milli == pytest.approx(10400.0 - 3000.0 - 3000.0)


def test_cpu_suppress_floors_never_negative():
    d = cpu_suppress(
        node_allocatable_milli=16000.0,
        node_used_milli=20000.0,
        be_used_milli=100.0,
        threshold_percent=65.0,
        min_be_cpus=2,
    )
    assert d.be_allowance_milli == 2000.0    # whole-cpu legacy floor
    pct = cpu_suppress(
        node_allocatable_milli=16000.0,
        node_used_milli=20000.0,
        be_used_milli=100.0,
        threshold_percent=65.0,
        min_threshold_percent=10.0,
    )
    assert pct.be_allowance_milli == pytest.approx(1600.0)  # percent floor


# ---- memory_evict (memory_evict.go watermark math) ----


def test_memory_evict_lowest_priority_largest_first_until_lower_watermark():
    pods = [
        ("be-big", 4000.0, 5000),
        ("be-small", 1000.0, 5000),
        ("be-mid", 2000.0, 5500),
        ("prodish", 2000.0, 9000),
    ]
    d = memory_evict(
        node_memory_used_mib=15000.0,
        node_memory_capacity_mib=16000.0,
        threshold_percent=70.0,
        lower_percent=60.0,
        be_pods=pods,
    )
    assert d.evict
    # same priority: larger usage evicts first
    assert d.victims[0] == "be-big"
    freed = sum(m for n, m, _p in pods if n in d.victims)
    assert 15000.0 - freed <= 16000.0 * 0.60 + 1e-6
    # it stops as soon as the lower watermark is reached
    assert "prodish" not in d.victims[:1]


def test_memory_evict_default_lower_is_threshold_minus_two():
    d = memory_evict(
        node_memory_used_mib=11250.0,     # 70.3%
        node_memory_capacity_mib=16000.0,
        threshold_percent=70.0,
        lower_percent=None,               # defaults to 68%
        be_pods=[("be", 500.0, 5000)],
    )
    assert d.evict
    assert 11250.0 - 500.0 <= 16000.0 * 0.68


def test_memory_evict_under_threshold_noop():
    d = memory_evict(
        node_memory_used_mib=10000.0,
        node_memory_capacity_mib=16000.0,
        threshold_percent=70.0,
        lower_percent=60.0,
        be_pods=[("be", 1000.0, 5000)],
    )
    assert not d.evict and not d.victims


# ---- cpu_evict (cpu_evict.go:262-282 release sizing) ----


def test_cpu_evict_release_targets_upper_watermark():
    """release = request × (upper − satisfaction), truncated like the
    reference's int64 cast; victims accumulate lowest-priority first
    until the release amount is covered."""
    pods = [("a", 2000.0, 5000), ("b", 2000.0, 5500), ("c", 2000.0, 6000)]
    d = cpu_evict(
        be_cpu_request_milli=10000.0,
        be_cpu_usage_milli=3800.0,
        be_cpu_limit_milli=4000.0,       # satisfaction 0.4
        satisfaction_threshold=0.6,
        usage_threshold_percent=90.0,    # usage 95% of limit → saturated
        be_pods=pods,
        satisfaction_upper_threshold=0.8,
    )
    assert d.evict
    # need 10000 × (0.8 − 0.4) = 4000m → two 2000m victims
    assert d.victims == ["a", "b"]


def test_cpu_evict_requires_both_conditions():
    base = dict(
        be_cpu_request_milli=10000.0,
        be_cpu_limit_milli=4000.0,
        satisfaction_threshold=0.6,
        usage_threshold_percent=90.0,
        be_pods=[("a", 2000.0, 5000)],
    )
    # usage saturates the limit but satisfaction is healthy → no evict
    # (usage 7900/8000 = 98.75% ≥ 90%, satisfaction 0.8 ≥ 0.6 — this
    # isolates the satisfaction clause)
    ok_sat = cpu_evict(
        be_cpu_usage_milli=7900.0, **{**base, "be_cpu_limit_milli": 8000.0}
    )
    assert not ok_sat.evict
    # poor satisfaction but BE barely using its limit → no evict
    idle = cpu_evict(be_cpu_usage_milli=1000.0, **base)
    assert not idle.evict


# ---- burst limiter token bucket (cpu_burst.go:112-163) ----


def test_burst_limiter_consumes_and_recovers():
    lim = BurstLimiter(
        burst_period_s=100.0, max_scale_percent=200.0, now=0.0, init_ratio=0.25
    )
    assert lim.capacity == 100 * 100
    ok0, t0 = lim.allow(now=1.0, usage_scale_percent=150.0)
    assert t0 == 2500 - 50                 # consumed (150-100)×1s
    # sustained overuse drains the bucket below zero → bursting blocked
    ok, tokens = lim.allow(now=60.0, usage_scale_percent=200.0)
    assert not ok and tokens <= 0
    # long quiet stretch refills (clamped at capacity)
    ok2, tokens2 = lim.allow(now=500.0, usage_scale_percent=10.0)
    assert ok2 and tokens2 == lim.capacity


def test_burst_limiter_midband_usage_neither_consumes_nor_saves():
    lim = BurstLimiter(
        burst_period_s=10.0, max_scale_percent=300.0, now=0.0, init_ratio=0.5
    )
    before = lim.tokens
    lim.allow(now=5.0, usage_scale_percent=80.0)   # 60 ≤ u < 100
    assert lim.tokens == before


def test_burst_limiter_reconfigure_resets_only_on_change():
    lim = BurstLimiter(
        burst_period_s=10.0, max_scale_percent=300.0, now=0.0, init_ratio=0.5
    )
    lim.allow(now=1.0, usage_scale_percent=150.0)
    drained = lim.tokens
    lim.update_if_changed(10.0, 300.0, now=2.0)    # unchanged → keep state
    assert lim.tokens == drained
    lim.update_if_changed(20.0, 300.0, now=3.0)    # changed → re-init
    assert lim.capacity == 20 * 200


# ---- resctrl schemata ----


def test_llc_mask_way_math():
    assert _llc_mask(100.0, 12) == format((1 << 12) - 1, "x")
    assert bin(int(_llc_mask(50.0, 12), 16)).count("1") == 6
    assert bin(int(_llc_mask(1.0, 12), 16)).count("1") == 1   # floor 1 way


def test_resctrl_schemata_tiers_and_domains():
    strat = ResctrlStrategy(
        enable=True,
        llc_percent={QoSClass.LSR: 100.0, QoSClass.LS: 60.0, QoSClass.BE: 20.0},
        mba_percent={QoSClass.LSR: 100.0, QoSClass.LS: 80.0, QoSClass.BE: 30.0},
    )
    plan = resctrl_schemata_plan(strat, cache_ways=10, n_l3_domains=2)
    by_tier = {g.split("/")[-1]: line for g, _f, line in plan}
    assert set(by_tier) == {"LSR", "LS", "BE"}

    def ways(tier):
        l3 = by_tier[tier].splitlines()[0]
        mask = l3.split("=")[-1]
        return bin(int(mask, 16)).count("1")

    assert ways("BE") <= ways("LS") <= ways("LSR")
    # every cache domain gets a mask + MB line
    l3_line, mb_line = by_tier["BE"].splitlines()
    assert l3_line.count("=") == 2 and mb_line.count("=") == 2
    assert "MB:" in mb_line and "30" in mb_line
