"""Snapshot-channel failure domain: typed errors, per-call deadlines,
retry-healed drops, and generation-gap full-resync under injected RPC
drops (robustness PR satellites — previously only the happy path of
``sync_with_resync`` was exercised)."""

import grpc
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
from koordinator_tpu.runtime.snapshot_channel import (
    ChannelCallError,
    ChannelError,
    ChannelTimeout,
    ChannelUnavailable,
    SolverClient,
    SolverService,
    _map_rpc_error,
    serve,
)
from koordinator_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.chaos


def cpu_mem_vec(cfg, cpu, mem):
    values = []
    for r in cfg.resources:
        if r == ext.RES_CPU:
            values.append(float(cpu))
        elif r == ext.RES_MEMORY:
            values.append(float(mem))
        else:
            values.append(0.0)
    return pb.ResourceVector(values=values)


@pytest.fixture()
def loopback():
    service = SolverService()
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service)
    yield service, port
    server.stop(grace=None)


class _FakeRpcError(grpc.RpcError):
    def __init__(self, code, details=""):
        self._code = code
        self._details = details

    def code(self):
        return self._code

    def details(self):
        return self._details


class TestTypedErrors:
    def test_status_codes_map_to_typed_errors(self):
        e = _map_rpc_error(
            "sync", _FakeRpcError(grpc.StatusCode.UNAVAILABLE, "conn reset")
        )
        assert isinstance(e, ChannelUnavailable)
        assert e.code == grpc.StatusCode.UNAVAILABLE
        e = _map_rpc_error(
            "sync", _FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)
        )
        assert isinstance(e, ChannelTimeout)
        e = _map_rpc_error(
            "nominate", _FakeRpcError(grpc.StatusCode.INTERNAL, "boom")
        )
        assert isinstance(e, ChannelCallError)
        assert isinstance(e, ChannelError)

    def test_unreachable_target_raises_typed_not_raw(self):
        # no server on this port; tight deadline turns it into a typed
        # error instead of a raw grpc.RpcError
        client = SolverClient("127.0.0.1:1", timeout_s=0.2)
        try:
            with pytest.raises(ChannelError) as ei:
                client.get_config()
            assert isinstance(
                ei.value, (ChannelUnavailable, ChannelTimeout)
            )
            assert not isinstance(ei.value, grpc.RpcError)
        finally:
            client.close()

    def test_per_call_deadline_times_out_hung_server(self, loopback):
        service, port = loopback
        # wedge the service lock so Sync can't answer
        service._lock.acquire()
        client = SolverClient(f"127.0.0.1:{port}", timeout_s=0.2)
        try:
            with pytest.raises(ChannelTimeout):
                client.sync(pb.SnapshotDelta(revision=1))
        finally:
            service._lock.release()
            client.close()


class TestInjectedDrops:
    def test_one_shot_drop_healed_by_retry(self, loopback):
        from koordinator_tpu.utils.metrics import Registry

        service, port = loopback
        cfg = service.snapshot.config
        reg = Registry()
        counter = reg.counter("retry_attempts_total", "", labels=("site",))
        chaos = FaultInjector()
        client = SolverClient(
            f"127.0.0.1:{port}",
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0),
            chaos=chaos,
            retry_counter=counter,
        )
        try:
            chaos.arm("channel.sync.drop", times=1)
            delta = pb.SnapshotDelta(revision=1)
            delta.node_upserts.add(
                name="n0", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17)
            )
            ack = client.sync(delta)
            assert ack.applied_revision == 1
            assert service.snapshot.node_count == 1
            assert counter.value(site="channel.sync") == 1.0
        finally:
            client.close()

    def test_persistent_drop_exhausts_retries(self, loopback):
        _service, port = loopback
        chaos = FaultInjector()
        client = SolverClient(
            f"127.0.0.1:{port}",
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0),
            chaos=chaos,
        )
        try:
            chaos.arm("channel.sync.drop")   # unlimited
            with pytest.raises(ChannelUnavailable):
                client.sync(pb.SnapshotDelta(revision=1))
            assert chaos.spec("channel.sync.drop").fired == 3
        finally:
            client.close()

    def test_injected_delay_applies_schedule(self, loopback):
        _service, port = loopback
        slept = []
        chaos = FaultInjector(sleep=slept.append)
        client = SolverClient(f"127.0.0.1:{port}", chaos=chaos)
        try:
            chaos.arm("channel.get_config.delay", latency_s=0.3, times=1)
            client.get_config()
            assert slept == [0.3]
        finally:
            client.close()


class TestGenerationGapUnderDrops:
    """The satellite: the full-resync protocol exercised by genuinely
    dropped RPCs (not just hand-built revision gaps)."""

    def _world(self, cfg):
        d1 = pb.SnapshotDelta(revision=1, now=1000.0)
        for i in range(3):
            d1.node_upserts.add(
                name=f"n{i}", allocatable=cpu_mem_vec(cfg, 32000, 1 << 17)
            )
        d2 = pb.SnapshotDelta(revision=2, now=1001.0)
        d2.pod_assumed.add(
            uid="p-a", node="n1", requests=cpu_mem_vec(cfg, 4000, 4096)
        )
        d3 = pb.SnapshotDelta(revision=3, now=1002.0)
        d3.pod_assumed.add(
            uid="p-b", node="n2", requests=cpu_mem_vec(cfg, 2000, 2048)
        )
        d3.pod_forgotten.append("p-a")

        def full_state():
            full = pb.SnapshotDelta(now=1002.0)
            for i in range(3):
                full.node_upserts.add(
                    name=f"n{i}",
                    allocatable=cpu_mem_vec(cfg, 32000, 1 << 17),
                )
            full.pod_assumed.add(
                uid="p-b", node="n2", requests=cpu_mem_vec(cfg, 2000, 2048)
            )
            return full

        return [d1, d2, d3], full_state

    def test_dropped_delta_forces_resync_and_converges(self, loopback):
        service, port = loopback
        cfg = service.snapshot.config
        chaos = FaultInjector()
        client = SolverClient(
            f"127.0.0.1:{port}",
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.001, jitter=0.0),
            chaos=chaos,
        )
        try:
            deltas, full_state = self._world(cfg)
            client.sync(deltas[0])
            # delta 2 dropped beyond the retry budget: genuinely lost
            chaos.arm("channel.sync.drop", times=2)
            with pytest.raises(ChannelUnavailable):
                client.sync(deltas[1])
            # delta 3 arrives: the solver detects the generation gap and
            # the client answers with the authoritative full re-list
            ack = client.sync_with_resync(deltas[2], full_state)
            assert not ack.resync_required
            assert ack.applied_revision == 3
            snap = service.snapshot
            assert snap.node_count == 3
            assert not snap.is_assumed("p-a")   # lost delta's assume absent
            assert snap.is_assumed("p-b")
            idx = snap.node_id("n2")
            cpu_i = list(cfg.resources).index(ext.RES_CPU)
            assert snap.nodes.requested[idx][cpu_i] == 2000.0
        finally:
            client.close()

    def test_drop_during_resync_answer_retried(self, loopback):
        service, port = loopback
        cfg = service.snapshot.config
        chaos = FaultInjector()
        client = SolverClient(
            f"127.0.0.1:{port}",
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0),
            chaos=chaos,
        )
        try:
            deltas, full_state = self._world(cfg)
            client.sync(deltas[0])
            chaos.arm("channel.sync.drop", times=5)   # loses delta 2 (3 fires)
            with pytest.raises(ChannelUnavailable):
                client.sync(deltas[1])
            # delta 3's first attempt burns fire 4, succeeds on 5's
            # exhaustion... and the RESYNC answer itself survives the
            # remaining drop budget through the same retry policy
            ack = client.sync_with_resync(deltas[2], full_state)
            assert not ack.resync_required
            assert service.snapshot.is_assumed("p-b")
        finally:
            client.close()
