"""Distributed-observability layer tests (fleet-tracing PR tentpole).

Covers: per-pod lifecycle tracing (event timelines, the gap-free
validator, the placement-latency histogram decomposition, journal-
context crash bridging); the per-shard SLO tracker (targets, violation
counting, burn rates, ``/slo``); the crash-surviving flight recorder
(per-cycle records, ring retention, dead-writer adoption over a shared
store, ``/debug/flightrecorder``); fleet aggregation (merged ``/metrics``
with a ``shard`` label, merged Chrome trace with per-shard process lanes
and linked handoff flows, per-shard ownership/epoch ``/healthz`` rows);
and speculation-gate introspection (``/debug/pipeline`` +
``pipeline_gate_closed_total{gate}`` attribution).
"""

import json

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.journal import BindJournal, EpochFence, MemoryJournalStore
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.obs.flightrecorder import FlightRecorder
from koordinator_tpu.obs.lifecycle import (
    LifecycleEvent,
    PodLifecycle,
    validate_timeline,
)
from koordinator_tpu.obs.slo import SloTarget, SloTracker
from koordinator_tpu.obs import fleet
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.stream import StreamScheduler
from koordinator_tpu.utils.metrics import Registry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def _node(name, cpu=16_000.0, mem=65_536.0):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _pod(name, cpu=1000.0, mem=2048.0):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
            priority=9000,
        ),
    )


def _sched(n_nodes=4, **kw):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(_node(f"n{i:02d}"))
    s = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=16, **kw)
    s.extender.monitor.stop_background()
    return s


# ---------------------------------------------------------------------------
# PodLifecycle
# ---------------------------------------------------------------------------


class TestPodLifecycle:
    def test_event_timeline_and_e2e_latency(self):
        clk = FakeClock()
        lc = PodLifecycle(clock=clk)
        lc.submitted("u1")
        clk.tick()
        lc.routed("u1", shard=2, detail="uid-hash")
        lc.event("u1", "enqueue", shard=2)
        clk.tick()
        lc.event("u1", "dispatch", shard=2)
        lc.event("u1", "decide", shard=2, detail="n01")
        clk.tick()
        e2e = lc.acked("u1", 2, "n01")
        assert e2e == pytest.approx(3.0)
        stages = [e.stage for e in lc.timeline("u1")]
        assert stages == [
            "submit", "route", "enqueue", "dispatch", "decide", "ack",
        ]
        assert validate_timeline(lc.timeline("u1")) == []
        assert lc.is_done("u1") and lc.seen("u1")

    def test_histogram_decomposition_per_stage(self):
        reg = Registry()
        clk = FakeClock()
        lc = PodLifecycle(registry=reg, clock=clk)
        lc.submitted("u1")
        clk.tick()                      # route span: 1s
        lc.event("u1", "enqueue", shard=0)
        clk.tick(2.0)                   # queue span: 2s
        lc.event("u1", "claim", shard=0)
        clk.tick(0.5)                   # claim→dispatch: 0.5s
        lc.event("u1", "dispatch", shard=0)
        clk.tick(3.0)                   # solve span: 3s
        lc.event("u1", "decide", shard=0, detail="n00")
        clk.tick(0.25)                  # commit span: 0.25s
        lc.acked("u1", 0, "n00")
        text = reg.expose()
        assert 'placement_latency_seconds_count{shard="0",stage="e2e"} 1' in text
        for stage in ("route", "queue", "claim", "solve", "commit"):
            assert (
                f'placement_latency_seconds_count{{shard="0",stage="{stage}"}} 1'
                in text
            ), stage
        h = reg.get("placement_latency_seconds")
        # e2e = 6.75s lands in the 10s bucket, not below 5s
        assert h.quantile(0.5, shard="0", stage="e2e") > 5.0

    def test_unsharded_queue_span_runs_enqueue_to_dispatch(self):
        reg = Registry()
        clk = FakeClock()
        lc = PodLifecycle(registry=reg, clock=clk)
        lc.submitted("u1")
        lc.event("u1", "enqueue", shard=-1)
        clk.tick(2.0)
        lc.event("u1", "dispatch", shard=-1)
        lc.event("u1", "decide", shard=-1, detail="n00")
        lc.acked("u1", -1, "n00")
        text = reg.expose()
        # no claim gate: queue observed, claim absent
        assert 'stage="queue"} 1' in text
        assert 'stage="claim"}' not in text

    def test_journal_context_bridges_a_fresh_tracker(self):
        clk = FakeClock(5.0)
        lc = PodLifecycle(clock=clk)
        lc.submitted("u1")
        clk.tick()
        lc.event("u1", "enqueue", shard=1)
        ctx = lc.context("u1")
        assert ctx == {"t0": 5.0, "hops": 1}
        # a genuinely fresh process: the journaled context re-seeds the
        # timeline with the TRUE arrival, bridged by a recover event
        clk2 = FakeClock(20.0)
        lc2 = PodLifecycle(clock=clk2)
        lc2.recovered("u1", 1, "n00", ctx=ctx)
        evs = lc2.timeline("u1")
        assert [e.stage for e in evs] == ["submit", "recover"]
        assert evs[0].t == 5.0
        e2e = lc2.acked("u1", 1, "n00")
        assert e2e == pytest.approx(15.0)
        assert validate_timeline(lc2.timeline("u1")) == []

    def test_recover_after_terminal_ack_is_a_noop(self):
        lc = PodLifecycle(clock=FakeClock())
        lc.submitted("u1")
        lc.event("u1", "enqueue", shard=0)
        lc.event("u1", "dispatch", shard=0)
        lc.event("u1", "decide", shard=0)
        lc.acked("u1", 0, "n00")
        before = [e.stage for e in lc.timeline("u1")]
        lc.recovered("u1", 0, "n00", ctx={"t0": 0.0, "hops": 1})
        assert [e.stage for e in lc.timeline("u1")] == before

    def test_bounded_eviction_drops_completed_keeps_live(self):
        lc = PodLifecycle(clock=FakeClock(), max_pods=10)
        for i in range(10):
            uid = f"done-{i}"
            lc.submitted(uid)
            lc.event(uid, "gone")
        lc.submitted("live-0")  # at capacity: evicts oldest completed
        assert lc.seen("live-0")
        assert not lc.seen("done-0")

    def test_bounded_eviction_falls_back_to_open_timelines(self):
        # a fleet dominated by never-placed pods has NO completed
        # timelines to evict — the bound must hold anyway
        lc = PodLifecycle(clock=FakeClock(), max_pods=10)
        for i in range(25):
            lc.submitted(f"open-{i}")  # never acked, never 'gone'
        assert len(lc.uids()) <= 10 + 1
        assert not lc.seen("open-0")  # oldest open evicted first
        assert lc.seen("open-24")


class TestPerShardBuffers:
    """PR 7 queued follow-on (devprof PR satellite): PodLifecycle events
    land in PER-SHARD buffers merged on read — the hot ``event()`` path
    contends only on its own shard's lock, never a fleet-wide mutex."""

    def test_per_shard_locks_are_distinct(self):
        lc = PodLifecycle(clock=FakeClock())
        lc.event("a", "enqueue", shard=0)
        lc.event("b", "enqueue", shard=1)
        assert lc._bufs[0].lock is not lc._bufs[1].lock

    def test_concurrent_shard_writers_never_cross_buffer_locks(self):
        import threading

        lc = PodLifecycle(clock=FakeClock())
        # prime both buffers (and register the uids) so the writer loop
        # below exercises ONLY the steady-state append path
        lc.event("s0-pod", "enqueue", shard=0)
        lc.event("s1-pod", "enqueue", shard=1)

        class RecordingLock:
            def __init__(self):
                self._lock = threading.Lock()
                self.owners = set()

            def __enter__(self):
                self.owners.add(threading.get_ident())
                self._lock.acquire()
                return self

            def __exit__(self, *exc):
                self._lock.release()

        locks = {s: RecordingLock() for s in (0, 1)}
        for s, rl in locks.items():
            lc._bufs[s].lock = rl

        n = 500
        idents = {}
        # both writers must be ALIVE simultaneously: pthread idents are
        # reused after a thread exits, so an unsynchronized fast writer
        # finishing before the other starts could alias their idents and
        # void the cross-lock assertion
        barrier = threading.Barrier(2)

        def writer(shard, uid):
            idents[shard] = threading.get_ident()
            barrier.wait()
            for i in range(n):
                lc.event(uid, "dispatch", shard=shard)

        threads = [
            threading.Thread(target=writer, args=(0, "s0-pod")),
            threading.Thread(target=writer, args=(1, "s1-pod")),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # contention shape: each shard's buffer lock was touched ONLY by
        # its own writer — the old fleet-wide mutex saw every event
        assert idents[1] not in locks[0].owners
        assert idents[0] not in locks[1].owners
        # correctness: nothing lost, per-shard order intact
        assert len(lc.timeline("s0-pod")) == n + 1
        assert len(lc.timeline("s1-pod")) == n + 1

    def test_cross_shard_merge_preserves_append_order_on_tied_clock(self):
        # the sharded soak runs on a cycle-count sim clock, so events on
        # DIFFERENT shards routinely tie on t; the merged timeline must
        # keep fleet-wide append (causal) order — orphan before resubmit
        # — or the validator's bracket checks break
        clock = FakeClock()  # constant until ticked
        lc = PodLifecycle(clock=clock)
        lc.event("p", "submit")
        lc.event("p", "enqueue", shard=1)
        lc.event("p", "orphan", shard=1)     # same t…
        lc.event("p", "resubmit", shard=0)   # …different shard
        lc.event("p", "dispatch", shard=0)
        lc.event("p", "decide", shard=0)
        lc.event("p", "ack", shard=0)
        stages = [e.stage for e in lc.timeline("p")]
        assert stages == [
            "submit", "enqueue", "orphan", "resubmit",
            "dispatch", "decide", "ack",
        ]
        assert validate_timeline(lc.timeline("p")) == []


class TestValidateTimeline:
    def _ev(self, stage, t, shard=0):
        return LifecycleEvent(stage=stage, t=t, shard=shard)

    def test_flags_missing_submit_and_non_terminal(self):
        probs = validate_timeline([self._ev("enqueue", 0.0)])
        assert any("not submit" in p for p in probs)
        assert any("not terminal" in p for p in probs)

    def test_flags_time_regression(self):
        probs = validate_timeline(
            [
                self._ev("submit", 5.0),
                self._ev("enqueue", 4.0),
                self._ev("dispatch", 6.0),
                self._ev("decide", 6.0),
                self._ev("ack", 7.0),
            ]
        )
        assert any("time went backwards" in p for p in probs)

    def test_flags_dispatch_before_enqueue_and_bare_ack(self):
        probs = validate_timeline(
            [
                self._ev("submit", 0.0),
                self._ev("dispatch", 1.0),
                self._ev("ack", 2.0),
            ]
        )
        assert any("dispatch before any enqueue" in p for p in probs)
        assert any("ack without a decide/recover" in p for p in probs)

    def test_flags_unbridged_orphan(self):
        # the dead-incarnation gap: decide after orphan with no
        # resubmit/recover/enqueue bridge
        probs = validate_timeline(
            [
                self._ev("submit", 0.0),
                self._ev("enqueue", 1.0),
                self._ev("orphan", 2.0),
                self._ev("dispatch", 3.0),
                self._ev("decide", 3.0),
                self._ev("ack", 4.0),
            ]
        )
        assert any("after orphan without" in p for p in probs)

    def test_accepts_bridged_orphan(self):
        assert (
            validate_timeline(
                [
                    self._ev("submit", 0.0),
                    self._ev("enqueue", 1.0),
                    self._ev("orphan", 2.0),
                    self._ev("resubmit", 3.0),
                    self._ev("dispatch", 4.0),
                    self._ev("decide", 4.0),
                    self._ev("ack", 5.0),
                ]
            )
            == []
        )


# ---------------------------------------------------------------------------
# SloTracker
# ---------------------------------------------------------------------------


class TestSloTracker:
    def test_violations_count_and_burn_rate(self):
        reg = Registry()
        slo = SloTracker(
            registry=reg,
            targets=(
                SloTarget("p99_latency", threshold_s=1.0, budget=0.5,
                          window=10),
            ),
            clock=FakeClock(),
        )
        for _ in range(8):
            assert not slo.observe_latency(0, 0.1)
        for _ in range(2):
            assert slo.observe_latency(0, 5.0)
        ev = slo.evaluate()["0"]["p99_latency"]
        assert ev["samples"] == 10 and ev["violations"] == 2
        # 2/10 of the window violate / 0.5 budget = 0.4 burn: within
        assert ev["burn_rate"] == pytest.approx(0.4)
        assert ev["ok"] and slo.ok()
        assert (
            reg.get("slo_violations_total").value(
                shard="0", slo="p99_latency"
            )
            == 2
        )
        # four more bad samples push burn past 1.0: budget overdrawn
        for _ in range(4):
            slo.observe_latency(0, 5.0)
        assert not slo.ok()

    def test_three_objectives_and_render(self):
        slo = SloTracker(clock=FakeClock())
        slo.observe_latency(0, 0.1)
        slo.observe_queue_age(0, 99.0)  # violates the 5s default
        slo.observe_recovery(1, 0.2)
        doc = json.loads(slo.render())
        assert set(doc["targets"]) == {
            "p99_latency", "queue_age", "recovery",
        }
        assert doc["shards"]["0"]["queue_age"]["violations"] == 1
        assert doc["shards"]["1"]["recovery"]["ok"]

    def test_unknown_slo_raises(self):
        with pytest.raises(ValueError):
            SloTracker()._observe(0, "nope", 1.0)

    def test_p99_nearest_rank_at_multiples_of_100(self):
        # regression: int(0.99*100)=99 picks the MAX (p100); nearest-rank
        # p99 of 100 samples is the 99th ranked, index 98
        slo = SloTracker(
            targets=(
                SloTarget("p99_latency", threshold_s=100.0, window=128),
            ),
            clock=FakeClock(),
        )
        for _ in range(99):
            slo.observe_latency(0, 0.001)
        slo.observe_latency(0, 60.0)  # one outlier
        ev = slo.evaluate()["0"]["p99_latency"]
        assert ev["window_p99_s"] == pytest.approx(0.001)
        assert ev["worst_s"] == pytest.approx(60.0)


# ---------------------------------------------------------------------------
# FlightRecorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_record_ring_and_render(self):
        fr = FlightRecorder(capacity=4, incarnation="inc-a",
                            clock=FakeClock())
        for c in range(6):
            fr.record(c, stage_ms={"solve": 1.5}, gates={"quotas": True},
                      speculation="kept", queue_depth=c, bound=2)
        recs = fr.last()
        assert len(recs) == 4  # ring bound
        assert [r["cycle"] for r in recs] == [2, 3, 4, 5]
        doc = json.loads(fr.render(2))
        assert doc["cycles"] == 2 and doc["recovered"] == 0
        assert doc["records"][-1]["stage_ms"] == {"solve": 1.5}

    def test_takeover_adopts_dead_writers_tail(self):
        store = MemoryJournalStore()
        dead = FlightRecorder(store, capacity=8, shard=1,
                              incarnation="inc-dead", clock=FakeClock())
        for c in range(5):
            dead.record(c, stage_ms={"cycle": 2.0})
        # the process dies; a takeover builds its recorder over the SAME
        # store and serves the dead incarnation's tail
        fr2 = FlightRecorder(store, capacity=8, shard=1,
                             incarnation="inc-new", clock=FakeClock())
        assert len(fr2.recovered_records()) == 5
        fr2.record(99, stage_ms={"cycle": 1.0})
        doc = json.loads(fr2.render())
        assert doc["recovered"] == 5
        flags = [r["recovered"] for r in doc["records"]]
        assert flags == [True] * 5 + [False]
        # seq continues past the dead writer's (no collision on replay)
        assert doc["records"][-1]["seq"] == 6

    def test_record_never_raises_into_scheduling_path(self):
        class ExplodingStore:
            def load(self):
                return []

            def append(self, rec):
                raise TypeError("not JSON serializable")

            def rewrite(self, recs):
                raise TypeError("boom")

        fr = FlightRecorder(ExplodingStore(), capacity=4,
                            incarnation="inc-a", clock=FakeClock())
        rec = fr.record(0, stage_ms={"solve": 1.0})  # must not raise
        assert rec["cycle"] == 0
        assert len(fr.last()) == 1  # ring retention degrades gracefully

    def test_store_compaction_bounds_reader_exposure(self):
        store = MemoryJournalStore()
        fr = FlightRecorder(store, capacity=4, incarnation="a",
                            clock=FakeClock())
        for c in range(8):  # 2*capacity appends triggers rewrite
            fr.record(c)
        assert len(store.load()) == 4  # rewritten to ring content
        assert [r["cycle"] for r in store.load()] == [4, 5, 6, 7]


# ---------------------------------------------------------------------------
# fleet aggregation
# ---------------------------------------------------------------------------


class TestFleetAggregation:
    def _regs(self):
        out = {}
        for s in (0, 1):
            reg = Registry()
            reg.counter("cycles_total", "cycles").inc(s + 1)
            reg.counter(
                "rej_total", "rejections", labels=("reason",)
            ).labels(reason="quota").inc()
            out[s] = reg
        return out

    def test_merged_metrics_injects_shard_label_once_per_meta(self):
        text = fleet.merged_metrics(self._regs())
        assert 'cycles_total{shard="0"} 1' in text
        assert 'cycles_total{shard="1"} 2' in text
        assert 'rej_total{shard="0",reason="quota"} 1' in text
        assert text.count("# HELP cycles_total") == 1
        assert text.count("# TYPE cycles_total") == 1

    def test_merged_metrics_groups_each_family_contiguously(self):
        # the exposition format requires ALL lines of a family in one
        # group: metric-major merge, not shard-major interleave
        lines = [
            ln
            for ln in fleet.merged_metrics(self._regs()).splitlines()
            if ln and not ln.startswith("#")
        ]
        fam = [ln.split("{", 1)[0] for ln in lines]
        assert fam == sorted(fam, key=fam.index)  # no family repeats
        # both shards' samples sit adjacent inside each family
        assert fam.count("cycles_total") == 2
        i = fam.index("cycles_total")
        assert fam[i + 1] == "cycles_total"

    def test_merge_chrome_traces_lanes_and_handoff_flows(self):
        from koordinator_tpu.obs.trace import Tracer

        tracers = {}
        for s in (0, 1):
            tr = Tracer(enabled=True)
            with tr.span("pump", cat="scheduler"):
                pass
            tracers[s] = tr
        # handoff stamps are ABSOLUTE readings on the tracers' shared
        # clock (perf_counter here), exactly as ShardedScheduler logs
        # them — the merge re-bases them onto the fleet epoch
        t_out = tracers[1].clock()
        t_in = t_out + 0.4
        doc = fleet.merge_chrome_traces(
            tracers,
            handoffs=[
                {"shard": 1, "t_out": t_out, "t_in": t_in,
                 "from": "inc-a", "to": "inc-b"},
            ],
        )
        evs = doc["traceEvents"]
        lanes = {
            e["args"]["name"]
            for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert {"shard-0", "shard-1"} <= lanes
        pids = {e["pid"] for e in evs if e.get("ph") == "X"}
        assert pids == {1, 2}  # one process lane per shard
        flow = [e for e in evs if e.get("cat") == "handoff"]
        assert [e["ph"] for e in flow] == ["s", "f"]
        assert flow[0]["pid"] == flow[1]["pid"] == 2
        assert flow[1]["ts"] - flow[0]["ts"] == pytest.approx(
            0.4e6, rel=1e-3
        )
        # clock alignment: arrows AND spans share the fleet-epoch axis —
        # the arrow lands at/after the spans, never at an absolute-clock
        # offset light-years off screen
        span_ts = [e["ts"] for e in evs if e.get("ph") == "X"]
        assert all(ts >= 0 for ts in span_ts)
        assert 0 <= flow[0]["ts"] < 60e6

    def test_merge_per_pod_flow_arrows_across_shard_lanes(self):
        """Per-pod Perfetto flow chains (devprof PR satellite): a placed
        pod's submit→route→dispatch→ack events link as ONE flow id
        across the shard lanes it crossed; the shardless submit anchors
        on the pod's first shard-scoped lane."""
        from koordinator_tpu.obs.trace import Tracer

        tracers = {0: Tracer(enabled=True), 1: Tracer(enabled=True)}
        t0 = tracers[0].clock()
        pod_flows = {
            "pod-x": [
                {"stage": "submit", "t": t0, "shard": -1},
                {"stage": "route", "t": t0 + 0.01, "shard": 0},
                {"stage": "handoff", "t": t0 + 0.02, "shard": 0},
                {"stage": "resubmit", "t": t0 + 0.03, "shard": 1},
                {"stage": "dispatch", "t": t0 + 0.04, "shard": 1},
                {"stage": "decide", "t": t0 + 0.045, "shard": 1},
                {"stage": "ack", "t": t0 + 0.05, "shard": 1},
            ],
        }
        doc = fleet.merge_chrome_traces(tracers, pod_flows=pod_flows)
        flow = [
            e for e in doc["traceEvents"] if e.get("cat") == "pod"
        ]
        # decide is not a flow stage; submit..ack minus decide = 6 points
        assert len(flow) == 6
        assert [e["ph"] for e in flow] == ["s", "t", "t", "t", "t", "f"]
        assert len({e["id"] for e in flow}) == 1
        # the shardless submit anchors on shard 0's lane; the chain ends
        # on shard 1's lane
        assert flow[0]["pid"] == 1 and flow[-1]["pid"] == 2
        ts = [e["ts"] for e in flow]
        assert ts == sorted(ts) and all(t >= 0 for t in ts)
        assert [e["args"]["stage"] for e in flow] == [
            "submit", "route", "handoff", "resubmit", "dispatch", "ack",
        ]

    def test_pod_flow_skips_unplaceable_chains(self):
        from koordinator_tpu.obs.trace import Tracer

        tr = Tracer(enabled=True)
        doc = fleet.merge_chrome_traces(
            {0: tr},
            pod_flows={
                # one point only — no arrow to draw
                "lonely": [{"stage": "submit", "t": 0.0, "shard": -1}],
            },
        )
        assert not [
            e for e in doc["traceEvents"] if e.get("cat") == "pod"
        ]

    def test_lifecycle_flows_feed(self):
        lc = PodLifecycle(clock=FakeClock())
        lc.submitted("u1")
        lc.event("u1", "route", shard=0)
        lc.event("u1", "enqueue", shard=0)
        lc.event("u1", "dispatch", shard=0)
        lc.event("u1", "decide", shard=0, detail="n0")
        lc.acked("u1", 0, "n0")
        lc.submitted("open-pod")  # never completes: not in the feed
        flows = lc.flows()
        assert set(flows) == {"u1"}
        stages = [e["stage"] for e in flows["u1"]]
        assert stages[0] == "submit" and stages[-1] == "ack"

    def test_merge_handoff_open_seam_renders_degenerate_arrow(self):
        from koordinator_tpu.obs.trace import Tracer

        tr = Tracer(enabled=True)
        doc = fleet.merge_chrome_traces(
            {0: tr},
            handoffs=[
                # drained but no successor granted yet: t_in still None
                {"shard": 0, "t_out": tr.clock(), "t_in": None,
                 "from": "inc-a", "to": ""},
            ],
        )
        flow = [
            e for e in doc["traceEvents"] if e.get("cat") == "handoff"
        ]
        assert [e["ph"] for e in flow] == ["s", "f"]
        assert flow[1]["ts"] >= flow[0]["ts"]


# ---------------------------------------------------------------------------
# services-engine surfaces (/slo, /debug/pipeline, /debug/flightrecorder)
# ---------------------------------------------------------------------------


class TestServicesEndpoints:
    def test_slo_endpoint_wiring(self):
        sched = _sched()
        eng = sched.extender.services
        assert eng.dispatch("GET", "/slo")[0] == 404
        slo = SloTracker(clock=FakeClock())
        slo.observe_latency(0, 0.1)
        eng.slo = slo
        code, body = eng.dispatch("GET", "/slo")
        assert code == 200 and json.loads(body)["ok"]

    def test_flightrecorder_endpoint_and_cycle_records(self):
        sched = _sched()
        eng = sched.extender.services
        assert eng.dispatch("GET", "/debug/flightrecorder")[0] == 404
        fr = FlightRecorder(capacity=16, incarnation="inc-a")
        sched.attach_flight_recorder(fr)
        out = sched.schedule([_pod("p0"), _pod("p1")])
        assert len(out.bound) == 2
        code, body = eng.dispatch("GET", "/debug/flightrecorder")
        assert code == 200
        doc = json.loads(body)
        assert doc["cycles"] == 1
        rec = doc["records"][0]
        assert rec["bound"] == 2 and rec["unschedulable"] == 0
        assert rec["speculation"] == "serial" and not rec["fenced"]
        # per-cycle stage breakdown rides in the black box
        assert {"cycle", "snapshot", "solve", "commit"} <= set(
            rec["stage_ms"]
        )
        assert rec["stage_ms"]["cycle"] > 0

    def test_debug_pipeline_defaults_to_not_pipelined(self):
        sched = _sched()
        code, body = sched.extender.services.dispatch(
            "GET", "/debug/pipeline"
        )
        assert code == 200 and json.loads(body) == {"pipelined": False}


# ---------------------------------------------------------------------------
# gate introspection (/debug/pipeline + pipeline_gate_closed_total)
# ---------------------------------------------------------------------------


class TestGateIntrospection:
    def test_gate_report_names_every_speculation_gate(self):
        sched = _sched()
        report = sched.speculation_gate_report()
        assert set(report) == {
            "reservations", "mesh", "numa", "devices", "quotas",
            "transformers", "preemption", "gangs", "sampling",
        }
        assert all(report.values())  # bare config: everything open
        assert sched._speculation_consume_ok()

    def test_closed_gate_attributed_in_counter_and_endpoint(self):
        # pod transformers are a state-bearing gate (preemption and the
        # reservations fast path now ride the chain — open the last
        # gates PR): the pipelined stream must fall back to serial AND
        # name the gate that did it
        sched = _sched(n_nodes=8)
        sched.extender.register_pod_transformer(lambda pod: pod)
        stream = StreamScheduler(sched, max_batch=8, pipelined=True)
        try:
            for i in range(3):
                stream.submit(_pod(f"p{i}"))
            bound = [r for r in stream.flush() if r[1] is not None]
            assert len(bound) == 3
            reg = sched.extender.registry
            assert (
                reg.get("pipeline_gate_closed_total").value(
                    gate="transformers"
                )
                > 0
            )
            code, body = sched.extender.services.dispatch(
                "GET", "/debug/pipeline"
            )
            assert code == 200
            doc = json.loads(body)
            assert doc["pipelined"] is True
            assert doc["last"]["closed"] == ["transformers"]
            assert doc["last"]["gates"]["transformers"] is False
            assert doc["last"]["gates"]["quotas"] is True
            assert doc["cycles_gated"] > 0 and doc["cycles_fast"] == 0
        finally:
            stream.close()

    def test_flight_record_gates_are_the_cycles_own_not_the_next_feeds(self):
        # regression: CyclePipeline.feed evaluates batch k's gates
        # BEFORE running batch k-1's trailing commit — the flight record
        # for cycle k-1 must carry k-1's feed-time verdicts, not k's
        sched = _sched(n_nodes=8)
        fr = FlightRecorder(capacity=16, incarnation="inc-a")
        sched.attach_flight_recorder(fr)
        stream = StreamScheduler(sched, max_batch=8, pipelined=True)
        try:
            stream.submit(_pod("p0"))
            assert stream.pump() == []  # batch 1 fed, gates OPEN
            # the world changes between feeds: a pod transformer lands
            # (preemption no longer closes the gate — open the last
            # gates PR — so the flip rides the transformers gate)
            sched.extender.register_pod_transformer(lambda pod: pod)
            stream.submit(_pod("p1"))
            stream.pump()  # batch 2 fed (gated) + batch 1's commit
            recs = fr.last()
            assert recs, "batch 1's cycle must have recorded"
            assert recs[0]["gates"].get("transformers") is True, (
                "cycle 1's record shows the NEXT feed's closed gate"
            )
            stream.flush()
            recs = fr.last()
            assert recs[-1]["gates"].get("transformers") is False
        finally:
            stream.close()

    def test_open_gates_take_fast_path_and_count_fast_cycles(self):
        sched = _sched(n_nodes=8)
        stream = StreamScheduler(sched, max_batch=8, pipelined=True)
        try:
            for i in range(3):
                stream.submit(_pod(f"p{i}"))
            bound = [r for r in stream.flush() if r[1] is not None]
            assert len(bound) == 3
            doc = json.loads(
                sched.extender.services.dispatch(
                    "GET", "/debug/pipeline"
                )[1]
            )
            assert doc["cycles_fast"] > 0
            assert doc["last"]["closed"] == []
        finally:
            stream.close()


# ---------------------------------------------------------------------------
# FleetServices over a live ShardedScheduler
# ---------------------------------------------------------------------------


class TestFleetServices:
    def _world(self, n_shards=2, n_nodes=8):
        from koordinator_tpu.runtime.shards import (
            ShardFabric,
            ShardedScheduler,
        )
        from koordinator_tpu.runtime.statehub import ClusterStateHub

        t = [0.0]
        fabric = ShardFabric(
            n_shards, clock=lambda: t[0], membership_ttl_s=2.5
        )
        hub = ClusterStateHub()
        for i in range(n_nodes):
            hub.publish(hub.nodes, _node(f"n{i:03d}"))

        def factory(shard, snapshot, fence, journal):
            s = BatchScheduler(
                snapshot,
                LoadAwareArgs(usage_thresholds={}),
                batch_bucket=16,
                journal=journal,
                fence=fence,
            )
            s.extender.monitor.stop_background()
            return s

        inc = ShardedScheduler(
            "inc-a",
            hub,
            fabric,
            factory,
            max_batch=16,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            lifecycle=PodLifecycle(
                registry=Registry(), clock=lambda: t[0]
            ),
            slo=SloTracker(clock=lambda: t[0]),
        )
        fabric.membership.heartbeat("inc-a")
        for _ in range(2):
            t[0] += 1.0
            inc.tick()
        return t, fabric, hub, inc

    def test_healthz_rows_metrics_and_slo_surfaces(self):
        t, fabric, hub, inc = self._world()
        try:
            assert set(inc.owned()) == {0, 1}
            fs = inc.fleet()
            # per-shard ownership/epoch rows (satellite): every owned
            # shard reports owned=True at its CURRENT fence epoch
            code, body = fs.dispatch("GET", "/healthz")
            assert code == 200
            doc = json.loads(body)
            assert doc["ok"] and doc["incarnation"] == "inc-a"
            assert doc["owned"] == [0, 1]
            for s in (0, 1):
                row = doc["shards"][str(s)]
                assert row["owned"] is True
                assert row["epoch"] == fabric.fences[s].current()
                assert row["health_ok"] is True
                assert row["backlog"] == 0
            # a pod through shard routing feeds the merged surfaces
            from koordinator_tpu.runtime.shards import ShardRouter

            router = ShardRouter(
                fabric.shard_map, lifecycle=inc.lifecycle
            )
            pod = _pod("p0")
            s = router.route(pod)
            assert inc.submit(s, pod, now=t[0])
            decided = inc.pump() + inc.flush()
            assert len(decided) == 1 and decided[0][2] is not None
            code, body = fs.dispatch("GET", "/metrics")
            assert code == 200
            assert f'shard="{s}"' in body
            assert (
                body.count(
                    "# HELP koord_scheduler_cycle_latency_seconds"
                )
                == 1
            )
            # the incarnation-level lifecycle histogram rides in the
            # same scrape with its OWN shard label, not a fleet-side
            # injected one (no doubled shard= on any sample line)
            assert (
                f'placement_latency_seconds_count{{shard="{s}",'
                f'stage="e2e"}} 1' in body
            )
            assert 'shard="0",shard=' not in body
            code, body = fs.dispatch("GET", "/slo")
            assert code == 200
            assert json.loads(body)["shards"][str(s)][
                "p99_latency"
            ]["samples"] == 1
            # merged chrome trace: one process lane per OWNED shard
            code, body = fs.dispatch("GET", "/trace")
            doc = json.loads(body)
            lanes = {
                e["args"]["name"]
                for e in doc["traceEvents"]
                if e.get("ph") == "M"
                and e.get("name") == "process_name"
            }
            assert lanes == {"shard-0", "shard-1"}
            # fleet gate introspection: one verdict doc per owned shard,
            # forwarded from each runtime's own services engine
            code, body = fs.dispatch("GET", "/debug/pipeline")
            assert code == 200
            doc = json.loads(body)
            assert doc["incarnation"] == "inc-a"
            assert set(doc["shards"]) == {"0", "1"}
            for row in doc["shards"].values():
                assert "pipelined" in row
            assert fs.dispatch("GET", "/nope")[0] == 404
        finally:
            inc.close()
            hub.stop()

    def test_voluntary_handoff_closes_one_seam_on_the_shared_log(self):
        from koordinator_tpu.runtime.shards import ShardedScheduler

        t, fabric, hub, inc = self._world()
        b = None
        try:
            assert set(inc.owned()) == {0, 1}

            def factory(shard, snapshot, fence, journal):
                s = BatchScheduler(
                    snapshot,
                    LoadAwareArgs(usage_thresholds={}),
                    batch_bucket=16,
                    journal=journal,
                    fence=fence,
                )
                s.extender.monitor.stop_background()
                return s

            b = ShardedScheduler(
                "inc-b", hub, fabric, factory, max_batch=16,
                lease_duration=3.0, renew_deadline=2.0,
                retry_period=0.5,
            )
            fabric.membership.heartbeat("inc-b")
            for _ in range(4):
                t[0] += 1.0
                fabric.membership.heartbeat("inc-a")
                fabric.membership.heartbeat("inc-b")
                inc.tick()
                b.tick()
            assert b.owned(), "joiner must win a rebalanced shard"
            # the donor's drain opened a seam; the takeover CLOSED it:
            # one entry spanning the ownership gap, not two point stubs
            seams = [
                h for h in fabric.handoff_log
                if h["from"] == "inc-a" and h["to"] == "inc-b"
            ]
            assert seams, fabric.handoff_log
            for h in seams:
                assert h["t_in"] is not None
                assert h["t_in"] >= h["t_out"]
            # the property serves a locked SNAPSHOT of the shared log
            # (another incarnation may append mid-iteration), same data
            assert b.handoff_log == list(fabric.handoff_log)
        finally:
            if b is not None:
                b.close()
            inc.close()
            hub.stop()

    def test_unowned_shard_row_reports_fence_epoch(self):
        t, fabric, hub, inc = self._world()
        try:
            # depose shard 1: the row flips to owned=False but still
            # reports the shard's current fence epoch for the operator
            inc._coords[1].leading = False
            ok, doc = inc.fleet().healthz()
            row = doc["shards"]["1"]
            assert row["owned"] is False
            assert row["epoch"] == fabric.fences[1].current()
            assert "health_ok" not in row
        finally:
            inc.close()
            hub.stop()


# ---------------------------------------------------------------------------
# stream lifecycle integration + journal context
# ---------------------------------------------------------------------------


class TestStreamLifecycleIntegration:
    def test_crash_extract_does_not_fake_a_graceful_handoff(self):
        # a killed queue must never read as a clean drain: kill() passes
        # event=None and stamps its own orphan events, so the timeline
        # brackets the crash — not a handoff that never happened
        lc = PodLifecycle(clock=FakeClock())
        sched = _sched()
        stream = StreamScheduler(
            sched, max_batch=8, lifecycle=lc, shard=0
        )
        pod = _pod("p0")
        stream.submit(pod)
        out = stream.extract_queued(event=None)
        assert len(out) == 1
        stages = [e.stage for e in lc.timeline(pod.meta.uid)]
        assert "handoff" not in stages
        # the graceful default still records the drain
        stream.submit(pod)
        stream.extract_queued()
        assert [e.stage for e in lc.timeline(pod.meta.uid)][-1] == (
            "handoff"
        )

    def test_pump_emits_full_timeline_and_slo_sample(self):
        lc = PodLifecycle(clock=FakeClock())
        slo = SloTracker(clock=FakeClock())
        sched = _sched()
        stream = StreamScheduler(
            sched, max_batch=8, lifecycle=lc, slo=slo, shard=3
        )
        pod = _pod("p0")
        stream.submit(pod)
        results = stream.pump()
        assert len(results) == 1 and results[0][1] is not None
        stages = [e.stage for e in lc.timeline(pod.meta.uid)]
        assert stages == ["submit", "enqueue", "dispatch", "decide", "ack"]
        assert validate_timeline(lc.timeline(pod.meta.uid)) == []
        assert all(
            e.shard == 3 for e in lc.timeline(pod.meta.uid)
            if e.stage != "submit"
        )
        ev = slo.evaluate()["3"]
        assert ev["p99_latency"]["samples"] == 1
        assert ev["queue_age"]["samples"] == 1

    def test_bind_journal_records_carry_lifecycle_context(self):
        lc = PodLifecycle(clock=FakeClock(7.0))
        store = MemoryJournalStore()
        fence = EpochFence()
        epoch = fence.advance()
        sched = _sched(
            journal=BindJournal(store), fence=fence,
        )
        sched.grant_leadership(epoch)
        stream = StreamScheduler(
            sched, max_batch=8, lifecycle=lc, shard=2
        )
        pod = _pod("p0")
        stream.submit(pod)
        assert len(stream.pump()) == 1
        binds = [
            e
            for r in store.load()
            if r.get("op") == "bind"
            for e in r["binds"]
        ]
        assert len(binds) == 1
        # the compact trace context rides in the durable record: the
        # takeover's replay bridges the timeline with the TRUE arrival
        assert binds[0]["lc"]["t0"] == 7.0
        assert binds[0]["lc"]["hops"] >= 1
