"""Elastic shard topology tests (elastic-topology PR tentpole).

Covers: the generation-based cell-tree ShardMap (gen-0 bit-identical to
the PR 6 modulo partition, split moves exactly the parent's nodes, merge
re-unifies under a fresh id, cell_covers/successors answer retired-range
questions); the journaled ShardTopology transaction log (generation
monotonicity, single open transition, reload replay, crash-void
intents); LIVE split and merge under traffic with queue continuity,
journal re-home, claim re-pointing and rollback on the named crash
points; disjoint ownership under membership churn DURING a split (no
window where two incarnations own overlapping node ranges); the
SLO-burn-driven TopologyController (sustain + cooldown hysteresis,
spawn/retire callbacks); and the router's spill-fan-out hysteresis.
"""

import json

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.journal import (
    BindJournal,
    MemoryJournalStore,
    StaleEpochError,
)
from koordinator_tpu.obs.lifecycle import (
    LifecycleEvent,
    PodLifecycle,
    validate_timeline,
)
from koordinator_tpu.obs.slo import SloTracker
from koordinator_tpu.runtime.elastic import (
    TopologyChangeError,
    TopologyController,
    merge_shards,
    split_shard,
)
from koordinator_tpu.runtime.shards import (
    ShardedScheduler,
    ShardFabric,
    ShardMap,
    ShardRouter,
    ShardTopology,
)
from koordinator_tpu.runtime.statehub import ClusterStateHub
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.utils import stable_hash

N_SHARDS = 3
N_NODES = 18


def _node(name, cpu=32_000.0, mem=128 * 1024.0):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _pod(name, cpu=2000.0, mem=4096.0):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}, priority=9000
        ),
    )


def _make_scheduler(shard, snapshot, fence, journal):
    s = BatchScheduler(
        snapshot,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=16,
        journal=journal,
        fence=fence,
    )
    s.extender.monitor.stop_background()
    return s


class _World:
    """Shared fabric + hub + simulated cycle clock (test_shards pattern,
    with the lifecycle tracker wired so topology brackets are visible)."""

    def __init__(self, n_shards=N_SHARDS, n_nodes=N_NODES, chaos=None):
        self.t = [0.0]
        self.chaos = chaos or FaultInjector(seed=0)
        self.fabric = ShardFabric(
            n_shards, clock=lambda: self.t[0], membership_ttl_s=2.5
        )
        self.lifecycle = PodLifecycle(clock=lambda: self.t[0])
        self.hub = ClusterStateHub()
        self.node_names = [f"n{i:03d}" for i in range(n_nodes)]
        for name in self.node_names:
            self.hub.publish(self.hub.nodes, _node(name))
        self.incs = []

    def incarnation(self, name):
        inc = ShardedScheduler(
            name,
            self.hub,
            self.fabric,
            _make_scheduler,
            pipelined=False,
            max_batch=32,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            chaos=self.chaos,
            lifecycle=self.lifecycle,
        )
        self.fabric.membership.heartbeat(name)
        self.incs.append(inc)
        return inc

    def live(self):
        return [i for i in self.incs if not i.dead]

    def settle(self, ticks=3):
        handoffs = []
        for _ in range(ticks):
            self.t[0] += 1.0
            for inc in self.live():
                for s, hand in sorted(inc.tick().items()):
                    handoffs.append((s, hand))
        return handoffs

    def owner_of(self, shard):
        for inc in self.live():
            if inc.owns(shard):
                return inc
        return None

    def close(self):
        for inc in self.live():
            inc.close()
        self.hub.stop()


# ---------------------------------------------------------------------------
# ShardMap: cell tree
# ---------------------------------------------------------------------------


def test_shard_map_gen0_is_bit_identical_to_modulo():
    m = ShardMap(5)
    names = [f"n{i:03d}" for i in range(96)] + ["weird-node", ""]
    for n in names:
        assert m.shard_of_node(n) == stable_hash(f"node|{n}") % 5
    for k in ("quota:team-a", "gang:ns/g1", "some-uid"):
        assert m.shard_of_key(k) == stable_hash(f"key|{k}") % 5
    assert m.n_shards == 5 and m.active_shards() == [0, 1, 2, 3, 4]
    assert m.generation == 0


def test_split_moves_exactly_the_parent_nodes_and_merge_reunifies():
    m = ShardMap(4)
    names = [f"n{i:03d}" for i in range(64)]
    before = {n: m.shard_of_node(n) for n in names}
    parent = 2
    a, b = m.allocate_ids(2)
    planned = {n: m.split_dest(parent, n, a, b) for n in names
               if before[n] == parent}
    m.split_cells(parent, a, b)
    assert m.generation == 1
    assert not m.is_active(parent) and m.is_active(a) and m.is_active(b)
    after = {n: m.shard_of_node(n) for n in names}
    for n in names:
        if before[n] != parent:
            assert after[n] == before[n], "non-parent nodes must not move"
        else:
            assert after[n] in (a, b)
            assert after[n] == planned[n], "split_dest must predict routing"
    # cell_covers: generation-independent range truth
    for n in names:
        assert m.cell_covers(before[n], n)
        assert m.cell_covers(after[n], n)
    assert m.siblings() == [(a, b)]
    assert m.successors(parent) == sorted([a, b])
    # merge re-unifies the range under a FRESH id
    (c,) = m.allocate_ids(1)
    m.merge_cells(a, b, c)
    assert m.generation == 2
    assert m.active_shards() == sorted(
        set(range(4)) - {parent} | {c}
    )
    for n in names:
        if before[n] == parent:
            assert m.shard_of_node(n) == c
    assert m.successors(a) == [c] and m.successors(parent) == [c]
    # non-siblings refuse to merge (base cells are the scale-in floor)
    with pytest.raises(ValueError):
        m.merge_cells(0, 1, 99)


def test_partition_keys_follow_the_topology():
    m = ShardMap(3)
    key = "quota:soak-team"
    home = m.shard_of_key(key)
    a, b = m.allocate_ids(2)
    m.split_cells(home, a, b)
    assert m.shard_of_key(key) in (a, b)
    part = m.partition([f"n{i}" for i in range(30)])
    assert sorted(part) == m.active_shards()
    assert sum(len(v) for v in part.values()) == 30


# ---------------------------------------------------------------------------
# ShardTopology: the journaled transition log
# ---------------------------------------------------------------------------


def test_topology_transactions_are_journaled_and_reloadable():
    store = MemoryJournalStore()
    m = ShardMap(3)
    topo = ShardTopology(m, store=store)
    intent = topo.begin_split(1)
    # one open transition at a time — epoch-monotonic discipline
    with pytest.raises(StaleEpochError):
        topo.begin_split(0)
    topo.commit(intent)
    a, b = (int(i) for i in intent["children"])
    assert m.is_active(a) and not m.is_active(1)
    # a rolled-back attempt burns its ids and leaves the map untouched
    intent2 = topo.begin_merge(a, b)
    topo.rollback(intent2, reason="test")
    assert m.is_active(a) and m.is_active(b)
    intent3 = topo.begin_merge(a, b)
    topo.commit(intent3)
    merged = int(intent3["merged"])
    assert m.is_active(merged)
    # generations in the journal are strictly monotonic incl. rollbacks
    gens = [r["gen"] for r in store.load() if "gen" in r]
    assert gens == sorted(gens) and len(set(gens)) == 3
    # RELOAD: a fresh map + the same store reproduce the live topology
    m2 = ShardMap(3)
    ShardTopology(m2, store=store)
    assert m2.active_shards() == m.active_shards()
    assert m2.generation == m.generation
    # fresh ids allocated after reload never collide with journaled ones
    assert m2.allocate_ids(1)[0] > merged


def test_split_shard_raises_typed_error_and_journals_the_rollback():
    """The raw transaction API: an injected crash surfaces as
    TopologyChangeError AFTER the rollback record landed."""
    chaos = FaultInjector(seed=0)
    fabric = ShardFabric(2)
    chaos.arm("shard.split_crash", times=1)
    with pytest.raises(TopologyChangeError):
        split_shard(fabric, 0, chaos=chaos)
    ops = [r.get("op") for r in fabric.topology.history()]
    assert ops == ["split_intent", "rollback"]
    # and the inverse transaction shares the discipline
    intent = fabric.topology.begin_split(0)
    fabric.topology.commit(intent)
    a, b = (int(i) for i in intent["children"])
    chaos.arm("shard.merge_crash", times=1)
    with pytest.raises(TopologyChangeError):
        merge_shards(fabric, a, b, chaos=chaos)
    assert fabric.topology.history()[-1]["op"] == "rollback"
    assert fabric.shard_map.is_active(a) and fabric.shard_map.is_active(b)


def test_orphaned_claims_on_retired_cells_self_heal():
    """The commit→claim-rehome window: a crash (or claims-journal
    failure) after a committed transition can strand a queued pod's
    claim on the RETIRED cell. The claim must self-heal to the live
    claimant at the next feed — dropping the pod forever is the one
    unacceptable outcome."""
    fabric = ShardFabric(3)
    t = fabric.claims
    parent = 1
    assert t.claim("stranded", parent, 1)
    # simulate the crash window: the topology commits but rehome never
    # runs (no claim_rehome record lands)
    intent = fabric.topology.begin_split(parent)
    fabric.topology.commit(intent)
    a, b = (int(i) for i in intent["children"])
    assert t.winner("stranded") == parent  # still pointing at the dead cell
    # the pod re-routes to a child and feeds: the claim self-heals
    assert t.claim("stranded", a, 1) is True
    assert t.winner("stranded") == a
    # …and a reload agrees (the later self-heal record is the truth)
    from koordinator_tpu.core.journal import ClaimTable

    t2 = ClaimTable(t.store, shard_live=fabric.shard_map.is_active)
    assert t2.winner("stranded") == a
    # claims on LIVE shards still arbitrate single-winner as before
    assert t.claim("stranded", b, 1) is False


def test_claims_rehome_failure_never_masquerades_as_rollback():
    """A claims-journal write failure AFTER the topology commit must
    not report a rollback (the transition is fact) — the split result
    carries claims_rehomed=False and the topology stays committed."""
    from koordinator_tpu.core.journal import JournalWriteError

    world = _World()
    world.incarnation("inc-a")
    try:
        world.settle(3)
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
        )
        parent = ctrl.pick_split_candidate()

        def boom(*_a, **_k):
            raise JournalWriteError("claims store down")

        world.fabric.claims.rehome = boom
        out = ctrl.split(parent)
        assert out is not None and out["claims_rehomed"] is False
        assert ctrl.stats["rollbacks"] == 0
        assert world.fabric.topology.generation == 1
        assert not world.fabric.shard_map.is_active(parent)
    finally:
        world.close()


def test_topology_reload_voids_a_trailing_open_intent():
    store = MemoryJournalStore()
    m = ShardMap(2)
    topo = ShardTopology(m, store=store)
    topo.begin_split(0)  # the splitting process "dies" here
    m2 = ShardMap(2)
    topo2 = ShardTopology(m2, store=store)
    assert topo2.open_transition() is None
    assert m2.active_shards() == [0, 1], "parent generation stays active"
    # and the next transition opens cleanly at a fresh generation
    intent = topo2.begin_split(0)
    topo2.commit(intent)
    assert m2.generation == 1


# ---------------------------------------------------------------------------
# Live split / merge under traffic
# ---------------------------------------------------------------------------


def _drive_placement(world, pods):
    """Route + submit + pump until every pod is decided; returns
    uid -> node and re-routes handoff/retired-shard pods like the soak
    driver does."""
    router = ShardRouter(world.fabric.shard_map, lifecycle=world.lifecycle)
    placed = {}
    backlog = list(pods)
    for _ in range(20):
        still = []
        for pod in backlog:
            s = router.route(pod)
            owner = world.owner_of(s)
            if owner is None or not owner.submit(s, pod, now=world.t[0]):
                still.append(pod)
        backlog = still
        for inc in world.live():
            for s, pod, node, _lat in inc.pump() + inc.flush():
                if node is not None:
                    placed[pod.meta.uid] = node
                else:
                    backlog.append(pod)
        for s, hand in world.settle(1):
            for pod, node, _lat in hand.decided:
                if node is not None:
                    placed[pod.meta.uid] = node
            for pod, _arr, _tries in hand.queued:
                backlog.append(pod)
        if not backlog and len(placed) == len(pods):
            break
    return placed


def test_live_split_rehomes_journal_queue_and_claims():
    world = _World()
    a = world.incarnation("inc-a")
    b = world.incarnation("inc-b")
    try:
        world.settle(3)
        pods = [_pod(f"pre{i:02d}") for i in range(12)]
        placed = _drive_placement(world, pods)
        assert len(placed) == 12
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
            lifecycle=world.lifecycle,
        )
        parent = ctrl.pick_split_candidate()
        assert parent is not None
        donor = world.owner_of(parent)
        donor_other = set(donor.owned()) - {parent}
        # queue a pod on the parent so the split must carry it over
        qpods = [
            p for p in (_pod(f"q{i:02d}") for i in range(40))
            if world.fabric.shard_map.shard_of_node(
                placed.get(p.meta.uid, "")
            ) is not None
        ]
        queued = None
        router = ShardRouter(
            world.fabric.shard_map, lifecycle=world.lifecycle
        )
        for p in qpods:
            if router.route(p) == parent:
                queued = p
                donor.submit(parent, p, now=world.t[0])
                break
        out = ctrl.split(parent)
        assert out is not None and out["op"] == "split"
        ca, cb = out["children"]
        # the donor's OTHER shards kept serving throughout
        assert donor_other <= set(donor.owned())
        assert not world.fabric.shard_map.is_active(parent)
        # journal re-home: every parent-live bind now lives in the child
        # journal owning its node (exact entries, replayable)
        parent_live = BindJournal(
            world.fabric.journal_stores[parent]
        ).replay().live
        for uid, entry in parent_live.items():
            child = world.fabric.shard_map.shard_of_node(entry["node"])
            assert child in (ca, cb)
            child_live = BindJournal(
                world.fabric.journal_stores[child]
            ).replay().live
            assert child_live[uid]["node"] == entry["node"]
            # claims followed the pod to its child shard
            assert world.fabric.claims.winner(uid) == child
        # children elect owners and recover the re-homed world bit-exact
        # (verify_recovery=True inside the takeover)
        world.settle(4)
        assert world.owner_of(ca) is not None
        assert world.owner_of(cb) is not None
        # queue continuity: the queued pod resurfaces via the handoff
        # and places on a child — with a gap-free bracketed timeline
        if queued is not None:
            placed2 = _drive_placement(world, [queued])
            assert queued.meta.uid in placed2
            evs = world.lifecycle.timeline(queued.meta.uid)
            stages = [e.stage for e in evs]
            assert "shard_split" in stages
            assert validate_timeline(evs) == []
    finally:
        world.close()


def test_split_crash_rolls_back_to_parent_generation():
    world = _World()
    a = world.incarnation("inc-a")
    try:
        world.settle(3)
        pods = [_pod(f"pre{i:02d}") for i in range(8)]
        placed = _drive_placement(world, pods)
        assert len(placed) == 8
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
            chaos=world.chaos,
            lifecycle=world.lifecycle,
        )
        parent = ctrl.pick_split_candidate()
        gen0 = world.fabric.topology.generation
        claims_before = {
            uid: world.fabric.claims.winner(uid) for uid in placed
        }
        world.chaos.arm("shard.split_crash", times=1)
        assert ctrl.split(parent) is None
        assert ctrl.stats["rollbacks"] == 1
        # the parent generation is still the active one — never a
        # half-owned range — and the map is untouched
        assert world.fabric.topology.generation == gen0
        assert world.fabric.shard_map.is_active(parent)
        assert world.fabric.topology.open_transition() is None
        # claims were NOT re-pointed (rollback precedes the claim move)
        for uid, shard in claims_before.items():
            assert world.fabric.claims.winner(uid) == shard
        # the relinquished parent re-elects and keeps placing
        world.settle(4)
        assert world.owner_of(parent) is not None
        more = _drive_placement(world, [_pod(f"post{i:02d}") for i in range(6)])
        assert len(more) == 6
        # a RETRY succeeds with fresh child ids (the crashed attempt's
        # ids stay burned)
        out = ctrl.split(parent)
        assert out is not None
        rolled_back_children = json.loads(
            json.dumps(
                [
                    r["children"]
                    for r in world.fabric.topology.history()
                    if r.get("op") == "split_intent"
                ]
            )
        )
        assert rolled_back_children[0] != rolled_back_children[1]
    finally:
        world.close()


def test_merge_crash_rolls_back_and_retry_succeeds():
    world = _World()
    a = world.incarnation("inc-a")
    try:
        world.settle(3)
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
            chaos=world.chaos,
            lifecycle=world.lifecycle,
        )
        parent = ctrl.pick_split_candidate()
        out = ctrl.split(parent)
        assert out is not None
        ca, cb = out["children"]
        world.settle(4)
        gen1 = world.fabric.topology.generation
        world.chaos.arm("shard.merge_crash", times=1)
        assert ctrl.merge(ca, cb) is None
        assert world.fabric.topology.generation == gen1
        assert world.fabric.shard_map.is_active(ca)
        assert world.fabric.shard_map.is_active(cb)
        # both donors re-elect after the rollback
        world.settle(4)
        assert world.owner_of(ca) is not None
        assert world.owner_of(cb) is not None
        merged_out = ctrl.merge(ca, cb)
        assert merged_out is not None
        c = merged_out["merged"]
        world.settle(4)
        assert world.owner_of(c) is not None
        # the merged shard serves the whole reunified range
        more = _drive_placement(
            world, [_pod(f"post{i:02d}") for i in range(8)]
        )
        assert len(more) == 8
    finally:
        world.close()


# ---------------------------------------------------------------------------
# Satellite: disjoint ownership under membership churn DURING a split
# ---------------------------------------------------------------------------


def _assert_disjoint_ownership(world):
    """No two incarnations may own shards with overlapping node ranges
    (same node covered by two owned cells) at any instant."""
    owned = [
        (inc.name, s)
        for inc in world.live()
        for s in inc.owned()
    ]
    for n in world.node_names:
        owners = {
            name
            for name, s in owned
            if world.fabric.shard_map.cell_covers(s, n)
            and world.fabric.shard_map.is_active(s)
        }
        assert len(owners) <= 1, (
            f"node {n} owned by {sorted(owners)}"
        )


def test_disjoint_ownership_under_membership_churn_during_split():
    """Rendezvous election under churn DURING a split: an incarnation
    dies mid-transition and a new one joins, and at every tick across
    the topology epoch bump no two incarnations own overlapping node
    ranges — in particular never parent AND child simultaneously."""
    world = _World()
    a = world.incarnation("inc-a")
    b = world.incarnation("inc-b")
    try:
        world.settle(3)
        _assert_disjoint_ownership(world)
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
            chaos=world.chaos,
            lifecycle=world.lifecycle,
        )
        parent = ctrl.pick_split_candidate()
        # crash the first attempt so the transition window really opens
        # and closes under churn (rollback path crosses the epoch bump)
        world.chaos.arm("shard.split_crash", times=1)
        assert ctrl.split(parent) is None
        # membership churn immediately after the rolled-back attempt:
        # the incarnation owning the parent's range dies…
        victim = world.owner_of(parent) or a
        victim.kill()
        _assert_disjoint_ownership(world)
        # …and a fresh one joins while the retry executes
        c = world.incarnation("inc-c")
        for _ in range(2):
            world.settle(1)
            _assert_disjoint_ownership(world)
        out = ctrl.split(parent)
        assert out is not None
        ca, cb = out["children"]
        # across the epoch bump: every tick stays disjoint, and the
        # children end up owned while the parent is owned by NOBODY
        for _ in range(6):
            world.settle(1)
            _assert_disjoint_ownership(world)
            for inc in world.live():
                assert parent not in inc.owned()
        assert world.owner_of(ca) is not None
        assert world.owner_of(cb) is not None
    finally:
        world.close()


# ---------------------------------------------------------------------------
# Satellite: router spill hysteresis
# ---------------------------------------------------------------------------


def test_router_spill_hysteresis_damps_backlog_flapping():
    m = ShardMap(4)
    backlog = {"v": 0}
    # the hysteresis band is PER-PRIMARY: probe for free pods that all
    # route to the same primary so every call exercises one band
    probe = ShardRouter(m)
    pods, i = [], 0
    primary = None
    while len(pods) < 48:
        p = _pod(f"flap-{i:04d}")
        i += 1
        s = probe.route(p)
        if primary is None:
            primary = s
        if s == primary:
            pods.append(p)

    def flips_over(router, group):
        flips, prev, states = 0, None, []
        for j, p in enumerate(group):
            backlog["v"] = 8 if j % 2 == 0 else 7
            fanned = len(
                router.targets(p, backlog_of=lambda s: backlog["v"])
            ) > 1
            states.append(fanned)
            if prev is not None and fanned != prev:
                flips += 1
            prev = fanned
        return flips, states

    # WITHOUT hysteresis (resume at the same threshold) a backlog
    # oscillating around the threshold toggles fan-out per pod —
    # repeatedly fanning pods out and churning claims/tombstones
    naive = ShardRouter(m, spill_backlog=8, spill_resume_frac=1.0)
    flips_naive, _ = flips_over(naive, pods[:20])
    assert flips_naive >= 10, "the flapping baseline must actually flap"

    # WITH hysteresis (default resume at half the threshold) the same
    # oscillation engages once and STAYS engaged — no claim churn
    router = ShardRouter(m, spill_backlog=8)
    flips, states = flips_over(router, pods[20:40])
    assert flips <= 1
    assert states[-1], "spill stays engaged inside the band"
    # …and disengages once the backlog genuinely drains below resume
    backlog["v"] = 2
    assert len(
        router.targets(pods[40], backlog_of=lambda s: backlog["v"])
    ) == 1


# ---------------------------------------------------------------------------
# SLO-burn-driven controller
# ---------------------------------------------------------------------------


def test_topology_controller_burn_driven_split_merge_and_scaling():
    clock = [0.0]
    fabric = ShardFabric(2, clock=lambda: clock[0])
    slo = SloTracker(clock=lambda: clock[0])
    names = [f"n{i:03d}" for i in range(24)]

    class _StubInc:
        dead = False

        def owns(self, _shard):
            return False

    spawned, retired = [], []
    ctrl = TopologyController(
        fabric,
        slo=slo,
        incarnations=lambda: spawned,
        node_names=lambda: names,
        sustain=3,
        cooldown=4,
        shards_per_incarnation=2,
        spawn=lambda: spawned.append(_StubInc()),
        retire=lambda: retired.append(spawned.pop()),
    )
    # burn one shard hot (queue-age violations), keep the other quiet
    hot = 0
    for _ in range(8):
        slo.observe_queue_age(hot, 60.0)   # way past the 5 s target
    assert ctrl.shard_burn(hot) > 1.0
    # sustain gate: no split until `sustain` consecutive hot ticks
    actions = ctrl.tick() + ctrl.tick()
    assert not any(a["op"] == "split" for a in actions)
    acted = ctrl.tick()
    splits = [a for a in acted if a["op"] == "split"]
    assert len(splits) == 1 and splits[0]["parent"] == hot
    assert ctrl.stats["splits"] == 1
    ca, cb = splits[0]["children"]
    assert fabric.shard_map.is_active(ca)
    # cooldown: the children stay cold but cannot merge immediately
    actions = ctrl.tick()
    assert not any(a["op"] == "merge" for a in actions)
    # after cooldown + sustained cold, the siblings merge back
    merged = None
    for _ in range(12):
        acted = ctrl.tick()
        for act in acted:
            if act["op"] == "merge":
                merged = act
    assert merged is not None and merged["merged"] in (
        fabric.shard_map.active_shards()
    )
    assert ctrl.stats["merges"] == 1
    # incarnation scaling tracked ceil(active/2) throughout
    assert spawned and ctrl.stats["spawned"] >= 1


def test_controller_refuses_a_split_that_would_mint_an_empty_child():
    fabric = ShardFabric(2)
    # ONE node: any split of its shard leaves an empty side
    only = "n000"
    shard = fabric.shard_map.shard_of_node(only)
    ctrl = TopologyController(
        fabric, incarnations=lambda: [], node_names=lambda: [only]
    )
    assert ctrl.split(shard) is None
    assert ctrl.stats["skipped"] == 1
    assert fabric.topology.generation == 0


# ---------------------------------------------------------------------------
# Validator arms + /topology endpoint
# ---------------------------------------------------------------------------


def test_validate_timeline_demands_a_bridge_across_shard_split():
    ok = [
        LifecycleEvent("submit", 0.0),
        LifecycleEvent("route", 0.1, shard=1),
        LifecycleEvent("enqueue", 0.2, shard=1),
        LifecycleEvent("handoff", 0.5, shard=1),
        LifecycleEvent("shard_split", 0.5, shard=1, detail="gen1:1->4/5"),
        LifecycleEvent("resubmit", 0.6, shard=4),
        LifecycleEvent("dispatch", 0.7, shard=4),
        LifecycleEvent("decide", 0.8, shard=4, detail="n1"),
        LifecycleEvent("ack", 0.9, shard=4, detail="n1"),
    ]
    assert validate_timeline(ok) == []
    # a dispatch straight across the split — no resubmit bridge — fails
    gap = [e for e in ok if e.stage != "resubmit"]
    problems = validate_timeline(gap)
    assert any("shard_split" in p for p in problems)
    # same arm for merges
    gap_merge = [
        LifecycleEvent("submit", 0.0),
        LifecycleEvent("enqueue", 0.2, shard=4),
        LifecycleEvent("shard_merge", 0.5, shard=4),
        LifecycleEvent("ack", 0.9, shard=6, detail="n1"),
    ]
    problems = validate_timeline(gap_merge)
    assert any("shard_merge" in p for p in problems)


def test_fleet_topology_endpoint_serves_the_live_generation():
    world = _World()
    a = world.incarnation("inc-a")
    try:
        world.settle(3)
        ctrl = TopologyController(
            world.fabric,
            incarnations=world.live,
            node_names=lambda: world.node_names,
        )
        parent = ctrl.pick_split_candidate()
        out = ctrl.split(parent)
        assert out is not None
        code, body = a.fleet().dispatch("GET", "/topology")
        assert code == 200
        doc = json.loads(body)
        assert doc["generation"] == 1
        assert doc["base_shards"] == N_SHARDS
        assert sorted(out["children"]) == [
            s for s in doc["active"] if s not in range(N_SHARDS)
        ]
        assert doc["open_transition"] is None
        assert any(
            r.get("op") == "split_commit" for r in doc["history"]
        )
    finally:
        world.close()
