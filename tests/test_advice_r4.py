"""Regressions for the round-3 advisor findings (ADVICE.md r3).

1. (medium) statehub cross-informer ordering: a bind parked because its
   node had not yet reached the snapshot must drain from the SAME
   informer thread that applies the node — a separate drain informer can
   consume the node event first and strand the bind forever.
2. (low) batch_solver defer_preemption: a pod helped by quota preemption
   must not ALSO nominate a disjoint priority-preemption victim set in
   the same cycle.
3. (low) coscheduling permit: the gang-free early return must recognize
   the native gang annotation, not just the legacy label.
4. (low) elasticquota sync_status stamps guaranteed / allocated /
   child-request like the reference controller.
5. (low) statehub reservation informer applies spec UPDATES, not only
   adds/deletes.
"""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
    ReservationOwner,
    ReservationPhase,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.runtime.statehub import ClusterStateHub
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.reservation import ReservationManager


def _node(name, cpu=64000, mem=262144):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
    )


def _bound_pod(name, node, cpu=4000):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, node_name=node
        ),
    )


def test_bind_before_node_drains_on_snapshot_informer():
    """A pod bound to a node the snapshot has not seen yet parks; when the
    node lands, the drain runs on the SAME node informer (registration
    order after upsert_node), so the charge appears — and no independent
    drain informer exists to race."""
    snap = ClusterSnapshot()
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    hub = ClusterStateHub()
    hub.wire_scheduler(sched)
    # exactly ONE informer watches the nodes tracker: the snapshot node
    # informer now carrying the drain handlers (the racing drain informer
    # is gone)
    assert sum(1 for inf in hub.informers if inf.tracker is hub.nodes) == 1
    hub.start()
    try:
        # bind FIRST (node unknown → parked), node second
        hub.publish(hub.pods, _bound_pod("early", "n0", cpu=4000))
        hub.publish(hub.nodes, _node("n0"))
        assert hub.wait_synced()
        idx = snap.node_id("n0")
        assert idx is not None
        assert snap.nodes.requested[idx, 0] == 4000.0
    finally:
        hub.stop()


def test_reservation_spec_update_via_informer():
    """A Reservation republished with changed requests must take effect
    (previously only add/delete did): the old hold is released and the
    new spec re-enters as PENDING."""
    snap = ClusterSnapshot()
    snap.upsert_node(_node("n0"))
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    hub = ClusterStateHub()
    hub.wire_scheduler(sched, reservations=rm)
    hub.start()
    try:
        r1 = Reservation(
            meta=ObjectMeta(name="resv"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096},
            owners=[ReservationOwner(label_selector={"app": "t"})],
        )
        hub.publish(hub.reservations, r1)
        assert hub.wait_synced()
        assert rm.get("resv").requests[ext.RES_CPU] == 4000

        # spec change: double the request
        r2 = Reservation(
            meta=ObjectMeta(name="resv"),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192},
            owners=[ReservationOwner(label_selector={"app": "t"})],
        )
        hub.publish(hub.reservations, r2)
        assert hub.wait_synced()
        assert rm.get("resv").requests[ext.RES_CPU] == 8000
        assert rm.get("resv").phase == ReservationPhase.PENDING

        # status-only republication (same spec object content) is a no-op
        r3 = Reservation(
            meta=ObjectMeta(name="resv"),
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 8192},
            owners=[ReservationOwner(label_selector={"app": "t"})],
        )
        r3.phase = ReservationPhase.AVAILABLE
        r3.node_name = "n0"
        before = rm.get("resv")
        hub.publish(hub.reservations, r3)
        assert hub.wait_synced()
        assert rm.get("resv") is before
    finally:
        hub.stop()


def _quota(name, minv, maxv, weight):
    from koordinator_tpu.api.types import ElasticQuota

    return ElasticQuota(
        meta=ObjectMeta(name=name),
        min={ext.RES_CPU: minv[0], ext.RES_MEMORY: minv[1]},
        max={ext.RES_CPU: maxv[0], ext.RES_MEMORY: maxv[1]},
        shared_weight={ext.RES_CPU: weight[0], ext.RES_MEMORY: weight[1]},
    )


def test_defer_preemption_no_double_nomination():
    """defer mode + priority preemption both on: a pod whose quota
    preemption already nominated victims must NOT also nominate a
    (disjoint) priority victim set in the same cycle."""
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    snap.upsert_node(_node("n0", cpu=12, mem=400))
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    mgr.upsert_quota(_quota("team-a", (6, 6), (6, 400), (1, 1)))
    mgr.upsert_quota(_quota("team-b", (6, 6), (400, 400), (1, 1)))
    sched = BatchScheduler(
        snap,
        quotas=mgr,
        defer_preemption=True,
        enable_priority_preemption=True,
    )
    sched.extender.monitor.stop_background()

    def qpod(name, q, cpu, prio):
        return Pod(
            meta=ObjectMeta(name=name, labels={ext.LABEL_QUOTA_NAME: q}),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu},
                priority=prio,
            ),
        )

    # node full (12/12) AND team-a at max (6/6)
    a_low = qpod("a-low", "team-a", 6.0, 5000)
    b_low = qpod("b-low", "team-b", 6.0, 4000)
    assert len(sched.schedule([a_low, b_low]).bound) == 2

    high = qpod("high", "team-a", 6.0, 9500)
    out = sched.schedule([high])
    # deferred: nothing binds this cycle, ONE victim set is nominated —
    # the quota preemptor's (a-low). Without the fix the priority pass
    # would also nominate b-low (its reprieve keeps the higher-priority
    # a-low), over-evicting through the migration controller.
    assert out.bound == []
    assert [v.meta.name for v in out.preempted] == ["a-low"]


def test_permit_native_gang_annotation_not_bypassed():
    """permit()'s gang-free early return must detect the NATIVE gang
    annotation: an all-or-nothing gang with a failed member rejects the
    placed member even when no gang state was pre-created."""
    from koordinator_tpu.scheduler.plugins.coscheduling import PodGroupManager

    pgm = PodGroupManager()

    def gpod(name, node):
        return (
            Pod(
                meta=ObjectMeta(
                    name=name,
                    annotations={
                        ext.ANNOTATION_GANG_NAME: "g1",
                        ext.ANNOTATION_GANG_MIN_AVAILABLE: "2",
                    },
                ),
                spec=PodSpec(requests={ext.RES_CPU: 1000}),
            ),
            node,
        )

    allowed, rejected = pgm.permit([gpod("m0", "n0"), gpod("m1", None)])
    assert allowed == []
    assert {p.meta.name for p in rejected} == {"m0", "m1"}


def test_sync_status_stamps_guaranteed_allocated_child_request():
    """Reference updateElasticQuotaStatusIfChanged stamps runtime,
    request, child-request, guaranteed and allocated
    (quota_info.go:62-67: leaf allocated = admitted usage; guaranteed =
    max(allocated, min); parent allocated = Σ children guaranteed)."""
    import json

    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100}
    )
    parent = ElasticQuota(
        meta=ObjectMeta(name="root-q"),
        min={ext.RES_CPU: 40, ext.RES_MEMORY: 40},
        max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
        is_parent=True,
    )
    child = ElasticQuota(
        meta=ObjectMeta(name="leaf-q"),
        min={ext.RES_CPU: 10, ext.RES_MEMORY: 10},
        max={ext.RES_CPU: 50, ext.RES_MEMORY: 50},
        parent="root-q",
    )
    mgr.upsert_quota(parent)
    mgr.upsert_quota(child)
    # admit 20 cpu of usage into the leaf
    mgr.set_leaf_requests(
        {"leaf-q": snap.config.res_vector({ext.RES_CPU: 20, ext.RES_MEMORY: 20})}
    )
    mgr.refresh_runtime()
    li = mgr.index_of("leaf-q")
    mgr.used[li] = snap.config.res_vector(
        {ext.RES_CPU: 20, ext.RES_MEMORY: 20}
    )

    report = mgr.sync_status()
    # leaf: allocated = used (20); guaranteed = max(20, min 10) = 20
    assert report["leaf-q"]["allocated"][ext.RES_CPU] == 20.0
    assert report["leaf-q"]["guaranteed"][ext.RES_CPU] == 20.0
    # parent: allocated = child guaranteed (20); guaranteed = max(20, 40) = 40
    assert report["root-q"]["allocated"][ext.RES_CPU] == 20.0
    assert report["root-q"]["guaranteed"][ext.RES_CPU] == 40.0
    # parent child-request = leaf's rolled-up request
    assert report["root-q"]["childRequest"][ext.RES_CPU] == 20.0
    # annotations stamped with the wire keys
    ann = parent.meta.annotations
    assert json.loads(ann[ext.ANNOTATION_QUOTA_GUARANTEED])[ext.RES_CPU] == 40.0
    assert json.loads(ann[ext.ANNOTATION_QUOTA_ALLOCATED])[ext.RES_CPU] == 20.0
    assert ext.ANNOTATION_QUOTA_CHILD_REQUEST in ann


def test_limit_request_propagation_caps_at_child_max():
    """Reference recursiveUpdateGroupTreeWithDeltaRequest
    (group_quota_manager.go:196-224): what a quota demands from its
    parent is min(request, max) — a child requesting over its own max
    must not inflate its parent's share against a sibling tree."""
    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100}
    )

    def quota(name, maxv, parent=""):
        return ElasticQuota(
            meta=ObjectMeta(name=name),
            min={ext.RES_CPU: 0, ext.RES_MEMORY: 0},
            max={ext.RES_CPU: maxv, ext.RES_MEMORY: maxv},
            parent=parent,
            is_parent=not parent,
        )

    # two sibling parents under the root pool; pa's only child is capped
    # at max 20 but demands 90
    mgr.upsert_quota(quota("pa", 100))
    mgr.upsert_quota(quota("pb", 100))
    mgr.upsert_quota(quota("leaf-a", 20, parent="pa"))
    mgr.upsert_quota(quota("leaf-b", 100, parent="pb"))
    mgr.set_leaf_requests(
        {
            "leaf-a": snap.config.res_vector(
                {ext.RES_CPU: 90, ext.RES_MEMORY: 90}
            ),
            "leaf-b": snap.config.res_vector(
                {ext.RES_CPU: 90, ext.RES_MEMORY: 90}
            ),
        }
    )
    rt = mgr.refresh_runtime()
    # pa's effective demand is 20 (leaf-a's limitRequest), so pb gets the
    # rest of the pool — not a 50/50 inflated split
    assert rt[mgr.index_of("pa")][0] <= 21.0
    assert rt[mgr.index_of("pb")][0] >= 79.0
    # the leaf's own request/childRequest stay uncapped (raw pod demand);
    # the parent sees only the capped propagation
    report = mgr.sync_status()
    assert report["leaf-a"]["childRequest"][ext.RES_CPU] == 90.0
    assert report["leaf-a"]["request"][ext.RES_CPU] == 90.0
    assert report["pa"]["request"][ext.RES_CPU] == 20.0


def test_pods_on_non_leaf_quota_still_counted():
    """A pod labeled with a PARENT quota (nothing forbids that) must
    contribute to that quota's request — the bottom-up propagation reads
    every level's own direct demand (the reference's SelfRequest), not
    just childless quotas'."""
    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100}
    )
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="par"),
            min={ext.RES_CPU: 0, ext.RES_MEMORY: 0},
            max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
            is_parent=True,
        )
    )
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="kid"),
            min={ext.RES_CPU: 0, ext.RES_MEMORY: 0},
            max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
            parent="par",
        )
    )
    mgr.set_leaf_requests(
        {
            "par": snap.config.res_vector({ext.RES_CPU: 30, ext.RES_MEMORY: 30}),
            "kid": snap.config.res_vector({ext.RES_CPU: 10, ext.RES_MEMORY: 10}),
        }
    )
    rt = mgr.refresh_runtime()
    # par's demand = own 30 + kid's 10
    assert mgr.requests[mgr.index_of("par")][0] == 40.0
    assert rt[mgr.index_of("par")][0] >= 40.0


def test_device_allocate_batch_mixed_fractional_and_whole():
    """A batch mixing a fractional-GPU pod (fallback path, which rebinds
    the node's free lists) and a whole-GPU pod (lean path) on one node
    must keep one coherent accounting view — the lean path re-hoists
    after every fallback."""
    from koordinator_tpu.api.types import Device, DeviceInfo
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    snap.upsert_node(_node("g0", cpu=128000, mem=1 << 20))
    dm = DeviceManager(snap)
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="g0"),
            devices=[DeviceInfo(dev_type="gpu", minor=m) for m in range(3)],
        )
    )
    res = dm.allocate_batch(
        uids=["frac", "whole"],
        annotations=[{}, {}],
        node_names=["g0", "g0"],
        whole_l=[0, 2],
        share_l=[50.0, 0.0],
        rdma_l=[0, 0],
        fpga_l=[0, 0],
        requests_l=[None, None],
    )
    assert res[0] is not None and res[1] is not None
    st = dm._nodes["g0"]
    # the fractional pod holds 50% of one minor, the whole pod holds the
    # two OTHER minors entirely: exactly one minor at 50, two at 0
    assert sorted(st.gpu_free) == [0.0, 0.0, 50.0]
    frac_minor = st.owners["frac"][0][0]
    whole_minors = {p[0] for p in st.owners["whole"]}
    assert frac_minor not in whole_minors
    # a third whole-GPU pod must now fail — nothing fully free remains
    res2 = dm.allocate_batch(
        uids=["late"],
        annotations=[{}],
        node_names=["g0"],
        whole_l=[1],
        share_l=[0.0],
        rdma_l=[0],
        fpga_l=[0],
        requests_l=[None],
    )
    assert res2[0] is None


def test_guaranteed_allocated_counts_parent_direct_usage():
    """A parent quota's own direct pod usage (pods labeled with the
    parent) must appear in its allocated/guaranteed — not only the
    children's rollup (quota_info.go:62-67 + this tree's SelfRequest
    support)."""
    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100}
    )
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="par"),
            min={ext.RES_CPU: 5, ext.RES_MEMORY: 5},
            max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
            is_parent=True,
        )
    )
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="kid"),
            min={ext.RES_CPU: 0, ext.RES_MEMORY: 0},
            max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
            parent="par",
        )
    )
    # charge 10 into the child and 30 DIRECTLY into the parent
    mgr.charge("kid", {ext.RES_CPU: 10, ext.RES_MEMORY: 10})
    mgr.charge("par", {ext.RES_CPU: 30, ext.RES_MEMORY: 30})
    guaranteed, allocated = mgr.guaranteed_allocated()
    pi = mgr.index_of("par")
    # parent allocated = child guaranteed (10) + own direct used (30)
    assert allocated[pi][0] == 40.0
    assert guaranteed[pi][0] == 40.0


def test_shared_weight_wire_annotation_overrides():
    """AnnotationSharedWeight (elastic_quota.go:95-105): a valid non-zero
    JSON resource list on the quota object overrides the typed field."""
    import json

    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 90, ext.RES_MEMORY: 90}
    )
    for name, weight in (("wa", 1.0), ("wb", 2.0)):
        q = ElasticQuota(
            meta=ObjectMeta(
                name=name,
                annotations={
                    ext.ANNOTATION_QUOTA_SHARED_WEIGHT: json.dumps(
                        {ext.RES_CPU: weight, ext.RES_MEMORY: weight}
                    )
                },
            ),
            min={ext.RES_CPU: 0, ext.RES_MEMORY: 0},
            max={ext.RES_CPU: 90, ext.RES_MEMORY: 90},
        )
        mgr.upsert_quota(q)
    mgr.set_leaf_requests(
        {
            "wa": snap.config.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
            "wb": snap.config.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
        }
    )
    rt = mgr.refresh_runtime()
    # demand 90+90 over 90 total, weights 1:2 → 30 / 60
    np.testing.assert_allclose(rt[mgr.index_of("wa")][0], 30.0, atol=1.5)
    np.testing.assert_allclose(rt[mgr.index_of("wb")][0], 60.0, atol=1.5)
