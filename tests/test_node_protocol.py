"""Node-level protocol annotations: reservation trim + custom usage
thresholds (reference ``apis/extension/node_reservation.go`` +
``apis/extension/load_aware.go`` / ``pkg/util/node.go``
TrimNodeAllocatableByNodeReservation)."""

import json

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.scheduler.batch_solver import BatchScheduler


def mknode(name, cpu=32000, mem=65536, annotations=None):
    return Node(
        meta=ObjectMeta(name=name, annotations=annotations or {}),
        status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
    )


def test_node_reservation_trims_allocatable():
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "n0",
            annotations={
                ext.ANNOTATION_NODE_RESERVATION: json.dumps(
                    {"resources": {ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}}
                )
            },
        )
    )
    idx = snap.node_id("n0")
    assert snap.nodes.allocatable[idx, 0] == 28000.0
    assert snap.nodes.allocatable[idx, 1] == 57344.0


def test_node_reservation_reserved_cpus_override():
    """reservedCPUs overrides the cpu quantity (GetNodeReservationResources:
    cpuset size wins) and ReservedCPUsOnly does not trim."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "n0",
            annotations={
                ext.ANNOTATION_NODE_RESERVATION: json.dumps(
                    {"resources": {ext.RES_CPU: 2000}, "reservedCPUs": "0-5"}
                )
            },
        )
    )
    assert snap.nodes.allocatable[snap.node_id("n0"), 0] == 26000.0  # 6 cpus
    snap.upsert_node(
        mknode(
            "n1",
            annotations={
                ext.ANNOTATION_NODE_RESERVATION: json.dumps(
                    {"reservedCPUs": "0-5", "applyPolicy": "ReservedCPUsOnly"}
                )
            },
        )
    )
    assert snap.nodes.allocatable[snap.node_id("n1"), 0] == 32000.0


def test_node_reservation_malformed_ignored():
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode("n0", annotations={ext.ANNOTATION_NODE_RESERVATION: "[broken"})
    )
    assert snap.nodes.allocatable[snap.node_id("n0"), 0] == 32000.0


def set_usage(snap, name, cpu_pct):
    idx = snap.node_id(name)
    alloc = snap.nodes.allocatable[idx]
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name=name),
            node_usage=ResourceMetric(
                usage={
                    ext.RES_CPU: alloc[0] * cpu_pct / 100,
                    ext.RES_MEMORY: alloc[1] * 0.1,
                }
            ),
            update_time=1000.0,
        ),
        now=1010.0,
    )


def test_custom_usage_thresholds_per_node():
    """A node carrying the usage-thresholds annotation filters with ITS
    threshold while others keep the plugin-args global (load_aware.go
    GetCustomUsageThresholds). Both nodes sit at 55% cpu: the global 65
    admits, the custom 50 rejects."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "strict",
            annotations={
                ext.ANNOTATION_CUSTOM_USAGE_THRESHOLDS: json.dumps(
                    {"usageThresholds": {ext.RES_CPU: 50}}
                )
            },
        )
    )
    snap.upsert_node(mknode("lax"))
    set_usage(snap, "strict", 55)
    set_usage(snap, "lax", 55)
    sched = BatchScheduler(snap, batch_bucket=128)
    sched.extender.monitor.stop_background()
    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 100, ext.RES_MEMORY: 64}, priority=9000
            ),
        )
        for i in range(4)
    ]
    out = sched.schedule(pods)
    assert len(out.bound) == 4
    assert {n for _, n in out.bound} == {"lax"}


def test_node_reservation_quantity_strings_dropped():
    """Code-review regression: non-numeric reservation values (k8s
    quantity strings) must not crash upsert_node — they're dropped."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "n0",
            annotations={
                ext.ANNOTATION_NODE_RESERVATION: json.dumps(
                    {"resources": {ext.RES_CPU: "300m", ext.RES_MEMORY: 1024}}
                )
            },
        )
    )
    idx = snap.node_id("n0")
    assert snap.nodes.allocatable[idx, 0] == 32000.0   # bad value dropped
    assert snap.nodes.allocatable[idx, 1] == 64512.0   # numeric one applied
    # non-dict resources / non-string reservedCPUs degrade safely too
    snap.upsert_node(
        mknode(
            "n1",
            annotations={
                ext.ANNOTATION_NODE_RESERVATION: json.dumps(
                    {"resources": 5, "reservedCPUs": 7}
                )
            },
        )
    )
    assert snap.nodes.allocatable[snap.node_id("n1"), 0] == 32000.0


def test_custom_thresholds_replace_wholesale():
    """Code-review regression: a non-empty custom map supersedes the
    global thresholds WHOLESALE — memory goes unchecked on the node whose
    custom map only names cpu."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "custom",
            annotations={
                ext.ANNOTATION_CUSTOM_USAGE_THRESHOLDS: json.dumps(
                    {"usageThresholds": {ext.RES_CPU: 90}}
                )
            },
        )
    )
    idx = snap.node_id("custom")
    alloc = snap.nodes.allocatable[idx]
    # memory at 99% (over the global 95), cpu at 10%
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name="custom"),
            node_usage=ResourceMetric(
                usage={
                    ext.RES_CPU: alloc[0] * 0.10,
                    ext.RES_MEMORY: alloc[1] * 0.99,
                }
            ),
            update_time=1000.0,
        ),
        now=1010.0,
    )
    sched = BatchScheduler(snap, batch_bucket=128)
    sched.extender.monitor.stop_background()
    pod = Pod(
        meta=ObjectMeta(name="p"),
        spec=PodSpec(requests={ext.RES_CPU: 100, ext.RES_MEMORY: 1}, priority=9000),
    )
    out = sched.schedule([pod])
    assert len(out.bound) == 1  # memory dim unchecked on this node


def test_per_node_reclaim_ratio_and_strategy_override():
    """node_colocation.go: the reclaim-ratio labels and the
    colocation-strategy annotation override the cluster strategy per
    node."""
    from koordinator_tpu.manager.noderesource import (
        ColocationStrategy,
        NodeResourceController,
    )

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("plain"))
    snap.upsert_node(
        Node(
            meta=ObjectMeta(
                name="tight",
                labels={ext.LABEL_CPU_RECLAIM_RATIO: "0.5"},
            ),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    snap.upsert_node(
        mknode(
            "off",
            annotations={
                ext.ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
                    {"enable": False}
                )
            },
        )
    )
    for name in ("plain", "tight", "off"):
        set_usage(snap, name, 10)
    ctl = NodeResourceController(snap, ColocationStrategy(reserve_ratio=0.1))
    batch, _mid = ctl.calculate()
    plain, tight, off = (
        snap.node_id("plain"), snap.node_id("tight"), snap.node_id("off")
    )
    # plain keeps 90% of cpu for colocation, tight only 50%
    assert batch[plain, 0] > batch[tight, 0] > 0
    assert batch[tight, 0] < 32000 * 0.55
    assert batch[off, 0] == 0 and batch[off, 1] == 0
    # illegal label value is ignored
    assert ext.parse_reclaim_ratio({ext.LABEL_CPU_RECLAIM_RATIO: "junk"},
                                   ext.LABEL_CPU_RECLAIM_RATIO) is None
    assert ext.parse_reclaim_ratio({ext.LABEL_CPU_RECLAIM_RATIO: "1.5"},
                                   ext.LABEL_CPU_RECLAIM_RATIO) is None


def test_disable_preemptible_label():
    """preemption.go:28: the disable-preemptible label opts a pod out of
    preemption victimhood."""
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        is_pod_non_preemptible,
    )

    p = Pod(meta=ObjectMeta(name="v"), spec=PodSpec())
    assert not is_pod_non_preemptible(p)
    p.meta.labels[ext.LABEL_DISABLE_PREEMPTIBLE] = "true"
    assert is_pod_non_preemptible(p)


def test_node_enable_true_overrides_cluster_disable():
    """Code-review regression: '{\"enable\": true}' on a node re-enables
    colocation past a cluster-wide disable (the annotation takes
    precedence in BOTH directions)."""
    from koordinator_tpu.manager.noderesource import (
        ColocationStrategy,
        NodeResourceController,
    )

    snap = ClusterSnapshot()
    snap.upsert_node(mknode("plain"))
    snap.upsert_node(
        mknode(
            "optin",
            annotations={
                ext.ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
                    {"enable": True}
                )
            },
        )
    )
    for name in ("plain", "optin"):
        set_usage(snap, name, 10)
    ctl = NodeResourceController(snap, ColocationStrategy(enable=False))
    batch, _ = ctl.calculate()
    assert batch[snap.node_id("plain"), 0] == 0.0
    assert batch[snap.node_id("optin"), 0] > 0.0


def test_bool_threshold_value_dropped():
    """Code-review regression: a bool in the custom-thresholds map (an int
    subclass) must be dropped, not treated as 1%."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        mknode(
            "n0",
            annotations={
                ext.ANNOTATION_CUSTOM_USAGE_THRESHOLDS: json.dumps(
                    {"usageThresholds": {ext.RES_CPU: True}}
                )
            },
        )
    )
    assert snap.nodes.custom_thresholds[snap.node_id("n0")].sum() == 0.0
