"""Informer layer tests (the pkg/client analog): list+watch, lister cache,
event handlers, re-list on disconnect, overflow recovery, periodic resync,
and the ClusterSnapshot-fed-by-informers composition."""

import threading
import time

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.utils.informer import (
    ADDED,
    DELETED,
    Informer,
    ObjectTracker,
)


def wait_until(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_informer_basic_watch_flow():
    tracker = ObjectTracker()
    inf = Informer(tracker)
    events = []
    inf.add_handlers(
        on_add=lambda k, o: events.append(("add", k)),
        on_update=lambda k, o: events.append(("upd", k)),
        on_delete=lambda k, o: events.append(("del", k)),
    )
    rv0 = tracker.upsert("a", 1)          # pre-existing object
    inf.start()
    try:
        assert inf.wait_synced(rv0)
        assert inf.get("a") == 1
        rv = tracker.upsert("b", 2)
        tracker.upsert("b", 3)
        rv = tracker.delete("a")
        assert inf.wait_synced(rv)
        assert inf.get("a") is None and inf.get("b") == 3
        assert ("add", "a") in events and ("add", "b") in events
        assert ("upd", "b") in events and ("del", "a") in events
    finally:
        inf.stop()


def test_relist_on_disconnect_converges():
    """Killing every watch mid-stream (apiserver disconnect) must trigger
    a re-list that reconciles whatever changed while blind."""
    tracker = ObjectTracker()
    inf = Informer(tracker)
    deletes = []
    inf.add_handlers(on_delete=lambda k, o: deletes.append(k))
    rv = tracker.upsert("a", 1)
    tracker.upsert("b", 1)
    inf.start()
    try:
        assert wait_until(lambda: inf.get("b") == 1)
        # disconnect; mutate the world while no watch is open
        tracker.close_all_watches()
        tracker.delete("a")
        rv = tracker.upsert("c", 9)
        assert wait_until(lambda: inf.get("c") == 9 and inf.get("a") is None)
        assert "a" in deletes            # diff-delivered by the re-list
        assert inf.relists >= 2
    finally:
        inf.stop()


def test_watch_overflow_forces_relist():
    """A watcher that falls behind (queue overflow) is closed and must
    re-list — it still converges, never silently drops to a stale view."""
    tracker = ObjectTracker()
    inf = Informer(tracker)
    inf.start()
    try:
        assert wait_until(lambda: inf.relists >= 1)
        # burst far past the watch queue capacity before the consumer
        # thread can drain
        for i in range(5000):
            tracker.upsert(f"k{i % 50}", i)
        final_rv = tracker.upsert("sentinel", "done")
        assert inf.wait_synced(final_rv, timeout=30)
        assert inf.get("sentinel") == "done"
        objs, rv = tracker.list()
        assert set(inf.keys()) == set(objs)
    finally:
        inf.stop()


def test_periodic_resync_redelivers_cache():
    tracker = ObjectTracker()
    inf = Informer(tracker, resync_interval_s=0.05)
    seen = []
    inf.add_handlers(on_update=lambda k, o: seen.append(k))
    tracker.upsert("a", 1)
    inf.start()
    try:
        assert wait_until(lambda: seen.count("a") >= 3, timeout=10)
    finally:
        inf.stop()


def test_snapshot_fed_by_informers():
    """The scheduler-side composition: Node and Pod informers keep a
    ClusterSnapshot in sync — including across a disconnect — and the
    snapshot's accounting matches the tracker's world exactly."""
    from koordinator_tpu.core.snapshot import ClusterSnapshot

    nodes = ObjectTracker()
    pods = ObjectTracker()
    snap = ClusterSnapshot()
    lock = threading.Lock()

    def on_node(key, node):
        with lock:
            snap.upsert_node(node)

    def on_node_del(key, node):
        with lock:
            snap.remove_node(node.meta.name)

    def on_pod(key, pod):
        with lock:
            snap.assume_pod(pod, pod.spec.node_name)

    def on_pod_del(key, pod):
        with lock:
            snap.forget_pod(pod.meta.uid)

    ninf = Informer(nodes)
    ninf.add_handlers(on_add=on_node, on_update=on_node, on_delete=on_node_del)
    # the pod informer may observe a pod BEFORE the node informer delivers
    # its node (assume_pod returns False, no charge); the periodic resync
    # re-delivers the cached pods as updates so the assume self-heals —
    # the same level-triggered recovery shared informers give the
    # reference's controllers
    pinf = Informer(pods, resync_interval_s=0.1)
    pinf.add_handlers(on_add=on_pod, on_update=on_pod, on_delete=on_pod_del)
    ninf.start()
    pinf.start()
    try:
        for i in range(4):
            nodes.upsert(
                f"n{i}",
                Node(
                    meta=ObjectMeta(name=f"n{i}"),
                    status=NodeStatus(
                        allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                    ),
                ),
            )
        rv = None
        for i in range(12):
            rv = pods.upsert(
                f"default/p{i}",
                Pod(
                    meta=ObjectMeta(name=f"p{i}"),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024},
                        node_name=f"n{i % 4}",
                    ),
                ),
            )
        assert pinf.wait_synced(rv)
        assert wait_until(lambda: snap.node_count == 4)
        # disconnect both informers; churn while blind
        nodes.close_all_watches()
        pods.close_all_watches()
        nodes.delete("n3")
        for i in range(3):
            pods.delete(f"default/p{i}")
        rv = pods.upsert(
            "default/extra",
            Pod(
                meta=ObjectMeta(name="extra"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 2000, ext.RES_MEMORY: 2048},
                    node_name="n0",
                ),
            ),
        )
        assert pinf.wait_synced(rv, timeout=30)
        assert wait_until(lambda: snap.node_count == 3)

        def converged():
            with lock:
                world, _ = pods.list()
                want = {}
                for pod in world.values():
                    if snap.node_id(pod.spec.node_name) is None:
                        continue
                    idx = snap.node_id(pod.spec.node_name)
                    vec = snap.config.res_vector(pod.spec.requests)
                    want[idx] = want.get(idx, 0) + vec[0]
                for idx in range(snap.nodes.n_real):
                    got = float(snap.nodes.requested[idx][0])
                    if abs(got - want.get(idx, 0.0)) > 1e-3:
                        return False
                return True

        assert wait_until(converged, timeout=30)
    finally:
        ninf.stop()
        pinf.stop()
