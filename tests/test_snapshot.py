"""ClusterSnapshot incremental-update tests (analog of the reference's
scheduler cache / podAssignCache unit tests)."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot, bucket_size


def mknode(name, cpu=64000, mem=256 * 1024):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
    )


def mkpod(name, cpu=1000, mem=2048, prio=9500):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}, priority=prio),
    )


def test_bucket_size():
    assert bucket_size(1) == 128
    assert bucket_size(128) == 128
    assert bucket_size(129) == 256
    assert bucket_size(5000) == 8192


def test_upsert_and_metric():
    snap = ClusterSnapshot()
    idx = snap.upsert_node(mknode("n1", cpu=32000))
    assert snap.node_id("n1") == idx
    assert snap.nodes.allocatable[idx][0] == 32000
    assert snap.nodes.schedulable[idx]

    metric = NodeMetric(
        meta=ObjectMeta(name="n1"),
        node_usage=ResourceMetric(usage={ext.RES_CPU: 8000}),
        aggregated={"p95": ResourceMetric(usage={ext.RES_CPU: 10000})},
        update_time=1000.0,
    )
    snap.set_node_metric(metric, now=1030.0)
    assert snap.nodes.usage_avg[idx][0] == 8000
    assert snap.nodes.usage_agg[idx][0] == 10000
    assert snap.nodes.metric_fresh[idx]

    # expiry (reference load_aware.go:143-149 degraded mode)
    snap.set_node_metric(metric, now=1000.0 + 400.0)
    assert not snap.nodes.metric_fresh[idx]


def test_assume_forget_roundtrip():
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n1"))
    pod = mkpod("p1", cpu=2000, mem=4096)
    snap.assume_pod(pod, "n1")
    idx = snap.node_id("n1")
    assert snap.nodes.requested[idx][0] == 2000
    assert snap.nodes.assigned_pending[idx][0] == 2000
    snap.forget_pod(pod.meta.uid)
    assert snap.nodes.requested[idx][0] == 0
    assert snap.nodes.assigned_pending[idx][0] == 0


def test_metric_report_absorbs_only_prior_assumptions():
    """Pods assumed before the report's update_time are absorbed into the
    reported usage; later assumptions keep contributing
    (reference load_aware.go:315-358)."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n1"))
    idx = snap.node_id("n1")
    snap.assume_pod(mkpod("p-before"), "n1", now=90.0)
    snap.assume_pod(mkpod("p-after"), "n1", now=105.0)
    assert snap.nodes.assigned_pending[idx][0] == 2000
    snap.set_node_metric(
        NodeMetric(meta=ObjectMeta(name="n1"), update_time=100.0), now=110.0
    )
    # only p-before (assumed at t=90 < report t=100) is absorbed
    assert snap.nodes.assigned_pending[idx][0] == 1000
    # forgetting the absorbed pod must not drive pending negative
    snap.forget_pod(mkpod("p-before").meta.uid)
    assert snap.nodes.assigned_pending[idx][0] == 1000
    assert snap.nodes.requested[idx][0] == 1000
    snap.forget_pod(mkpod("p-after").meta.uid)
    assert snap.nodes.assigned_pending[idx][0] == 0
    assert snap.nodes.requested[idx][0] == 0


def test_prod_pending_tracked_separately():
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n1"))
    idx = snap.node_id("n1")
    snap.assume_pod(mkpod("prod-pod", prio=9500), "n1")
    snap.assume_pod(mkpod("batch-pod", prio=5500), "n1")
    assert snap.nodes.assigned_pending[idx][0] == 2000
    assert snap.nodes.assigned_pending_prod[idx][0] == 1000


def test_remove_node_purges_assumed_entries():
    """forget_pod after remove_node must not corrupt a reused slot."""
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n1"))
    pod = mkpod("p1", cpu=2000)
    snap.assume_pod(pod, "n1")
    snap.remove_node("n1")
    i3 = snap.upsert_node(mknode("n3"))
    snap.forget_pod(pod.meta.uid)  # stale forget: must be a no-op
    assert snap.nodes.requested[i3][0] == 0
    assert snap.nodes.assigned_pending[i3][0] == 0


def test_remove_node_and_slot_reuse():
    snap = ClusterSnapshot()
    snap.upsert_node(mknode("n1"))
    snap.upsert_node(mknode("n2"))
    i1 = snap.node_id("n1")
    snap.remove_node("n1")
    assert snap.node_id("n1") is None
    assert not snap.nodes.schedulable[i1]
    i3 = snap.upsert_node(mknode("n3"))
    assert i3 == i1  # slot reused
    assert snap.node_name(i3) == "n3"


def test_node_growth_past_bucket():
    snap = ClusterSnapshot()
    for i in range(300):
        snap.upsert_node(mknode(f"n{i}"))
    assert snap.node_count == 300
    assert snap.nodes.allocatable.shape[0] == 512
    assert snap.nodes.schedulable[:300].all()
    assert not snap.nodes.schedulable[300:].any()


def test_build_pods_gangs_and_padding():
    snap = ClusterSnapshot()
    pods = [mkpod(f"p{i}") for i in range(5)]
    pods[1].meta.labels[ext.LABEL_GANG_NAME] = "g1"
    pods[3].meta.labels[ext.LABEL_GANG_NAME] = "g1"
    pods[4].meta.labels[ext.LABEL_GANG_NAME] = "g2"
    arr = snap.build_pods(pods)
    assert arr.requests.shape[0] == 128
    assert arr.valid[:5].all() and not arr.valid[5:].any()
    assert arr.gang_id[1] == arr.gang_id[3] != arr.gang_id[4]
    assert arr.gang_id[0] == -1
    assert (arr.prio_class[:5] == int(ext.PriorityClass.PROD)).all()


def test_node_constraint_masks_enforced():
    """nodeSelector / required node-affinity / spec.nodeName restrict
    placement (upstream NodeAffinity+NodeName Filter semantics folded into
    the solver's feasibility mask)."""
    import jax

    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = ClusterSnapshot()
    for i, pool in enumerate(["cpu", "cpu", "gpu", "gpu"]):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}", labels={"pool": pool}),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                ),
            )
        )
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def pod(name, **spec_kw):
        return Pod(
            meta=ObjectMeta(name=name),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024},
                priority=9000,
                **spec_kw,
            ),
        )

    out = sched.schedule(
        [
            pod("sel", node_selector={"pool": "gpu"}),
            pod("named", node_name="n1"),
            pod("aff", affinity_required_nodes=["n0", "n3"]),
            pod("impossible", node_selector={"pool": "tpu"}),
            pod("free"),
        ]
    )
    nodes_of = {p.meta.name: n for p, n in out.bound}
    assert nodes_of["sel"] in ("n2", "n3")
    assert nodes_of["named"] == "n1"
    assert nodes_of["aff"] in ("n0", "n3")
    assert "impossible" not in nodes_of
    assert [p.meta.name for p in out.unschedulable] == ["impossible"]
    assert "free" in nodes_of


def test_device_resources_on_dense_axis_still_parsed():
    """Code-review regression: when a deployment appends a device resource
    to SnapshotConfig.resources, build_pods must both write the dense dim
    AND surface the device request (gpu_whole) to the device manager."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot, SnapshotConfig

    cfg = SnapshotConfig(resources=ext.DEFAULT_RESOURCES + (ext.RES_GPU,))
    snap = ClusterSnapshot(cfg)
    pod = Pod(
        meta=ObjectMeta(name="g"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 64, ext.RES_GPU: 2},
            priority=9000,
        ),
    )
    arrays = snap.build_pods([pod])
    gpu_dim = cfg.resources.index(ext.RES_GPU)
    assert arrays.requests[0, gpu_dim] == 2.0
    assert arrays.gpu_whole[0] == 2
