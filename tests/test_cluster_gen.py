"""Region-scale fleet generator (first-class multichip PR satellite):
the columnar path must stay fast enough for 100k–1M-node fixtures inside
tier-1, cohorts must be region-contiguous and heterogeneous, and the
object materialization of any one region must be bit-consistent with
the columns it came from."""

import time

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.sim.cluster_gen import (
    FLEET_SHAPES,
    FleetConfig,
    gen_fleet_arrays,
    gen_fleet_pod_arrays,
    gen_region_nodes,
)


def test_fleet_arrays_shape_and_speed_at_scale():
    t0 = time.perf_counter()
    f = gen_fleet_arrays(FleetConfig(n_nodes=1_000_000, n_regions=16))
    elapsed = time.perf_counter() - t0
    n = 1_000_000
    assert f["allocatable"].shape == (n, 2)
    assert f["allocatable"].dtype == np.float32
    assert f["estimated_used"].shape == (n, 2)
    assert f["prod_used"].shape == (n, 2)
    assert f["schedulable"].shape == (n,)
    assert f["region_bounds"].shape == (17,)
    # columnar, not per-object: 1M nodes must generate in seconds — the
    # whole point vs the gen_nodes object path (minutes at this scale)
    assert elapsed < 30.0, f"1M-node fleet took {elapsed:.1f}s"
    # sane physics: usage below allocatable, prod below estimate
    assert (f["estimated_used"] <= f["allocatable"]).all()
    assert (f["prod_used"] <= f["estimated_used"]).all()


def test_region_cohorts_contiguous_and_heterogeneous():
    cfg = FleetConfig(n_nodes=100_000, n_regions=8, seed=3)
    f = gen_fleet_arrays(cfg)
    b = f["region_bounds"]
    assert b[0] == 0 and b[-1] == cfg.n_nodes
    for r in range(cfg.n_regions):
        lo, hi = int(b[r]), int(b[r + 1])
        assert hi > lo
        assert (f["region"][lo:hi] == r).all()
    # every fleet shape appears somewhere, and the per-region shape
    # mixes differ (dirichlet tilt: regions are plausible, not clones)
    assert set(np.unique(f["shape_id"])) == set(range(len(FLEET_SHAPES)))
    mixes = [
        np.bincount(
            f["shape_id"][int(b[r]) : int(b[r + 1])],
            minlength=len(FLEET_SHAPES),
        )
        for r in range(cfg.n_regions)
    ]
    assert any(not np.array_equal(mixes[0], m) for m in mixes[1:])
    # utilization skew tilts region means across the fleet
    util = f["estimated_used"][:, 0] / f["allocatable"][:, 0]
    means = [
        util[int(b[r]) : int(b[r + 1])].mean() for r in range(cfg.n_regions)
    ]
    assert max(means) - min(means) > cfg.region_util_skew
    # a cordoned sliver exists but stays a sliver
    unsched = (~f["schedulable"]).mean()
    assert 0.0 < unsched < 0.05


def test_gen_region_nodes_matches_columns():
    cfg = FleetConfig(n_nodes=2_000, n_regions=4, seed=7)
    f = gen_fleet_arrays(cfg)
    region = 2
    nodes, metrics = gen_region_nodes(cfg, region, arrays=f)
    lo, hi = int(f["region_bounds"][region]), int(f["region_bounds"][region + 1])
    assert len(nodes) == len(metrics) == hi - lo
    for j, i in enumerate(range(lo, hi)):
        assert nodes[j].meta.name == f"r02-node-{i:07d}"
        assert nodes[j].status.allocatable[ext.RES_CPU] == float(
            f["allocatable"][i, 0]
        )
        assert nodes[j].status.allocatable[ext.RES_MEMORY] == float(
            f["allocatable"][i, 1]
        )
        # p95 aggregate in the metric reproduces the estimated_used column
        p95 = metrics[j].aggregated["p95"].usage
        np.testing.assert_allclose(
            [p95[ext.RES_CPU], p95[ext.RES_MEMORY]],
            f["estimated_used"][i],
            rtol=1e-5,
        )


def test_fleet_pod_arrays_mix():
    cfg = FleetConfig(seed=1)
    p = gen_fleet_pod_arrays(cfg, 50_000)
    assert p["requests"].shape == (50_000, 2)
    assert p["requests"].dtype == np.float32
    assert set(np.unique(p["requests"][:, 0])) == {500.0, 1000.0, 2000.0, 4000.0}
    # prod pods ride the prod priority band, batch the batch band
    assert (p["priority"][p["is_prod"]] >= 9000).all()
    assert (p["priority"][~p["is_prod"]] < 6000).all()
    assert 0.25 < p["is_prod"].mean() < 0.35


def test_fleet_node_state_feeds_solver():
    """End-to-end: the 100k-node fleet table drives one real solver
    batch and places pods (the loadaware_100k_nodes scenario's shape,
    one pass, small round budget — tier-1 fast)."""
    import jax.numpy as jnp

    from koordinator_tpu.ops.solver import (
        PodBatch,
        SolverParams,
        assign,
    )
    from koordinator_tpu.sim.cluster_gen import fleet_node_state

    cfg = FleetConfig(n_nodes=100_000)
    nodes = fleet_node_state(cfg)
    assert int(nodes.allocatable.shape[0]) >= 100_000
    fix = gen_fleet_pod_arrays(cfg, 256)
    pods = PodBatch.create(
        requests=fix["requests"], estimate=fix["estimate"],
        priority=fix["priority"], is_prod=fix["is_prod"],
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray((65.0, 95.0), jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )
    r = assign(pods, nodes, params, max_rounds=4, approx_topk=True)
    a = np.asarray(r.assignment)
    assert a.shape == (256,)
    assert int((a >= 0).sum()) > 0, "fleet placed no pods"
