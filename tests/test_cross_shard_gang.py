"""Cross-shard gang scheduling tests (elastic-topology PR).

The PR 6 router routes gangs whole to a home shard, so a gang whose
feasible nodes SPAN shards was unplaceable. The two-phase
claim-then-commit protocol fixes that: phase 1 takes all-or-nothing
ClaimTable HOLDS on every member, phase 2 schedules each shard's
members as a local sub-gang and commits the holds into claims — or
aborts, unbinding partial placements and dropping every hold.

Covers: the ClaimTable hold protocol (all-or-nothing prepare, rival
claims lose against holds, commit→claims, abort→fully claimable again,
epoch fencing, CRASHED claim phase leaves zero holds on reload); and
the end-to-end coordinator (a gang pinned across two shards places
all-or-nothing; an infeasible member aborts the WHOLE gang with zero
zombie holds and zero residual binds).
"""

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.journal import (
    ClaimTable,
    MemoryJournalStore,
    StaleEpochError,
)
from koordinator_tpu.runtime.elastic import CrossShardGangCoordinator
from koordinator_tpu.runtime.shards import (
    ShardedScheduler,
    ShardFabric,
    ShardRouter,
)
from koordinator_tpu.runtime.statehub import ClusterStateHub
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

N_SHARDS = 3
N_NODES = 18


# ---------------------------------------------------------------------------
# ClaimTable: the two-phase hold protocol
# ---------------------------------------------------------------------------


def test_gang_prepare_is_all_or_nothing_and_holds_beat_rivals():
    t = ClaimTable()
    assert t.gang_prepare("g1", {"u1": 0, "u2": 1}, {0: 1, 1: 1})
    assert t.gang_holds() == 2
    # the holder shard's own feed-time claim proceeds; rivals lose
    assert t.claim("u1", 0, 1) is True
    assert t.claim("u1", 2, 1) is False
    # a second gang touching a held member is refused with ZERO holds
    assert not t.gang_prepare("g2", {"u2": 2, "u3": 2}, {2: 1})
    assert t.gang_holds("g2") == 0 and t.gang_holds() == 2
    # an already-claimed pod can only be prepared on its winning shard
    assert t.claim("w1", 2, 1)
    assert not t.gang_prepare("g3", {"w1": 0}, {0: 1})
    assert t.gang_prepare("g4", {"w1": 2}, {2: 1})


def test_gang_commit_converts_holds_to_claims():
    store = MemoryJournalStore()
    t = ClaimTable(store)
    assert t.gang_prepare("g1", {"u1": 0, "u2": 1}, {0: 1, 1: 1})
    t.gang_commit("g1")
    assert t.gang_holds() == 0
    assert t.winner("u1") == 0 and t.winner("u2") == 1
    # committed claims survive a reload (ordinary claim semantics from
    # here: release tombstones at pod GC, etc.)
    t2 = ClaimTable(store)
    assert t2.winner("u1") == 0 and t2.gang_holds() == 0
    assert t2.claim("u1", 1, 1) is False


def test_gang_abort_leaves_members_fully_claimable():
    t = ClaimTable()
    assert t.gang_prepare("g1", {"u1": 0, "u2": 1}, {0: 1, 1: 1})
    t.gang_abort("g1")
    assert t.gang_holds() == 0
    # no tombstone: an aborted member is NOT settled — any shard may
    # claim it for the retry
    assert t.claim("u1", 2, 1) is True
    assert t.winner("u2") is None


def test_crashed_claim_phase_leaves_zero_holds_on_reload():
    store = MemoryJournalStore()
    t = ClaimTable(store)
    assert t.gang_prepare("g1", {"u1": 0, "u2": 1, "u3": 2}, {0: 1, 1: 1, 2: 1})
    assert t.gang_holds() == 3
    # the claiming coordinator DIES here: a fresh table over the same
    # store must see a hold record with no commit — and drop it
    t2 = ClaimTable(store)
    assert t2.gang_holds() == 0
    assert t2.claim("u1", 2, 1) is True  # members claimable again
    # …while a committed gang in the same store would have survived
    assert t2.winner("u2") is None


def test_gang_prepare_is_epoch_fenced_per_shard():
    t = ClaimTable()
    t.claim("x", 3, 5)  # shard 3's claim-epoch high is now 5
    with pytest.raises(StaleEpochError):
        t.gang_prepare("g1", {"u1": 3}, {3: 4})
    assert t.gang_holds() == 0
    # missing epoch for an involved shard is refused outright
    with pytest.raises(StaleEpochError):
        t.gang_prepare("g2", {"u2": 7}, {})


def test_gang_holds_survive_tombstone_gc():
    store = MemoryJournalStore()
    t = ClaimTable(store, clock=lambda: 100.0)
    t.claim("old", 0, 1)
    t.release("old")  # tombstoned at t=100
    assert t.gang_prepare("g1", {"u1": 1}, {1: 1})
    t2 = ClaimTable(store, clock=lambda: 10_000.0)
    # (reload drops the uncommitted hold per crash semantics; exercise
    # GC on the ORIGINAL table where the hold is live)
    live = t.gc_tombstones(retention_s=60.0, now=10_000.0)
    assert live == 0
    assert t.gang_holds() == 1, "GC must not drop live gang holds"
    t.gang_commit("g1")
    assert t.winner("u1") == 1


# ---------------------------------------------------------------------------
# End-to-end: a gang spanning shards places all-or-nothing
# ---------------------------------------------------------------------------


def _node(name, cpu=32_000.0, mem=128 * 1024.0):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _gang_pod(name, gang, node=None, cpu=2000.0, mem=4096.0):
    return Pod(
        meta=ObjectMeta(
            name=name,
            namespace="team",
            annotations={
                ext.ANNOTATION_GANG_NAME: gang,
                ext.ANNOTATION_GANG_MIN_AVAILABLE: "3",
                ext.ANNOTATION_GANG_TOTAL_NUM: "3",
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
            priority=9000,
            node_name=node,
        ),
    )


def _make_scheduler(shard, snapshot, fence, journal):
    s = BatchScheduler(
        snapshot,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=16,
        journal=journal,
        fence=fence,
    )
    s.extender.monitor.stop_background()
    return s


class _World:
    def __init__(self):
        self.t = [0.0]
        self.fabric = ShardFabric(
            N_SHARDS, clock=lambda: self.t[0], membership_ttl_s=2.5
        )
        self.hub = ClusterStateHub()
        self.node_names = [f"n{i:03d}" for i in range(N_NODES)]
        for name in self.node_names:
            self.hub.publish(self.hub.nodes, _node(name))
        self.incs = []

    def incarnation(self, name):
        inc = ShardedScheduler(
            name,
            self.hub,
            self.fabric,
            _make_scheduler,
            pipelined=False,
            max_batch=32,
            max_retries=3,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
        )
        self.fabric.membership.heartbeat(name)
        self.incs.append(inc)
        return inc

    def settle(self, ticks=3):
        for _ in range(ticks):
            self.t[0] += 1.0
            for inc in self.incs:
                if not inc.dead:
                    inc.tick()

    def owner_of(self, shard):
        for inc in self.incs:
            if not inc.dead and inc.owns(shard):
                return inc
        return None

    def nodes_on(self, shard, count):
        return [
            n
            for n in self.node_names
            if self.fabric.shard_map.shard_of_node(n) == shard
        ][:count]

    def close(self):
        for inc in self.incs:
            if not inc.dead:
                inc.close()
        self.hub.stop()


def _drive_gang(world, coord, ticket, publish=True, rounds=10):
    """Pump until the ticket completes; the driver publishes bound
    members (the bind-API ack) and reports every decision."""
    verdict = None
    bound_nodes = {}
    for _ in range(rounds):
        for inc in world.incs:
            if inc.dead:
                continue
            for s, pod, node, _lat in inc.pump() + inc.flush():
                if node is not None:
                    bound_nodes[pod.meta.uid] = (s, node)
                    if publish:
                        pod.spec.node_name = node
                        world.hub.publish(world.hub.pods, pod)
                v = coord.note(ticket, pod.meta.uid, node)
                if v is not None:
                    verdict = v
        world.settle(1)
        if verdict is not None:
            break
    return verdict, bound_nodes


def _requested_cpu(world):
    """Total requested batch-CPU across every owned shard snapshot."""
    total = 0.0
    for inc in world.incs:
        if inc.dead:
            continue
        for s in inc.owned():
            rt = inc.runtime(s)
            if rt is not None:
                total += float(rt.sched.snapshot.nodes.requested.sum())
    return total


def test_cross_shard_gang_places_all_or_nothing_and_commits():
    world = _World()
    world.incarnation("inc-a")
    world.incarnation("inc-b")
    try:
        world.settle(3)
        # pin members across two DIFFERENT shards — the configuration
        # the gang-home router cannot place at all
        shards = world.fabric.shard_map.active_shards()
        sa, sb = shards[0], shards[1]
        na = world.nodes_on(sa, 2)
        nb = world.nodes_on(sb, 1)
        assert len(na) == 2 and len(nb) == 1
        pods = [
            _gang_pod("g-m0", "span", node=na[0]),
            _gang_pod("g-m1", "span", node=na[1]),
            _gang_pod("g-m2", "span", node=nb[0]),
        ]
        router = ShardRouter(world.fabric.shard_map)
        coord = CrossShardGangCoordinator(
            world.fabric, router, world.owner_of
        )
        ticket = coord.begin(pods)
        assert ticket is not None
        assert set(ticket.members.values()) == {sa, sb}, "gang spans shards"
        assert world.fabric.claims.gang_holds() == 3
        verdict, bound = _drive_gang(world, coord, ticket)
        assert verdict is True, f"gang must fully place, got {ticket.decided}"
        assert coord.finish(ticket) is True
        # holds became ordinary claims on the binding shards
        assert world.fabric.claims.gang_holds() == 0
        for uid, shard in ticket.members.items():
            assert world.fabric.claims.winner(uid) == shard
        # every member on its pinned node
        assert {n for _s, n in bound.values()} == set(na) | set(nb)
        assert coord.stats["placed"] == 1
    finally:
        world.close()


def test_cross_shard_gang_aborts_whole_with_zero_zombie_state():
    world = _World()
    world.incarnation("inc-a")
    world.incarnation("inc-b")
    try:
        world.settle(3)
        shards = world.fabric.shard_map.active_shards()
        sa, sb = shards[0], shards[1]
        na = world.nodes_on(sa, 2)
        nb = world.nodes_on(sb, 1)
        base_cpu = _requested_cpu(world)
        pods = [
            _gang_pod("g-m0", "doomed", node=na[0]),
            _gang_pod("g-m1", "doomed", node=na[1]),
            # infeasible member: requests more CPU than any node has
            _gang_pod("g-m2", "doomed", node=nb[0], cpu=64_000.0),
        ]
        router = ShardRouter(world.fabric.shard_map)
        coord = CrossShardGangCoordinator(
            world.fabric, router, world.owner_of
        )
        ticket = coord.begin(pods)
        assert ticket is not None
        verdict, bound = _drive_gang(world, coord, ticket)
        assert verdict is False, "an infeasible member fails the gang"
        unbound = []

        def unbind(pod, shard, node):
            # the driver's bind-API delete: releases snapshot/journal
            # charges through the ordinary informer fan-out
            world.hub.delete(world.hub.pods, pod)
            unbound.append((pod.meta.uid, shard, node))

        assert coord.finish(ticket, unbind=unbind) is False
        # the unbind deletes release through the informer fan-out —
        # wait for delivery before reading the snapshots
        assert world.hub.wait_synced()
        world.settle(1)
        # ZERO zombie holds, ZERO residual claims, ZERO residual binds
        assert world.fabric.claims.gang_holds() == 0
        for p in pods:
            assert world.fabric.claims.winner(p.meta.uid) is None
        assert len(unbound) == len(
            [u for u, n in ticket.decided.items() if n is not None]
        )
        assert _requested_cpu(world) == pytest.approx(base_cpu)
        # the abort restored every member to its ORIGINAL gang shape —
        # a retry must route and size by the true gang, not a first
        # attempt's sub-group residue
        from koordinator_tpu.scheduler.plugins.coscheduling import (
            gang_key_of,
        )

        for p in pods:
            assert gang_key_of(p) == "team/doomed"
            assert (
                p.meta.annotations[ext.ANNOTATION_GANG_MIN_AVAILABLE]
                == "3"
            )
        # …and the aborted members are RE-PLACEABLE: the two feasible
        # ones re-enter as a plain 2-member gang and bind
        retry = [
            _gang_pod("r-m0", "retry", node=na[0]),
            _gang_pod("r-m1", "retry", node=na[1]),
        ]
        for p in retry:
            p.meta.annotations[ext.ANNOTATION_GANG_MIN_AVAILABLE] = "2"
            p.meta.annotations[ext.ANNOTATION_GANG_TOTAL_NUM] = "2"
        ticket2 = coord.begin(retry)
        assert ticket2 is not None
        verdict2, _ = _drive_gang(world, coord, ticket2)
        assert verdict2 is True and coord.finish(ticket2) is True
    finally:
        world.close()


def test_gang_submit_refusal_still_drains_to_abort_with_zero_holds():
    """An owner can lose its shard between begin()'s ownership check
    and the submit (lease lapse / step-down). The refused members are
    marked terminally undecided so the ticket still completes and
    finish() aborts through the ordinary path — zero zombie holds, the
    already-submitted members unbound."""
    world = _World()
    world.incarnation("inc-a")
    world.incarnation("inc-b")
    try:
        world.settle(3)
        shards = world.fabric.shard_map.active_shards()
        sa, sb = shards[0], shards[1]
        na = world.nodes_on(sa, 1)
        nb = world.nodes_on(sb, 1)
        pods = [
            _gang_pod("g-m0", "lost-owner", node=na[0]),
            _gang_pod("g-m1", "lost-owner", node=nb[0]),
        ]
        for p in pods:
            p.meta.annotations[ext.ANNOTATION_GANG_MIN_AVAILABLE] = "2"
            p.meta.annotations[ext.ANNOTATION_GANG_TOTAL_NUM] = "2"

        class _FlakyOwner:
            """Looks owned at check time, refuses the submit."""

            def __init__(self, real):
                self.real = real

            def runtime(self, shard):
                return self.real.runtime(shard)

            def submit(self, shard, pod, now=None):
                return False

        def owner_of(shard):
            real = world.owner_of(shard)
            if shard == sb and real is not None:
                return _FlakyOwner(real)
            return real

        router = ShardRouter(world.fabric.shard_map)
        coord = CrossShardGangCoordinator(world.fabric, router, owner_of)
        ticket = coord.begin(pods)
        assert ticket is not None
        # the refused member is already terminally undecided
        uid_b = pods[1].meta.uid
        assert ticket.decided.get(uid_b, "") is None
        verdict, _bound = _drive_gang(world, coord, ticket)
        assert verdict is False
        unbound = []
        assert coord.finish(
            ticket,
            unbind=lambda p, s, n: (
                world.hub.delete(world.hub.pods, p),
                unbound.append(p.meta.uid),
            ),
        ) is False
        assert world.fabric.claims.gang_holds() == 0
        for p in pods:
            assert world.fabric.claims.winner(p.meta.uid) is None
    finally:
        world.close()


def test_gang_refused_when_a_member_shard_is_ownerless():
    world = _World()
    world.incarnation("inc-a")
    try:
        world.settle(1)  # some shards may still be ownerless
        # force an ownerless member shard by killing the only owner
        world.incs[0].kill()
        shards = world.fabric.shard_map.active_shards()
        na = world.nodes_on(shards[0], 1)
        nb = world.nodes_on(shards[1], 1)
        pods = [
            _gang_pod("g-m0", "nobody", node=na[0]),
            _gang_pod("g-m1", "nobody", node=nb[0]),
        ]
        router = ShardRouter(world.fabric.shard_map)
        coord = CrossShardGangCoordinator(
            world.fabric, router, world.owner_of
        )
        assert coord.begin(pods) is None
        assert world.fabric.claims.gang_holds() == 0, "zero holds on refusal"
        assert coord.stats["refused"] == 1
    finally:
        world.close()
