"""Randomized equivalence of the vectorized node-constraint mask (built
from the snapshot's inverted label index) against a straightforward
per-pod / per-node reference implementation, plus the scanned-dispatch
contract: chunks carrying node constraints now thread their lowered
[C, P, N] masks through solve_stream_full instead of bailing to the
per-chunk path."""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.snapshot import ClusterSnapshot, bucket_size
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs


def _random_cluster(rng, n_nodes):
    snap = ClusterSnapshot()
    zones = ["zone-a", "zone-b", "zone-c"]
    tiers = ["gold", "silver"]
    for i in range(n_nodes):
        labels = {}
        if rng.random() < 0.8:
            labels["topology.kubernetes.io/zone"] = zones[
                rng.integers(0, len(zones))
            ]
        if rng.random() < 0.5:
            labels["node.koordinator.sh/tier"] = tiers[
                rng.integers(0, len(tiers))
            ]
        if rng.random() < 0.2:
            labels["gpu"] = "true"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:03d}", labels=labels),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                ),
            )
        )
    return snap


def _random_pods(rng, snap, n_pods):
    names = [snap.node_name(i) for i in range(snap.node_count)]
    pods = []
    for i in range(n_pods):
        kind = rng.integers(0, 6)
        spec = PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024}, priority=9000
        )
        if kind == 0:
            spec.node_name = names[rng.integers(0, len(names))]
        elif kind == 1:
            spec.node_name = "no-such-node"
        elif kind == 2:
            spec.affinity_required_nodes = [
                names[j]
                for j in rng.choice(
                    len(names), size=rng.integers(1, 5), replace=False
                )
            ]
        elif kind == 3:
            spec.node_selector = {
                "topology.kubernetes.io/zone": ["zone-a", "zone-b", "zone-x"][
                    rng.integers(0, 3)
                ]
            }
        elif kind == 4:
            spec.node_selector = {
                "topology.kubernetes.io/zone": "zone-a",
                "node.koordinator.sh/tier": "gold",
            }
            if rng.random() < 0.5:
                spec.node_name = names[rng.integers(0, len(names))]
        # kind == 5: unconstrained
        pods.append(Pod(meta=ObjectMeta(name=f"p{i:04d}"), spec=spec))
    return pods


def _reference_mask(sched, chunk, p_bucket):
    """The pre-vectorization semantics: per-pod × per-node walk over the
    live label dicts (node_allowed's logic, applied row by row)."""
    snap = sched.snapshot
    n_bucket = snap.nodes.allocatable.shape[0]
    mask = np.ones((p_bucket, n_bucket), bool)
    for i, pod in enumerate(chunk):
        spec = pod.spec
        if not (
            spec.node_selector
            or spec.affinity_required_nodes
            or spec.node_name
        ):
            continue
        row = np.zeros((n_bucket,), bool)
        for name, j in snap._node_index.items():
            if spec.node_name and name != spec.node_name:
                continue
            if (
                not spec.node_name
                and spec.affinity_required_nodes is not None
                and name not in set(spec.affinity_required_nodes)
            ):
                continue
            labels = snap.node_labels(name)
            if all(
                labels.get(k) == v for k, v in spec.node_selector.items()
            ):
                row[j] = True
        mask[i] = row
    return mask


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_vectorized_mask_matches_reference(seed):
    rng = np.random.default_rng(seed)
    snap = _random_cluster(rng, n_nodes=60)
    sched = BatchScheduler(snap, LoadAwareArgs())
    sched.extender.monitor.stop_background()
    # exercise label churn and node removal so the eagerly-maintained
    # bitmaps must track updates, not just the initial lazy build
    pods = _random_pods(rng, snap, n_pods=80)
    p_bucket = bucket_size(len(pods), snap.config.min_bucket)
    got = np.asarray(sched._node_constraint_mask(pods, p_bucket))
    want = _reference_mask(sched, pods, p_bucket)
    np.testing.assert_array_equal(got, want)

    # mutate: relabel some nodes, remove one, add one — masks must follow
    for i in (3, 7, 11):
        name = snap.node_name(i)
        snap.upsert_node(
            Node(
                meta=ObjectMeta(
                    name=name,
                    labels={"topology.kubernetes.io/zone": "zone-c"},
                ),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                ),
            )
        )
    snap.remove_node(snap.node_name(5))
    snap.upsert_node(
        Node(
            meta=ObjectMeta(
                name="late-node",
                labels={"node.koordinator.sh/tier": "gold", "gpu": "true"},
            ),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    got = np.asarray(sched._node_constraint_mask(pods, p_bucket))
    want = _reference_mask(sched, pods, p_bucket)
    np.testing.assert_array_equal(got, want)


def test_vectorized_mask_with_window():
    rng = np.random.default_rng(7)
    snap = _random_cluster(rng, n_nodes=40)
    sched = BatchScheduler(snap, LoadAwareArgs())
    sched.extender.monitor.stop_background()
    pods = _random_pods(rng, snap, n_pods=30)
    p_bucket = bucket_size(len(pods), snap.config.min_bucket)
    sub = np.asarray(sorted(rng.choice(40, size=17, replace=False)), np.int32)
    got = np.asarray(sched._node_constraint_mask(pods, p_bucket, sub))
    want_full = _reference_mask(sched, pods, p_bucket)
    b = bucket_size(len(sub), snap.config.min_bucket)
    want = np.zeros((p_bucket, b), bool)
    want[:, : len(sub)] = want_full[:, sub]
    np.testing.assert_array_equal(got, want)


def _constrained_setup():
    snap = ClusterSnapshot()
    for i in range(32):
        labels = {"topology.kubernetes.io/zone": "zone-a" if i < 16 else "zone-b"}
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:03d}", labels=labels),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            )
        )
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=64)
    sched.extender.monitor.stop_background()
    pods = []
    for i in range(160):
        spec = PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048}, priority=9000
        )
        if i % 3 == 0:
            spec.node_selector = {"topology.kubernetes.io/zone": "zone-a"}
        elif i % 7 == 0:
            spec.node_name = f"n{i % 32:03d}"
        pods.append(Pod(meta=ObjectMeta(name=f"p{i:04d}"), spec=spec))
    return sched, pods


def test_scanned_dispatch_handles_node_constraints():
    """_dispatch_scanned must no longer return None for constrained
    chunks, and its placements must equal the per-chunk pipelined path's
    (same assign, same carried state — the mask just rides the scan)."""
    a, pods_a = _constrained_setup()
    engaged = []
    orig = a._dispatch_scanned

    def spy(chunks, sub=None):
        r = orig(chunks, sub)
        engaged.append(r is not None)
        return r

    a._dispatch_scanned = spy
    out_a = a.schedule(pods_a)
    assert engaged == [True], engaged

    b, pods_b = _constrained_setup()
    b._dispatch_scanned = lambda chunks, sub=None: None
    out_b = b.schedule(pods_b)

    assert {p.meta.name: n for p, n in out_a.bound} == {
        p.meta.name: n for p, n in out_b.bound
    }
    assert sorted(p.meta.name for p in out_a.unschedulable) == sorted(
        p.meta.name for p in out_b.unschedulable
    )
    # selector semantics must actually bind: zone-a pods only on zone-a
    for p, node in out_a.bound:
        if p.spec.node_selector:
            assert int(node[1:]) < 16, (p.meta.name, node)
        if p.spec.node_name:
            assert node == p.spec.node_name
