"""Annotation/priority protocol tests (reference table-driven style,
``apis/extension/priority_test.go`` / ``qos_test.go``)."""

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import PriorityClass, QoSClass


def test_priority_bands():
    cases = [
        (9000, PriorityClass.PROD),
        (9999, PriorityClass.PROD),
        (7500, PriorityClass.MID),
        (5000, PriorityClass.BATCH),
        (5999, PriorityClass.BATCH),
        (3000, PriorityClass.FREE),
        (8999, PriorityClass.NONE),
        (0, PriorityClass.NONE),
        (None, PriorityClass.NONE),
    ]
    for prio, want in cases:
        assert PriorityClass.from_priority(prio) is want, (prio, want)


def test_qos_parse_and_defaults():
    assert QoSClass.parse("LS") is QoSClass.LS
    assert QoSClass.parse("lsr") is QoSClass.LSR
    assert QoSClass.parse("bogus") is QoSClass.NONE
    assert QoSClass.parse(None) is QoSClass.NONE
    assert ext.qos_for_priority(PriorityClass.BATCH) is QoSClass.BE
    assert ext.qos_for_priority(PriorityClass.PROD) is QoSClass.LS
    assert ext.qos_for_priority(PriorityClass.NONE) is QoSClass.NONE


def test_qos_strictness_order():
    assert QoSClass.SYSTEM > QoSClass.LSE > QoSClass.LSR > QoSClass.LS > QoSClass.BE


def test_parse_gpu_partition_spec_malformed_payloads():
    """Malformed user annotations must degrade to defaults, never crash the
    scheduling cycle (mirrors parse_reservation_affinity's guards)."""
    key = ext.ANNOTATION_GPU_PARTITION_SPEC
    assert ext.parse_gpu_partition_spec({}) == (False, 0.0)
    assert ext.parse_gpu_partition_spec({key: "not json"}) == (False, 0.0)
    assert ext.parse_gpu_partition_spec({key: "[1]"}) == (False, 0.0)
    assert ext.parse_gpu_partition_spec({key: '"str"'}) == (False, 0.0)
    assert ext.parse_gpu_partition_spec(
        {key: '{"ringBusBandwidth": "fast"}'}
    ) == (False, 0.0)
    assert ext.parse_gpu_partition_spec(
        {key: '{"ringBusBandwidth": null}'}
    ) == (False, 0.0)
    assert ext.parse_gpu_partition_spec(
        {key: '{"allocatePolicy": "Restricted", "ringBusBandwidth": 200}'}
    ) == (True, 200.0)


def test_reservation_ignored_and_allocated_annotations():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec

    p = Pod(meta=ObjectMeta(name="x"), spec=PodSpec())
    assert not ext.is_reservation_ignored(p)
    p.meta.labels[ext.LABEL_RESERVATION_IGNORED] = "true"
    assert ext.is_reservation_ignored(p)


def test_custom_estimated_scaling_factors():
    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import SnapshotConfig
    from koordinator_tpu.ops.estimator import estimate_pod, scale_vector

    cfg = SnapshotConfig()
    scale = scale_vector(cfg.resources, {})
    pod = Pod(
        meta=ObjectMeta(
            name="p",
            annotations={
                ext.ANNOTATION_CUSTOM_ESTIMATED_SCALING_FACTORS: (
                    '{"%s": 100}' % ext.RES_CPU
                )
            },
        ),
        spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 1024}),
    )
    est = estimate_pod(cfg, pod, scale)
    cpu_dim = cfg.resources.index(ext.RES_CPU)
    mem_dim = cfg.resources.index(ext.RES_MEMORY)
    assert est[cpu_dim] == 4000.0          # 100% override, not the 85% default
    assert est[mem_dim] == round(1024 * 0.7)  # memory keeps the default factor
    # unparseable annotation falls back to defaults
    pod.meta.annotations[ext.ANNOTATION_CUSTOM_ESTIMATED_SCALING_FACTORS] = "bogus"
    est2 = estimate_pod(cfg, pod, scale)
    assert est2[cpu_dim] == round(4000 * 0.85)
