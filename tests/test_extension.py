"""Annotation/priority protocol tests (reference table-driven style,
``apis/extension/priority_test.go`` / ``qos_test.go``)."""

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import PriorityClass, QoSClass


def test_priority_bands():
    cases = [
        (9000, PriorityClass.PROD),
        (9999, PriorityClass.PROD),
        (7500, PriorityClass.MID),
        (5000, PriorityClass.BATCH),
        (5999, PriorityClass.BATCH),
        (3000, PriorityClass.FREE),
        (8999, PriorityClass.NONE),
        (0, PriorityClass.NONE),
        (None, PriorityClass.NONE),
    ]
    for prio, want in cases:
        assert PriorityClass.from_priority(prio) is want, (prio, want)


def test_qos_parse_and_defaults():
    assert QoSClass.parse("LS") is QoSClass.LS
    assert QoSClass.parse("lsr") is QoSClass.LSR
    assert QoSClass.parse("bogus") is QoSClass.NONE
    assert QoSClass.parse(None) is QoSClass.NONE
    assert ext.qos_for_priority(PriorityClass.BATCH) is QoSClass.BE
    assert ext.qos_for_priority(PriorityClass.PROD) is QoSClass.LS
    assert ext.qos_for_priority(PriorityClass.NONE) is QoSClass.NONE


def test_qos_strictness_order():
    assert QoSClass.SYSTEM > QoSClass.LSE > QoSClass.LSR > QoSClass.LS > QoSClass.BE
