"""Tier-1 enforcement of the koordlint static-analysis framework.

Replaces the three standalone lint test modules
(``test_exception_sites_lint``, ``test_fence_boundaries_lint``,
``test_reject_reasons_lint``) with one per-pass-parametrized suite:

* the CURRENT TREE is clean under every registered pass (the framework's
  acceptance bar: ``python -m tools.koordlint`` exits 0);
* every pass FAILS on its seeded-violation fixture (a lint that cannot
  fail enforces nothing);
* golden migration — the three legacy lints, now registered passes,
  produce verdicts identical to their standalone CLIs;
* the suppression syntax works, and unused/unknown suppressions are
  themselves findings;
* generated ``*_pb2.py`` files and ``__pycache__`` are excluded from
  every walk;
* the structural self-checks the old modules carried (pinned guarded
  append set, the scanner really sees the real commit boundary, the
  reject-reason exemption table splits the enum exactly).
"""

from __future__ import annotations

import ast
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tools.koordlint import (  # noqa: E402
    RepoIndex,
    all_passes,
    run as lint_run,
)
from tools.koordlint.__main__ import main as cli_main  # noqa: E402
from tools.koordlint import jitindex  # noqa: E402
from tools.koordlint.passes import (  # noqa: E402
    chaos_coverage,
    exception_sites,
    fence_boundaries,
    reject_reasons,
)

PASSES = all_passes()
PASS_NAMES = sorted(PASSES)


def _write_tree(root: Path, files: dict) -> Path:
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return root


def _run_pass(root: Path, name: str):
    return PASSES[name].run(RepoIndex(root))


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# the acceptance bar: the tree is clean, per pass and end to end
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pass_name", PASS_NAMES)
def test_current_tree_is_clean_per_pass(pass_name):
    report = lint_run(ROOT, select=[pass_name])
    assert not report.findings, "\n".join(
        f.render() for f in report.findings
    )


def test_cli_exits_zero_on_tree(capsys):
    rc = cli_main([])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 finding(s)" in out and "13 passes" in out


# ---------------------------------------------------------------------------
# seeded-violation fixtures: every pass must be able to FAIL
# ---------------------------------------------------------------------------

#: pass name -> (fixture tree, finding code that must appear)
FIXTURES = {
    "exception-sites": (
        {
            "koordinator_tpu/mod.py": """
            def f():
                try:
                    g()
                except Exception:
                    pass
            """,
        },
        "EX001",
    ),
    "fence-boundaries": (
        {
            "koordinator_tpu/mod.py": """
            def commit(jnl, epoch, cid, planned):
                jnl.append_intent(epoch, cid, planned)
            """,
        },
        "FB001",
    ),
    "reject-reasons": (
        {
            "koordinator_tpu/obs/rejections.py": """
            import enum

            class RejectReason(str, enum.Enum):
                INSUFFICIENT_RESOURCES = "insufficient_resources"
                BRAND_NEW_REASON = "brand_new_reason"
            """,
            "koordinator_tpu/scheduler/batch_solver.py": """
            from ..obs.rejections import RejectReason

            class BatchScheduler:
                def _classify_solver_reject(self, pod, req, est):
                    return RejectReason.INSUFFICIENT_RESOURCES
            """,
        },
        "RR001",
    ),
    "retrace-hazard": (
        {
            "koordinator_tpu/ops/foo.py": """
            import jax

            @jax.jit
            def hookless(x):
                if x > 0:
                    return x
                return -x

            def dispatch(x):
                return hookless(x)
            """,
        },
        "RH001",
    ),
    "donation-safety": (
        {
            "koordinator_tpu/ops/foo.py": """
            import functools
            import jax
            from koordinator_tpu.obs import devprof as _devprof

            @functools.partial(jax.jit, donate_argnums=0)
            def donor(x):
                _devprof.tracing("donor")
                return x + 1

            def caller(x):
                y = donor(x)
                return x + y
            """,
        },
        "DS001",
    ),
    "guarded-by": (
        {
            "koordinator_tpu/obs/t.py": """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: self._lock

                def good(self):
                    with self._lock:
                        self._items.append(1)

                def bad(self):
                    self._items.append(2)
            """,
        },
        "GB001",
    ),
    "chaos-coverage": (
        {
            "koordinator_tpu/mod.py": """
            class C:
                def f(self):
                    self.chaos.fire("domain.lonely")
            """,
        },
        "CC001",
    ),
    "bench-verdicts": (
        {
            "tools/bench_regress.py": """
            VERDICTS = ("OK",)

            def compare():
                return [{"scenario": "s", "verdict": "WAT"},
                        {"scenario": "t", "verdict": "OK"}]
            """,
        },
        "BV001",
    ),
    "gate-coverage": (
        {
            # a REAL opened gate (reservations left the exemption table
            # in the open-the-last-gates PR) with no GATE_ARMS arm: the
            # pass must FAIL — an opened gate cannot land without its
            # bit-exactness equivalence arm
            "koordinator_tpu/scheduler/batch_solver.py": """
            class BatchScheduler:
                def speculation_gate_report(self):
                    return {"reservations": True, "preemption": True}
            """,
            "tests/test_pipelined_stream.py": """
            GATE_ARMS = {}
            """,
        },
        "GT001",
    ),
    "shed-paths": (
        {
            # the declared canonical shed site drops the pod silently:
            # no shed lifecycle event, no metric, no delegation
            "koordinator_tpu/runtime/overload.py": """
            class AdmissionController:
                def shed(self, pod, shard, arrival, detail=""):
                    return None
            """,
        },
        "SP001",
    ),
    "decision-ledger": (
        {
            # a brand-new controller whose tick() moves control state
            # without recording on the decision ledger: invisible to the
            # decision observatory until it joins CONTROLLER_SITES (or
            # EXEMPT, with a written reason)
            "koordinator_tpu/runtime/novel.py": """
            class NovelController:
                def tick(self):
                    if self._hot >= self.sustain:
                        self._level += 1
                    self._hot += 1
            """,
        },
        "DL002",
    ),
    "staleness-snapshot": (
        {
            # a controller reading the freshness verdict LIVE mid-act,
            # outside any declared capture site: a verdict flip between
            # snapshot and act would make the recorded decision
            # unexplainable on replay
            "koordinator_tpu/runtime/novel.py": """
            class NovelController:
                def act(self):
                    if self.freshness():
                        return None
                    return self.evict()
            """,
        },
        "SS001",
    ),
    "store-integrity": (
        {
            # a new durable store bypassing the checksummed codec: its
            # append writes raw records and its load never screens —
            # exactly the silent-truncation regression the pass blocks
            "koordinator_tpu/core/kvstore.py": """
            class KvJournalStore:
                def __init__(self):
                    self._records = []

                def append(self, record):
                    self._records.append(dict(record))

                def load(self):
                    return [dict(r) for r in self._records]

                def rewrite(self, records):
                    self._records = [dict(r) for r in records]
            """,
        },
        "SI001",
    ),
}


def test_every_pass_has_a_fixture():
    assert set(FIXTURES) == set(PASS_NAMES)


@pytest.mark.parametrize("pass_name", PASS_NAMES)
def test_pass_fails_on_seeded_violation(pass_name, tmp_path):
    files, expected = FIXTURES[pass_name]
    _write_tree(tmp_path, files)
    findings = _run_pass(tmp_path, pass_name)
    assert expected in _codes(findings), (
        f"{pass_name} did not flag its seeded violation: "
        + "\n".join(f.render() for f in findings)
    )


def test_retrace_fixture_catches_all_three_hazards(tmp_path):
    files, _ = FIXTURES["retrace-hazard"]
    _write_tree(tmp_path, files)
    codes = _codes(_run_pass(tmp_path, "retrace-hazard"))
    # hookless (RH001), traced-param branch (RH002), unwatched host
    # dispatch (RH003)
    assert {"RH001", "RH002", "RH003"} <= codes


def test_retrace_watch_len_signature_flagged(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/ops/foo.py": """
        def site(dp, batch):
            with dp.watch("assign", n=len(batch)) as w:
                w.result(None)
        """,
    })
    assert "RH004" in _codes(_run_pass(tmp_path, "retrace-hazard"))


def test_retrace_nested_jit_needs_no_hook(tmp_path):
    # a jit whose only call site is inside another jitted body is a
    # sub-jaxpr of that entry point: no hook required, no RH001
    _write_tree(tmp_path, {
        "koordinator_tpu/ops/foo.py": """
        import jax
        from koordinator_tpu.obs import devprof as _devprof

        @jax.jit
        def inner(x):
            return x * 2

        @jax.jit
        def outer(x):
            _devprof.tracing("outer")
            return inner(x)

        def dispatch(dp, x):
            with dp.watch("outer", n=x.shape[0]) as w:
                w.result(outer(x))
        """,
    })
    assert _run_pass(tmp_path, "retrace-hazard") == []


def test_retrace_static_argnames_and_is_none_exempt(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/ops/foo.py": """
        import functools
        import jax
        from koordinator_tpu.obs import devprof as _devprof

        @functools.partial(jax.jit, static_argnames=("flag",))
        def solver(x, mask=None, flag=False):
            _devprof.tracing("solver")
            if mask is None:
                return x
            if flag:
                return x * 2
            return x * mask
        """,
    })
    assert _run_pass(tmp_path, "retrace-hazard") == []


def test_donation_rebind_is_clean_and_self_attr_flagged(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/ops/foo.py": """
        import functools
        import jax
        from koordinator_tpu.obs import devprof as _devprof

        @functools.partial(jax.jit, donate_argnums=0)
        def donor(x):
            _devprof.tracing("donor")
            return x + 1

        def clean(x):
            x = donor(x)
            return x

        class C:
            def racy(self):
                self.buf = donor(self.buf)
        """,
    })
    findings = _run_pass(tmp_path, "donation-safety")
    assert _codes(findings) == {"DS002"}   # the rebind path stays clean
    assert any("self.buf" in f.message for f in findings)


def test_guarded_by_cross_object_holds_and_locked_suffix(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/obs/t.py": """
        import threading

        class Fabric:
            def __init__(self):
                self.handoff_lock = threading.Lock()
                self.seams = []  # guarded-by: self.handoff_lock

        class User:
            def good(self, fabric):
                with fabric.handoff_lock:
                    fabric.seams.append(1)

            def bad(self, fabric):
                fabric.seams.append(2)

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}  # guarded-by: self._lock

            def _evict_locked(self):
                self._items.clear()      # caller-holds convention

            def helper(self):  # koordlint: holds=self._lock
                self._items["k"] = 1
        """,
    })
    findings = _run_pass(tmp_path, "guarded-by")
    assert _codes(findings) == {"GB002"}
    assert len(findings) == 1 and "fabric.seams" in findings[0].message


def test_guarded_by_two_annotated_classes_any_lock_satisfies(tmp_path):
    # two classes annotate the same attr name with DIFFERENT locks: a
    # cross-object writer holding either rebased lock passes (types are
    # unknowable statically); holding neither is still flagged
    _write_tree(tmp_path, {
        "koordinator_tpu/obs/t.py": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._ring = []  # guarded-by: self._lock

        class B:
            def __init__(self):
                self._ring_lock = threading.Lock()
                self._ring = []  # guarded-by: self._ring_lock

        class User:
            def good_a(self, obj):
                with obj._lock:
                    obj._ring.append(1)

            def good_b(self, obj):
                with obj._ring_lock:
                    obj._ring.append(2)

            def bad(self, obj):
                obj._ring.append(3)
        """,
    })
    findings = _run_pass(tmp_path, "guarded-by")
    assert len(findings) == 1 and findings[0].code == "GB002"
    assert "obj._ring" in findings[0].message


def test_chaos_coverage_stale_schedule_entry(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        class C:
            def f(self):
                self.chaos.fire("domain.covered")
        """,
        "koordinator_tpu/sim/longrun.py": """
        def soak(chaos):
            chaos.arm("domain.covered", times=1)
            chaos.arm("ghost.point", times=1)
        """,
    })
    findings = _run_pass(tmp_path, "chaos-coverage")
    assert "CC002" in _codes(findings)
    assert any("ghost.point" in f.message for f in findings)


def test_chaos_coverage_fstring_pattern_matches(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        class C:
            def f(self, name):
                self.chaos.fire(f"channel.{name}.drop")
        """,
        "koordinator_tpu/sim/longrun.py": """
        def soak(chaos):
            chaos.arm("channel.sync.drop", times=1)
        """,
    })
    findings = _run_pass(tmp_path, "chaos-coverage")
    assert "CC001" not in _codes(findings)
    assert "CC002" not in _codes(findings)


# ---------------------------------------------------------------------------
# migrated edge cases (carried from the deleted lint test modules — the
# behaviors golden identity depends on must stay directly pinned)
# ---------------------------------------------------------------------------


def test_exception_sites_bare_and_tuple_forms(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def f():
            try:
                g()
            except:
                x = 1
            try:
                g()
            except (ValueError, Exception) as exc:
                log(exc)
        """,
    })
    findings = _run_pass(tmp_path, "exception-sites")
    assert len(findings) == 2   # bare except + tuple form both flagged


def test_exception_sites_accepts_report_reraise_helper_and_narrow(
    tmp_path,
):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def f(self):
            try:
                g()
            except Exception as exc:
                report_exception("site", exc)
            try:
                g()
            except Exception:
                raise
            try:
                g()
            except Exception as exc:
                self._note_solver_failure(0, exc)
            try:
                g()
            except ValueError:
                pass
        """,
    })
    assert _run_pass(tmp_path, "exception-sites") == []


def test_fence_nested_closure_does_not_leak_check(tmp_path):
    # a fence check inside a nested def does not guard the outer frame
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def outer(self, jnl, epoch, cid, planned):
            def gate():
                self.fence.check(epoch)
            jnl.append_intent(epoch, cid, planned)
        """,
    })
    assert len(_run_pass(tmp_path, "fence-boundaries")) == 1


def test_fence_accepts_checks_and_forget_is_exempt(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def commit(self, jnl, epoch, cid, planned):
            self.fence.check(epoch)
            jnl.append_intent(epoch, cid, planned)

        def commit2(self, jnl, epoch, cid, entries):
            if self._fence_stale() is not None:
                return
            jnl.append_bind(epoch, cid, entries)

        def commit3(self, fabric, jnl, s, epoch, cid, entries):
            fabric.fences[s].check(epoch)
            jnl.append_bind(epoch, cid, entries)

        def release(jnl, cid, uid):
            jnl.append_forget(None, cid, [uid])
        """,
    })
    assert _run_pass(tmp_path, "fence-boundaries") == []


def _rr_repo(tmp_path, members, classifier_body, extra=""):
    files = {
        "koordinator_tpu/obs/rejections.py": (
            "import enum\n\nclass RejectReason(str, enum.Enum):\n"
            + "".join(f'    {m} = "{m.lower()}"\n' for m in members)
        ),
        "koordinator_tpu/scheduler/batch_solver.py": (
            "from ..obs.rejections import RejectReason\n\n"
            "class BatchScheduler:\n"
            "    def _classify_solver_reject(self, pod, req, est):\n"
            + textwrap.indent(textwrap.dedent(classifier_body), " " * 8)
        ),
    }
    if extra:
        files["koordinator_tpu/other.py"] = (
            "from .obs.rejections import RejectReason\n" + extra
        )
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tmp_path


def test_reject_reasons_stale_exemption_for_covered_member(tmp_path):
    root = _rr_repo(
        tmp_path,
        ["STALE_LEADER_EPOCH"],
        "return RejectReason.STALE_LEADER_EPOCH\n",
        extra="REASON = RejectReason.STALE_LEADER_EPOCH\n",
    )
    out = reject_reasons.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    )
    assert len(out) == 1 and "stale exemption" in out[0][2]


def test_reject_reasons_exempt_member_with_no_site(tmp_path):
    root = _rr_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES", "STALE_LEADER_EPOCH"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
    )
    out = reject_reasons.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    )
    assert len(out) == 1 and "the site is gone" in out[0][2]


def test_reject_reasons_accepts_exempt_member_with_live_site(tmp_path):
    root = _rr_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES", "STALE_LEADER_EPOCH"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
        extra="REASON = RejectReason.STALE_LEADER_EPOCH\n",
    )
    assert reject_reasons.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    ) == []


# ---------------------------------------------------------------------------
# golden migration: legacy CLIs == framework passes
# ---------------------------------------------------------------------------


def _shim(name, *args):
    return subprocess.run(
        [sys.executable, str(ROOT / "tools" / name), *map(str, args)],
        capture_output=True,
        text=True,
        cwd=ROOT,
    )


@pytest.mark.parametrize(
    "shim,pass_name",
    [
        ("check_exception_sites.py", "exception-sites"),
        ("check_fence_boundaries.py", "fence-boundaries"),
        ("check_reject_reasons.py", "reject-reasons"),
    ],
)
def test_golden_legacy_cli_clean_on_tree(shim, pass_name):
    """Both surfaces agree on the current tree: zero verdicts, exit 0."""
    proc = _shim(shim)
    assert proc.returncode == 0, proc.stderr
    assert proc.stderr.strip() == ""
    assert _run_pass(ROOT, pass_name) == []


def test_golden_fence_boundaries_on_seeded_tree(tmp_path):
    files, _ = FIXTURES["fence-boundaries"]
    _write_tree(tmp_path, files)
    proc = _shim("check_fence_boundaries.py", tmp_path / "koordinator_tpu")
    assert proc.returncode == 1
    cli_lines = {
        ln for ln in proc.stderr.splitlines()
        if ln.endswith("fence before journal")
    }
    fw_lines = {
        # the framework prefixes the finding ID; strip to the legacy form
        f"{tmp_path / f.file}:{f.line}: {f.message}"
        for f in _run_pass(tmp_path, "fence-boundaries")
    }
    assert cli_lines == fw_lines and len(fw_lines) == 1


def test_golden_reject_reasons_on_seeded_tree(tmp_path):
    files, _ = FIXTURES["reject-reasons"]
    _write_tree(tmp_path, files)
    proc = _shim("check_reject_reasons.py", tmp_path)
    assert proc.returncode == 1
    cli_lines = {
        ln for ln in proc.stderr.splitlines()
        if "RejectReason." in ln and not ln.endswith("reasons")
    }
    fw_lines = {
        f"{f.file}:{f.line}: {f.message}"
        for f in _run_pass(tmp_path, "reject-reasons")
    }
    assert cli_lines == fw_lines
    assert any("BRAND_NEW_REASON" in ln for ln in fw_lines)


def test_golden_exception_sites_functions_are_shared(tmp_path):
    """The shim's importable surface IS the pass implementation — same
    function, same verdicts (the delegation the golden contract rides)."""
    import importlib

    shim = importlib.import_module("tools.check_exception_sites")
    assert shim.check_paths is exception_sites.check_paths
    files, _ = FIXTURES["exception-sites"]
    _write_tree(tmp_path, files)
    legacy = shim.check_paths([tmp_path / "koordinator_tpu"], tmp_path)
    fw = _run_pass(tmp_path, "exception-sites")
    assert [(f.file, f.line, f.message) for f in fw] == legacy
    assert len(legacy) == 1


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_line_suppression_silences_and_is_tracked(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def f():
            try:
                g()
            except Exception:  # koordlint: disable=exception-sites
                pass
        """,
    })
    report = lint_run(tmp_path, select=["exception-sites"])
    assert report.findings == []
    assert len(report.suppressed) == 1


def test_unused_and_unknown_suppressions_are_findings(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        def f():
            return 1  # koordlint: disable=exception-sites

        def g():
            return 2  # koordlint: disable=no-such-pass
        """,
    })
    report = lint_run(tmp_path, select=["exception-sites"])
    codes = _codes(report.findings)
    assert codes == {"SUP001", "SUP002"}


def test_file_wide_suppression(tmp_path):
    _write_tree(tmp_path, {
        "koordinator_tpu/mod.py": """
        # koordlint: disable-file=exception-sites

        def f():
            try:
                g()
            except Exception:
                pass
        """,
    })
    report = lint_run(tmp_path, select=["exception-sites"])
    assert report.findings == [] and len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# walk hygiene: generated files and bytecode caches are out of scope
# ---------------------------------------------------------------------------


def test_pb2_and_pycache_excluded_from_all_walks(tmp_path):
    bad = """
    def f():
        try:
            g()
        except Exception:
            pass

    def commit(jnl, epoch, cid, planned):
        jnl.append_intent(epoch, cid, planned)
    """
    _write_tree(tmp_path, {
        "koordinator_tpu/runtime/proto/snapshot_pb2.py": bad,
        "koordinator_tpu/__pycache__/mod.py": bad,
        "koordinator_tpu/ok.py": "x = 1\n",
    })
    for name in ("exception-sites", "fence-boundaries"):
        assert _run_pass(tmp_path, name) == []


def test_pb2_syntax_error_does_not_trip_lints(tmp_path):
    # the failure mode that motivated the shared walk: a generated file
    # an AST lint cannot parse
    _write_tree(tmp_path, {
        "koordinator_tpu/runtime/proto/gen_pb2.py": "this is ) not python",
        "koordinator_tpu/ok.py": "x = 1\n",
    })
    report = lint_run(tmp_path, select=[
        "exception-sites", "fence-boundaries", "retrace-hazard",
        "donation-safety", "guarded-by",
    ])
    assert report.findings == []


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_select_ignore_and_json(tmp_path, capsys):
    files, _ = FIXTURES["exception-sites"]
    _write_tree(tmp_path, files)
    rc = cli_main([
        "--root", str(tmp_path), "--select", "exception-sites",
        "--json", "-",
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["exit"] == 1 and doc["passes"] == ["exception-sites"]
    assert doc["findings"][0]["code"] == "EX001"

    rc = cli_main([
        "--root", str(tmp_path), "--ignore", "exception-sites",
        "--select", "exception-sites,fence-boundaries",
    ])
    capsys.readouterr()
    assert rc == 0  # the only violating pass was ignored


def test_cli_unknown_pass_is_an_error(capsys):
    rc = cli_main(["--select", "no-such-pass"])
    assert rc == 2
    assert "unknown pass" in capsys.readouterr().err


def test_cli_path_scoping(tmp_path, capsys):
    files, _ = FIXTURES["exception-sites"]
    _write_tree(tmp_path, files)
    rc = cli_main([
        "koordinator_tpu/other_dir",
        "--root", str(tmp_path), "--select", "exception-sites",
    ])
    capsys.readouterr()
    assert rc == 0  # finding exists, but outside the reported scope

    rc = cli_main([
        "koordinator_tpu",
        "--root", str(tmp_path), "--select", "exception-sites",
    ])
    capsys.readouterr()
    assert rc == 1


def test_cli_list_passes(capsys):
    rc = cli_main(["--list-passes"])
    out = capsys.readouterr().out
    assert rc == 0
    for name in PASS_NAMES:
        assert name in out


# ---------------------------------------------------------------------------
# structural self-checks (carried over from the legacy test modules, so
# the scanners cannot rot into silent pass-by-absence)
# ---------------------------------------------------------------------------


def test_guarded_call_set_is_pinned():
    assert fence_boundaries.GUARDED_APPENDS == {
        "append_intent",
        "append_bind",
        "append_abort",
    }


def test_ast_walk_sees_real_commit_boundary():
    src = (ROOT / "koordinator_tpu/scheduler/batch_solver.py").read_text()
    tree = ast.parse(src)
    found = {
        node.func.attr
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in fence_boundaries.GUARDED_APPENDS
    }
    assert {"append_intent", "append_bind", "append_abort"} <= found


def test_reject_reason_exemptions_split_the_enum_exactly():
    members = set(reject_reasons.enum_members(ROOT))
    assert set(reject_reasons.EXEMPT) <= members
    covered = reject_reasons.classifier_coverage(ROOT)
    assert covered and covered.isdisjoint(reject_reasons.EXEMPT)
    assert covered | set(reject_reasons.EXEMPT) == members


def test_jit_registry_sees_the_real_solver_surface():
    """Self-check against silent rot: the jit scanner must actually FIND
    the real entry points (renames must update the lint, not silently
    shrink its coverage)."""
    jitted = jitindex.collect_jitted(RepoIndex(ROOT))
    names = {j.name for j in jitted}
    assert {
        "assign",
        "solve_stream",
        "solve_stream_full",
        "scatter_rows",
        "gather_rows",
        "_chain_commit_deltas",
        "_apply_commit_deltas_donated",
    } <= names
    donated = {j.name: j.donated for j in jitted if j.donated}
    assert donated["scatter_rows"] == (0,)
    assert donated["_apply_commit_deltas_donated"] == (0, 1, 2)
    hooks = {j.hook for j in jitted if j.hook}
    assert {
        "sharded_assign", "sharded_solve_stream", "shard_map_nominate",
    } <= hooks


def test_chaos_coverage_sees_real_points_and_schedule():
    index = RepoIndex(ROOT)
    fires = chaos_coverage._fire_points(index)
    assert "pipeline.worker_stall" in fires
    assert "channel.*.drop" in fires        # the f-string pattern form
    scheduled = chaos_coverage._scheduled_points(index)
    # the PR's schedule extensions (koordlint chaos-coverage findings)
    for point in (
        "solver.dispatch_chunk",
        "channel.sync.delay",
        "leader.stale_commit",
        "journal.write_fail",
        # gray-failure containment PR: the soak arm arms all three
        "solver.poison_batch",
        "informer.silent_stall",
        "scheduler.boot_crash",
    ):
        assert point in scheduled, point
    for point in (
        "solver.poison_batch",
        "informer.silent_stall",
        "scheduler.boot_crash",
    ):
        assert point in fires, point
    # every exemption's promised dedicated arm exists in the NAMED file
    armed = chaos_coverage._test_armed_points(index)
    for point, (site, _why) in chaos_coverage.EXEMPT.items():
        assert site in armed.get(point, set()), (
            f"{point} (promised by {site})"
        )
