"""Tier-1 enforcement of the fence-before-journal discipline (PR 6
satellite): every ``append_intent``/``append_bind``/``append_abort``
call site in ``koordinator_tpu/`` must evaluate an epoch check in the
same function. See ``tools/check_fence_boundaries.py``."""

import ast
import sys
import textwrap
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import check_fence_boundaries as lint  # noqa: E402


def test_repo_has_no_unfenced_journal_writes():
    violations = lint.check_paths([ROOT / "koordinator_tpu"], ROOT)
    assert not violations, "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in violations
    )


def _check_src(src: str, tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(src))
    return lint.check_file(f, tmp_path)


def test_lint_flags_unfenced_append(tmp_path):
    out = _check_src(
        """
        def commit(jnl, epoch, cid, planned):
            jnl.append_intent(epoch, cid, planned)
        """,
        tmp_path,
    )
    assert len(out) == 1 and "append_intent" in out[0][2]


def test_lint_accepts_fence_check_and_helper(tmp_path):
    out = _check_src(
        """
        def commit(self, jnl, epoch, cid, planned):
            self.fence.check(epoch)
            jnl.append_intent(epoch, cid, planned)

        def commit2(self, jnl, epoch, cid, entries):
            if self._fence_stale() is not None:
                return
            jnl.append_bind(epoch, cid, entries)

        def commit3(self, fabric, jnl, s, epoch, cid, entries):
            fabric.fences[s].check(epoch)
            jnl.append_bind(epoch, cid, entries)
        """,
        tmp_path,
    )
    assert out == []


def test_lint_forgets_are_exempt(tmp_path):
    # forgets mirror apiserver-authoritative deletions: fence-EXEMPT
    out = _check_src(
        """
        def release(jnl, cid, uid):
            jnl.append_forget(None, cid, [uid])
        """,
        tmp_path,
    )
    assert out == []


def test_lint_nested_closure_does_not_leak_check(tmp_path):
    # a fence check inside a nested def does not guard the outer frame
    out = _check_src(
        """
        def outer(self, jnl, epoch, cid, planned):
            def gate():
                self.fence.check(epoch)
            jnl.append_intent(epoch, cid, planned)
        """,
        tmp_path,
    )
    assert len(out) == 1


def test_guarded_call_set_is_pinned():
    assert lint.GUARDED_APPENDS == {
        "append_intent",
        "append_bind",
        "append_abort",
    }


def test_ast_walk_sees_real_commit_boundary():
    """Self-check against silent rot: the scanner must actually FIND the
    real _commit boundary's appends (if batch_solver's journal calls are
    renamed, the lint must be updated, not silently pass-by-absence)."""
    src = (ROOT / "koordinator_tpu/scheduler/batch_solver.py").read_text()
    tree = ast.parse(src)
    found = {
        node.func.attr
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in lint.GUARDED_APPENDS
    }
    assert {"append_intent", "append_bind", "append_abort"} <= found
