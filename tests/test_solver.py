"""Golden tests: batched TPU solver vs scalar sequential reference.

Follows the SURVEY §4 strategy: the reference's strongest pattern (pure
cost/mask functions against synthetic fixtures) becomes golden comparisons
between the vectorized kernels and ``sim.golden.sequential_assign``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    SolverParams,
    assign,
    assign_sequential,
)
from koordinator_tpu.sim import golden


def make_fixture(
    p=32,
    n=16,
    d=2,
    seed=0,
    base_util=0.0,
    thresholds=(0.0, 0.0),
    prod_thresholds=(0.0, 0.0),
    pod_scale=1.0,
):
    rng = np.random.default_rng(seed)
    alloc = rng.choice([32.0, 64.0, 96.0], (n, 1)) * np.ones((1, d), np.float32)
    alloc = alloc.astype(np.float32)
    requested = np.zeros((n, d), np.float32)
    est_used = (alloc * base_util * rng.uniform(0.5, 1.5, (n, d))).astype(np.float32)
    prod_used = est_used * 0.6
    fresh = np.ones(n, bool)
    sched = np.ones(n, bool)

    req = (rng.choice([1.0, 2.0, 4.0, 8.0], (p, d)) * pod_scale).astype(np.float32)
    est = (req * 0.85).astype(np.float32)
    prio = rng.integers(5000, 9999, p).astype(np.int32)
    is_prod = prio >= 9000

    params = SolverParams(
        usage_thresholds=jnp.asarray(thresholds, jnp.float32),
        prod_thresholds=jnp.asarray(prod_thresholds, jnp.float32),
        score_weights=jnp.ones(d, jnp.float32),
    )
    pods = PodBatch.create(
        requests=req, estimate=est, priority=prio, is_prod=is_prod
    )
    nodes = NodeState.create(
        allocatable=alloc,
        requested=requested,
        estimated_used=est_used,
        prod_used=prod_used,
        metric_fresh=fresh,
        schedulable=sched,
    )
    np_fix = dict(
        pod_req=req,
        pod_estimate=est,
        pod_priority=prio,
        pod_is_prod=is_prod,
        allocatable=alloc,
        requested0=requested,
        estimated_used0=est_used,
        prod_used0=prod_used,
        metric_fresh=fresh,
        schedulable=sched,
        usage_thresholds=np.asarray(thresholds, np.float32),
        prod_thresholds=np.asarray(prod_thresholds, np.float32),
        score_weights=np.ones(d, np.float32),
    )
    return pods, nodes, params, np_fix


def run_both(pods, nodes, params, np_fix, solver=assign_sequential):
    result = solver(pods, nodes, params)
    got = np.asarray(result.assignment)
    want = golden.sequential_assign(**np_fix)
    return got, want


def test_exact_match_low_contention():
    """With ample capacity the batched solver must reproduce the sequential
    reference exactly (every pod gets its argmin in round one)."""
    pods, nodes, params, np_fix = make_fixture(p=24, n=12, seed=1)
    got, want = run_both(pods, nodes, params, np_fix)
    np.testing.assert_array_equal(got, want)


def test_exact_match_with_usage_thresholds():
    pods, nodes, params, np_fix = make_fixture(
        p=24, n=12, seed=2, base_util=0.5, thresholds=(65.0, 95.0)
    )
    got, want = run_both(pods, nodes, params, np_fix)
    np.testing.assert_array_equal(got, want)


def test_invariants_under_contention():
    """Heavy contention: allow order divergence from the sequential oracle but
    require feasibility invariants and comparable placement count."""
    pods, nodes, params, np_fix = make_fixture(
        p=256, n=8, seed=3, pod_scale=4.0, thresholds=(80.0, 80.0), base_util=0.3
    )
    got, want = run_both(pods, nodes, params, np_fix)
    golden.validate_assignment(
        got,
        np_fix["pod_req"],
        np_fix["allocatable"],
        np_fix["requested0"],
        np_fix["schedulable"],
    )
    n_got, n_want = (got >= 0).sum(), (want >= 0).sum()
    assert n_got >= 0.95 * n_want, (n_got, n_want)


def test_all_infeasible():
    pods, nodes, params, np_fix = make_fixture(p=8, n=4, seed=4, pod_scale=1000.0)
    got, want = run_both(pods, nodes, params, np_fix)
    assert (got == -1).all()
    assert (want == -1).all()


def test_unschedulable_nodes_excluded():
    pods, nodes, params, np_fix = make_fixture(p=16, n=6, seed=5)
    sched = np.zeros(6, bool)
    sched[2] = True
    nodes = nodes.replace(schedulable=jnp.asarray(sched))
    np_fix["schedulable"] = sched
    got, want = run_both(pods, nodes, params, np_fix)
    placed = got >= 0
    assert (got[placed] == 2).all()
    np.testing.assert_array_equal(got, want)


def test_stale_metric_degrades_to_fit_only():
    """Expired NodeMetric skips usage checks (load_aware.go:143-149)."""
    pods, nodes, params, np_fix = make_fixture(
        p=16, n=6, seed=6, base_util=0.9, thresholds=(50.0, 50.0)
    )
    # fresh metrics + over-threshold usage => nothing schedulable
    got_fresh, want_fresh = run_both(pods, nodes, params, np_fix)
    assert (got_fresh == -1).all() and (want_fresh == -1).all()
    # stale metrics => usage ignored, fit admits everything
    stale = np.zeros(6, bool)
    nodes = nodes.replace(metric_fresh=jnp.asarray(stale))
    np_fix["metric_fresh"] = stale
    got, want = run_both(pods, nodes, params, np_fix)
    assert (got >= 0).all()
    np.testing.assert_array_equal(got, want)


def test_priority_order_wins_capacity():
    """When one node fits exactly one pod, the higher-priority pod gets it."""
    d = 2
    alloc = np.array([[8.0, 8.0]], np.float32)
    req = np.array([[8.0, 8.0], [8.0, 8.0]], np.float32)
    prio = np.array([5000, 9500], np.int32)
    pods = PodBatch.create(requests=req, estimate=req * 0.85, priority=prio)
    nodes = NodeState.create(allocatable=alloc)
    params = SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )
    got = np.asarray(assign(pods, nodes, params).assignment)
    assert got[1] == 0 and got[0] == -1


def test_padded_pods_never_assigned():
    pods, nodes, params, _ = make_fixture(p=16, n=6, seed=7)
    valid = np.zeros(16, bool)
    valid[:3] = True
    pods = pods.replace(valid=jnp.asarray(valid))
    got = np.asarray(assign(pods, nodes, params).assignment)
    assert (got[3:] == -1).all()
    assert (got[:3] >= 0).all()


# ---- round-based fast solver (ops.solver.assign) ----


def test_round_solver_invariants_and_quality():
    """The fast solver must satisfy feasibility invariants, place a
    comparable number of pods, and keep LoadAware balance close to the
    sequential oracle (its nominations are revalidated host-side anyway)."""
    pods, nodes, params, np_fix = make_fixture(
        p=128, n=16, seed=11, thresholds=(80.0, 80.0), base_util=0.2
    )
    got, want = run_both(pods, nodes, params, np_fix, solver=assign)
    golden.validate_assignment(
        got,
        np_fix["pod_req"],
        np_fix["allocatable"],
        np_fix["requested0"],
        np_fix["schedulable"],
    )
    assert (got >= 0).sum() >= 0.95 * (want >= 0).sum()

    def peak_util(assignment):
        used = np_fix["estimated_used0"].copy()
        placed = assignment >= 0
        np.add.at(used, assignment[placed], np_fix["pod_estimate"][placed])
        return float((used / np_fix["allocatable"]).max())

    # balance: peak estimated utilization within 15 points of the oracle
    assert peak_util(got) <= peak_util(want) + 0.15, (
        peak_util(got),
        peak_util(want),
    )


def test_round_solver_matches_sequential_on_tiny_case():
    pods, nodes, params, np_fix = make_fixture(p=4, n=8, seed=12)
    got = np.asarray(assign(pods, nodes, params).assignment)
    want = golden.sequential_assign(**np_fix)
    golden.validate_assignment(
        got,
        np_fix["pod_req"],
        np_fix["allocatable"],
        np_fix["requested0"],
        np_fix["schedulable"],
    )
    assert (got >= 0).sum() == (want >= 0).sum()


def test_scan_solver_agrees_with_round_solver_feasibility():
    pods, nodes, params, np_fix = make_fixture(
        p=64, n=8, seed=13, pod_scale=2.0, thresholds=(75.0, 90.0), base_util=0.4
    )
    seq = np.asarray(assign_sequential(pods, nodes, params).assignment)
    fast = np.asarray(assign(pods, nodes, params).assignment)
    for a in (seq, fast):
        golden.validate_assignment(
            a,
            np_fix["pod_req"],
            np_fix["allocatable"],
            np_fix["requested0"],
            np_fix["schedulable"],
        )


def test_round_solver_jitter_zero_is_strict_argmin():
    """nomination_jitter=0.0 with topk=1 restores strict argmin
    *nomination*: every placed pod sits on a node that was its exact
    current-state argmin in some round (batched commit may still diverge
    from the one-at-a-time oracle; the invariant tests own that). Here:
    same feasibility + the same number of placements as the oracle."""
    pods, nodes, params, np_fix = make_fixture(p=24, n=12, seed=1)
    got = np.asarray(
        assign(
            pods, nodes, params, nomination_jitter=0.0, topk=1
        ).assignment
    )
    want = golden.sequential_assign(**np_fix)
    golden.validate_assignment(
        got,
        np_fix["pod_req"],
        np_fix["allocatable"],
        np_fix["requested0"],
        np_fix["schedulable"],
    )
    assert (got >= 0).sum() == (want >= 0).sum()


def test_round_solver_jitter_bounded_deviation():
    """With jitter on, every placement stays within nomination_jitter score
    points of that pod's best feasible node (the knob's contract)."""
    pods, nodes, params, np_fix = make_fixture(p=32, n=16, seed=21)
    amp = 4.0
    got = np.asarray(
        assign(pods, nodes, params, nomination_jitter=amp).assignment
    )
    # recompute true round-1 scores against the initial state; pods placed
    # in later rounds face tighter state, so only check round-1-placeable
    # pods loosely: every assigned node's initial score must be within amp
    # of the pod's initial best.
    from koordinator_tpu.ops import costs as cost_ops
    import jax.numpy as jnp

    cost = np.asarray(
        cost_ops.load_aware_cost(
            pods.estimate,
            nodes.estimated_used,
            nodes.allocatable,
            params.score_weights,
        )
    )
    for i, node in enumerate(got):
        if node < 0:
            continue
        best = cost[i].min()
        assert cost[i, node] <= best + amp + 1e-3


def test_solve_stream_threads_capacity_between_batches():
    """solve_stream must be equivalent to manually chaining assign() with
    consumed capacity fed forward — the on-device scan is a pure dispatch
    optimization, not a semantic change."""
    import jax

    from koordinator_tpu.ops.solver import solve_stream

    pods, nodes, params, _ = make_fixture(p=64, n=16, base_util=0.2)
    b, pp = 4, 16
    stacked = jax.tree.map(lambda a: a.reshape((b, pp) + a.shape[1:]), pods)

    assigns, final_nodes, placed, _ = solve_stream(stacked, nodes, params)
    assigns = np.asarray(assigns)
    placed = np.asarray(placed)

    cur = nodes
    for i in range(b):
        batch = jax.tree.map(lambda a: a[i], stacked)
        res = assign(batch, cur, params)
        np.testing.assert_array_equal(np.asarray(res.assignment), assigns[i])
        assert int((np.asarray(res.assignment) >= 0).sum()) == placed[i]
        cur = cur.replace(
            requested=res.node_requested,
            estimated_used=res.node_estimated_used,
        )
    np.testing.assert_allclose(
        np.asarray(final_nodes.requested), np.asarray(cur.requested), rtol=1e-6
    )


def test_solve_stream_respects_quota_across_batches():
    """Quota used must accumulate across batches: a quota exhausted by batch
    0 admits nothing in batch 1 (reference used+request<=runtime recursion,
    plugin_helper.go:281-317, carried across scheduleOne cycles)."""
    import jax

    from koordinator_tpu.ops.solver import QuotaState, solve_stream

    pods, nodes, params, _ = make_fixture(p=32, n=16)
    # all pods charged to quota 0 with runtime for only ~6 pods' requests
    chain = np.full((32, 4), -1, np.int32)
    chain[:, 0] = 0
    pods = pods.replace(quota_chain=jnp.asarray(chain))
    total_req = np.asarray(pods.requests).sum(0)
    runtime = np.stack([total_req * 0.2, np.full(2, np.inf)], 0).astype(np.float32)
    quotas = QuotaState(
        runtime=jnp.asarray(runtime), used=jnp.zeros((2, 2), jnp.float32)
    )
    stacked = jax.tree.map(lambda a: a.reshape((2, 16) + a.shape[1:]), pods)
    assigns, _, placed, fq = solve_stream(stacked, nodes, params, quotas=quotas)
    placed = np.asarray(placed)
    # quota admits strictly fewer than everything, and batch 1 sees batch
    # 0's charges (cannot place more than remaining headroom allows)
    assert placed.sum() < 32
    charged = np.asarray(stacked.requests).reshape(32, 2)[
        np.asarray(assigns).reshape(32) >= 0
    ].sum(0)
    assert np.all(charged <= runtime[0] + 1e-4)
    # the returned QuotaState carries cumulative consumption so a second
    # stream threads it exactly like node capacity
    np.testing.assert_allclose(np.asarray(fq.used)[0], charged, rtol=1e-5)


def test_solve_stream_threads_prod_usage_between_batches():
    """prod_used must carry between batches: a prod threshold filled by
    batch 0 blocks batch 1's prod pods (without SolveResult.node_prod_used
    every batch would re-check against the initial prod usage)."""
    import jax

    from koordinator_tpu.ops.solver import solve_stream

    d = 1
    nodes = NodeState.create(
        allocatable=np.full((1, d), 100.0, np.float32),
        estimated_used=np.zeros((1, d), np.float32),
        prod_used=np.zeros((1, d), np.float32),
    )
    params = SolverParams(
        usage_thresholds=jnp.zeros(d, jnp.float32),
        prod_thresholds=jnp.asarray([50.0], jnp.float32),
        score_weights=jnp.ones(d, jnp.float32),
    )

    def batch():
        req = np.full((5, d), 10.0, np.float32)
        return PodBatch.create(
            requests=req,
            estimate=req,
            priority=np.full(5, 9500, np.int32),
            is_prod=np.ones(5, bool),
        )

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), batch(), batch())
    _, final_nodes, placed, _ = solve_stream(stacked, nodes, params)
    placed = np.asarray(placed)
    # batch 0 fills prod usage exactly to the 50% threshold; batch 1's
    # prod pods must all be rejected against the carried prod_used
    assert placed[0] == 5
    assert placed[1] == 0
    np.testing.assert_allclose(np.asarray(final_nodes.prod_used), [[50.0]])


def test_enforce_gangs_refunds_prod_used():
    """Gang rollback must refund node_prod_used for prod members, or the
    carried prod usage leaks capacity batch over batch."""
    from koordinator_tpu.ops.solver import SolveResult, enforce_gangs

    req = jnp.full((2, 1), 10.0)
    result = SolveResult(
        assignment=jnp.asarray([0, -1], jnp.int32),  # gang min 2, one missing
        node_requested=jnp.asarray([[10.0]]),
        node_estimated_used=jnp.asarray([[10.0]]),
        node_prod_used=jnp.asarray([[10.0]]),
        quota_used=jnp.zeros((1, 1)),
        rounds_used=jnp.array(1, jnp.int32),
    )
    pods = PodBatch.create(
        requests=req,
        estimate=req,
        priority=jnp.full(2, 9500, jnp.int32),
        is_prod=jnp.ones(2, bool),
        gang_id=[0, 0],
        gang_min=[2, 0],
    )
    out = enforce_gangs(result, pods)
    assert np.asarray(out.assignment).tolist() == [-1, -1]
    np.testing.assert_allclose(np.asarray(out.node_prod_used), [[0.0]])


def test_approx_topk_places_pod_with_single_feasible_node():
    """approx_max_k recall < 1 must never cost a constrained pod its only
    feasible node: slot 0 of the candidate set is pinned to the exact
    argmin, so a pod feasible on exactly one node out of thousands still
    places."""
    p, n, d = 8, 4096, 2
    alloc = np.full((n, d), 4.0, np.float32)
    alloc[1234] = 1000.0  # the only node a big pod fits on
    req = np.full((p, d), 8.0, np.float32)
    pods = PodBatch.create(
        requests=req, estimate=req, priority=np.full(p, 9000, np.int32)
    )
    nodes = NodeState.create(allocatable=alloc)
    params = SolverParams(
        usage_thresholds=jnp.zeros(d, jnp.float32),
        prod_thresholds=jnp.zeros(d, jnp.float32),
        score_weights=jnp.ones(d, jnp.float32),
    )
    res = assign(pods, nodes, params, approx_topk=True)
    got = np.asarray(res.assignment)
    assert np.all(got == 1234)


def test_assign_approx_topk_matches_exact_quality():
    """approx_max_k nomination must preserve solver invariants (no capacity
    violation) and achieve the same placement count on an uncontended
    fixture."""
    pods, nodes, params, _ = make_fixture(p=48, n=24, base_util=0.1)
    exact = assign(pods, nodes, params)
    approx = assign(pods, nodes, params, approx_topk=True)
    n_exact = int((np.asarray(exact.assignment) >= 0).sum())
    n_approx = int((np.asarray(approx.assignment) >= 0).sum())
    assert n_approx == n_exact == 48
    req = np.asarray(approx.node_requested)
    assert np.all(req <= np.asarray(nodes.allocatable) + 1e-4)


def test_fidelity_sweep_random_fixtures():
    """Property sweep: across random fixtures spanning contention regimes,
    the round solver must (a) never violate feasibility invariants,
    (b) place ≥95% of what the sequential oracle places, and (c) keep peak
    estimated utilization within 15 points when usage thresholds are on
    (the regime the reference itself bounds; without thresholds balance is
    best-effort and the band widens to 30) — the distilled contract behind
    every per-seed test above (SURVEY §4 golden strategy at scale)."""
    rng = np.random.default_rng(123)
    for trial in range(10):
        p = int(rng.choice([16, 64, 160]))
        n = int(rng.choice([8, 24, 64]))
        base_util = float(rng.choice([0.0, 0.25, 0.5]))
        thresholds = (0.0, 0.0) if trial % 3 == 0 else (70.0, 90.0)
        pod_scale = float(rng.choice([1.0, 2.0, 6.0]))
        pods, nodes, params, np_fix = make_fixture(
            p=p,
            n=n,
            seed=1000 + trial,
            base_util=base_util,
            thresholds=thresholds,
            pod_scale=pod_scale,
        )
        got = np.asarray(assign(pods, nodes, params).assignment)
        want = golden.sequential_assign(**np_fix)
        ctx = dict(trial=trial, p=p, n=n, base_util=base_util,
                   thresholds=thresholds, pod_scale=pod_scale)
        golden.validate_assignment(
            got,
            np_fix["pod_req"],
            np_fix["allocatable"],
            np_fix["requested0"],
            np_fix["schedulable"],
        )
        n_got, n_want = (got >= 0).sum(), (want >= 0).sum()
        assert n_got >= 0.95 * n_want, (ctx, n_got, n_want)

        def peak(a):
            used = np_fix["estimated_used0"].copy()
            placed = a >= 0
            np.add.at(used, a[placed], np_fix["pod_estimate"][placed])
            return float((used / np_fix["allocatable"]).max())

        band = 0.15 if thresholds[0] > 0 else 0.30
        assert peak(got) <= peak(want) + band, (ctx, peak(got), peak(want))


def test_gang_rollback_refunds_quota():
    """SURVEY hard part (c) — gang × quota joint constraint: when a gang
    misses minMember and rolls back, its members' quota charges must be
    refunded, or the next cycle sees phantom consumption (the reference
    resolves the interplay with Permit-time rejection + Unreserve refunds)."""
    from koordinator_tpu.ops.solver import QuotaState

    d = 2
    # node fits exactly 2 pods; gang of 3 with minMember 3 can never place
    alloc = np.array([[8.0, 8.0]], np.float32)
    req = np.full((4, d), 4.0, np.float32)
    prio = np.array([9000, 9000, 9000, 5000], np.int32)
    gang_id = np.array([0, 0, 0, -1], np.int32)
    gang_min = np.array([3, 0, 0, 0], np.int32)
    chain = np.full((4, 4), -1, np.int32)
    chain[:, 0] = 0
    pods = PodBatch.create(
        requests=req,
        estimate=req,
        priority=prio,
        gang_id=gang_id,
        gang_min=gang_min,
        quota_chain=chain,
    )
    nodes = NodeState.create(allocatable=alloc)
    params = SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )
    quotas = QuotaState(
        runtime=jnp.full((2, d), 100.0, jnp.float32),
        used=jnp.zeros((2, d), jnp.float32),
    )
    res = assign(pods, nodes, params, quotas=quotas)
    got = np.asarray(res.assignment)
    # gang rolled back entirely; the non-gang pod may hold the node
    assert (got[:3] == -1).all()
    # quota used reflects ONLY surviving placements — gang charges refunded
    placed_req = req[got >= 0].sum(0) if (got >= 0).any() else np.zeros(d)
    np.testing.assert_allclose(np.asarray(res.quota_used)[0], placed_req, atol=1e-4)
    # node capacity also returned
    assert np.asarray(res.node_requested)[0].max() <= 8.0 + 1e-4
