"""Regression tests for the round-4 advisor findings (ADVICE.md r4)."""

import gc
import threading

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.yaml_loader import load_objects
from koordinator_tpu.scheduler.batch_solver import _gc_pause, _gc_resume


def test_yaml_pod_effective_requests_init_containers_and_overhead():
    """Effective pod requests = max(initContainers, sum(containers)) +
    overhead (advisor r4: an init container larger than the mains must
    gate placement)."""
    doc = """
apiVersion: v1
kind: Pod
metadata:
  name: initpod
spec:
  overhead:
    cpu: 100m
  initContainers:
  - name: init
    resources:
      requests:
        cpu: "4"
        memory: 1Gi
  containers:
  - name: a
    resources:
      requests:
        cpu: "1"
        memory: 2Gi
  - name: b
    resources:
      requests:
        cpu: "1"
"""
    objs = load_objects(doc)
    pod = next(o for o in objs if hasattr(o, "spec") and hasattr(o.spec, "requests"))
    # cpu: max(4000, 1000+1000) + 100 overhead; memory: max(1Gi, 2Gi)
    assert pod.spec.requests[ext.RES_CPU] == 4100
    assert pod.spec.requests[ext.RES_MEMORY] == 2048


def test_gc_pause_refcounted_across_overlapping_cycles():
    """Two overlapping schedulers keep the collector paused until the
    LAST cycle exits (advisor r4: bare disable()/enable() re-enables GC
    mid-cycle)."""
    assert gc.isenabled()
    _gc_pause()          # scheduler A enters
    assert not gc.isenabled()
    _gc_pause()          # scheduler B enters
    _gc_resume()         # A exits — B still mid-cycle
    assert not gc.isenabled(), "GC re-enabled while another cycle is live"
    _gc_resume()         # B exits
    assert gc.isenabled()


def test_numa_unregister_invalidates_zone_cache():
    """NodeResourceTopology deletion must zero the cached zone row even
    though node_epoch doesn't bump (code-review r5)."""
    import numpy as np

    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(allocatable={ext.RES_CPU: 32000}),
        )
    )
    mgr = NUMAManager(snap)
    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8)
    mgr.register_node("n0", topo, NUMAPolicy.SINGLE_NUMA_NODE, 65536)
    zone_free, _cap, policy = mgr.arrays()
    assert policy[snap.node_id("n0")] == int(NUMAPolicy.SINGLE_NUMA_NODE)
    assert np.any(zone_free[snap.node_id("n0")] > 0)
    mgr.unregister_node("n0")
    zone_free, _cap, policy = mgr.arrays()
    assert policy[snap.node_id("n0")] == 0
    assert np.all(zone_free[snap.node_id("n0")] == 0)


def _quota_sampled_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from koordinator_tpu.api.types import (
        ElasticQuota,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager

    snap = ClusterSnapshot()
    for i in range(150):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:03d}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}
                ),
            )
        )
    gqm = GroupQuotaManager(snap.config)
    # max leaves headroom above full-cluster occupancy, so a scheduling
    # failure with every node full is NODE fit, not quota — exactly the
    # case the sampled-window preemption gate defers on
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="team"),
            min={ext.RES_CPU: 600_000, ext.RES_MEMORY: 1 << 20},
            max={ext.RES_CPU: 1_200_000, ext.RES_MEMORY: 2 << 20},
        )
    )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(),
        quotas=gqm,
        batch_bucket=128,
        percentage_of_nodes_to_score=67,  # window of 100/150 nodes
    )
    sched.extender.monitor.stop_background()

    def mk(name, prio, node_name=None):
        return Pod(
            meta=ObjectMeta(
                name=name, labels={ext.LABEL_QUOTA_NAME: "team"}
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096},
                priority=prio,
                node_name=node_name,
            ),
        )

    return snap, sched, mk


def test_sampled_window_preemption_not_starved_for_pinned_pod():
    """A spec.nodeName-pinned pod whose node is full of lower-priority
    same-quota pods must preempt IMMEDIATELY even under a sampled window
    (its node is in every window, so the failure is never transient —
    code-review r5)."""
    snap, sched, mk = _quota_sampled_cluster()
    out = sched.schedule([mk("low", 5000, node_name="n140")])
    assert len(out.bound) == 1
    out = sched.schedule([mk("high", 9000, node_name="n140")])
    # the low-priority victim was evicted and the pinned pod landed on
    # its node in the SAME cycle (retry window includes the target node)
    assert [n for _p, n in out.bound] == ["n140"], (
        out.bound,
        out.unschedulable,
        out.preempted,
    )
    assert [v.meta.name for v in out.preempted] == ["low"]


def test_sampled_window_preemption_eventually_runs_for_unconstrained_pod():
    """An unconstrained pod with clear quota headroom defers preemption
    until the window has fully rotated, then preempts (anti-starvation
    escape of the headroom gate)."""
    snap, sched, mk = _quota_sampled_cluster()
    # fill EVERY node with a low-priority pod: no free capacity anywhere
    # (several cycles — the sampled window covers 100 of 150 nodes)
    fillers = [mk(f"f{i:03d}", 5000) for i in range(150)]
    total_bound = 0
    for _ in range(4):
        out = sched.schedule(fillers)
        total_bound += len(out.bound)
        fillers = list(out.unschedulable)
        if not fillers:
            break
    assert total_bound == 150
    high = mk("high", 9000)
    preempted = []
    for _cycle in range(4):  # rotation at 67% window = 2 cycles
        out = sched.schedule([high])
        preempted.extend(out.preempted)
        if out.bound:
            break
    assert out.bound, "high-priority pod starved"
    assert preempted and all(
        (v.spec.priority or 0) == 5000 for v in preempted
    )


def test_gc_pause_thread_race():
    """Hammer pause/resume from threads; depth bookkeeping must land the
    collector back at enabled."""
    def worker():
        for _ in range(200):
            _gc_pause()
            _gc_resume()

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert gc.isenabled()
