"""Tests for the manager's remaining control-plane pieces: quota topology
webhook, quota admission, quota profile controller, node/cm validation,
nodemetric controller, noderesource plugin chain, and the colocation
profile reconciler (SURVEY §2.5)."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.api.types import (
    ClusterColocationProfile,
    Device,
    DeviceInfo,
    ElasticQuota,
    ElasticQuotaProfile,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
)
from koordinator_tpu.manager.colocation_controller import (
    ColocationProfileController,
)
from koordinator_tpu.manager.node_webhook import (
    validate_colocation_strategy,
    validate_node,
    validate_threshold_strategy,
)
from koordinator_tpu.manager.nodemetric import (
    NodeMetricCollectPolicy,
    NodeMetricController,
)
from koordinator_tpu.manager.noderesource import ColocationStrategy
from koordinator_tpu.manager.noderesource_plugins import (
    CPUBasicInfo,
    CPUNormalizationPlugin,
    CPUNormalizationStrategy,
    GPUDeviceResourcePlugin,
    RDMADeviceResourcePlugin,
    ResourceAmplificationPlugin,
    apply_items,
    parse_amplification,
)
from koordinator_tpu.manager.profile import ProfileMutator
from koordinator_tpu.manager.quota_profile import (
    ANNOTATION_RESOURCE_RATIO,
    QuotaProfileController,
)
from koordinator_tpu.manager.quota_webhook import (
    QuotaAdmissionEvaluator,
    QuotaTopologyValidator,
)
from koordinator_tpu.api.types import ResourceThresholdStrategy
from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager


def eq(name, parent="", minr=None, maxr=None, is_parent=False, tree=""):
    return ElasticQuota(
        meta=ObjectMeta(name=name),
        min=minr or {},
        max=maxr or {},
        parent=parent,
        is_parent=is_parent,
        tree_id=tree,
    )


# ---- quota topology webhook ----


def test_quota_self_validation():
    v = QuotaTopologyValidator()
    bad = eq("a", minr={"cpu": 10.0}, maxr={"cpu": 5.0})
    errs = v.validate_self(bad)
    assert any("min[cpu]" in e for e in errs)
    assert v.validate_self(eq("b", minr={"cpu": -1.0}, maxr={"cpu": 5.0}))
    # min key missing from max is rejected (quota_topology_check.go:69)
    assert v.validate_self(eq("c", minr={"gpu": 1.0}, maxr={"cpu": 5.0}))
    assert not v.validate_self(eq("d", minr={"cpu": 1.0}, maxr={"cpu": 5.0}))


def test_quota_parent_invariants():
    v = QuotaTopologyValidator()
    assert not v.admit(eq("root", minr={"cpu": 100.0}, maxr={"cpu": 100.0}, is_parent=True))
    # parent must exist
    assert v.validate_create(eq("child", parent="ghost"))
    # parent must be is-parent
    assert not v.admit(eq("leafy", minr={}, maxr={}))
    errs = v.validate_create(eq("child", parent="leafy"))
    assert any("is-parent" in e for e in errs)
    # child min sum must stay under parent min
    assert not v.admit(eq("c1", parent="root", minr={"cpu": 60.0}, maxr={"cpu": 100.0}))
    errs = v.validate_create(
        eq("c2", parent="root", minr={"cpu": 60.0}, maxr={"cpu": 100.0})
    )
    assert any("min sum" in e for e in errs)
    assert not v.admit(eq("c2", parent="root", minr={"cpu": 40.0}, maxr={"cpu": 100.0}))
    # shrinking the parent's min below Σ child min is rejected
    errs = v.validate_update(
        eq("root", minr={"cpu": 50.0}, maxr={"cpu": 100.0}, is_parent=True)
    )
    assert any("new min" in e for e in errs)


def test_quota_tree_id_immutable_and_delete_guard():
    v = QuotaTopologyValidator()
    assert not v.admit(eq("root", is_parent=True, tree="t1"))
    updated = eq("root", is_parent=True, tree="t2")
    errs = v.validate_update(updated)
    assert any("immutable" in e for e in errs)
    # two-step move t1 -> "" -> t2 is also rejected
    errs = v.validate_update(eq("root", is_parent=True, tree=""))
    assert any("immutable" in e for e in errs)
    assert not v.admit(eq("kid", parent="root", tree="t1"))
    assert v.validate_delete("root")  # has a child
    assert not v.delete("kid")
    v.pod_counts["root"] = 2
    assert v.validate_delete("root")  # has pods
    v.pod_counts["root"] = 0
    assert not v.delete("root")


def test_quota_admission_evaluator():
    mgr = GroupQuotaManager(cluster_total={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 1000.0})
    mgr.upsert_quota(
        eq("team", minr={ext.RES_CPU: 100.0}, maxr={ext.RES_CPU: 100.0})
    )
    mgr.set_leaf_requests(
        {"team": mgr.config.res_vector({ext.RES_CPU: 100.0})}
    )
    ev = QuotaAdmissionEvaluator(mgr, enabled=True)
    pod = Pod(
        meta=ObjectMeta(name="p", labels={ext.LABEL_QUOTA_NAME: "team"}),
        spec=PodSpec(requests={ext.RES_CPU: 50.0}),
    )
    assert ev.admit(pod) == []
    mgr.charge("team", {ext.RES_CPU: 80.0})
    assert ev.admit(pod)  # 80 + 50 > 100
    ev.enabled = False
    assert ev.admit(pod) == []
    # default follows the EnableQuotaAdmission feature gate LIVE (off
    # upstream; flipping the gate affects an already-built evaluator)
    from koordinator_tpu.utils.features import MANAGER_GATES

    gated = QuotaAdmissionEvaluator(mgr)
    assert gated.admit(pod) == []           # gate off -> no admission check
    with MANAGER_GATES.override("EnableQuotaAdmission", True):
        assert gated.admit(pod)             # same instance, gate now on


# ---- quota profile controller ----


def test_quota_profile_sums_selected_nodes():
    ctrl = QuotaProfileController()
    ctrl.upsert(
        ElasticQuotaProfile(
            meta=ObjectMeta(name="gpu-pool"),
            node_selector={"pool": "gpu"},
            resource_keys=[ext.RES_CPU],
        )
    )
    nodes = [
        Node(
            meta=ObjectMeta(name=f"n{i}", labels={"pool": "gpu" if i < 2 else "cpu"}),
            status=NodeStatus(allocatable={ext.RES_CPU: 100.0, ext.RES_MEMORY: 50.0}),
        )
        for i in range(4)
    ]
    (quota,) = ctrl.reconcile(nodes)
    assert quota.meta.name == "gpu-pool"
    assert quota.min == {ext.RES_CPU: 200.0}
    assert quota.is_parent and quota.tree_id == "gpu-pool"


def test_quota_profile_ratio_decoration():
    ctrl = QuotaProfileController()
    prof = ElasticQuotaProfile(
        meta=ObjectMeta(
            name="p", annotations={ANNOTATION_RESOURCE_RATIO: "0.5"}
        ),
        node_selector={},
    )
    ctrl.upsert(prof)
    nodes = [
        Node(
            meta=ObjectMeta(name="n"),
            status=NodeStatus(allocatable={ext.RES_CPU: 100.0}),
        )
    ]
    (quota,) = ctrl.reconcile(nodes)
    assert quota.min[ext.RES_CPU] == 50.0


# ---- node / cm webhooks ----


def test_node_amplification_validation():
    node = Node(meta=ObjectMeta(name="n"))
    assert validate_node(node) == []
    node.meta.annotations[ext.ANNOTATION_NODE_AMPLIFICATION] = "cpu=1.5"
    assert validate_node(node) == []
    node.meta.annotations[ext.ANNOTATION_NODE_AMPLIFICATION] = "cpu=0.5"
    assert any("< 1.0" in e for e in validate_node(node))
    node.meta.annotations[ext.ANNOTATION_NODE_AMPLIFICATION] = "cpu=abc"
    assert any("malformed" in e for e in validate_node(node))


def test_config_validation():
    assert validate_colocation_strategy(ColocationStrategy()) == []
    assert validate_colocation_strategy(ColocationStrategy(reserve_ratio=1.5))
    s = ResourceThresholdStrategy(memory_evict_threshold_percent=70.0,
                                  memory_evict_lower_percent=80.0)
    assert any("LowerPercent" in e for e in validate_threshold_strategy(s))
    assert validate_threshold_strategy(ResourceThresholdStrategy()) == []


# ---- nodemetric controller ----


def test_nodemetric_reconcile_creates_and_prunes():
    ctrl = NodeMetricController(NodeMetricCollectPolicy(report_interval_s=30.0))
    out = ctrl.reconcile(["a", "b"])
    assert set(out) == {"a", "b"}
    assert out["a"].report_interval_s == 30.0
    out = ctrl.reconcile(["b"])
    assert set(out) == {"b"}


# ---- noderesource plugin chain ----


def test_cpu_normalization_ratio_selection():
    strat = CPUNormalizationStrategy(
        enable=True,
        ratio_model={
            "Xeon": {"base": 1.0, "ht": 0.65, "turbo": 1.2, "ht_turbo": 0.8}
        },
    )
    plugin = CPUNormalizationPlugin(strat)
    assert plugin.ratio_for(CPUBasicInfo("Xeon", True, True)) == 0.8
    assert plugin.ratio_for(CPUBasicInfo("Xeon", False, False)) == 1.0
    node = Node(meta=ObjectMeta(name="n"))
    item = plugin.calculate(node, CPUBasicInfo("Xeon", True, False))
    assert item.annotations[ext.ANNOTATION_NODE_CPU_NORMALIZATION] == "0.6500"
    # unknown model degrades to reset
    assert plugin.calculate(node, CPUBasicInfo("M1", False, False)).reset


def test_amplification_chain_and_parse():
    node = Node(meta=ObjectMeta(name="n"))
    amp = ResourceAmplificationPlugin({ext.RES_CPU: 2.0})
    item = amp.calculate(node, normalization_ratio=0.8)
    apply_items(node, [item])
    ratios = parse_amplification(node)
    assert abs(ratios[ext.RES_CPU] - 1.6) < 1e-6
    # sub-1.0 final ratio is never published (reference plugin.go:107-109),
    # so the node webhook's ratio >= 1 rule always holds
    item = ResourceAmplificationPlugin().calculate(node, normalization_ratio=0.8)
    assert item.reset
    apply_items(node, [item])
    assert validate_node(node) == []
    assert ext.ANNOTATION_NODE_AMPLIFICATION not in node.meta.annotations


def test_device_resource_plugins():
    node = Node(meta=ObjectMeta(name="n"))
    dev = Device(
        meta=ObjectMeta(name="n"),
        devices=[
            DeviceInfo("gpu", 0, {ext.RES_GPU_CORE: 100, ext.RES_GPU_MEMORY: 80_000}),
            DeviceInfo("gpu", 1, {ext.RES_GPU_CORE: 100, ext.RES_GPU_MEMORY: 80_000}),
            DeviceInfo("rdma", 0, {}),
        ],
    )
    items = [
        GPUDeviceResourcePlugin().calculate(node, dev, gpu_model="A100"),
        RDMADeviceResourcePlugin().calculate(node, dev),
    ]
    apply_items(node, items)
    assert node.status.allocatable[ext.RES_GPU] == 2.0
    assert node.status.allocatable[ext.RES_GPU_MEMORY] == 160_000.0
    assert node.status.allocatable[ext.RES_RDMA] == 1.0
    assert node.meta.labels["node.koordinator.sh/gpu-model"] == "A100"
    # device removal: reset clears the owned resources and labels too
    reset_items = [
        GPUDeviceResourcePlugin().calculate(node, None),
        RDMADeviceResourcePlugin().calculate(node, None),
    ]
    assert all(i.reset for i in reset_items)
    apply_items(node, reset_items)
    assert ext.RES_GPU not in node.status.allocatable
    assert ext.RES_GPU_MEMORY not in node.status.allocatable
    assert ext.RES_RDMA not in node.status.allocatable
    assert "node.koordinator.sh/gpu-model" not in node.meta.labels


# ---- colocation profile reconciler ----


def test_colocation_controller_reconciles_existing_pods():
    profile = ClusterColocationProfile(
        meta=ObjectMeta(name="spark"),
        selector={"app": "spark"},
        qos_class=QoSClass.BE,
        priority=5500,
        labels={"managed": "koord"},
        resource_translation={ext.RES_CPU: ext.RES_BATCH_CPU},
    )
    ctrl = ColocationProfileController(ProfileMutator([profile]))
    pending = Pod(
        meta=ObjectMeta(name="exec-1", labels={"app": "spark"}),
        spec=PodSpec(requests={ext.RES_CPU: 1000.0}),
    )
    bound = Pod(
        meta=ObjectMeta(name="exec-2", labels={"app": "spark"}),
        spec=PodSpec(requests={ext.RES_CPU: 1000.0}, node_name="n0"),
        phase=PodPhase.RUNNING,
    )
    other = Pod(meta=ObjectMeta(name="web", labels={"app": "web"}))
    changed = ctrl.reconcile([pending, bound, other])
    assert {p.meta.name for p in changed} == {"exec-1", "exec-2"}
    # a translation-only profile still reports the pending pod as changed
    xlate_only = ClusterColocationProfile(
        meta=ObjectMeta(name="xlate"),
        selector={"app": "ml"},
        resource_translation={ext.RES_MEMORY: ext.RES_BATCH_MEMORY},
    )
    ctrl2 = ColocationProfileController(ProfileMutator([xlate_only]))
    p = Pod(
        meta=ObjectMeta(name="ml-1", labels={"app": "ml"}),
        spec=PodSpec(requests={ext.RES_MEMORY: 2048.0}),
    )
    assert [q.meta.name for q in ctrl2.reconcile([p])] == ["ml-1"]
    # pending pod got the full mutation including resource rewrite
    assert ext.RES_BATCH_CPU in pending.spec.requests
    assert pending.spec.priority == 5500
    # bound pod got metadata only — spec untouched
    assert ext.RES_CPU in bound.spec.requests
    assert bound.meta.labels["managed"] == "koord"
    assert bound.spec.priority is None


def test_node_amplification_mutation_idempotent():
    """pkg/webhook/node/mutating: amplified allocatable = raw x ratio with
    the raw base preserved in the annotation, so repeated status updates
    never compound the ratio; the scheduler snapshot then sees amplified
    capacity."""
    import json

    from koordinator_tpu.manager.node_webhook import mutate_node_status

    node = Node(
        meta=ObjectMeta(
            name="amp",
            annotations={
                ext.ANNOTATION_NODE_AMPLIFICATION: f"{ext.RES_CPU}=1.5"
            },
        ),
        status=NodeStatus(allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 1024}),
    )
    mutate_node_status(node)
    assert node.status.allocatable[ext.RES_CPU] == 96000
    assert node.status.allocatable[ext.RES_MEMORY] == 1024
    raw = json.loads(node.meta.annotations[ext.ANNOTATION_NODE_RAW_ALLOCATABLE])
    assert raw[ext.RES_CPU] == 64000
    # idempotent: a second webhook pass must not compound
    mutate_node_status(node)
    assert node.status.allocatable[ext.RES_CPU] == 96000

    # the snapshot ingests the amplified capacity
    from koordinator_tpu.core.snapshot import ClusterSnapshot

    snap = ClusterSnapshot()
    idx = snap.upsert_node(node)
    cpu_i = list(snap.config.resources).index(ext.RES_CPU)
    assert snap.nodes.allocatable[idx][cpu_i] == 96000


# ---- device-resource + annotation-shape validation
# (verify_device_resource.go:68-176, verify_annotations.go:60-76) ----


def _vpod(requests=None, annotations=None, labels=None, prio=9000):
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec

    return Pod(
        meta=ObjectMeta(
            name="v", labels=labels or {}, annotations=annotations or {}
        ),
        spec=PodSpec(requests=requests or {}, priority=prio),
    )


def test_validate_gpu_and_share_mutually_exclusive():
    from koordinator_tpu.manager.validating import validate_pod

    errs = validate_pod(
        _vpod(requests={ext.RES_KOORD_GPU: 100, ext.RES_GPU_SHARED: 1})
    )
    assert errs == ["cannot declare GPU and GPU share at the same time"]


def test_validate_percentage_gpu_rules():
    from koordinator_tpu.manager.validating import validate_pod

    assert validate_pod(_vpod(requests={ext.RES_KOORD_GPU: 0})) != []
    assert any(
        "percentage of 100" in e
        for e in validate_pod(_vpod(requests={ext.RES_KOORD_GPU: 150}))
    )
    assert validate_pod(_vpod(requests={ext.RES_KOORD_GPU: 50})) == []
    assert validate_pod(_vpod(requests={ext.RES_KOORD_GPU: 200})) == []


def test_validate_gpu_share_rules():
    from koordinator_tpu.manager.validating import validate_pod

    # neither memory nor ratio declared
    assert any(
        "both zero" in e
        for e in validate_pod(_vpod(requests={ext.RES_GPU_SHARED: 1}))
    )
    # both declared
    assert any(
        "at the same time" in e
        for e in validate_pod(
            _vpod(
                requests={
                    ext.RES_GPU_SHARED: 1,
                    ext.RES_GPU_MEMORY: 1024,
                    ext.RES_GPU_MEMORY_RATIO: 50,
                }
            )
        )
    )
    # ratio not a multiple of the share count
    assert any(
        "multiple of shared" in e
        for e in validate_pod(
            _vpod(requests={ext.RES_GPU_SHARED: 2, ext.RES_GPU_MEMORY_RATIO: 101})
        )
    )
    # valid shared declaration
    assert (
        validate_pod(
            _vpod(requests={ext.RES_GPU_SHARED: 2, ext.RES_GPU_MEMORY_RATIO: 200})
        )
        == []
    )


def test_validate_forbidden_reserve_pod_annotation():
    from koordinator_tpu.manager.validating import validate_pod

    errs = validate_pod(
        _vpod(annotations={f"scheduling.{ext.DOMAIN}/reserve-pod": "true"})
    )
    assert any("cannot be set" in e for e in errs)


def test_validate_annotation_shapes():
    from koordinator_tpu.manager.validating import validate_pod

    cases = [
        ({ext.ANNOTATION_RESOURCE_SPEC: "not json"}, "not valid JSON"),
        ({ext.ANNOTATION_RESOURCE_SPEC: '{"preferredCPUBindPolicy": "Weird"}'},
         "unknown preferredCPUBindPolicy"),
        ({ext.ANNOTATION_RESOURCE_STATUS: "[1]"}, "must be an object"),
        ({ext.ANNOTATION_RESOURCE_STATUS: '{"cpuset": 3}'}, "must be a string"),
        ({ext.ANNOTATION_RESOURCE_STATUS: '{"numaNodeResources": [{}]}'},
         "numaNodeResources"),
        ({ext.ANNOTATION_DEVICE_ALLOCATED: '{"gpu": [{"resources": {}}]}'},
         "device-allocated[gpu]"),
        ({ext.ANNOTATION_RESERVATION_AFFINITY: "[1]"}, "must be an object"),
        ({ext.ANNOTATION_GPU_PARTITION_SPEC:
          '{"ringBusBandwidth": "fast"}'}, "must be numeric"),
        ({ext.ANNOTATION_GPU_PARTITION_SPEC:
          '{"allocatePolicy": "Always"}'}, "allocatePolicy"),
        ({ext.ANNOTATION_DEVICE_JOINT_ALLOCATE: '{"deviceTypes": "gpu"}'},
         "deviceTypes"),
    ]
    for ann, want in cases:
        errs = validate_pod(_vpod(annotations=ann))
        assert any(want in e for e in errs), (ann, errs)
    # well-formed payloads pass
    ok = _vpod(
        annotations={
            ext.ANNOTATION_RESOURCE_SPEC: '{"preferredCPUBindPolicy": "FullPCPUs"}',
            ext.ANNOTATION_GPU_PARTITION_SPEC:
                '{"allocatePolicy": "Restricted", "ringBusBandwidth": 200}',
            ext.ANNOTATION_DEVICE_JOINT_ALLOCATE:
                '{"deviceTypes": ["gpu", "rdma"], "requiredScope": "SamePCIe"}',
        }
    )
    assert validate_pod(ok) == []


# ---- slo-controller-config validating webhook (pkg/webhook/cm) ----


def test_sloconfig_ranges_and_orderings():
    import json

    from koordinator_tpu.manager.sloconfig_webhook import (
        RESOURCE_THRESHOLD_CONFIG_KEY,
        validate_slo_configmap,
    )

    ok = {
        RESOURCE_THRESHOLD_CONFIG_KEY: json.dumps(
            {
                "clusterStrategy": {},
                "cpuSuppressThresholdPercent": 65,
                "memoryEvictLowerPercent": 68,
                "memoryEvictThresholdPercent": 70,
            }
        )
    }
    assert validate_slo_configmap(ok) == []
    bad = {
        RESOURCE_THRESHOLD_CONFIG_KEY: json.dumps(
            {
                "cpuSuppressThresholdPercent": 120,     # > 100
                "memoryEvictLowerPercent": 80,
                "memoryEvictThresholdPercent": 70,      # lower >= threshold
            }
        )
    }
    errs = validate_slo_configmap(bad)
    assert any("cpuSuppressThresholdPercent" in e for e in errs)
    assert any("memoryEvictLowerPercent" in e for e in errs)


def test_sloconfig_unchanged_keys_skipped_and_bad_json():
    import json

    from koordinator_tpu.manager.sloconfig_webhook import (
        CPU_BURST_CONFIG_KEY,
        validate_slo_configmap,
    )

    bad = {CPU_BURST_CONFIG_KEY: "{not json"}
    assert validate_slo_configmap(bad)
    # unchanged (even invalid) keys are not re-validated (CommonChecker
    # IsCfgNotEmptyAndChanged)
    assert validate_slo_configmap(bad, old_data=bad) == []
    changed = {CPU_BURST_CONFIG_KEY: json.dumps({"cfsQuotaBurstPercent": 50})}
    errs = validate_slo_configmap(changed, old_data=bad)
    assert any("cfsQuotaBurstPercent" in e for e in errs)


def test_sloconfig_profile_checks():
    import json

    from koordinator_tpu.manager.sloconfig_webhook import (
        COLOCATION_CONFIG_KEY,
        node_profile_conflicts,
        validate_slo_configmap,
    )

    cfg = {
        COLOCATION_CONFIG_KEY: json.dumps(
            {
                "enable": True,
                "nodeConfigs": [
                    {"name": "a", "nodeSelector": {"matchLabels": {"pool": "x"}}},
                    {"name": "a", "nodeSelector": {"matchLabels": {"pool": "y"}}},
                    {"name": "c", "nodeSelector": {}},
                ],
            }
        )
    }
    errs = validate_slo_configmap(cfg)
    assert any("duplicate profile name" in e for e in errs)
    assert any("must not be empty" in e for e in errs)
    # overlap: {pool: x} and {pool: x, zone: z} can match the same node
    overlap = {
        COLOCATION_CONFIG_KEY: json.dumps(
            {
                "nodeConfigs": [
                    {"name": "a", "nodeSelector": {"matchLabels": {"pool": "x"}}},
                    {
                        "name": "b",
                        "nodeSelector": {
                            "matchLabels": {"pool": "x", "zone": "z"}
                        },
                    },
                ]
            }
        )
    }
    errs2 = validate_slo_configmap(overlap)
    assert any("overlapping node selectors" in e for e in errs2)
    # disjoint selectors are fine, and node-conflict check agrees
    disjoint = {
        COLOCATION_CONFIG_KEY: json.dumps(
            {
                "nodeConfigs": [
                    {"name": "a", "nodeSelector": {"matchLabels": {"pool": "x"}}},
                    {"name": "b", "nodeSelector": {"matchLabels": {"pool": "y"}}},
                ]
            }
        )
    }
    assert validate_slo_configmap(disjoint) == []
    assert node_profile_conflicts(disjoint, {"pool": "x"}) == []
    assert node_profile_conflicts(overlap, {"pool": "x", "zone": "z"})


def test_sloconfig_qos_class_leaf_ranges():
    import json

    from koordinator_tpu.manager.sloconfig_webhook import (
        RESOURCE_QOS_CONFIG_KEY,
        validate_slo_configmap,
    )

    bad = {
        RESOURCE_QOS_CONFIG_KEY: json.dumps(
            {
                "beClass": {
                    "cpuQOS": {"groupIdentity": 5},       # max 2
                    "memoryQOS": {"wmarkMinAdj": -30},    # min -25
                }
            }
        )
    }
    errs = validate_slo_configmap(bad)
    assert any("groupIdentity" in e for e in errs)
    assert any("wmarkMinAdj" in e for e in errs)


def test_profile_mutates_reservation():
    """Reservation mutating webhook
    (pkg/webhook/reservation/mutating/cluster_colocation_profile.go):
    matching profiles rewrite reservation labels/QoS/resource names."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        ClusterColocationProfile,
        ObjectMeta,
        Reservation,
    )
    from koordinator_tpu.api.extension import QoSClass
    from koordinator_tpu.manager.profile import ProfileMutator

    mutator = ProfileMutator(
        [
            ClusterColocationProfile(
                meta=ObjectMeta(name="batch-profile"),
                selector={"workload": "spark"},
                labels={"injected": "yes"},
                qos_class=QoSClass.BE,
                resource_translation={
                    ext.RES_CPU: ext.RES_BATCH_CPU,
                    ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
                },
            )
        ]
    )
    r = Reservation(
        meta=ObjectMeta(name="hold", labels={"workload": "spark"}),
        requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096},
    )
    mutator.mutate_reservation(r)
    assert r.meta.labels["injected"] == "yes"
    assert r.meta.labels[ext.LABEL_POD_QOS] == "BE"
    assert r.requests == {ext.RES_BATCH_CPU: 4000, ext.RES_BATCH_MEMORY: 4096}
    # non-matching reservation untouched
    r2 = Reservation(meta=ObjectMeta(name="other"), requests={ext.RES_CPU: 1})
    mutator.mutate_reservation(r2)
    assert r2.requests == {ext.RES_CPU: 1}


def test_sloconfig_match_expressions_overlap():
    """Advisor r2 regression: profiles whose nodeSelector uses only
    matchExpressions must go through the requirement-conflict test, not be
    treated as match-all. Disjoint In sets on the same key do not overlap;
    an In set vs a covering NotIn does not overlap; genuinely
    co-satisfiable expressions do."""
    import json

    from koordinator_tpu.manager.sloconfig_webhook import (
        COLOCATION_CONFIG_KEY,
        node_profile_conflicts,
        validate_slo_configmap,
    )

    def cfg_of(*profiles):
        return {
            COLOCATION_CONFIG_KEY: json.dumps({"nodeConfigs": list(profiles)})
        }

    def expr(key, op, *vals):
        e = {"key": key, "operator": op}
        if vals:
            e["values"] = list(vals)
        return e

    # disjoint In sets on one key: no overlap — must be admitted
    disjoint = cfg_of(
        {"name": "a", "nodeSelector": {"matchExpressions": [expr("pool", "In", "x")]}},
        {"name": "b", "nodeSelector": {"matchExpressions": [expr("pool", "In", "y")]}},
    )
    assert validate_slo_configmap(disjoint) == []
    # In {x} vs NotIn {x}: no overlap
    innotin = cfg_of(
        {"name": "a", "nodeSelector": {"matchExpressions": [expr("pool", "In", "x")]}},
        {"name": "b", "nodeSelector": {"matchExpressions": [expr("pool", "NotIn", "x")]}},
    )
    assert validate_slo_configmap(innotin) == []
    # Exists vs DoesNotExist: no overlap
    existence = cfg_of(
        {"name": "a", "nodeSelector": {"matchExpressions": [expr("gpu", "Exists")]}},
        {"name": "b", "nodeSelector": {"matchExpressions": [expr("gpu", "DoesNotExist")]}},
    )
    assert validate_slo_configmap(existence) == []
    # overlapping: In {x, y} vs In {y, z} share y — rejected
    shared = cfg_of(
        {"name": "a", "nodeSelector": {"matchExpressions": [expr("pool", "In", "x", "y")]}},
        {"name": "b", "nodeSelector": {"matchExpressions": [expr("pool", "In", "y", "z")]}},
    )
    assert any("overlapping" in e for e in validate_slo_configmap(shared))
    # mixed: matchLabels {pool: x} vs matchExpressions In {x} — rejected
    mixed = cfg_of(
        {"name": "a", "nodeSelector": {"matchLabels": {"pool": "x"}}},
        {"name": "b", "nodeSelector": {"matchExpressions": [expr("pool", "In", "x")]}},
    )
    assert any("overlapping" in e for e in validate_slo_configmap(mixed))
    # the concrete-node conflict check also evaluates expressions
    assert node_profile_conflicts(mixed, {"pool": "x"})
    assert node_profile_conflicts(mixed, {"pool": "y"}) == []
