"""Deterministic fault injection + failure-domain hardening (robustness
PR tentpole): the injector's schedule/determinism/zero-overhead contract,
the shared RetryPolicy, and the scheduler's defenses — NaN quarantine,
solver fallback ladder with re-promotion, the transactional Reserve
journal, the per-cycle deadline degrade, the feeder-queue stall guard —
plus /healthz and the exceptions_total audit."""

import time

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.chaos import (
    NULL_INJECTOR,
    ChaosError,
    FaultInjector,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# injector mechanics
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_unarmed_point_is_inert(self):
        inj = FaultInjector(seed=1)
        assert inj.fire("anything") is False
        assert inj.trace == []

    def test_error_schedule_raises_and_traces(self):
        inj = FaultInjector(seed=1)
        inj.arm("p.err", error=ChaosError, times=2)
        with pytest.raises(ChaosError):
            inj.fire("p.err")
        with pytest.raises(ChaosError):
            inj.fire("p.err")
        assert inj.fire("p.err") is False   # times exhausted
        assert [(p, k) for _s, p, k in inj.trace] == [
            ("p.err", "error"),
            ("p.err", "error"),
        ]

    def test_latency_schedule_uses_injected_sleep(self):
        slept = []
        inj = FaultInjector(seed=1, sleep=slept.append)
        inj.arm("p.slow", latency_s=0.5)
        assert inj.fire("p.slow") is True
        assert slept == [0.5]

    def test_at_hits_fires_exactly_on_those_evaluations(self):
        inj = FaultInjector(seed=1)
        inj.arm("p", at_hits={2, 4})
        assert [inj.fire("p") for _ in range(5)] == [
            False, True, False, True, False,
        ]

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            inj = FaultInjector(seed=seed)
            inj.arm("p", probability=0.5)
            return [inj.fire("p") for _ in range(32)]

        assert run(3) == run(3)
        assert run(3) != run(4)   # astronomically unlikely to collide

    def test_disarm_restores_fast_path(self):
        inj = FaultInjector()
        inj.arm("p")
        assert inj.enabled
        inj.disarm("p")
        assert not inj.enabled

    def test_counter_records_fired_points(self):
        from koordinator_tpu.utils.metrics import Registry

        reg = Registry()
        c = reg.counter("fault_injected_total", "", labels=("point",))
        inj = FaultInjector(counter=c)
        inj.arm("p.x", times=3)
        for _ in range(5):
            inj.fire("p.x")
        assert c.value(point="p.x") == 3.0


class TestDisabledOverhead:
    def test_null_injector_is_shared_and_disabled(self):
        assert NULL_INJECTOR.enabled is False
        assert NULL_INJECTOR.fire("any.point") is False

    def test_disabled_fire_overhead_negligible(self):
        # same guard shape as test_obs_overhead: 100k disabled fire()
        # calls well under a second (one attribute read + return each)
        inj = FaultInjector()
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            inj.fire("hot.point")
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"{n} disabled fires took {elapsed:.2f}s"
        assert inj.trace == []

    def test_scheduler_without_chaos_uses_null_injector(self):
        s = BatchScheduler()
        s.extender.monitor.stop_background()
        assert s.chaos is NULL_INJECTOR


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_exponential_backoff_capped(self):
        p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.35,
                        jitter=0.0)
        assert [p.delay_for(i) for i in range(4)] == [
            0.1, 0.2, 0.35, 0.35,
        ]

    def test_delay_for_never_overflows_on_huge_attempt_counts(self):
        # never-die loops (informer re-list, koordlet ticks) feed an
        # unbounded attempt counter; 2.0**1075 would raise OverflowError
        p = RetryPolicy(base_delay_s=0.5, multiplier=2.0, max_delay_s=30.0,
                        jitter=0.0)
        assert p.delay_for(2000) == 30.0
        assert p.delay_for(10**9) == 30.0

    def test_run_retries_then_succeeds(self):
        calls = []
        slept = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        p = RetryPolicy(max_attempts=5, base_delay_s=0.01, jitter=0.0)
        assert p.run(fn, retry_on=(OSError,), sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2

    def test_run_exhausts_attempts(self):
        p = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("always")

        with pytest.raises(ValueError):
            p.run(fn, retry_on=(ValueError,), sleep=lambda _s: None)
        assert len(calls) == 3

    def test_non_retryable_escapes_immediately(self):
        p = RetryPolicy(max_attempts=5)
        calls = []

        def fn():
            calls.append(1)
            raise KeyError("fatal")

        with pytest.raises(KeyError):
            p.run(fn, retry_on=(OSError,), sleep=lambda _s: None)
        assert len(calls) == 1

    def test_deadline_bounds_total_wait(self):
        p = RetryPolicy(
            max_attempts=100, base_delay_s=1.0, jitter=0.0, deadline_s=2.5
        )
        clock = [0.0]

        def fake_sleep(s):
            clock[0] += s

        def fn():
            raise OSError("down")

        with pytest.raises(OSError):
            p.run(
                fn,
                retry_on=(OSError,),
                sleep=fake_sleep,
                clock=lambda: clock[0],
            )
        assert clock[0] <= 2.5

    def test_deadline_shorter_than_first_backoff_raises_without_sleep(self):
        # HA recovery satellite: a takeover-path caller with a tight
        # deadline must fail FAST — the first backoff alone would blow
        # the budget, so the original error escapes with zero sleeping
        p = RetryPolicy(
            max_attempts=5, base_delay_s=1.0, jitter=0.0, deadline_s=0.5
        )
        calls, slept = [], []

        def fn():
            calls.append(1)
            raise OSError("down")

        with pytest.raises(OSError):
            p.run(
                fn,
                retry_on=(OSError,),
                sleep=slept.append,
                clock=lambda: 0.0,
            )
        assert len(calls) == 1 and slept == []

    def test_jitter_never_pushes_past_deadline(self):
        # the deadline check runs on the JITTERED delay, so an unlucky
        # +jitter draw can only shorten the retry budget, never sleep
        # through the deadline — over many seeded draws the total slept
        # time stays within deadline_s
        import random as _random

        p = RetryPolicy(
            max_attempts=1000,
            base_delay_s=0.4,
            multiplier=1.0,
            max_delay_s=0.4,
            jitter=0.5,
            deadline_s=2.0,
        )
        for seed in range(20):
            clock = [0.0]

            def fake_sleep(s):
                clock[0] += s

            def fn():
                raise OSError("down")

            with pytest.raises(OSError):
                p.run(
                    fn,
                    retry_on=(OSError,),
                    sleep=fake_sleep,
                    clock=lambda: clock[0],
                    rng=_random.Random(seed),
                )
            assert clock[0] <= 2.0, seed

    def test_jitter_bounded_by_fraction(self):
        import random as _random

        p = RetryPolicy(base_delay_s=1.0, max_delay_s=1.0, jitter=0.25)
        rng = _random.Random(0)
        for _ in range(200):
            d = p.delay_for(0, rng)
            assert 0.75 <= d <= 1.25

    def test_counter_labels_site(self):
        from koordinator_tpu.utils.metrics import Registry

        reg = Registry()
        c = reg.counter("retry_attempts_total", "", labels=("site",))
        p = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
        state = {"n": 0}

        def fn():
            state["n"] += 1
            if state["n"] < 3:
                raise OSError()
            return 1

        p.run(fn, retry_on=(OSError,), site="s1", counter=c,
              sleep=lambda _s: None)
        assert c.value(site="s1") == 2.0


# ---------------------------------------------------------------------------
# scheduler hardening
# ---------------------------------------------------------------------------


def _mk_sched(n_nodes=4, **kw):
    s = BatchScheduler(
        args=LoadAwareArgs(usage_thresholds={}), batch_bucket=8, **kw
    )
    s.extender.monitor.stop_background()
    for i in range(n_nodes):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000.0, ext.RES_MEMORY: 65536.0}
                ),
            )
        )
    return s


def _pods(n, prefix="p", cpu=1000.0):
    return [
        Pod(
            meta=ObjectMeta(name=f"{prefix}{i}", uid=f"{prefix}{i}"),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 256.0},
                priority=9000,
            ),
        )
        for i in range(n)
    ]


def _accounting_ok(snap):
    want = np.zeros_like(snap.nodes.requested)
    for _uid, ap in snap._assumed.items():
        want[ap.node_idx] += ap.request
    np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)


def _resident_ok(sched):
    from koordinator_tpu.sim.longrun import assert_resident_state_converged

    assert_resident_state_converged(sched)


class TestNanQuarantine:
    def test_injected_nan_row_is_quarantined_not_placed(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        chaos.arm("solver.nan_rows", times=1)
        pods = _pods(4)
        out = s.schedule(pods)
        # the corrupted pod (row 0) is rejected with the new reason;
        # everyone else places normally
        assert len(out.bound) == 3
        assert [p.meta.uid for p in out.unschedulable] == ["p0"]
        recs = s.extender.rejections.for_uid("p0")
        assert recs and recs[-1].reason == "nan_inf_quarantined"
        assert recs[-1].plugin == "numeric_guard"
        _accounting_ok(s.snapshot)

    def test_genuinely_nonfinite_spec_is_quarantined(self):
        s = _mk_sched()
        bad = Pod(
            meta=ObjectMeta(name="bad", uid="bad"),
            spec=PodSpec(
                requests={ext.RES_CPU: float("inf"), ext.RES_MEMORY: 1.0},
                priority=9000,
            ),
        )
        out = s.schedule([bad] + _pods(2, prefix="ok"))
        assert {p.meta.uid for p in out.unschedulable} == {"bad"}
        assert len(out.bound) == 2

    def test_quarantined_pod_retries_clean_next_cycle(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        chaos.arm("solver.nan_rows", times=1)
        pods = _pods(2)
        out1 = s.schedule(pods)
        assert len(out1.unschedulable) == 1
        out2 = s.schedule(out1.unschedulable)   # injection exhausted
        assert len(out2.bound) == 1
        _accounting_ok(s.snapshot)


class TestFallbackLadder:
    def test_dispatch_failure_falls_back_and_still_places(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos, fallback_repromote_after=2)
        chaos.arm("solver.dispatch", error=RuntimeError, times=1)
        pods = _pods(6)
        out = s.schedule(pods)
        # the host reference path placed everyone despite the failure
        assert len(out.bound) == 6
        assert s._fallback_level >= 1
        reg = s.extender.registry
        assert reg.get("solver_fallback_total").value(level="1") >= 1.0
        assert not s.extender.health.ok()
        _accounting_ok(s.snapshot)

    def test_repromotion_after_clean_cycles(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos, fallback_repromote_after=2)
        chaos.arm("solver.dispatch", error=RuntimeError, times=1)
        s.schedule(_pods(2, prefix="a"))
        assert s._fallback_level == 1
        s.schedule(_pods(2, prefix="b"))
        s.schedule(_pods(2, prefix="c"))
        assert s._fallback_level == 0
        assert s.extender.health.ok()

    def test_both_device_levels_fail_host_reference_places(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        chaos.arm("solver.dispatch", error=RuntimeError, times=1)
        chaos.arm("solver.dispatch_chunk", error=RuntimeError, times=1)
        out = s.schedule(_pods(5))
        assert len(out.bound) == 5
        assert s._fallback_level == 2
        _accounting_ok(s.snapshot)

    def test_host_reference_respects_node_constraints(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        s._fallback_level = 2   # pin degraded mode
        pods = _pods(3)
        pods[1].spec.node_name = "n2"
        out = s.schedule(pods)
        nodes = {p.meta.uid: n for p, n in out.bound}
        assert nodes["p1"] == "n2"
        assert len(out.bound) == 3

    def test_host_reference_respects_quota_max(self):
        from koordinator_tpu.api.types import ElasticQuota
        from koordinator_tpu.scheduler.plugins.elasticquota import (
            GroupQuotaManager,
        )
        from koordinator_tpu.core.snapshot import ClusterSnapshot

        snap = ClusterSnapshot()
        gqm = GroupQuotaManager(snap.config, enable_preemption=False)
        gqm.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="team"),
                min={ext.RES_CPU: 1000, ext.RES_MEMORY: 256},
                max={ext.RES_CPU: 2000, ext.RES_MEMORY: 512},
            )
        )
        s = BatchScheduler(
            snap,
            LoadAwareArgs(usage_thresholds={}),
            quotas=gqm,
            batch_bucket=8,
        )
        s.extender.monitor.stop_background()
        for i in range(4):
            snap.upsert_node(
                Node(
                    meta=ObjectMeta(name=f"n{i}"),
                    status=NodeStatus(
                        allocatable={
                            ext.RES_CPU: 32000.0,
                            ext.RES_MEMORY: 65536.0,
                        }
                    ),
                )
            )
        s._fallback_level = 2
        pods = _pods(4)
        for p in pods:
            p.meta.labels[ext.LABEL_QUOTA_NAME] = "team"
        out = s.schedule(pods)
        # max of 2000 CPU admits exactly two 1000-CPU pods
        assert len(out.bound) == 2
        q = s.quotas.index_of("team")
        assert np.all(
            s.quotas.used[q] <= snap.config.res_vector(
                {ext.RES_CPU: 2000, ext.RES_MEMORY: 512}
            ) + 1e-3
        )


class TestReserveJournal:
    def test_mid_commit_crash_rolls_back_bit_exactly(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        # prime: one normal cycle so the resident state exists
        pre = s.schedule(_pods(2, prefix="pre"))
        assert len(pre.bound) == 2
        before_req = s.snapshot.nodes.requested.copy()
        before_assumed = set(s.snapshot._assumed)
        chaos.arm("commit.crash", error=RuntimeError, times=1)
        out = s.schedule(_pods(4, prefix="x"))
        # the whole chunk rolled back: nothing bound, nothing leaked
        assert out.bound == []
        assert len(out.unschedulable) == 4
        np.testing.assert_array_equal(
            s.snapshot.nodes.requested, before_req
        )
        assert set(s.snapshot._assumed) == before_assumed
        reg = s.extender.registry
        assert reg.get("commit_rollbacks_total").value() == 1.0
        recs = s.extender.rejections.for_uid("x0")
        assert recs and recs[-1].reason == "commit_rolled_back"
        # the dirty-row ledger reconciled: resident state == full re-lower
        _resident_ok(s)
        _accounting_ok(s.snapshot)

    def test_rolled_back_pods_place_next_cycle(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        chaos.arm("commit.crash", error=RuntimeError, times=1)
        out1 = s.schedule(_pods(3))
        assert out1.bound == []
        out2 = s.schedule(out1.unschedulable)
        assert len(out2.bound) == 3
        _resident_ok(s)
        assert s.extender.health.ok()   # commit recovered after clean cycle

    def test_reassume_rollback_restores_prior_charge(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        pods = _pods(1)
        out = s.schedule(pods)
        assert len(out.bound) == 1
        prior_req = s.snapshot.nodes.requested.copy()
        # schedule the SAME pod again (retry/re-schedule path re-assumes)
        chaos.arm("commit.crash", error=RuntimeError, times=1)
        out2 = s.schedule(pods)
        assert out2.bound == []
        # prior charge restored bit-exactly, pod still assumed
        np.testing.assert_array_equal(
            s.snapshot.nodes.requested, prior_req
        )
        assert s.snapshot.is_assumed("p0")
        _resident_ok(s)


class TestCycleDeadline:
    def test_deadline_defers_remaining_chunks_and_degrades(self):
        chaos = FaultInjector()
        s = _mk_sched(
            n_nodes=8, chaos=chaos, cycle_deadline_s=0.05,
            fallback_repromote_after=2,
        )
        s.batch_bucket = 64   # allow degrade room (floor is 16)
        chaos.arm("solver.dispatch", latency_s=0.2, times=1)
        # force multiple chunks via a tiny effective bucket: 70 pods over
        # bucket 64 → 2 chunks; the injected latency blows the deadline
        pods = _pods(70, cpu=100.0)
        out = s.schedule(pods)
        reg = s.extender.registry
        assert reg.get("cycle_deadline_exceeded_total").value() == 1.0
        # some pods deferred with the counted reason, none lost
        deferred = [
            r
            for p in out.unschedulable
            for r in s.extender.rejections.for_uid(p.meta.uid)
            if r.reason == "cycle_deadline_exceeded"
        ]
        assert deferred
        assert len(out.bound) + len(out.unschedulable) == 70
        # batch degraded for the next cycle
        assert s.effective_batch_bucket() < 64
        # deferred pods place on the (fault-free) next cycles
        pending = out.unschedulable
        for _ in range(4):
            nxt = s.schedule(pending)
            pending = nxt.unschedulable
            if not pending:
                break
        assert not pending
        _accounting_ok(s.snapshot)

    def test_clean_cycles_restore_bucket(self):
        s = _mk_sched(cycle_deadline_s=10.0, fallback_repromote_after=1)
        s.batch_bucket = 64
        s._bucket_degrade = 1
        s.schedule(_pods(2))
        assert s._bucket_degrade == 0
        assert s.effective_batch_bucket() == 64


class TestFeederStall:
    def test_stalled_fetch_surfaces_and_requeues(self):
        chaos = FaultInjector()
        s = _mk_sched(n_nodes=8, chaos=chaos, fetch_timeout_s=0.5)
        s.batch_bucket = 4
        # per-chunk pipelined path uses the prefetch feeder; stall it
        s._fallback_level = 1
        chaos.arm("solver.fetch.stall", times=1)
        pods = _pods(12, cpu=100.0)
        out = s.schedule(pods)
        stalled = [
            r
            for p in out.unschedulable
            for r in s.extender.rejections.for_uid(p.meta.uid)
            if r.reason == "solve_result_stalled"
        ]
        assert stalled, "stall must surface as a counted RejectReason"
        assert len(out.bound) + len(out.unschedulable) == 12
        # re-enqueued pods drain next cycle
        out2 = s.schedule(out.unschedulable)
        assert not out2.unschedulable
        _accounting_ok(s.snapshot)


# ---------------------------------------------------------------------------
# /healthz + exception accounting
# ---------------------------------------------------------------------------


class TestHealthz:
    def test_healthy_engine_returns_200(self):
        s = _mk_sched()
        code, body = s.extender.services.dispatch("GET", "/healthz")
        assert code == 200
        import json

        doc = json.loads(body)
        assert doc["ok"] is True
        assert doc["subsystems"]["solver"]["ok"] is True

    def test_degraded_solver_returns_503_then_recovers(self):
        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos, fallback_repromote_after=1)
        chaos.arm("solver.dispatch", error=RuntimeError, times=1)
        s.schedule(_pods(2, prefix="a"))
        code, body = s.extender.services.dispatch("GET", "/healthz")
        assert code == 503
        assert '"ok": false' in body
        s.schedule(_pods(2, prefix="b"))   # clean cycle re-promotes
        code, _ = s.extender.services.dispatch("GET", "/healthz")
        assert code == 200


class TestExceptionAudit:
    def test_report_exception_counts_into_registry(self):
        from koordinator_tpu.obs import report_exception
        from koordinator_tpu.utils.metrics import Registry

        reg = Registry()
        report_exception("site.a", ValueError("x"), registry=reg)
        report_exception("site.a", ValueError("y"), registry=reg)
        assert reg.get("exceptions_total").value(site="site.a") == 2.0

    def test_informer_handler_errors_are_counted(self):
        from koordinator_tpu.utils.informer import Informer, ObjectTracker
        from koordinator_tpu.utils.metrics import Registry

        reg = Registry()
        tracker = ObjectTracker()
        inf = Informer(tracker, error_registry=reg)
        inf.add_handlers(on_add=lambda k, o: 1 / 0)
        tracker.upsert("a", object())
        inf._relist()
        assert inf.handler_errors
        assert reg.get("exceptions_total").value(site="informer.handler") >= 1.0

    def test_koordlet_collector_failures_are_counted(self):
        from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig

        k = Koordlet(KoordletConfig(n_cpus=2, cgroup_root="/nonexistent",
                                    proc_root="/nonexistent"))

        class Boom:
            def collect(self, now):
                raise RuntimeError("collector down")

        k.collectors = [Boom()]
        k.collect_tick(now=1000.0)
        assert (
            k.registry.get("collect_errors_total").value(collector="Boom")
            == 1.0
        )
        assert (
            k.registry.get("exceptions_total").value(
                site="koordlet.collector.Boom"
            )
            == 1.0
        )


class TestInformerBackoff:
    def test_repeated_disconnects_back_off_and_recover(self):
        from koordinator_tpu.obs import HealthRegistry
        from koordinator_tpu.utils.informer import Informer, ObjectTracker

        chaos = FaultInjector()
        health = HealthRegistry()
        tracker = ObjectTracker()
        inf = Informer(
            tracker,
            chaos=chaos,
            health=health,
            name="informer.test",
            retry=RetryPolicy(
                max_attempts=1 << 30, base_delay_s=0.01, max_delay_s=0.05,
                jitter=0.0,
            ),
        )
        tracker.upsert("a", object())
        chaos.arm("informer.watch_closed", times=4)
        # the dedicated arm for the re-list latency point (chaos-coverage
        # exemption: informer points fire on informer threads, so they
        # cannot ride the deterministic soak schedule)
        chaos.arm("informer.relist.delay", latency_s=0.01, times=2)
        inf.start()
        try:
            deadline = time.monotonic() + 5.0
            while inf.relists < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert inf.relists >= 5   # initial + 4 injected disconnects
            assert inf.backoff_total_s > 0.0
            assert chaos.spec("informer.relist.delay").fired >= 1
            # after the injection budget is spent the stream stabilizes
            deadline = time.monotonic() + 5.0
            while not health.ok() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert health.ok()
            assert inf.consecutive_disconnects == 0
        finally:
            inf.stop()

    def test_wait_synced_wakes_on_condition_not_poll(self):
        from koordinator_tpu.utils.informer import Informer, ObjectTracker

        tracker = ObjectTracker()
        inf = Informer(tracker)
        inf.start()
        try:
            rv = tracker.upsert("k", object())
            t0 = time.perf_counter()
            assert inf.wait_synced(rv, timeout=5.0)
            assert time.perf_counter() - t0 < 2.0
            # timeout path returns False promptly
            assert inf.wait_synced(rv + 100, timeout=0.05) is False
        finally:
            inf.stop()
