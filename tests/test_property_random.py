"""Randomized invariant tests over the constrained scheduling paths.

Seeded generators (deterministic across runs) drive mixed workloads
through the full BatchScheduler and assert the invariants the r5 design
rests on: the solver's carried device/zone tables stay consistent with
the host managers, no resource is ever overcommitted, and hint paths
never change semantics.
"""

import json

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.plugins.deviceshare import FULL, DeviceManager
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NUMAManager,
    NUMAPolicy,
)


def _gpu_cluster(n_nodes, gpus_per_node, hetero=False, seed=0):
    rng = np.random.default_rng(seed)
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for i in range(n_nodes):
        name = f"n{i:03d}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 128000, ext.RES_MEMORY: 1 << 20}
                ),
            )
        )
        g = gpus_per_node
        if hetero:
            g = int(rng.choice([2, 4, gpus_per_node]))
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=m, numa_node=m % 2)
                    for m in range(g)
                ],
            )
        )
    return snap, dm


def _random_gpu_pods(n, seed):
    rng = np.random.default_rng(seed)
    pods = []
    for i in range(n):
        req = {ext.RES_CPU: int(rng.choice([1000, 2000, 4000]))}
        kind = rng.integers(0, 6)
        if kind < 3:
            req[ext.RES_GPU] = int(rng.choice([1, 2, 4]))
        elif kind < 5:
            req[ext.RES_GPU_MEMORY_RATIO] = int(rng.choice([20, 30, 50, 60]))
        # kind 5: no device demand at all
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"p{i:04d}"),
                spec=PodSpec(requests=req, priority=int(rng.integers(5000, 9999))),
            )
        )
    return pods


@pytest.mark.parametrize("seed", [1, 7, 23])
@pytest.mark.parametrize("hetero", [False, True])
def test_device_slots_never_overcommit_and_bound_pods_hold_minors(
    seed, hetero
):
    """Random mixed whole/fractional GPU workloads over (optionally
    heterogeneous) inventories, multiple chunks: after the drain, every
    minor's allocations sum within capacity, every bound GPU pod holds
    concrete minors, and unschedulable pods genuinely did not fit."""
    snap, dm = _gpu_cluster(12, 8, hetero=hetero, seed=seed)
    sched = BatchScheduler(snap, LoadAwareArgs(), devices=dm, batch_bucket=32)
    sched.extender.monitor.stop_background()
    pods = _random_gpu_pods(80, seed + 100)
    out = sched.schedule(pods)
    assert len(out.bound) + len(out.unschedulable) == len(pods)
    # no minor below zero free, and owner charges reconcile exactly
    for i in range(12):
        st = dm.node(f"n{i:03d}")
        if st is None:
            continue
        for free in st.gpu_free:
            assert -1e-6 <= free <= FULL + 1e-6
        per_minor = [0.0] * len(st.gpu_free)
        for picks in st.owners.values():
            for minor, pct, _core in picks:
                per_minor[minor] += pct
        for minor, used in enumerate(per_minor):
            assert used <= FULL + 1e-6, (i, minor, used)
            np.testing.assert_allclose(
                st.gpu_free[minor], FULL - used, atol=1e-3
            )
    for pod, node in out.bound:
        whole, share = ext.parse_gpu_request(pod.spec.requests)
        if whole or share:
            alloc = json.loads(
                pod.meta.annotations[ext.ANNOTATION_DEVICE_ALLOCATED]
            )
            minors = [e["minor"] for e in alloc["gpu"]]
            assert len(set(minors)) == len(minors)
            if whole and not share:
                assert len(minors) == whole


@pytest.mark.parametrize("seed", [3, 11])
def test_reschedule_after_release_reuses_freed_slots(seed):
    """Bind → release → rebind cycles keep the incremental lowering cache
    and the host slot state coherent (the dirty-row path, not just fresh
    lowering)."""
    snap, dm = _gpu_cluster(4, 4, seed=seed)
    sched = BatchScheduler(snap, LoadAwareArgs(), devices=dm, batch_bucket=32)
    sched.extender.monitor.stop_background()
    pods = _random_gpu_pods(16, seed)
    out1 = sched.schedule(pods)
    bound1 = list(out1.bound)
    assert bound1
    # release every bound pod (pod deleted), then schedule a fresh copy
    for pod, node in bound1:
        dm.release(pod.meta.uid, node)
        snap.forget_pod(pod.meta.uid)
    for i in range(4):
        st = dm.node(f"n{i:03d}")
        assert all(abs(f - FULL) < 1e-6 for f in st.gpu_free), st.gpu_free
    # the IDENTICAL mix binds at least as fully on the restored slots
    pods2 = _random_gpu_pods(16, seed)
    for p in pods2:
        p.meta.name = "re-" + p.meta.name
    out2 = sched.schedule(pods2)
    assert len(out2.bound) >= len(bound1)


@pytest.mark.parametrize("seed", [5, 19])
def test_numa_zone_accounting_reconciles_after_random_drain(seed):
    """Random LSR/LS mixes over SINGLE_NUMA_NODE topologies: per-zone
    used never exceeds capacity and equals the sum of owner charges;
    cpusets of co-located pods never overlap."""
    rng = np.random.default_rng(seed)
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8)
    for i in range(8):
        name = f"m{i}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            )
        )
        numa.register_node(
            name, topo, NUMAPolicy.SINGLE_NUMA_NODE, memory_per_zone_mib=65536
        )
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, batch_bucket=32)
    sched.extender.monitor.stop_background()
    pods = []
    for i in range(48):
        lsr = bool(rng.integers(0, 2))
        cpu = int(rng.choice([2000, 4000])) if lsr else int(rng.choice([500, 1500]))
        pods.append(
            Pod(
                meta=ObjectMeta(
                    name=f"q{i:03d}",
                    labels={ext.LABEL_POD_QOS: "LSR"} if lsr else {},
                ),
                spec=PodSpec(
                    requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 2048},
                    priority=int(rng.integers(6000, 9999)),
                ),
            )
        )
    out = sched.schedule(pods)
    assert len(out.bound) + len(out.unschedulable) == 48
    cpusets_by_node = {}
    for pod, node in out.bound:
        raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS)
        if raw and "cpuset" in raw:
            ids = set()
            for part in json.loads(raw)["cpuset"].split(","):
                if "-" in part:
                    a, b = part.split("-")
                    ids.update(range(int(a), int(b) + 1))
                elif part:
                    ids.add(int(part))
            prev = cpusets_by_node.setdefault(node, set())
            assert not (ids & prev), (node, ids, prev)
            prev |= ids
    for i in range(8):
        st = numa.node(f"m{i}")
        for z, (alloc, used) in enumerate(zip(st.zone_alloc, st.zone_used)):
            assert used[0] <= alloc[0] + 1e-3, (i, z, used, alloc)
            assert used[1] <= alloc[1] + 1e-3
        charge = [[0.0, 0.0] for _ in st.zone_alloc]
        for zone, vec, _nominal in st.owners.values():
            charge[zone][0] += vec[0]
            charge[zone][1] += vec[1]
        for z in range(len(charge)):
            np.testing.assert_allclose(
                st.zone_used[z][:2], charge[z], atol=1e-3
            )


def test_stream_scheduler_decides_every_pod_exactly_once():
    """Random submit/pump interleavings: every submitted pod is decided
    exactly once (bound or surfaced unschedulable after retries), and the
    backlog drains to zero."""
    rng = np.random.default_rng(42)
    from koordinator_tpu.scheduler.stream import StreamScheduler

    snap = ClusterSnapshot()
    for i in range(20):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"s{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384}
                ),
            )
        )
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=32)
    sched.extender.monitor.stop_background()
    stream = StreamScheduler(sched, max_batch=16, max_retries=2)
    decided = {}
    submitted = 0
    for wave in range(8):
        for _ in range(int(rng.integers(1, 12))):
            cpu = int(rng.choice([500, 1000, 10**7]))  # some can never fit
            stream.submit(
                Pod(
                    meta=ObjectMeta(name=f"w{wave}-{submitted}"),
                    spec=PodSpec(
                        requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 512}
                    ),
                )
            )
            submitted += 1
        for pod, node, lat in stream.pump():
            assert pod.meta.uid not in decided, "double decision"
            decided[pod.meta.uid] = (node, lat)
            assert lat >= 0
    for _ in range(6):
        if stream.backlog() == 0:
            break
        for pod, node, lat in stream.pump():
            assert pod.meta.uid not in decided
            decided[pod.meta.uid] = (node, lat)
    assert stream.backlog() == 0
    assert len(decided) == submitted
    # the impossible pods were surfaced, not silently dropped
    giants = [u for u, (n, _l) in decided.items() if n is None]
    assert giants, "expected at least one unschedulable giant"
