"""CPUAccumulator policy edges (reference
``pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go:87-800``):
SMT-aware FullPCPUs picks, strict-vs-default fallback, spread ordering,
reserved-CPU interplay, zone pinning and release/retake cycles — the
behavioral depth the r4 LoC diagnostic flagged inside NodeNUMAResource.
"""

import pytest

from koordinator_tpu.core.topology import (
    CPUAccumulator,
    CPUBindPolicy,
    CPUTopology,
)


def _smt_topo(sockets=2, cores=4):
    # threads_per_core=2: cpu ids pair up per core
    return CPUTopology.uniform(
        sockets=sockets, numa_per_socket=1, cores_per_numa=cores
    )


def _cores_of(topo, cpus):
    by_core = {}
    for c in topo.cpus:
        if c.cpu_id in cpus:
            by_core.setdefault((c.socket, c.core_id), set()).add(c.cpu_id)
    return by_core


def test_full_pcpus_takes_whole_cores_only():
    topo = _smt_topo()
    acc = CPUAccumulator(topo)
    got = acc.take("a", 4, policy=CPUBindPolicy.FULL_PCPUS)
    assert got is not None and len(got) == 4
    for _core, threads in _cores_of(topo, got).items():
        assert len(threads) == 2, "partial core taken under FullPCPUs"


def test_full_pcpus_strict_rejects_odd_count():
    """Strict FullPCPUs cannot satisfy an odd CPU count on SMT
    (cpu_accumulator: n % threadsPerCore != 0 → error); DEFAULT falls
    back to the spread path instead."""
    acc = CPUAccumulator(_smt_topo())
    assert acc.take("odd", 3, policy=CPUBindPolicy.FULL_PCPUS) is None
    got = acc.take("odd2", 3, policy=CPUBindPolicy.DEFAULT)
    assert got is not None and len(got) == 3


def test_default_falls_back_to_spread_when_cores_fragment():
    """DEFAULT prefers whole cores but must still satisfy from partial
    cores once fragmentation makes whole-core picks impossible."""
    topo = _smt_topo(sockets=1, cores=4)     # 8 cpus / 4 cores
    acc = CPUAccumulator(topo)
    # fragment: take one THREAD from each of 3 cores via spread
    first = acc.take("frag", 3, policy=CPUBindPolicy.SPREAD_BY_PCPUS)
    assert len(_cores_of(topo, first)) == 3
    # 4 cpus remain: 1 whole core + 3 lone threads; DEFAULT must take 4
    got = acc.take("rest", 4, policy=CPUBindPolicy.DEFAULT)
    assert got is not None and len(got) == 4
    # nothing double-allocated
    assert not (got & first)


def test_spread_by_pcpus_prefers_distinct_cores():
    topo = _smt_topo(sockets=1, cores=4)
    acc = CPUAccumulator(topo)
    got = acc.take("s", 4, policy=CPUBindPolicy.SPREAD_BY_PCPUS)
    assert len(_cores_of(topo, got)) == 4, "threads stacked on one core"


def test_numa_pinning_is_respected_until_exhausted():
    topo = _smt_topo(sockets=2, cores=4)      # zone 0/1 = 8 cpus each
    acc = CPUAccumulator(topo)
    a = acc.take("a", 8, policy=CPUBindPolicy.FULL_PCPUS, numa=0)
    assert a is not None
    zones = {c.numa_node for c in topo.cpus if c.cpu_id in a}
    assert zones == {0}
    # zone 0 exhausted: a pinned request must fail, unpinned succeeds
    assert acc.take("b", 2, policy=CPUBindPolicy.FULL_PCPUS, numa=0) is None
    c = acc.take("c", 2, policy=CPUBindPolicy.FULL_PCPUS, numa=1)
    assert c is not None


def test_release_returns_capacity_and_heaps_recover():
    topo = _smt_topo(sockets=1, cores=4)
    acc = CPUAccumulator(topo)
    a = acc.take("a", 8, policy=CPUBindPolicy.FULL_PCPUS, numa=0)
    assert a is not None and len(a) == 8
    assert acc.take("b", 2, policy=CPUBindPolicy.FULL_PCPUS, numa=0) is None
    acc.release("a")
    b = acc.take("b", 8, policy=CPUBindPolicy.FULL_PCPUS, numa=0)
    assert b is not None and len(b) == 8


def test_take_reserved_blocks_future_takes():
    topo = _smt_topo(sockets=1, cores=2)      # 4 cpus
    acc = CPUAccumulator(topo)
    acc.take_reserved("kubelet", {0, 1})
    got = acc.take("p", 2, policy=CPUBindPolicy.DEFAULT)
    assert got is not None
    assert not (got & {0, 1}), "handed out kubelet-reserved cpus"
    assert acc.take("q", 4, policy=CPUBindPolicy.DEFAULT) is None


def test_take_bulk_matches_sequential_takes():
    """take_bulk's hot path must be pick-for-pick identical to repeated
    take() calls on a fresh accumulator."""
    topo = _smt_topo(sockets=2, cores=8)
    reqs = [
        (f"o{i}", n, CPUBindPolicy.DEFAULT, numa)
        for i, (n, numa) in enumerate(
            [(4, 0), (2, 1), (4, 0), (2, None), (6, 1), (4, None)]
        )
    ]
    seq = CPUAccumulator(topo)
    expected = [
        seq.take(o, n, policy=p, numa=z) for o, n, p, z in reqs
    ]
    bulk = CPUAccumulator(topo).take_bulk(reqs)
    assert bulk == expected


@pytest.mark.parametrize("n", [2, 4, 6, 8])
def test_full_pcpus_socket_locality_preference(n):
    """Whole-core picks that fit one NUMA node stay on one NUMA node
    (domain ordering: numa, then socket, then spill)."""
    topo = _smt_topo(sockets=2, cores=4)
    acc = CPUAccumulator(topo)
    got = acc.take("x", n, policy=CPUBindPolicy.FULL_PCPUS)
    assert got is not None
    zones = {c.numa_node for c in topo.cpus if c.cpu_id in got}
    assert len(zones) == 1, f"{n} cpus spilled across zones: {zones}"


def test_oversized_request_spills_across_sockets_largest_first():
    topo = _smt_topo(sockets=2, cores=2)      # 4 cpus per zone
    acc = CPUAccumulator(topo)
    got = acc.take("big", 6, policy=CPUBindPolicy.FULL_PCPUS)
    assert got is not None and len(got) == 6
    for _core, threads in _cores_of(topo, got).items():
        assert len(threads) == 2
