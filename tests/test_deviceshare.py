"""DeviceShare tests: gpu request parsing, slot masks, exact allocation,
gang+device e2e (the BASELINE config #4 shape: 8-GPU nodes, multi-GPU
all-or-nothing pods)."""

import json

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.ops.device import DeviceState, device_fit_mask
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.deviceshare import (
    DeviceManager,
    parse_gpu_request,
)


def gpu_pod(name, whole=0, ratio=0.0, cpu=1000, gang=None, min_avail=None):
    requests = {ext.RES_CPU: cpu, ext.RES_MEMORY: 1024}
    if whole:
        requests[ext.RES_GPU] = whole
    if ratio:
        requests[ext.RES_GPU_MEMORY_RATIO] = ratio
    labels = {}
    if gang:
        labels[ext.LABEL_GANG_NAME] = gang
        labels[ext.LABEL_GANG_MIN_AVAILABLE] = str(min_avail)
    return Pod(
        meta=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(requests=requests, priority=9000),
    )


def test_parse_gpu_request():
    assert parse_gpu_request(gpu_pod("a", whole=2)) == (2, 0.0)
    assert parse_gpu_request(gpu_pod("b", ratio=50)) == (0, 50.0)
    assert parse_gpu_request(gpu_pod("c", ratio=250)) == (2, 50.0)
    assert parse_gpu_request(gpu_pod("d")) == (0, 0.0)


def test_device_fit_mask():
    # node 0: 2 full gpus; node 1: one 40% partial; node 2: none
    state = DeviceState(
        slot_free=jnp.asarray(
            [[100.0, 100.0], [40.0, 0.0], [0.0, 0.0]], jnp.float32
        )
    )
    full, partial, total = state.aggregates()
    whole = jnp.asarray([1, 2, 0, 0], jnp.int32)
    share = jnp.asarray([0.0, 0.0, 30.0, 60.0], jnp.float32)
    mask = np.asarray(device_fit_mask(whole, share, full, partial))
    assert mask[0].tolist() == [True, False, False]   # 1 whole
    assert mask[1].tolist() == [True, False, False]   # 2 whole
    assert mask[2].tolist() == [True, True, False]    # 30% fits partial
    assert mask[3].tolist() == [True, False, False]   # 60% needs fresh


def make_cluster(n_nodes=2, gpus=8):
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for i in range(n_nodes):
        name = f"n{i}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                ),
            )
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g) for g in range(gpus)
                ],
            )
        )
    return snap, dm


def test_exact_allocation_and_release():
    snap, dm = make_cluster(n_nodes=1, gpus=2)
    p1 = gpu_pod("p1", ratio=30)
    patch = dm.allocate(p1, "n0")
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    assert alloc["gpu"][0]["resources"][ext.RES_GPU_MEMORY_RATIO] == 30
    # second fractional goes best-fit onto the same partial slot
    p2 = gpu_pod("p2", ratio=50)
    alloc2 = json.loads(
        dm.allocate(p2, "n0")[ext.ANNOTATION_DEVICE_ALLOCATED]
    )
    assert alloc2["gpu"][0]["minor"] == alloc["gpu"][0]["minor"]
    # whole-gpu request takes the remaining full slot
    p3 = gpu_pod("p3", whole=1)
    assert dm.allocate(p3, "n0") is not None
    # nothing left for another whole gpu
    assert dm.allocate(gpu_pod("p4", whole=1), "n0") is None
    dm.release(p3.meta.uid, "n0")
    assert dm.allocate(gpu_pod("p5", whole=1), "n0") is not None


def test_end_to_end_gpu_scheduling():
    snap, dm = make_cluster(n_nodes=2, gpus=8)
    sched = BatchScheduler(snap, devices=dm)
    pods = [gpu_pod(f"w{i}", whole=4) for i in range(4)]  # 16 gpus over 2 nodes
    out = sched.schedule(pods)
    assert len(out.bound) == 4
    # every gpu allocated exactly once
    assert all(len(st.owners) == 2 for st in dm._nodes.values())
    # a 5th whole-gpu pod finds nothing
    out2 = sched.schedule([gpu_pod("extra", whole=1)])
    assert out2.bound == []


def test_end_to_end_gang_multi_gpu_all_or_nothing():
    """BASELINE config #4: multi-GPU gang across 8-GPU nodes."""
    snap, dm = make_cluster(n_nodes=2, gpus=8)
    sched = BatchScheduler(snap, devices=dm)
    # gang of 3 pods x 8 gpus needs 3 full nodes but only 2 exist
    gang = [
        gpu_pod(f"g{i}", whole=8, gang="train", min_avail=3) for i in range(3)
    ]
    out = sched.schedule(gang)
    assert out.bound == []
    # no leaked device allocations after rollback
    assert all(not st.owners for st in dm._nodes.values())
    # a 2-pod gang fits and lands on distinct nodes
    gang2 = [
        gpu_pod(f"h{i}", whole=8, gang="train2", min_avail=2) for i in range(2)
    ]
    out2 = sched.schedule(gang2)
    assert len(out2.bound) == 2
    assert {node for _, node in out2.bound} == {"n0", "n1"}


def test_fractional_gpu_packing_e2e():
    snap, dm = make_cluster(n_nodes=1, gpus=1)
    sched = BatchScheduler(snap, devices=dm)
    pods = [gpu_pod(f"f{i}", ratio=40) for i in range(3)]  # 120% > 1 gpu
    out = sched.schedule(pods)
    assert len(out.bound) == 2
    assert len(out.unschedulable) == 1


def test_device_resync_preserves_allocations():
    """Re-upserting a node's Device inventory must not wipe live
    allocations (watch re-sync)."""
    snap, dm = make_cluster(n_nodes=1, gpus=2)
    p1 = gpu_pod("p1", whole=1)
    assert dm.allocate(p1, "n0") is not None
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(2)],
        )
    )
    st = dm.node("n0")
    assert p1.meta.uid in st.owners
    assert sorted(st.gpu_free) == [0.0, 100.0]
    # releasing after re-sync returns the capacity
    dm.release(p1.meta.uid, "n0")
    assert st.gpu_free == [100.0, 100.0]


def test_slot_array_grows_beyond_default():
    snap, dm = make_cluster(n_nodes=1, gpus=16)
    slots = dm.slot_array()
    assert slots.shape[1] == 16
    assert (slots[snap.node_id("n0")] == 100.0).all()


# ---- partition / topology-aware whole-GPU allocation ----
# (reference allocator_gpu.go allocateByPartition + selectPartitionByBinPack)


def h800_partitions():
    """8-GPU node with NVLink partition table: pairs, quads, and the full
    octet, all at allocation score 1 except one 'preferred' quad tier."""
    from koordinator_tpu.api.types import GPUPartition

    return {
        1: [GPUPartition(minors=[m]) for m in range(8)],
        2: [
            GPUPartition(minors=[0, 1]),
            GPUPartition(minors=[2, 3]),
            GPUPartition(minors=[4, 5]),
            GPUPartition(minors=[6, 7]),
        ],
        4: [
            GPUPartition(minors=[0, 1, 2, 3], ring_bus_bandwidth=400.0),
            GPUPartition(minors=[4, 5, 6, 7], ring_bus_bandwidth=400.0),
        ],
        8: [
            GPUPartition(
                minors=list(range(8)), ring_bus_bandwidth=400.0
            )
        ],
    }


def partition_cluster(policy="Honor"):
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[
                DeviceInfo(dev_type="gpu", minor=g, numa_node=g // 4)
                for g in range(8)
            ],
            partitions=h800_partitions(),
            partition_policy=policy,
        )
    )
    return snap, dm


def minors_of(patch):
    return sorted(
        a["minor"] for a in json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])["gpu"]
    )


def test_partition_quad_stays_intact():
    _, dm = partition_cluster()
    patch = dm.allocate(gpu_pod("quad", whole=4), "n0")
    assert minors_of(patch) in ([0, 1, 2, 3], [4, 5, 6, 7])


def test_partition_binpack_preserves_intact_quad():
    """After one GPU is taken from the first quad, a 2-GPU request must
    land on the *broken* quad's remaining pair, keeping the second quad
    fully intact (selectPartitionByBinPack weighting)."""
    _, dm = partition_cluster()
    # occupy minor 0 (breaks quad {0..3} and pair {0,1})
    assert minors_of(dm.allocate(gpu_pod("single", whole=1), "n0")) == [0]
    pair = minors_of(dm.allocate(gpu_pod("pair", whole=2), "n0"))
    assert pair == [2, 3]
    # quad {4..7} remains allocatable as a unit
    quad = minors_of(dm.allocate(gpu_pod("quad", whole=4), "n0"))
    assert quad == [4, 5, 6, 7]


def test_partition_honor_rejects_unsupported_size():
    """Honor policy: a size with no partition entry (3 GPUs) is
    unschedulable on this node (ErrUnsupportedGPURequests)."""
    _, dm = partition_cluster(policy="Honor")
    assert dm.allocate(gpu_pod("three", whole=3), "n0") is None


def test_partition_prefer_falls_back_to_topology():
    """Prefer policy: the same 3-GPU request falls back to topology
    packing and lands within one NUMA domain."""
    _, dm = partition_cluster(policy="Prefer")
    got = minors_of(dm.allocate(gpu_pod("three", whole=3), "n0"))
    assert len(got) == 3
    # all on one NUMA node (minors 0-3 are numa 0, 4-7 numa 1)
    assert all(m < 4 for m in got) or all(m >= 4 for m in got)


def test_partition_honor_rejects_fragmented_node():
    """Honor: 4-GPU request with both quads broken fails even though 4
    full GPUs remain (partition integrity is binding)."""
    _, dm = partition_cluster(policy="Honor")
    dm.allocate(gpu_pod("s1", whole=1), "n0")   # breaks quad 0-3
    # break the second quad too
    st = dm.node("n0")
    st.gpu_free[4] = 0.0
    assert dm.allocate(gpu_pod("quad", whole=4), "n0") is None


def test_partition_ring_bandwidth_filter():
    """A pod demanding more ring bandwidth than the pair partitions offer
    cannot use them (pairs carry no bandwidth in the fixture)."""
    pod = gpu_pod("bw", whole=2)
    pod.meta.annotations[ext.ANNOTATION_GPU_PARTITION_SPEC] = json.dumps(
        {"allocatePolicy": "BestEffort", "ringBusBandwidth": 100.0}
    )
    _, dm = partition_cluster(policy="Honor")
    assert dm.allocate(pod, "n0") is None


def test_topology_packing_without_table():
    """No partition table: whole-GPU picks pack onto one NUMA domain."""
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(allocatable={ext.RES_CPU: 64000}),
        )
    )
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[
                DeviceInfo(dev_type="gpu", minor=g, numa_node=g // 4)
                for g in range(8)
            ],
        )
    )
    # consume 3 of numa0; a 4-GPU request must go to intact numa1
    for i in range(3):
        dm.node("n0").gpu_free[i] = 0.0
    got = minors_of(dm.allocate(gpu_pod("quad", whole=4), "n0"))
    assert got == [4, 5, 6, 7]


def test_device_holding_reservation_end_to_end():
    """A reservation requesting GPUs holds real minors (the ghost flows
    through the device allocator); non-owners cannot take them, the owner
    consumes them through the fast path, and expiry releases them
    (reference deviceshare Reservation{Restore,Filter,PreBind} hooks)."""
    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
        ReservationPhase,
    )

    snap, dm = make_cluster(n_nodes=1, gpus=2)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="gpu-hold"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096, ext.RES_GPU: 2},
            owners=[ReservationOwner(label_selector={"app": "train"})],
            allocate_once=True,
        )
    )
    assert rm.schedule_pending() == 1
    assert rm.get("gpu-hold").phase == ReservationPhase.AVAILABLE
    # both minors are held by the ghost: a non-owner GPU pod finds none
    out = sched.schedule([gpu_pod("intruder", whole=1)])
    assert out.bound == []
    # the owner consumes the held minors through the fast path
    owner = gpu_pod("train-0", whole=2)
    owner.meta.labels["app"] = "train"
    out2 = sched.schedule([owner])
    assert [(p.meta.name, n) for p, n in out2.bound] == [("train-0", "n0")]
    assert dm.node("n0").owners.get("") is None
    assert len(dm.node("n0").owners) == 1   # only the owner pod holds minors

    # a fresh reservation whose hold expires releases its minors
    rm.add(
        Reservation(
            meta=ObjectMeta(name="gpu-hold-2"),
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024, ext.RES_GPU: 1},
            owners=[ReservationOwner(label_selector={"app": "never"})],
        )
    )
    # owner released its pods? node has 0 free minors -> cannot reserve
    assert rm.schedule_pending() == 0


def test_failed_owner_commit_reacquires_ghost_holds():
    """When an owner pod matches a reservation but its own device Reserve
    fails, the ghost's minor holds (released ahead of the owner's
    allocation) must be re-acquired — otherwise the still-Available
    reservation's GPUs leak to unrelated pods."""
    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
        ReservationPhase,
        _ghost_uid,
    )

    snap, dm = partition_cluster(policy="Honor")
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    res = Reservation(
        meta=ObjectMeta(name="pair-hold"),
        requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096, ext.RES_GPU: 2},
        owners=[ReservationOwner(label_selector={"app": "train"})],
    )
    rm.add(res)
    assert rm.schedule_pending() == 1
    ghost = _ghost_uid(res)
    assert len(dm.node("n0").owners[ghost]) == 2

    # owner demands ring bandwidth no pair partition offers: its device
    # Reserve fails under Honor policy after the ghost hold was released
    owner = gpu_pod("train-0", whole=2)
    owner.meta.labels["app"] = "train"
    owner.meta.annotations[ext.ANNOTATION_GPU_PARTITION_SPEC] = json.dumps(
        {"allocatePolicy": "BestEffort", "ringBusBandwidth": 100.0}
    )
    out = sched.schedule([owner])
    assert out.bound == []
    # reservation still Available and the ghost holds its 2 minors again
    assert res.phase == ReservationPhase.AVAILABLE
    assert len(dm.node("n0").owners.get(ghost, [])) == 2
    assert owner.meta.uid not in dm.node("n0").owners


def test_required_affinity_no_fallthrough_on_failed_reserve():
    """A required-reservation-affinity pod whose matched reservation's
    Reserve fails must stay unschedulable — not fall through to normal
    node scheduling on an unrelated node."""
    from koordinator_tpu.api.types import Reservation, ReservationOwner

    from koordinator_tpu.api.types import Device, DeviceInfo, Node, NodeStatus
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    snap, dm = partition_cluster(policy="Honor")
    # a second, unconstrained node that could host the pod normally (too
    # small for the reservation itself, so the ghost lands on n0)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n1"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n1"),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(8)],
        )
    )
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    res = Reservation(
        meta=ObjectMeta(name="pair-hold"),
        requests={ext.RES_CPU: 40000, ext.RES_MEMORY: 4096, ext.RES_GPU: 2},
        owners=[ReservationOwner(label_selector={"app": "train"})],
    )
    rm.add(res)
    assert rm.schedule_pending() == 1
    assert res.node_name == "n0"  # the partitioned node fits the pair

    owner = gpu_pod("train-0", whole=2)
    owner.meta.labels["app"] = "train"
    owner.meta.annotations[ext.ANNOTATION_RESERVATION_AFFINITY] = json.dumps(
        {"name": "pair-hold"}
    )
    # bandwidth demand no pair partition offers -> owner Reserve fails
    owner.meta.annotations[ext.ANNOTATION_GPU_PARTITION_SPEC] = json.dumps(
        {"allocatePolicy": "BestEffort", "ringBusBandwidth": 100.0}
    )
    out = sched.schedule([owner])
    assert out.bound == []
    assert [p.meta.name for p in out.unschedulable] == ["train-0"]
    # in particular it must NOT have bound on n1
    assert owner.meta.uid not in dm.node("n1").owners


def test_ghost_holds_survive_assumed_pod_expiry():
    """Ghost assumes are owned by the ReservationManager, not a pod_assumed
    sync: expire_assumed must never drop an Available reservation's
    capacity hold."""
    import time

    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    snap, dm = make_cluster(n_nodes=1, gpus=2)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    rm.add(
        Reservation(
            meta=ObjectMeta(name="gpu-hold"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096, ext.RES_GPU: 2},
            owners=[ReservationOwner(label_selector={"app": "train"})],
        )
    )
    assert rm.schedule_pending() == 1
    before = snap.nodes.requested[snap.node_id("n0")].copy()
    assert snap.expire_assumed(now=time.time() + 10_000, ttl=300.0) == 0
    np.testing.assert_allclose(
        snap.nodes.requested[snap.node_id("n0")], before
    )


def test_hopper_partition_table_matches_reference_layout():
    """GPUPartitionIndexOfNVIDIAHopper: singles, pairs (0,1)(2,3)(4,5)(6,7),
    quads (0-3)(4-7), octet; dispatched for H100/H800/H20 models."""
    from koordinator_tpu.scheduler.plugins.deviceshare import (
        partition_table_for_model,
    )

    for model in ("H100", "H800", "H20", "H800-SXM"):
        table = partition_table_for_model(model)
        assert sorted(table) == [1, 2, 4, 8]
        assert [p.minors for p in table[2]] == [[0, 1], [2, 3], [4, 5], [6, 7]]
        assert [p.minors for p in table[4]] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert table[8][0].minors == list(range(8))
        assert all(
            p.allocation_score == 1 for ps in table.values() for p in ps
        )
    assert partition_table_for_model("A100") == {}


def test_pipelined_multichunk_schedule_consistency():
    """A schedule() call spanning several solver chunks (batch_bucket <
    pending) chains capacity on device; committed placements must respect
    exact node/GPU capacity with zero overcommit, matching the per-chunk
    path's totals."""
    snap, dm = make_cluster(n_nodes=4, gpus=8)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=8)
    sched.extender.monitor.stop_background()
    # 16 pods x 2 GPUs = exactly the cluster's 32 GPUs, across 2 chunks
    pods = [gpu_pod(f"w{i:02d}", whole=2, cpu=4000) for i in range(16)]
    out = sched.schedule(pods)
    assert len(out.bound) == 16
    per_node = {}
    for pod, node in out.bound:
        per_node[node] = per_node.get(node, 0) + 2
    assert all(v <= 8 for v in per_node.values())
    # exact slot accounting: every GPU allocated exactly once
    for st in dm._nodes.values():
        assert sum(st.gpu_free) == 0.0
    # a 17th pod finds nothing
    assert sched.schedule([gpu_pod("extra", whole=1)]).bound == []


# ---- RDMA + joint GPU/RDMA allocation (device_allocator.go:205-252) ----


def rdma_cluster():
    """One node: 4 GPUs + 4 NICs split over two PCIe roots."""
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    devs = [
        DeviceInfo(dev_type="gpu", minor=g, numa_node=g // 2, pcie_bus=f"p{g//2}")
        for g in range(4)
    ] + [
        DeviceInfo(dev_type="rdma", minor=r, pcie_bus=f"p{r//2}")
        for r in range(4)
    ]
    dm.upsert_device(Device(meta=ObjectMeta(name="n0"), devices=devs))
    return snap, dm


def joint_pod(name, gpus=2, rdma=200, scope="SamePCIe"):
    pod = gpu_pod(name, whole=gpus)
    if rdma:
        pod.spec.requests[ext.RES_RDMA] = rdma
    pod.meta.annotations[ext.ANNOTATION_DEVICE_JOINT_ALLOCATE] = json.dumps(
        {"deviceTypes": ["gpu", "rdma"], "requiredScope": scope}
    )
    return pod


def test_joint_allocate_same_pcie():
    """SamePCIe scope: the NICs' PCIe set must equal the GPUs' — both land
    on one root (topology packing keeps the 2 GPUs together)."""
    snap, dm = rdma_cluster()
    patch = dm.allocate(joint_pod("j1"), "n0")
    assert patch is not None
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    gpu_minors = [a["minor"] for a in alloc["gpu"]]
    rdma_minors = [a["minor"] for a in alloc["rdma"]]
    st = dm.node("n0")
    gpu_pcies = {st.pcie_of[m] for m in gpu_minors}
    rdma_pcies = {st.rdma_pcie[m] for m in rdma_minors}
    assert len(gpu_pcies) == 1 and rdma_pcies == gpu_pcies
    assert len(rdma_minors) == 2


def test_joint_allocate_same_pcie_infeasible():
    """If the GPUs' PCIe root has no free NIC, SamePCIe fails the Reserve
    (validateJointAllocation rules violation)."""
    snap, dm = rdma_cluster()
    st = dm.node("n0")
    st.rdma_free = [0.0, 0.0, 100.0, 100.0]   # p0 NICs busy
    st.gpu_free = [100.0, 100.0, 0.0, 0.0]    # only p0 GPUs free
    assert dm.allocate(joint_pod("j2"), "n0") is None
    # preferred (non-binding) scope succeeds with cross-root NICs
    assert dm.allocate(joint_pod("j3", scope=""), "n0") is not None


def test_joint_allocate_covers_every_gpu_pcie():
    """GPUs spanning two roots with SamePCIe need a NIC per root even when
    the pod asked for just one (desiredCount bumped to the root count)."""
    snap, dm = rdma_cluster()
    st = dm.node("n0")
    st.gpu_free = [100.0, 0.0, 100.0, 0.0]    # one free GPU per root
    patch = dm.allocate(joint_pod("j4", gpus=2, rdma=100), "n0")
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    rdma_pcies = {st.rdma_pcie[a["minor"]] for a in alloc["rdma"]}
    assert rdma_pcies == {"p0", "p1"}
    assert len(alloc["rdma"]) == 2


def test_rdma_capacity_e2e():
    """Solver-level RDMA feasibility: three 2-NIC pods over a 4-NIC node
    place exactly two; release restores capacity."""
    snap, dm = rdma_cluster()
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    pods = []
    for i in range(3):
        p = gpu_pod(f"r{i}")
        p.spec.requests[ext.RES_RDMA] = 200
        pods.append(p)
    out = sched.schedule(pods)
    assert len(out.bound) == 2
    assert len(out.unschedulable) == 1
    st = dm.node("n0")
    assert sum(st.rdma_free) == 0.0
    # release one and the third pod fits on retry
    dm.release(out.bound[0][0].meta.uid, "n0")
    out2 = sched.schedule(out.unschedulable)
    assert len(out2.bound) == 1


def test_fpga_capacity_and_allocation_e2e():
    """FPGA devices (device_share.go:49): count-based instances, solver
    feasibility plus exact minor assignment and release."""
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[DeviceInfo(dev_type="fpga", minor=f) for f in range(2)],
        )
    )
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    pods = []
    for i in range(3):
        p = gpu_pod(f"f{i}")
        p.spec.requests[ext.RES_FPGA] = 100
        pods.append(p)
    out = sched.schedule(pods)
    assert len(out.bound) == 2 and len(out.unschedulable) == 1
    st = dm.node("n0")
    assert sum(st.fpga_free) == 0.0
    alloc = json.loads(
        out.bound[0][0].meta.annotations[ext.ANNOTATION_DEVICE_ALLOCATED]
    )
    assert alloc["fpga"][0]["resources"][ext.RES_FPGA] == 100.0
    dm.release(out.bound[0][0].meta.uid, "n0")
    assert sorted(st.fpga_free) == [0.0, 100.0]


def test_partition_table_from_annotation_and_model():
    """Partition resolution order (GetGPUPartitionTable → model dispatch):
    the Device CR's gpu-partitions annotation wins, then the gpu-model
    label's default table; the Honor/Prefer label is honored."""
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(allocatable={ext.RES_CPU: 64000}),
        )
    )
    ann = {
        ext.ANNOTATION_GPU_PARTITIONS: json.dumps(
            {
                "2": [
                    {"minors": [0, 1], "ringBusBandwidth": 200,
                     "allocationScore": 3},
                    {"minors": [2, 3]},
                ]
            }
        )
    }
    labels = {ext.LABEL_GPU_PARTITION_POLICY: "Honor"}
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0", annotations=ann, labels=labels),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(4)],
        )
    )
    st = dm.node("n0")
    assert st.partition_policy == "Honor"
    assert [p.minors for p in st.partitions[2]] == [[0, 1], [2, 3]]
    assert st.partitions[2][0].ring_bus_bandwidth == 200.0
    # Honor is binding: the higher-score pair wins first
    got = minors_of(dm.allocate(gpu_pod("pair", whole=2), "n0"))
    assert got == [0, 1]
    # unsupported size under Honor fails
    assert dm.allocate(gpu_pod("tri", whole=3), "n0") is None

    # model-label fallback: H800 gets the Hopper table, default Prefer
    dm2 = DeviceManager(snap)
    dm2.upsert_device(
        Device(
            meta=ObjectMeta(
                name="n0", labels={ext.LABEL_GPU_MODEL: "H800"}
            ),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(8)],
        )
    )
    st2 = dm2.node("n0")
    assert sorted(st2.partitions) == [1, 2, 4, 8]
    assert st2.partition_policy == "Prefer"
    # malformed annotation degrades to no table
    dm3 = DeviceManager(snap)
    dm3.upsert_device(
        Device(
            meta=ObjectMeta(
                name="n0",
                annotations={ext.ANNOTATION_GPU_PARTITIONS: "not json"},
            ),
            devices=[DeviceInfo(dev_type="gpu", minor=0)],
        )
    )
    assert dm3.node("n0").partitions == {}


def test_resize_pod_reservation_allocatable():
    """ResizePod (frameworkext/framework_extender_factory.go:280-298 +
    deviceshare/plugin.go:519-539): with the gate on, an Available
    reservation created with raw ``nvidia.com/gpu`` exposes the concrete
    allocation in normalized units (gpu-memory-ratio), so owners
    requesting normalized GPU units can draw from it."""
    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
        ReservationPhase,
    )
    from koordinator_tpu.utils.features import SCHEDULER_GATES

    def build():
        snap, dm = make_cluster(n_nodes=1, gpus=4)
        sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
        sched.extender.monitor.stop_background()
        rm = ReservationManager(sched)
        rm.add(
            Reservation(
                meta=ObjectMeta(name="hold"),
                requests={
                    ext.RES_CPU: 4000,
                    ext.RES_MEMORY: 4096,
                    ext.RES_GPU: 2,
                },
                owners=[ReservationOwner(label_selector={"app": "train"})],
            )
        )
        assert rm.schedule_pending() == 1
        return sched, rm

    # gate off (default): requests stay as created
    _, rm0 = build()
    assert ext.RES_GPU_MEMORY_RATIO not in rm0.get("hold").requests
    assert rm0.get("hold").requests[ext.RES_GPU] == 2

    with SCHEDULER_GATES.override("ResizePod", True):
        sched, rm = build()
        r = rm.get("hold")
        assert r.phase == ReservationPhase.AVAILABLE
        # resized: 2 whole GPUs -> 200 ratio, raw dim normalized away
        assert r.requests[ext.RES_GPU_MEMORY_RATIO] == 200.0
        assert ext.RES_GPU not in r.requests
        # an owner requesting normalized units now matches the reservation
        owner = gpu_pod("train-0", ratio=100)
        owner.meta.labels["app"] = "train"
        assert rm.match(owner) is r


def test_device_scoring_strategy():
    """DeviceShare Score (scoring.go:45-110): LeastAllocated spreads GPU
    pods to the emptier GPU node; MostAllocated packs onto the busier one.
    CPU/memory are identical across nodes so the device term decides."""

    def run(strategy):
        snap = ClusterSnapshot()
        dm = DeviceManager(snap, scoring_strategy=strategy)
        for i in range(2):
            snap.upsert_node(
                Node(
                    meta=ObjectMeta(name=f"n{i}"),
                    status=NodeStatus(
                        allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                    ),
                )
            )
            dm.upsert_device(
                Device(
                    meta=ObjectMeta(name=f"n{i}"),
                    devices=[
                        DeviceInfo(dev_type="gpu", minor=g) for g in range(4)
                    ],
                )
            )
        # n0 starts with 2 GPUs consumed
        warm = gpu_pod("warm", whole=2)
        warm.spec.node_name = "n0"
        assert dm.allocate(warm, "n0") is not None
        sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
        sched.extender.monitor.stop_background()
        out = sched.schedule([gpu_pod("probe", whole=1, cpu=100)])
        assert len(out.bound) == 1
        return out.bound[0][1]

    assert run("LeastAllocated") == "n1"
    assert run("MostAllocated") == "n0"


def _one_gpu_node(mem_cap_bytes=None):
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm = DeviceManager(snap)
    res = {ext.RES_GPU_MEMORY: mem_cap_bytes} if mem_cap_bytes else {}
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[DeviceInfo(dev_type="gpu", minor=0, resources=res)],
        )
    )
    return snap, dm


def test_gpu_core_memory_independent_dims():
    """VERDICT r2 missing #3: a high-memory/low-core pod and a
    low-memory/high-core pod must share one GPU — gpu-core and
    gpu-memory-ratio account independently per minor (reference
    device_cache.go resource-vector accounting)."""
    snap, dm = _one_gpu_node()
    st = dm.node("n0")
    high_mem = Pod(
        meta=ObjectMeta(name="hm"),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 1000,
                ext.RES_GPU_CORE: 20,
                ext.RES_GPU_MEMORY_RATIO: 70,
            },
            priority=9000,
        ),
    )
    low_mem = Pod(
        meta=ObjectMeta(name="lm"),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 1000,
                ext.RES_GPU_CORE: 70,
                ext.RES_GPU_MEMORY_RATIO: 20,
            },
            priority=9000,
        ),
    )
    p1 = dm.allocate(high_mem, "n0")
    assert p1 is not None and ext.ANNOTATION_DEVICE_ALLOCATED in p1
    p2 = dm.allocate(low_mem, "n0")  # 70+20 ratio, 20+70 core — both fit
    assert p2 is not None
    assert st.gpu_free[0] == 10.0
    assert st.gpu_core_free[0] == 10.0
    # the payload reports BOTH dims per the reference resource names
    alloc = json.loads(p1[ext.ANNOTATION_DEVICE_ALLOCATED])
    res = alloc["gpu"][0]["resources"]
    assert res[ext.RES_GPU_CORE] == 20 and res[ext.RES_GPU_MEMORY_RATIO] == 70
    # a third pod over either dim is rejected
    third = Pod(
        meta=ObjectMeta(name="x"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_GPU_CORE: 20,
                      ext.RES_GPU_MEMORY_RATIO: 5},
            priority=9000,
        ),
    )
    assert dm.allocate(third, "n0") is None
    # releasing one pod frees exactly its vector
    dm.release(high_mem.meta.uid, "n0")
    assert st.gpu_free[0] == 80.0 and st.gpu_core_free[0] == 30.0


def test_gpu_memory_bytes_request():
    """Byte-denominated gpu-memory requests convert via the minor's
    declared capacity (16 GiB here): 4 GiB = 25% of the memory dim."""
    cap = 16 * 1024**3
    snap, dm = _one_gpu_node(mem_cap_bytes=cap)
    st = dm.node("n0")
    pod = Pod(
        meta=ObjectMeta(name="bytes"),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 1000,
                ext.RES_GPU_CORE: 50,
                ext.RES_GPU_MEMORY: 4 * 1024**3,
            },
            priority=9000,
        ),
    )
    patch = dm.allocate(pod, "n0")
    assert patch is not None
    assert st.gpu_free[0] == 75.0 and st.gpu_core_free[0] == 50.0
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    res = alloc["gpu"][0]["resources"]
    assert res[ext.RES_GPU_MEMORY] == 4 * 1024**3
    # a bytes request on a node with UNDECLARED capacity cannot account
    snap2, dm2 = _one_gpu_node(mem_cap_bytes=None)
    assert dm2.allocate(pod, "n0") is None


def test_rdma_vf_sharing():
    """VERDICT r2 missing #2: two pods share one NIC via SR-IOV virtual
    functions (apis/extension/device_share.go:126-139 VirtualFunctions);
    a VF-carrying NIC is never consumed whole."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm = DeviceManager(snap)
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[
                DeviceInfo(
                    dev_type="rdma",
                    minor=0,
                    pcie_bus="0000:09",
                    vfs=["0000:09:00.2", "0000:09:00.3"],
                )
            ],
        )
    )
    st = dm.node("n0")

    def rdma_pod(name):
        return Pod(
            meta=ObjectMeta(name=name),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_RDMA: 100},
                priority=9000,
            ),
        )

    p1 = dm.allocate(rdma_pod("a"), "n0")
    p2 = dm.allocate(rdma_pod("b"), "n0")
    assert p1 is not None and p2 is not None
    # both pods share minor 0, each holding a distinct VF
    a1 = json.loads(p1[ext.ANNOTATION_DEVICE_ALLOCATED])["rdma"][0]
    a2 = json.loads(p2[ext.ANNOTATION_DEVICE_ALLOCATED])["rdma"][0]
    assert a1["minor"] == 0 and a2["minor"] == 0
    vf1 = a1["extension"]["vfs"][0]["busID"]
    vf2 = a2["extension"]["vfs"][0]["busID"]
    assert vf1 != vf2
    assert st.rdma_vfs[0] == []          # both VFs handed out
    # third pod: no free VF left
    assert dm.allocate(rdma_pod("c"), "n0") is None
    # releasing returns the VF and a new pod can take it
    dm.release("default/a", "n0")
    assert vf1 in st.rdma_vfs[0]
    assert dm.allocate(rdma_pod("d"), "n0") is not None


def test_parse_gpu_request_vector():
    v = ext.parse_gpu_request_vector
    assert v({ext.RES_GPU: 2}) == (2, 0.0, 0.0, None)
    assert v({ext.RES_GPU_CORE: 30, ext.RES_GPU_MEMORY_RATIO: 80}) == (
        0, 30.0, 80.0, None,
    )
    assert v({ext.RES_KOORD_GPU: 50}) == (0, 50.0, 50.0, None)
    # equal multiples of 100 split to whole devices
    assert v({ext.RES_GPU_CORE: 200, ext.RES_GPU_MEMORY_RATIO: 200}) == (
        2, 0.0, 0.0, None,
    )
    assert v({ext.RES_GPU_MEMORY_RATIO: 250}) == (2, 50.0, 50.0, None)
    assert v({ext.RES_GPU_CORE: 40, ext.RES_GPU_MEMORY: 1024}) == (
        0, 40.0, 0.0, 1024.0,
    )


def _rdma_node(n_nics=4, numa_split=True):
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
            ),
        )
    )
    dm = DeviceManager(snap)
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="n0"),
            devices=[
                DeviceInfo(
                    dev_type="rdma",
                    minor=i,
                    numa_node=(i // 2 if numa_split else 0),
                    pcie_bus=f"0000:{i:02d}",
                )
                for i in range(n_nics)
            ],
        )
    )
    return snap, dm


def test_device_allocate_hint_apply_for_all():
    """device_share.go:168 ApplyForAll: the pod gets EVERY rdma device of
    the node (the machine-wide NIC pattern for distributed training)."""
    snap, dm = _rdma_node(n_nics=4)
    pod = Pod(
        meta=ObjectMeta(
            name="train",
            annotations={
                ext.ANNOTATION_DEVICE_ALLOCATE_HINT: (
                    '{"rdma": {"allocateStrategy": "ApplyForAll"}}'
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_RDMA: 100}, priority=9000
        ),
    )
    patch = dm.allocate(pod, "n0")
    assert patch is not None
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    assert sorted(e["minor"] for e in alloc["rdma"]) == [0, 1, 2, 3]


def test_device_allocate_hint_requests_as_count():
    """device_share.go:169 RequestsAsCount: the raw request value IS the
    device count (rdma: 2 = two NICs, not 2/100 of one)."""
    snap, dm = _rdma_node(n_nics=4)
    pod = Pod(
        meta=ObjectMeta(
            name="двa",
            annotations={
                ext.ANNOTATION_DEVICE_ALLOCATE_HINT: (
                    '{"rdma": {"allocateStrategy": "RequestsAsCount"}}'
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_RDMA: 2}, priority=9000
        ),
    )
    patch = dm.allocate(pod, "n0")
    assert patch is not None
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    assert len(alloc["rdma"]) == 2


def test_device_allocate_hint_numa_topology_scope():
    """DeviceHint.RequiredTopologyScope=NUMANode: the chosen NICs must
    share one NUMA node; an unsatisfiable scope fails the allocation."""
    snap, dm = _rdma_node(n_nics=4, numa_split=True)   # numa 0: {0,1}, numa 1: {2,3}
    def rdma_pod(name, count):
        return Pod(
            meta=ObjectMeta(
                name=name,
                annotations={
                    ext.ANNOTATION_DEVICE_ALLOCATE_HINT: (
                        '{"rdma": {"allocateStrategy": "RequestsAsCount", '
                        '"requiredTopologyScope": "NUMANode"}}'
                    )
                },
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_RDMA: count},
                priority=9000,
            ),
        )

    patch = dm.allocate(rdma_pod("two", 2), "n0")
    assert patch is not None
    alloc = json.loads(patch[ext.ANNOTATION_DEVICE_ALLOCATED])
    numas = {e["minor"] // 2 for e in alloc["rdma"]}
    assert len(numas) == 1                  # both NICs on one NUMA node
    # 3 NICs cannot share a NUMA node on this box: failed Reserve
    assert dm.allocate(rdma_pod("three", 3), "n0") is None
