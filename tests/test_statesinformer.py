"""statesinformer + pleg + koordlet HTTP surface (reference
pkg/koordlet/statesinformer, pkg/koordlet/pleg, pkg/koordlet/audit)."""

import json
import os
import urllib.request

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    DeviceInfo,
    Node,
    NodeSLO,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.topology import CPUTopology
from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig
from koordinator_tpu.koordlet.pleg import EventType, Pleg
from koordinator_tpu.koordlet.resourceexecutor import AuditEvent, Auditor
from koordinator_tpu.koordlet.server import KoordletServer, koordlet_registry
from koordinator_tpu.koordlet.statesinformer import (
    FakeDeviceProber,
    StatesInformer,
    StateType,
)


class TestStatesInformer:
    def test_callbacks_fire_in_registration_order(self):
        inf = StatesInformer("n1")
        calls = []
        inf.callbacks.register(StateType.ALL_PODS, "a", lambda v: calls.append("a"))
        inf.callbacks.register(StateType.ALL_PODS, "b", lambda v: calls.append("b"))
        inf.set_pods([])
        assert calls == ["a", "b"]

    def test_state_is_readable_back(self):
        inf = StatesInformer("n1")
        node = Node(meta=ObjectMeta(name="n1"), status=NodeStatus())
        inf.set_node(node)
        pod = Pod(meta=ObjectMeta(name="p"), spec=PodSpec())
        inf.set_pods([pod])
        slo = NodeSLO(meta=ObjectMeta(name="n1"))
        inf.set_node_slo(slo)
        assert inf.node() is node
        assert inf.pods()[0].meta.name == "p"
        assert inf.node_slo() is slo

    def test_topology_report_builds_zones(self):
        inf = StatesInformer("n1")
        got = []
        inf.callbacks.register(StateType.NODE_TOPOLOGY, "t", got.append)
        topo = CPUTopology.uniform(
            sockets=2, numa_per_socket=1, cores_per_numa=4, threads_per_core=2
        )
        report = inf.report_topology(
            topo, kubelet_reserved=[0, 1], policy="SingleNUMANode",
            mem_per_numa_bytes=float(32 << 30),
        )
        assert len(report.zones) == 2
        # 8 logical CPUs per NUMA node → 8000 milli
        assert report.zones[0].allocatable[ext.RES_CPU] == 8000.0
        assert report.kubelet_reserved_cpus == [0, 1]
        assert report.cpu_topology[0] == (0, 0, 0)
        assert got == [report] and inf.topology() is report

    def test_device_report_via_prober(self):
        inf = StatesInformer("n1")
        prober = FakeDeviceProber(
            devices=[DeviceInfo(dev_type="gpu", minor=i, numa_node=i % 2) for i in range(4)]
        )
        report = inf.report_devices(prober)
        assert len(report.devices) == 4
        assert inf.device() is report


class TestPleg:
    def test_lifecycle_events(self, tmp_path):
        root = str(tmp_path)
        pleg = Pleg(root)
        events = []
        hid = pleg.register_handler(events.append)
        assert pleg.tick() == []
        os.makedirs(os.path.join(root, "kubepods/besteffort/pod-abc/ctr-1"))
        got = pleg.tick()
        assert [e.type for e in got] == [
            EventType.POD_ADDED,
            EventType.CONTAINER_ADDED,
        ]
        assert got[0].pod_dir == "kubepods/besteffort/pod-abc"
        assert got[1].container_id == "ctr-1"
        # container exits, then the pod dir vanishes
        os.rmdir(os.path.join(root, "kubepods/besteffort/pod-abc/ctr-1"))
        assert [e.type for e in pleg.tick()] == [EventType.CONTAINER_DELETED]
        os.rmdir(os.path.join(root, "kubepods/besteffort/pod-abc"))
        assert [e.type for e in pleg.tick()] == [EventType.POD_DELETED]
        assert len(events) == 4
        pleg.unregister_handler(hid)
        os.makedirs(os.path.join(root, "kubepods/pod-x"))
        pleg.tick()
        assert len(events) == 4  # unregistered handler not called

    def test_non_pod_dirs_ignored(self, tmp_path):
        root = str(tmp_path)
        os.makedirs(os.path.join(root, "kubepods/burstable"))
        os.makedirs(os.path.join(root, "kubepods/someother"))
        assert Pleg(root).tick() == []


class TestKoordletServer:
    def test_audit_pull_api(self):
        auditor = Auditor()
        auditor.record(
            AuditEvent(ts=10.0, group="kubepods/pod-a", file="cpu.shares",
                       old="1024", new="2", reason="suppress")
        )
        auditor.record(
            AuditEvent(ts=20.0, group="kubepods/pod-b", file="cpu.shares",
                       old=None, new="2", reason="suppress")
        )
        srv = KoordletServer(koordlet_registry(), auditor)
        code, body = srv.dispatch("/apis/v1/audit?since=15")
        assert code == 200
        events = json.loads(body)
        assert len(events) == 1 and events[0]["group"] == "kubepods/pod-b"
        code, body = srv.dispatch("/apis/v1/audit?group=kubepods/pod-a")
        assert json.loads(body)[0]["file"] == "cpu.shares"
        assert srv.dispatch("/nope")[0] == 404

    def test_metrics_over_http(self):
        reg = koordlet_registry()
        reg.get("node_cpu_usage_milli").set(1234.0)
        srv = KoordletServer(reg, Auditor())
        port = srv.serve()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
            assert "koordlet_node_cpu_usage_milli 1234.0" in body
        finally:
            srv.shutdown()


class TestDaemonWiring:
    def test_informer_drives_reconciler_and_metrics(self, tmp_path):
        cfg = KoordletConfig(cgroup_root=str(tmp_path), n_cpus=4)
        agent = Koordlet(cfg)
        pod = Pod(
            meta=ObjectMeta(
                name="be-pod", uid="u1", labels={ext.LABEL_POD_QOS: "BE"}
            ),
            spec=PodSpec(requests={ext.RES_BATCH_CPU: 2000.0}),
        )
        agent.update_pods([pod])
        assert agent.pods and agent.pods[0].meta.name == "be-pod"
        agent.collect_tick(now=100.0)
        # collector health metrics exist for every collector
        text = agent.registry.expose()
        assert "koordlet_collector_last_collect_ts" in text or (
            "koordlet_collect_errors_total" in text
        )


def test_tpu_device_prober_reports_chips():
    """TPU chips surface through the same Device CR path GPUs do (the
    NVML-analog discovery for TPU hosts)."""
    from koordinator_tpu.koordlet.statesinformer import TpuDeviceProber

    devs = TpuDeviceProber().probe()
    # CPU test env: jax still enumerates >=1 device; each reports one chip
    assert len(devs) >= 1
    assert all(d.dev_type == "tpu" for d in devs)
    assert all(d.resources == {"google.com/tpu": 1.0} for d in devs)
    minors = [d.minor for d in devs]
    assert len(set(minors)) == len(minors)


def test_setters_drop_malformed_input():
    """Malformed watch payloads must be dropped at the door (the
    reference's informer only delivers schema-valid objects): None, wrong
    types, misrouted node objects, and duplicate pod uids never reach
    state or callbacks."""
    si = StatesInformer(node_name="me")
    fired = []
    si.callbacks.register(StateType.NODE, "t", lambda n: fired.append(n))
    si.callbacks.register(StateType.ALL_PODS, "t", lambda ps: fired.append(ps))

    si.set_node(None)
    si.set_node("not-a-node")
    si.set_node(Node(meta=ObjectMeta(name="someone-else")))
    assert si.node() is None and fired == []

    me = Node(meta=ObjectMeta(name="me"))
    si.set_node(me)
    assert si.node() is me and fired == [me]

    si.set_pods(None)
    assert si.pods() == []
    dup = Pod(meta=ObjectMeta(name="a"))
    good = Pod(meta=ObjectMeta(name="b"))
    si.set_pods([dup, "garbage", Pod(meta=ObjectMeta(name="a")), good, None])
    assert [p.meta.name for p in si.pods()] == ["a", "b"]

    si.set_node_slo("nope")
    assert si.node_slo() is None
    si.set_node_metric_spec(12)
    assert si._node_metric_spec is None


def test_kubelet_stub_pulls_pods_over_http():
    """KubeletStub: a real HTTP round trip against a fake kubelet /pods
    endpoint (impl/kubelet_stub.go); failures leave the pod view intact."""
    import http.server
    import threading

    payload = {
        "items": [
            {
                "metadata": {"name": "web-1", "namespace": "prod",
                             "uid": "u1", "labels": {"app": "web"}},
                "spec": {
                    "priority": 9500,
                    "nodeName": "me",
                    "containers": [
                        {"resources": {"requests": {"cpu": "500m",
                                                    "memory": "1Gi"}}},
                        {"resources": {"requests": {"cpu": "2"}}},
                    ],
                },
            },
            {"metadata": {}},          # malformed item: dropped
            "garbage",
        ]
    }

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/pods/":
                self.send_response(404); self.end_headers(); return
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True); t.start()
    try:
        from koordinator_tpu.koordlet.statesinformer import KubeletStub

        stub = KubeletStub(addr="127.0.0.1", port=srv.server_address[1])
        si = StatesInformer(node_name="me")
        assert stub.sync_into(si)
        pods = si.pods()
        assert [p.meta.name for p in pods] == ["web-1"]
        # quantities normalized: 500m + 2 cpus = 2500 milli; 1Gi = 1024 MiB
        assert pods[0].spec.requests["cpu"] == 2500.0
        assert pods[0].spec.requests["memory"] == 1024.0
        assert pods[0].spec.priority == 9500

        # unreachable kubelet: state untouched, False returned
        dead = KubeletStub(addr="127.0.0.1", port=1, timeout_s=0.2)
        assert not dead.sync_into(si)
        assert [p.meta.name for p in si.pods()] == ["web-1"]
    finally:
        srv.shutdown()


def test_pvc_surface():
    from koordinator_tpu.koordlet.statesinformer import PersistentVolumeClaim

    si = StatesInformer(node_name="me")
    seen = []
    si.callbacks.register(StateType.PVCS, "t", lambda v: seen.append(v))
    claim = PersistentVolumeClaim(
        meta=ObjectMeta(name="data-0", namespace="db"),
        capacity_gib=100.0,
        storage_class="ssd",
    )
    si.set_pvcs([claim, "junk", None])
    assert si.pvcs() == [claim]
    assert seen == [[claim]]
