"""Tier-1 enforcement of the PR 3 exception-accounting invariant:
every broad ``except Exception`` in the package routes through
``report_exception`` (directly or via a reporting helper) or re-raises
— previously a review-only rule, now a failing test."""

import importlib.util
import pathlib
import textwrap

ROOT = pathlib.Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location(
    "check_exception_sites", ROOT / "tools" / "check_exception_sites.py"
)
lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(lint)


def test_package_has_no_unaccounted_broad_excepts():
    violations = lint.check_paths([ROOT / "koordinator_tpu"], ROOT)
    assert violations == [], "\n".join(
        f"{f}:{line}: {msg}" for f, line, msg in violations
    )


def _check_src(tmp_path, src):
    f = tmp_path / "koordinator_tpu_frag.py"
    f.write_text(textwrap.dedent(src))
    return lint.check_file(f, tmp_path)


def test_lint_flags_silent_swallow(tmp_path):
    bad = _check_src(
        tmp_path,
        """
        def f():
            try:
                g()
            except Exception:
                pass
        """,
    )
    assert len(bad) == 1 and "report_exception" in bad[0][2]


def test_lint_flags_bare_except_and_tuple_form(tmp_path):
    bad = _check_src(
        tmp_path,
        """
        def f():
            try:
                g()
            except:
                x = 1
            try:
                g()
            except (ValueError, Exception) as exc:
                log(exc)
        """,
    )
    assert len(bad) == 2


def test_lint_accepts_report_reraise_and_helper(tmp_path):
    good = _check_src(
        tmp_path,
        """
        def f(self):
            try:
                g()
            except Exception as exc:
                report_exception("site", exc)
            try:
                g()
            except Exception:
                raise
            try:
                g()
            except Exception as exc:
                self._note_solver_failure(0, exc)
        """,
    )
    assert good == []


def test_lint_ignores_narrow_handlers(tmp_path):
    assert (
        _check_src(
            tmp_path,
            """
            def f():
                try:
                    g()
                except ValueError:
                    pass
            """,
        )
        == []
    )
