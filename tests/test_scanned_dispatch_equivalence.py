"""The scanned multi-chunk dispatch (solve_stream_full) must be
DECISION-IDENTICAL to the per-chunk pipelined dispatch: both run the
same `assign` with the same carried state, so placements, zones, cpusets
and device minors must match byte-for-byte — the scan only removes
per-chunk launch/fetch round trips."""

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    ElasticQuota,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager
from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NUMAManager,
    NUMAPolicy,
)


def _build(with_everything=True):
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    dm = DeviceManager(snap)
    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8)
    for i in range(48):
        name = f"n{i:03d}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            )
        )
        if with_everything:
            numa.register_node(
                name, topo, NUMAPolicy.SINGLE_NUMA_NODE, memory_per_zone_mib=65536
            )
            dm.upsert_device(
                Device(
                    meta=ObjectMeta(name=name),
                    devices=[
                        DeviceInfo(dev_type="gpu", minor=g, numa_node=g % 2)
                        for g in range(4)
                    ],
                )
            )
    gqm = GroupQuotaManager(
        snap.config,
        cluster_total={ext.RES_CPU: 32000 * 48, ext.RES_MEMORY: 131072 * 48},
    )
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="eq-team"),
            min={ext.RES_CPU: 400_000, ext.RES_MEMORY: 2 << 20},
            max={ext.RES_CPU: 800_000, ext.RES_MEMORY: 4 << 20},
        )
    )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(),
        quotas=gqm,
        numa=numa if with_everything else None,
        devices=dm if with_everything else None,
        batch_bucket=64,  # 260 pods → 5 chunks
    )
    sched.extender.monitor.stop_background()
    return sched


def _pods():
    out = []
    for i in range(120):  # LSR cpuset pods
        out.append(
            Pod(
                meta=ObjectMeta(
                    name=f"lsr{i:03d}", labels={ext.LABEL_POD_QOS: "LSR"}
                ),
                spec=PodSpec(
                    requests={ext.RES_CPU: 2000, ext.RES_MEMORY: 2048},
                    priority=9500,
                ),
            )
        )
    for i in range(100):  # quota gpu pods
        out.append(
            Pod(
                meta=ObjectMeta(
                    name=f"gpu{i:03d}",
                    labels={ext.LABEL_QUOTA_NAME: "eq-team"},
                ),
                spec=PodSpec(
                    requests={
                        ext.RES_CPU: 1000,
                        ext.RES_MEMORY: 2048,
                        ext.RES_GPU: 1,
                    },
                    priority=9000,
                ),
            )
        )
    for i in range(40):  # plain burstable
        out.append(
            Pod(
                meta=ObjectMeta(name=f"ls{i:03d}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 500, ext.RES_MEMORY: 1024},
                    priority=7000,
                ),
            )
        )
    return out


def _placements(out):
    m = {}
    for p, node in out.bound:
        m[p.meta.name] = (
            node,
            p.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS, ""),
            p.meta.annotations.get(ext.ANNOTATION_DEVICE_ALLOCATED, ""),
        )
    return m


@pytest.mark.parametrize("with_everything", [True, False])
def test_scanned_equals_pipelined(with_everything):
    a = _build(with_everything)
    # the scanned path must actually ENGAGE (return non-None), or this
    # degenerates into pipelined-vs-pipelined and verifies nothing
    engaged = []
    orig = a._dispatch_scanned

    def spy(chunks, sub=None):
        r = orig(chunks, sub)
        engaged.append(r is not None)
        return r

    a._dispatch_scanned = spy
    pods_a = _pods()
    out_a = a.schedule(pods_a)
    assert engaged == [True], engaged

    b = _build(with_everything)
    # force the per-chunk pipelined path
    b._dispatch_scanned = lambda chunks, sub=None: None
    pods_b = _pods()
    out_b = b.schedule(pods_b)

    assert len(out_a.bound) == len(out_b.bound)
    assert _placements(out_a) == _placements(out_b)
    assert sorted(p.meta.name for p in out_a.unschedulable) == sorted(
        p.meta.name for p in out_b.unschedulable
    )
