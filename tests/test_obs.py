"""Observability layer: Span/Tracer semantics, Chrome trace export,
rejection attribution across the batch cycle, and the services-engine
/trace + /debug/rejections endpoints (ISSUE 1 acceptance criteria)."""

import json
import threading

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.obs import (
    RejectionLog,
    RejectReason,
    RejectStage,
    Tracer,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.utils.metrics import Registry


def mkpod(name, cpu=1000, mem=1 << 20, priority=9500, **meta_kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, **meta_kw),
        spec=PodSpec(
            requests={ext.RES_CPU: float(cpu), ext.RES_MEMORY: float(mem)},
            priority=priority,
        ),
    )


@pytest.fixture
def sched():
    s = BatchScheduler()
    s.extender.monitor.stop_background()
    for i in range(4):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"node-{i}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 32000.0,
                        ext.RES_MEMORY: float(64 << 30),
                    }
                ),
            )
        )
    return s


class TestTracer:
    def test_span_records_nesting_and_duration(self):
        tr = Tracer(enabled=True)
        with tr.span("outer", cat="t"):
            with tr.span("inner", cat="t", k=1):
                pass
        recs = tr.records()
        assert [r.name for r in recs] == ["inner", "outer"]
        inner, outer = recs
        assert inner.depth == 1 and outer.depth == 0
        assert inner.dur <= outer.dur
        assert inner.t0 >= outer.t0
        assert inner.args == {"k": 1}

    def test_ring_retention(self):
        tr = Tracer(enabled=True, capacity=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        names = [r.name for r in tr.records()]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_thread_safety_and_lanes(self):
        tr = Tracer(enabled=True)
        gate = threading.Barrier(4)  # hold all threads live concurrently

        def work(i):
            gate.wait()
            for _ in range(50):
                with tr.span(f"t{i}"):
                    pass

        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(tr.records()) == 200
        trace = tr.to_chrome_trace()
        lanes = {
            e["tid"] for e in trace["traceEvents"] if e.get("ph") == "X"
        }
        assert len(lanes) == 4

    def test_chrome_export_shape(self):
        tr = Tracer(enabled=True)
        with tr.span("a", cat="x", n=3):
            pass
        doc = json.loads(tr.export_json())
        assert isinstance(doc["traceEvents"], list)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 1
        (e,) = xs
        assert e["name"] == "a" and e["cat"] == "x"
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert {"pid", "tid", "args"} <= set(e)
        # metadata events name the process and each thread lane
        phs = {ev["ph"] for ev in doc["traceEvents"]}
        assert "M" in phs

    def test_stage_timer_feeds_span_and_histogram(self):
        tr = Tracer(enabled=True)
        reg = Registry()
        h = reg.histogram("stage_seconds", "x", labels=("stage",))
        with tr.stage("work", h, labels={"stage": "work"}):
            pass
        assert [r.name for r in tr.records()] == ["work"]
        text = reg.expose()
        assert 'stage_seconds_count{stage="work"} 1' in text

    def test_stage_timer_histogram_fires_even_when_disabled(self):
        tr = Tracer(enabled=False)
        reg = Registry()
        h = reg.histogram("h", "x")
        with tr.stage("work", h):
            pass
        assert tr.records() == []
        assert "h_count 1" in reg.expose()


class TestRejectionLog:
    def test_record_counts_and_ring(self):
        reg = Registry()
        c = reg.counter("rej", "x", labels=("stage", "plugin", "reason"))
        log = RejectionLog(counter=c, capacity=2)
        for i in range(3):
            log.record(
                7,
                mkpod(f"p{i}"),
                RejectStage.FILTER,
                "noderesources",
                RejectReason.INSUFFICIENT_RESOURCES,
            )
        recs = log.records()
        assert len(recs) == 2  # ring evicted the oldest
        assert recs[0].pod == "p1"
        assert (
            c.value(
                stage="filter",
                plugin="noderesources",
                reason="insufficient_resources",
            )
            == 3  # the counter survives ring eviction
        )
        assert log.stage_tally() == {"filter": 2}
        doc = json.loads(log.render())
        assert doc["tally"] == {"filter": 2}
        assert doc["records"][0]["cycle"] == 7

    def test_cycle_filter(self):
        log = RejectionLog()
        log.record(1, mkpod("a"), RejectStage.GATE, "x", RejectReason.GANG_NOT_READY)
        log.record(2, mkpod("b"), RejectStage.GATE, "x", RejectReason.GANG_NOT_READY)
        assert [r.pod for r in log.records(cycle_id=2)] == ["b"]
        assert [r.cycle_id for r in log.for_uid("a")] == [1]


class TestSchedulerCycleTrace:
    """The ISSUE acceptance criterion: a BatchScheduler run over a
    synthetic cluster produces a Chrome trace whose spans cover ≥95% of
    the cycle's wall time with distinct snapshot/lower/solve/commit
    stages, and every unscheduled pod has a retrievable, counted
    rejection record."""

    def test_trace_coverage_and_stages(self, sched):
        sched.extender.tracer.enabled = True
        # deterministic monotonic fake clock: every read advances one
        # fixed tick, so span durations count CLOCK READS, not host
        # wall time. The old wall-clock form of this test (stage durs
        # sum to ≥95% of the cycle span) flaked under host contention —
        # a descheduled instant between two stages showed up as an
        # untimed gap. On the tick clock the tiling property is exact:
        # a stage transition costs a constant handful of reads, so any
        # inter-stage gap beyond that constant means instrumented work
        # escaped the stage sequence.
        class TickClock:
            t = 0.0

            def __call__(self):
                TickClock.t += TICK
                return TickClock.t

        TICK = 1e-6
        sched.extender.tracer.set_clock(TickClock())
        pods = [mkpod(f"p{i}") for i in range(8)]
        pods.append(mkpod("giant", cpu=999_000))  # cannot fit anywhere
        out = sched.schedule(pods)
        assert len(out.bound) == 8
        assert [p.meta.name for p in out.unschedulable] == ["giant"]

        recs = sched.extender.tracer.records()
        by_name = {r.name for r in recs}
        assert {"cycle", "snapshot", "lower", "solve", "commit"} <= by_name
        (cycle,) = [r for r in recs if r.name == "cycle"]
        stages = [
            r
            for r in recs
            if r.depth == 1
            and r.name in ("snapshot", "solve", "commit", "postfilter")
        ]
        # contiguity, deterministically: stages tile the cycle — every
        # gap (cycle start → first stage, stage → stage, last stage →
        # cycle end) is at most the constant transition overhead
        # (~3 clock reads; 6 leaves structural headroom)
        stages.sort(key=lambda r: r.t0)
        edges = [cycle.t0] + [r.t0 + r.dur for r in stages]
        starts = [r.t0 for r in stages] + [cycle.t0 + cycle.dur]
        names = ["cycle-open"] + [r.name for r in stages]
        for prev_end, nxt, name in zip(edges, starts, names):
            gap = round((nxt - prev_end) / TICK)
            assert gap <= 6, f"{gap}-tick untimed gap after {name}"
        # cycle_id joins every span of the cycle
        cid = cycle.args["cycle"]
        assert all(r.args.get("cycle") == cid for r in stages)
        # the trace round-trips as Chrome trace_event JSON
        doc = json.loads(
            sched.extender.services.dispatch("GET", "/trace")[1]
        )
        assert any(
            e["name"] == "cycle" for e in doc["traceEvents"] if e["ph"] == "X"
        )

    def test_unscheduled_pods_have_attributed_records(self, sched):
        impossible = mkpod("pinned")
        impossible.spec.node_name = "no-such-node"
        giant = mkpod("giant", cpu=999_000)
        out = sched.schedule([mkpod("ok"), giant, impossible])
        assert {p.meta.name for p in out.unschedulable} == {
            "giant",
            "pinned",
        }
        rej = sched.extender.rejections
        (g,) = rej.for_uid("giant")
        assert (g.stage, g.plugin, g.reason) == (
            "filter",
            "noderesources",
            "insufficient_resources",
        )
        (p,) = rej.for_uid("pinned")
        assert (p.stage, p.plugin, p.reason) == (
            "filter",
            "nodeaffinity",
            "no_matching_node",
        )
        # retrievable over the services engine…
        code, body = sched.extender.services.dispatch(
            "GET", "/debug/rejections"
        )
        assert code == 200
        doc = json.loads(body)
        assert {r["pod"] for r in doc["records"]} == {"giant", "pinned"}
        assert all(
            {"stage", "plugin", "reason", "cycle"} <= set(r)
            for r in doc["records"]
        )
        # …and counted in /metrics
        metrics = sched.extender.services.dispatch("GET", "/metrics")[1]
        assert (
            'koord_scheduler_rejections_total{plugin="noderesources",'
            'reason="insufficient_resources",stage="filter"} 1.0' in metrics
        )

    def test_usage_threshold_attribution(self, sched):
        from koordinator_tpu.api.types import NodeMetric, ResourceMetric

        # every node hot: estimated usage already above the 65% CPU
        # threshold, so the fit succeeds but LoadAware masks all nodes
        for i in range(4):
            sched.snapshot.set_node_metric(
                NodeMetric(
                    meta=ObjectMeta(name=f"node-{i}"),
                    node_usage=ResourceMetric(
                        usage={
                            ext.RES_CPU: 31000.0,
                            ext.RES_MEMORY: float(1 << 30),
                        }
                    ),
                    update_time=100.0,
                ),
                now=100.0,  # fresh at ingest time
            )
        out = sched.schedule([mkpod("hotput", cpu=4000)])
        assert out.unschedulable
        (r,) = sched.extender.rejections.for_uid("hotput")
        assert (r.stage, r.plugin, r.reason) == (
            "filter",
            "loadaware",
            "usage_exceeds_threshold",
        )

    def test_gang_gate_attribution(self, sched):
        from koordinator_tpu.api.types import PodGroup

        sched.pod_groups.upsert_pod_group(
            PodGroup(
                meta=ObjectMeta(name="gang-a", namespace="default"),
                min_member=3,
            )
        )
        member = mkpod(
            "m0",
            labels={ext.LABEL_GANG_NAME: "gang-a"},
            namespace="default",
        )
        out = sched.schedule([member])
        assert out.unschedulable
        recs = sched.extender.rejections.for_uid("m0")
        assert recs and recs[0].plugin == "coscheduling"

    def test_bound_pods_leave_no_records(self, sched):
        sched.schedule([mkpod(f"p{i}") for i in range(4)])
        assert sched.extender.rejections.records() == []

    def test_preemption_retry_bind_leaves_no_record(self):
        """A pod that fails the first pass but binds via the postfilter
        preemption retry was NOT rejected by the cycle — it must leave no
        rejection record (and no rejections_total increment)."""
        from koordinator_tpu.api.types import ElasticQuota
        from koordinator_tpu.core.snapshot import ClusterSnapshot
        from koordinator_tpu.scheduler.plugins.elasticquota import (
            GroupQuotaManager,
        )

        snap = ClusterSnapshot()
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 400.0, ext.RES_MEMORY: 400.0}
                ),
            )
        )
        mgr = GroupQuotaManager(
            snap.config,
            cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400},
        )
        mgr.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="team-a"),
                min={ext.RES_CPU: 8, ext.RES_MEMORY: 8},
                max={ext.RES_CPU: 12, ext.RES_MEMORY: 400},
            )
        )

        def qpod(name, prio):
            return Pod(
                meta=ObjectMeta(
                    name=name,
                    uid=name,
                    labels={ext.LABEL_QUOTA_NAME: "team-a"},
                ),
                spec=PodSpec(
                    requests={ext.RES_CPU: 6.0, ext.RES_MEMORY: 6.0},
                    priority=prio,
                ),
            )

        s = BatchScheduler(snap, quotas=mgr)
        s.extender.monitor.stop_background()
        out0 = s.schedule([qpod("low0", 5000), qpod("low1", 5000)])
        assert len(out0.bound) == 2  # team-a now at its 12-cpu max
        out = s.schedule([qpod("high", 9500)])
        assert [p.meta.name for p, _ in out.bound] == ["high"]
        assert [p.meta.name for p in out.preempted] == ["low1"]
        assert s.extender.rejections.for_uid("high") == []
        assert (
            s.extender.registry.get("rejections_total").value(
                stage="quota", plugin="elasticquota", reason="quota_exhausted"
            )
            == 0
        )

    def test_stream_pump_span(self, sched):
        from koordinator_tpu.scheduler.stream import StreamScheduler

        sched.extender.tracer.enabled = True
        stream = StreamScheduler(sched, max_batch=16)
        for i in range(3):
            stream.submit(mkpod(f"s{i}"))
        results = stream.pump()
        assert len(results) == 3
        pumps = [
            r for r in sched.extender.tracer.records() if r.name == "pump"
        ]
        assert len(pumps) == 1
        assert pumps[0].args["batch"] == 3
        assert pumps[0].args["bound"] == 3


class TestServicesEngineEndpoints:
    def test_trace_toggle_and_export(self, sched):
        eng = sched.extender.services
        assert sched.extender.tracer.enabled is False
        code, body = eng.dispatch("POST", "/trace", "1")
        assert (code, body) == (200, "True")
        sched.schedule([mkpod("p")])
        doc = json.loads(eng.dispatch("GET", "/trace")[1])
        assert any(e["ph"] == "X" for e in doc["traceEvents"])
        # disabling clears the ring
        code, body = eng.dispatch("POST", "/trace", "0")
        assert (code, body) == (200, "False")
        doc = json.loads(eng.dispatch("GET", "/trace")[1])
        assert not any(e["ph"] == "X" for e in doc["traceEvents"])

    def test_dispatch_error_paths(self, sched):
        eng = sched.extender.services
        assert eng.dispatch("GET", "/nope")[0] == 404
        assert eng.dispatch("POST", "/trace", "banana")[0] == 400
        assert eng.dispatch("POST", "/debug/scores", "not-an-int")[0] == 400
        assert eng.dispatch("POST", "/debug/rejections", "x")[0] == 405
        # plugin routes are exact-path: a prefix must not match
        eng.install("demo", "/x", lambda body: (200, "ok"))
        assert eng.dispatch("GET", "/apis/v1/demo/x")[0] == 200
        assert eng.dispatch("GET", "/apis/v1/demo/x/y")[0] == 404

    def test_filters_dump_carries_stage_tally(self, sched):
        eng = sched.extender.services
        assert eng.dispatch("POST", "/debug/filters", "1") == (200, "True")
        sched.schedule([mkpod("giant", cpu=999_000)])
        doc = json.loads(eng.dispatch("GET", "/debug/filters")[1])
        assert doc == {"filter:noderesources": 1}

    def test_rejections_served_over_http(self, sched):
        import urllib.request

        sched.schedule([mkpod("giant", cpu=999_000)])
        port = sched.extender.services.serve()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/rejections", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["records"][0]["pod"] == "giant"
        finally:
            sched.extender.services.shutdown()


class TestKoordletAndDeschedulerSpans:
    def test_qosmanager_strategy_spans(self, tmp_path):
        from koordinator_tpu.api.types import NodeSLO
        from koordinator_tpu.koordlet import resourceexecutor as rex
        from koordinator_tpu.koordlet.qosmanager import QoSManager

        tr = Tracer(enabled=True)
        qos = QoSManager(
            rex.ResourceExecutor(str(tmp_path)),
            total_cpus=8,
            node_allocatable_milli=8000.0,
            node_memory_capacity_mib=4096.0,
            tracer=tr,
        )
        slo = NodeSLO(meta=ObjectMeta(name="n"))
        slo.threshold.enable = True
        qos.run_once(
            slo,
            node_used_milli=6000.0,
            be_used_milli=1000.0,
            node_memory_used_mib=1000.0,
        )
        names = {r.name for r in tr.records()}
        assert "qos_tick" in names
        assert "strategy:cpusuppress" in names
        assert "strategy:cgreconcile" in names
        tick = [r for r in tr.records() if r.name == "qos_tick"][0]
        assert tick.args["cycle"] == 1

    def test_koordlet_collect_tick_spans_and_trace_endpoint(self, tmp_path):
        from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig

        agent = Koordlet(
            KoordletConfig(
                cgroup_root=str(tmp_path), n_cpus=2,
                node_memory_capacity_mib=1024.0,
            )
        )
        # sampling starts OFF and is armed over the server, like the
        # scheduler's services engine
        assert agent.tracer.enabled is False
        code, body = agent.server.dispatch("/trace", "POST", "1")
        assert (code, body) == (200, "True")
        agent.collect_tick(now=100.0)
        names = {r.name for r in agent.tracer.records()}
        assert "collect_tick" in names
        assert any(n.startswith("collect:") for n in names)
        code, body = agent.server.dispatch("/trace")
        assert code == 200
        assert json.loads(body)["traceEvents"]
        assert agent.server.dispatch("/trace", "POST", "bogus")[0] == 400
        assert agent.server.dispatch("/trace", "POST", "0") == (200, "False")
        assert agent.tracer.enabled is False

    def test_descheduler_profile_spans(self):
        from koordinator_tpu.descheduler.framework import Profile

        class FakePlugin:
            name = "lownodeload"

            def balance(self, ctx):
                return 0

        tr = Tracer(enabled=True)
        prof = Profile("default", balance_plugins=[FakePlugin()], tracer=tr)
        prof.run_once(nodes=[], pods=[])
        names = [r.name for r in tr.records()]
        assert "plugin:lownodeload:balance" in names
        assert "round:default" in names
