"""Solver observatory (devprof tentpole): compile/retrace ledger,
on-demand device-timeline capture, device-memory census, leak sentinel,
and the /debug/compiles + /debug/profile surfaces."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.obs.devprof import (
    CompileLedger,
    DevProf,
    LeakSentinel,
    donation_dead,
)
from koordinator_tpu.ops.solver import NodeState, PodBatch, SolverParams, assign
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.utils.metrics import Registry


def _params(d=2):
    return SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )


def _nodes(n=8, d=2):
    return NodeState.create(np.full((n, d), 100.0, np.float32))


def _pods(p, d=2):
    return PodBatch.create(
        np.ones((p, d), np.float32), np.arange(p, dtype=np.int32)
    )


class TestCompileLedger:
    def test_trace_hook_counts_only_cache_misses(self):
        led = CompileLedger().install()
        try:
            # a NOVEL shape so the process-wide jit cache misses
            r = assign(_pods(5), _nodes(7), _params())
            np.asarray(r.assignment)
            first = led.report()["functions"].get("assign", {}).get(
                "traces", 0
            )
            assert first == 1
            # warm call: cache hit, the hook body never runs
            r = assign(_pods(5), _nodes(7), _params())
            np.asarray(r.assignment)
            assert (
                led.report()["functions"]["assign"]["traces"] == first
            )
        finally:
            led.uninstall()

    def test_uninstalled_ledger_sees_nothing(self):
        led = CompileLedger()
        r = assign(_pods(3), _nodes(9), _params())
        np.asarray(r.assignment)
        assert led.total_traces() == 0

    def test_watch_attributes_retrace_cause_and_compile_wall(self):
        dp = DevProf(registry=Registry()).install()
        try:
            with dp.watch("assign", cycle=1, p=6, n=11) as w:
                r = assign(_pods(6), _nodes(11), _params())
                w.result(r.assignment)
            with dp.watch("assign", cycle=2, p=12, n=11) as w:
                r = assign(_pods(12), _nodes(11), _params())
                w.result(r.assignment)
            rep = dp.ledger.report()
            row = rep["functions"]["assign"]
            assert row["traces"] == 2 and row["calls"] == 2
            assert row["signatures"] == 2
            assert row["compile_seconds"] > 0
            # the second trace's cause names the changed signature key
            cause = rep["recent_causes"][-1]
            assert cause["fn"] == "assign" and cause["cycle"] == 2
            assert cause["delta"] == {"p": [6, 12]}
            assert cause["wall_s"] > 0
        finally:
            dp.uninstall()

    def test_steady_state_marking(self):
        dp = DevProf().install()
        try:
            with dp.watch("assign", p=7, n=13) as w:
                w.result(assign(_pods(7), _nodes(13), _params()).assignment)
            dp.ledger.mark_steady()
            assert dp.ledger.steady_retraces() == 0
            # warm repeat: still retrace-free
            with dp.watch("assign", p=7, n=13) as w:
                w.result(assign(_pods(7), _nodes(13), _params()).assignment)
            assert dp.ledger.steady_retraces() == 0
            # a NEW shape after the mark is the violation the longrun
            # assertion exists to catch
            with dp.watch("assign", p=9, n=13) as w:
                w.result(assign(_pods(9), _nodes(13), _params()).assignment)
            assert dp.ledger.steady_retraces() == 1
            assert any(
                c.get("steady_state") for c in dp.ledger.steady_causes()
            )
        finally:
            dp.uninstall()

    def test_metrics_land_in_registry(self):
        reg = Registry()
        dp = DevProf(registry=reg).install()
        try:
            with dp.watch("assign", p=4, n=17) as w:
                w.result(assign(_pods(4), _nodes(17), _params()).assignment)
        finally:
            dp.uninstall()
        assert reg.get("solver_compiles_total").value(fn="assign") == 1.0
        assert reg.get("solver_compile_seconds").value(fn="assign") > 0


class TestDeviceMemoryCensus:
    def test_table_bytes_and_live_totals(self):
        dp = DevProf(registry=(reg := Registry()))
        nodes = _nodes(16)
        out = dp.census.sample({"nodes": nodes, "absent": None})
        want = sum(
            leaf.nbytes
            for leaf in [
                nodes.allocatable, nodes.requested, nodes.estimated_used,
                nodes.prod_used, nodes.metric_fresh, nodes.schedulable,
                nodes.cpu_amp, nodes.custom_thresholds,
                nodes.custom_prod_thresholds,
            ]
        )
        assert out == {"nodes": want}
        assert reg.get("solver_device_bytes").value(table="nodes") == want
        assert dp.census.last_live[0] > 0

    def test_donation_effectiveness(self):
        from koordinator_tpu.ops.solver import scatter_rows

        def distinct(n):
            # NodeState.create aliases one zeros buffer across fields,
            # which donation rejects (same buffer donated twice) — build
            # each field as its own array like the scheduler's lowering
            return NodeState.create(
                np.full((n, 2), 100.0, np.float32),
                requested=np.zeros((n, 2), np.float32),
                estimated_used=np.zeros((n, 2), np.float32),
                prod_used=np.zeros((n, 2), np.float32),
                custom_thresholds=np.zeros((n, 2), np.float32),
                custom_prod_thresholds=np.zeros((n, 2), np.float32),
            )

        full = distinct(8)
        rows = distinct(2)
        idx = jnp.asarray([0, 1], jnp.int32)
        census = DevProf().census
        new = scatter_rows(full, idx, rows)
        assert census.check_donation(full) is True  # donated input died
        assert donation_dead(new) is False          # output is alive
        assert census.donation_misses == 0

    def test_leak_sentinel_flags_only_monotone_growth(self):
        s = LeakSentinel(tolerance_bytes=100)
        s.samples = [("a", 1, 1000), ("b", 2, 2000), ("c", 3, 3000)]
        assert s.problems()
        # non-monotone (a dip) is not a leak
        s.samples = [("a", 1, 1000), ("b", 2, 500), ("c", 3, 3000)]
        assert not s.problems()
        # monotone but under tolerance is noise
        s2 = LeakSentinel(tolerance_bytes=10_000)
        s2.samples = [("a", 1, 1000), ("b", 2, 2000), ("c", 3, 3000)]
        assert not s2.problems()
        # too few samples: no verdict
        s3 = LeakSentinel(tolerance_bytes=100)
        s3.samples = [("a", 1, 1000), ("b", 2, 2000)]
        assert not s3.problems()


def _mini_sched(n_nodes=4, bucket=32):
    s = BatchScheduler(batch_bucket=bucket)
    s.extender.monitor.stop_background()
    for i in range(n_nodes):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000.0, ext.RES_MEMORY: 1e9}
                ),
            )
        )
    return s


def _pod(name, cpu=1000.0):
    return Pod(
        meta=ObjectMeta(name=name, uid=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 1e6}, priority=9500
        ),
    )


class TestSchedulerIntegration:
    def test_debug_compiles_and_profile_endpoints(self):
        s = _mini_sched()
        dp = DevProf(registry=s.extender.registry)
        s.attach_devprof(dp)
        try:
            svc = s.extender.services
            code, txt = svc.dispatch("GET", "/debug/profile?cycles=3")
            assert code == 200
            assert json.loads(txt)["cycles_remaining"] == 3
            out = s.schedule([_pod("p0"), _pod("p1")])
            assert len(out.bound) == 2
            code, txt = svc.dispatch("GET", "/debug/compiles")
            assert code == 200
            rep = json.loads(txt)
            # the watch records every CALL; whether it also traced
            # depends on what the process-wide jit cache already holds
            # (the full suite warms these shapes), so assert on calls
            assert rep["functions"]["assign"]["calls"] >= 1
            code, txt = svc.dispatch("GET", "/debug/profile")
            doc = json.loads(txt)
            assert doc["status"]["device_events"] > 0
            assert doc["breakdown_ms"]["device_compute_ms"] > 0
            assert doc["census"]["tables_bytes"]["nodes"] > 0
            code, _ = svc.dispatch("GET", "/debug/profile?cycles=bogus")
            assert code == 400
        finally:
            dp.uninstall()

    def test_device_lane_merges_into_chrome_trace(self):
        s = _mini_sched()
        dp = DevProf()
        s.attach_devprof(dp)
        try:
            s.extender.tracer.enabled = True
            dp.capture(2)
            s.schedule([_pod("q0")])
            code, txt = s.extender.services.dispatch("GET", "/trace")
            assert code == 200
            doc = json.loads(txt)
            lanes = {
                e["args"]["name"]
                for e in doc["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "thread_name"
            }
            assert "device" in lanes
            dev = [
                e for e in doc["traceEvents"] if e.get("cat") == "device"
            ]
            assert dev and all(
                e["args"]["cycle"] >= 1 and e["ts"] >= 0 for e in dev
            )
            # device ops align under host spans: the solve-stage device
            # op must fall inside the traced cycle's wall window
            cycles = [
                e
                for e in doc["traceEvents"]
                if e.get("ph") == "X" and e["name"] == "cycle"
            ]
            assert cycles
            lo = min(c["ts"] for c in cycles)
            hi = max(c["ts"] + c["dur"] for c in cycles)
            solve_ops = [
                e for e in dev if e["args"]["stage"] == "solve"
            ]
            assert solve_ops
            assert all(
                lo - 1000 <= e["ts"] <= hi + 1000 for e in solve_ops
            )
        finally:
            dp.uninstall()

    def test_capture_window_expires(self):
        s = _mini_sched()
        dp = DevProf()
        s.attach_devprof(dp)
        try:
            dp.capture(1)
            s.schedule([_pod("w0")])
            n_after_first = len(dp.device_events)
            assert n_after_first > 0
            s.schedule([_pod("w1")])
            assert len(dp.device_events) == n_after_first
            assert dp.status()["capturing"] is False
        finally:
            dp.uninstall()

    def test_scatter_refresh_records_transfer_and_donation(self):
        s = _mini_sched(n_nodes=12)
        dp = DevProf()
        s.attach_devprof(dp)
        try:
            s.schedule([_pod("a0")])  # builds the resident state
            dp.capture(4)
            s.snapshot.touch_rows([1, 2])
            s.schedule([_pod("a1")])
            kinds = {ev["kind"] for ev in dp.device_events}
            assert "transfer" in kinds
            assert dp.census.donation_checks >= 1
            assert dp.census.donation_misses == 0
        finally:
            dp.uninstall()


class TestFleetForwarding:
    def test_fleet_services_forward_debug_compiles(self):
        """FleetServices forwards /debug/compiles and /debug/profile to
        every owned shard's engine (same shape as /debug/pipeline)."""

        class _Svc:
            def dispatch(self, method, path, body=""):
                return 200, json.dumps({"functions": {}, "path": path})

        class _Ext:
            services = _Svc()

        class _Sched:
            extender = _Ext()

        class _Rt:
            sched = _Sched()

        class _Sharded:
            name = "inc0"
            _runtimes = {0: _Rt(), 2: _Rt()}
            lifecycle = None

        from koordinator_tpu.obs.fleet import FleetServices

        fs = FleetServices(_Sharded())
        code, txt = fs.dispatch("GET", "/debug/compiles")
        assert code == 200
        doc = json.loads(txt)
        assert set(doc["shards"]) == {"0", "2"}
        code, txt = fs.dispatch("GET", "/debug/profile?cycles=2")
        assert code == 200
        assert json.loads(txt)["shards"]["0"]["path"] == (
            "/debug/profile?cycles=2"
        )


class TestNullWatchIdiom:
    def test_null_watch_is_shared_and_inert(self):
        from koordinator_tpu.obs.devprof import NULL_WATCH

        with NULL_WATCH as w:
            w.result(object())
        with NULL_WATCH:
            pass

    def test_disabled_scheduler_has_no_observatory_cost(self):
        s = _mini_sched()
        assert s.devprof is None
        out = s.schedule([_pod("z0")])
        assert len(out.bound) == 1
        # no observatory: none of its metric families exist
        text = s.extender.services.dispatch("GET", "/metrics")[1]
        assert "solver_compiles_total" not in text
        assert "solver_device_bytes" not in text
        code, _ = s.extender.services.dispatch("GET", "/debug/compiles")
        assert code == 404
        code, _ = s.extender.services.dispatch("GET", "/debug/profile")
        assert code == 404
