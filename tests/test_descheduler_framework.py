"""Descheduler framework tests: plugin registry, profile loop, dry-run,
evictability policy, the three evictor mechanisms, and LowNodeLoad wired
through the framework (SURVEY §2.4)."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.descheduler.evictor import (
    ANNOTATION_EVICT_OPT_OUT,
    DeleteEvictor,
    LABEL_SOFT_EVICTION,
    NativeEvictor,
    PodEvictionPolicy,
    SoftEvictor,
)
from koordinator_tpu.descheduler.framework import (
    Descheduler,
    Profile,
    Registry,
)
from koordinator_tpu.descheduler.low_node_load import (
    LowNodeLoad,
    LowNodeLoadArgs,
    LowNodeLoadBalance,
)


def pod(name, prio=5500, owner=True, node=None, cpu=1000.0, labels=None):
    lab = dict(labels or {})
    if owner:
        lab.setdefault("owner-kind", "ReplicaSet")
    return Pod(
        meta=ObjectMeta(name=name, labels=lab),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu},
            priority=prio,
            node_name=node,
        ),
        phase=PodPhase.RUNNING if node else PodPhase.PENDING,
    )


# ---- evictability policy ----


def test_policy_guards():
    policy = PodEvictionPolicy()
    assert policy.evictable(pod("ok"))
    assert not policy.evictable(pod("sys", prio=10_000))
    assert not policy.evictable(pod("orphan", owner=False))
    opt_out = pod("optout")
    opt_out.meta.annotations[ANNOTATION_EVICT_OPT_OUT] = "true"
    assert not policy.evictable(opt_out)
    done = pod("done")
    done.phase = PodPhase.SUCCEEDED
    assert not policy.evictable(done)
    scoped = PodEvictionPolicy(label_selector={"tier": "batch"})
    assert not scoped.evictable(pod("other"))
    assert scoped.evictable(pod("batchy", labels={"tier": "batch"}))


# ---- evictors ----


def test_native_evictor_respects_pdb():
    deleted = []
    ev = NativeEvictor(
        delete_fn=lambda p: (deleted.append(p.meta.name), True)[1],
        pdb_check=lambda p: p.meta.name != "protected",
    )
    assert ev.evict(pod("free"), "test")
    assert not ev.evict(pod("protected"), "test")
    assert deleted == ["free"]


def test_soft_evictor_marks_once():
    ev = SoftEvictor()
    p = pod("victim")
    assert ev.evict(p, "rebalance")
    assert p.meta.labels[LABEL_SOFT_EVICTION] == "true"
    # SoftEvictionSpec lives under the reference annotation name
    # (descheduling.go AnnotationSoftEviction)
    assert "rebalance" in p.meta.annotations["scheduling.koordinator.sh/soft-eviction"]
    assert not ev.evict(p, "again")
    assert len(ev.marked) == 1


# ---- registry / profile / dry-run ----


class FakeDeschedule:
    name = "FakePolicy"

    def deschedule(self, ctx):
        n = 0
        for p in ctx.pods:
            if p.meta.labels.get("bad") == "true":
                if ctx.evict(p, "policy violation", self.name):
                    n += 1
        return n


def test_registry_builds_and_rejects_dupes():
    reg = Registry()
    reg.register("FakePolicy", FakeDeschedule)
    assert isinstance(reg.build("FakePolicy"), FakeDeschedule)
    try:
        reg.register("FakePolicy", FakeDeschedule)
        raise AssertionError("dup registration allowed")
    except ValueError:
        pass
    assert reg.names() == ["FakePolicy"]


def test_profile_dry_run_records_without_evicting():
    deleted = []
    prof = Profile(
        name="dry",
        deschedule_plugins=[FakeDeschedule()],
        evictor=DeleteEvictor(lambda p: (deleted.append(p), True)[1]),
        dry_run=True,
    )
    pods = [pod("a", labels={"bad": "true"}), pod("b")]
    counts = prof.run_once([], pods)
    assert counts["FakePolicy"] == 1
    assert deleted == []                      # dry-run: nothing deleted
    assert len(prof.records) == 1
    assert prof.records[0].executed is False


def test_profile_eviction_budget_and_policy():
    deleted = []
    prof = Profile(
        name="real",
        deschedule_plugins=[FakeDeschedule()],
        evictor=DeleteEvictor(lambda p: (deleted.append(p.meta.name), True)[1]),
        max_evictions_per_round=1,
    )
    pods = [
        pod("a", labels={"bad": "true"}),
        pod("b", labels={"bad": "true"}),
        pod("sys", prio=10_000, labels={"bad": "true"}),  # policy blocks
    ]
    counts = prof.run_once([], pods)
    assert counts["FakePolicy"] == 1          # budget capped the second
    assert deleted == ["a"]


# ---- LowNodeLoad through the framework ----


def make_cluster():
    snap = ClusterSnapshot()
    for i, util in enumerate([0.9, 0.9, 0.2, 0.2]):
        name = f"n{i}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 10_000, ext.RES_MEMORY: 10_000}
                ),
            )
        )
        snap.set_node_metric(
            NodeMetric(
                meta=ObjectMeta(name=name),
                node_usage=ResourceMetric(
                    usage={ext.RES_CPU: 10_000 * util, ext.RES_MEMORY: 10_000 * util}
                ),
                update_time=1000.0,
            ),
            now=1001.0,
        )
    return snap


def test_low_node_load_balance_plugin():
    snap = make_cluster()
    lnl = LowNodeLoad(
        snap, LowNodeLoadArgs(anomaly_condition_count=2, max_evictions_per_node=2)
    )
    balance = LowNodeLoadBalance(lnl)
    evictor = SoftEvictor()
    prof = Profile(name="load", balance_plugins=[balance], evictor=evictor)
    nodes, pods = [], [
        pod("be-1", prio=5200, node="n0"),
        pod("ls-1", prio=9200, node="n0"),
        pod("be-2", prio=5200, node="n1"),
    ]
    desched = Descheduler([prof])
    # round 1: debounce holds fire
    out = desched.run_once(nodes, pods)
    assert out["load"]["LowNodeLoad"] == 0
    # round 2: overutilized nodes are actionable; batch pods go first
    out = desched.run_once(nodes, pods)
    assert out["load"]["LowNodeLoad"] >= 1
    assert all(p.meta.labels.get(LABEL_SOFT_EVICTION) == "true" for p in evictor.marked)
    # lowest priority band leaves n0 first; the prod pod may follow only
    # because the node is still far above target after the batch eviction
    n0_marked = [p.meta.name for p in evictor.marked if p.spec.node_name == "n0"]
    assert n0_marked[0] == "be-1"
