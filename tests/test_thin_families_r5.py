"""Behavior tests for the round-4 verdict's named thin families
(VERDICT r4 #6): metriccache retention, NodeSLO rendering across every
strategy field, arbitrator rate-limit/group edges, and runtimeproxy
hook-crash + Ignore-policy paths."""

import dataclasses

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.koordlet import metriccache as mc

# ---------------------------------------------------------------------------
# metriccache retention (reference tsdb_storage.go:117 RetentionDuration,
# config.go:50 default 12h)
# ---------------------------------------------------------------------------


def test_retention_default_matches_reference():
    assert mc.DEFAULT_RETENTION_S == 12 * 3600.0
    assert mc.MetricCache().retention_s == mc.DEFAULT_RETENTION_S


def test_query_horizon_hides_expired_samples():
    cache = mc.MetricCache(capacity_per_series=64, retention_s=100.0)
    for t in range(0, 200, 10):
        cache.append(mc.NODE_CPU_USAGE, "n", float(t), float(t))
    # data-time horizon: newest=190 → samples < 90 invisible to queries
    agg = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 1e9)
    assert agg is not None
    assert agg.count == 11                    # 90..190 inclusive
    assert min(agg.percentiles.values()) >= 90.0


def test_aggregate_window_clamped_to_horizon():
    cache = mc.MetricCache(capacity_per_series=64, retention_s=50.0)
    cache.append(mc.NODE_CPU_USAGE, "n", 0.0, 1.0)
    cache.append(mc.NODE_CPU_USAGE, "n", 100.0, 2.0)
    agg = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 100.0)
    assert agg.count == 1 and agg.avg == 2.0


def test_clock_skewed_future_sample_cannot_erase_history():
    """A corrupt far-future timestamp hides history at query time but
    must NOT destroy it: once real-time samples resume past the glitch,
    aggregation over real history works again (code-review r5 — the
    append hot path never compacts)."""
    cache = mc.MetricCache(capacity_per_series=64, retention_s=100.0)
    for t in range(0, 100, 10):
        cache.append(mc.NODE_CPU_USAGE, "n", 1000.0 + t, float(t))
    cache.append(mc.NODE_CPU_USAGE, "n", 1e7, 999.0)  # clock glitch
    hidden = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 2000.0)
    assert hidden is None or hidden.count == 0
    # glitch sample swept by wall-time retention; history survives
    cache.enforce_retention(now=1100.0 + 100.0)
    # (the glitch ts 1e7 > horizon so it stays; but real samples remain
    # in the ring too — verify by windowing directly past the clamp)
    agg_all = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 1e9)
    assert agg_all is not None  # nothing was physically destroyed early


def test_retention_zero_disables():
    cache = mc.MetricCache(capacity_per_series=64, retention_s=0.0)
    cache.append(mc.NODE_CPU_USAGE, "n", 0.0, 1.0)
    cache.append(mc.NODE_CPU_USAGE, "n", 1e9, 2.0)
    agg = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 1e9)
    assert agg.count == 2


def test_enforce_retention_sweeps_and_drops_series():
    cache = mc.MetricCache(capacity_per_series=64, retention_s=100.0)
    cache.append(mc.NODE_CPU_USAGE, "live", 1000.0, 1.0)
    cache.append(mc.NODE_MEMORY_USAGE, "dead", 10.0, 1.0)
    samples, series = cache.enforce_retention(now=1050.0)
    assert series == 1                       # "dead" dropped whole
    assert cache.latest(mc.NODE_MEMORY_USAGE, "dead") is None
    assert cache.latest(mc.NODE_CPU_USAGE, "live") == (1000.0, 1.0)
    # a second sweep past the live sample drops it too
    _s, series = cache.enforce_retention(now=2000.0)
    assert series == 1
    assert cache.latest(mc.NODE_CPU_USAGE, "live") is None


def test_compact_preserves_ring_order_across_wrap():
    ring = mc._Ring(8)
    for t in range(12):                      # wraps the 8-slot ring
        ring.append(float(t), float(t * 10))
    dropped = ring.compact(7.0)              # keep ts 7..11
    assert dropped == 3                      # ring held 4..11
    assert ring.count == 5
    vals = ring.window(0.0, 100.0)
    assert sorted(vals.tolist()) == [70.0, 80.0, 90.0, 100.0, 110.0]
    # appends after compaction keep working
    ring.append(12.0, 120.0)
    assert ring.latest() == (12.0, 120.0)


def test_checkpoint_restore_round_trips_compacted_ring(tmp_path):
    cache = mc.MetricCache(capacity_per_series=32, retention_s=100.0)
    for t in range(0, 300, 20):
        cache.append(mc.NODE_CPU_USAGE, "n", float(t), float(t))
    path = str(tmp_path / "tsdb.npz")
    cache.checkpoint(path)
    back = mc.MetricCache.restore(path, capacity_per_series=32, retention_s=100.0)
    a = cache.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 1e9)
    b = back.aggregate(mc.NODE_CPU_USAGE, "n", 0.0, 1e9)
    assert a.count == b.count and a.avg == b.avg


# ---------------------------------------------------------------------------
# NodeSLO rendering across every strategy field
# (reference pkg/slo-controller/nodeslo/resource_strategy.go)
# ---------------------------------------------------------------------------


def _controller(**cfg_kw):
    from koordinator_tpu.api.types import (
        BlkIOStrategy,
        CPUBurstStrategy,
        QoSClass,
        ResctrlStrategy,
        ResourceThresholdStrategy,
        SystemStrategy,
    )
    from koordinator_tpu.manager.nodeslo import (
        NodeSLOController,
        SLOControllerConfig,
    )

    return NodeSLOController(SLOControllerConfig(**cfg_kw)), {
        "threshold": ResourceThresholdStrategy,
        "cpu_burst": CPUBurstStrategy,
        "system": SystemStrategy,
        "resctrl": ResctrlStrategy,
        "blkio": BlkIOStrategy,
        "qos": QoSClass,
    }


def test_render_covers_every_strategy_field():
    from koordinator_tpu.api.types import NodeSLO, QoSClass
    from koordinator_tpu.api.types import (
        ResctrlStrategy,
        SystemStrategy,
    )

    ctrl, _t = _controller(
        system=SystemStrategy(enable=True, watermark_scale_factor=250.0),
        resctrl=ResctrlStrategy(enable=True),
        resource_qos={QoSClass.BE: {"memoryQoS.wmarkRatio": 95.0}},
        host_applications=[("nginx", "host-latency-sensitive/nginx", "LS")],
    )
    slo = ctrl.render("n0")
    # every NodeSLO strategy field is populated from the cluster config
    assert slo.system.enable and slo.system.watermark_scale_factor == 250.0
    assert slo.resctrl.enable
    assert slo.resource_qos[QoSClass.BE]["memoryQoS.wmarkRatio"] == 95.0
    assert slo.host_applications == [
        ("nginx", "host-latency-sensitive/nginx", "LS")
    ]
    assert slo.threshold.enable  # cluster default
    # no NodeSLO dataclass field is silently un-rendered
    rendered_fields = {"threshold", "cpu_burst", "system", "resctrl",
                       "blkio", "resource_qos", "host_applications", "meta"}
    assert {f.name for f in dataclasses.fields(NodeSLO)} <= rendered_fields


@pytest.mark.parametrize(
    "family, override_field",
    [
        ("node_overrides", "threshold"),
        ("cpu_burst_overrides", "cpu_burst"),
        ("system_overrides", "system"),
        ("resctrl_overrides", "resctrl"),
        ("blkio_overrides", "blkio"),
    ],
)
def test_per_node_override_first_match_wins(family, override_field):
    from koordinator_tpu.api.types import (
        BlkIOStrategy,
        CPUBurstStrategy,
        ResctrlStrategy,
        ResourceThresholdStrategy,
        SystemStrategy,
    )

    override_types = {
        "threshold": ResourceThresholdStrategy(
            enable=True, cpu_suppress_threshold_percent=40.0
        ),
        "cpu_burst": CPUBurstStrategy(policy="auto"),
        "system": SystemStrategy(enable=True, min_free_kbytes_factor=50.0),
        "resctrl": ResctrlStrategy(enable=True),
        "blkio": BlkIOStrategy(enable=True),
    }
    ctrl, _t = _controller(
        **{
            family: {
                "pool=gold": override_types[override_field],
                "pool=silver": type(override_types[override_field])(),
            }
        }
    )
    rendered = ctrl.render("n-gold", {"pool": "gold"})
    plain = ctrl.render("n-plain", {"pool": "bronze"})
    assert getattr(rendered, override_field) == override_types[override_field]
    assert getattr(plain, override_field) != override_types[override_field]
    # rendered objects are copies — mutating one node's SLO must not
    # leak into the cluster config or other nodes
    field_obj = getattr(rendered, override_field)
    if hasattr(field_obj, "enable"):
        field_obj.enable = not field_obj.enable
    again = ctrl.render("n-gold2", {"pool": "gold"})
    assert getattr(again, override_field) == override_types[override_field]


def test_configmap_ingestion_renders_dynamically():
    """The slo-controller-config ConfigMap channel end-to-end: blobs
    parsed by the yaml loader reconfigure the renderer (threshold,
    burst, system, host apps), including nodeStrategies overrides."""
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    ctrl = NodeSLOController()
    ctrl.apply_configmap(
        {
            "resource-threshold-config": {
                "clusterStrategy": {
                    "enable": True,
                    "cpuSuppressThresholdPercent": 55.0,
                },
                "nodeStrategies": [
                    {
                        "nodeSelector": {"matchLabels": {"tier": "edge"}},
                        "enable": True,
                        "cpuSuppressThresholdPercent": 30.0,
                    }
                ],
            },
            "cpu-burst-config": {
                "clusterStrategy": {"policy": "auto", "cpuBurstPercent": 500.0}
            },
            "system-config": {
                "clusterStrategy": {
                    "enable": True,
                    "watermarkScaleFactor": 200.0,
                }
            },
            "host-application-config": {
                "applications": [
                    {
                        "name": "dns",
                        "cgroupPath": {"relativePath": "host/dns"},
                        "qos": "LSR",
                    }
                ]
            },
        }
    )
    slo = ctrl.render("n0")
    assert slo.threshold.cpu_suppress_threshold_percent == 55.0
    assert slo.cpu_burst.policy == "auto"
    assert slo.cpu_burst.cpu_burst_percent == 500.0
    assert slo.system.watermark_scale_factor == 200.0
    assert slo.host_applications == [("dns", "host/dns", "LSR")]
    edge = ctrl.render("n-edge", {"tier": "edge"})
    assert edge.threshold.cpu_suppress_threshold_percent == 30.0


def test_configmap_reapply_drops_stale_overrides():
    """A nodeStrategies entry deleted from the ConfigMap must stop
    applying on the next apply (code-review r5: the reference re-renders
    from the full current ConfigMap)."""
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    ctrl = NodeSLOController()
    ctrl.apply_configmap(
        {
            "resource-threshold-config": {
                "clusterStrategy": {"cpuSuppressThresholdPercent": 60.0},
                "nodeStrategies": [
                    {
                        "nodeSelector": {"matchLabels": {"tier": "edge"}},
                        "cpuSuppressThresholdPercent": 30.0,
                    }
                ],
            }
        }
    )
    assert (
        ctrl.render("e", {"tier": "edge"}).threshold.cpu_suppress_threshold_percent
        == 30.0
    )
    ctrl.apply_configmap(
        {
            "resource-threshold-config": {
                "clusterStrategy": {"cpuSuppressThresholdPercent": 58.0}
            }
        }
    )
    assert (
        ctrl.render("e", {"tier": "edge"}).threshold.cpu_suppress_threshold_percent
        == 58.0
    )


def test_multi_label_selector_requires_all_pairs():
    """matchLabels with several pairs must match the WHOLE set
    (code-review r5: keeping only the first pair over-matched nodes)."""
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    ctrl = NodeSLOController()
    ctrl.apply_configmap(
        {
            "resource-threshold-config": {
                "clusterStrategy": {"cpuSuppressThresholdPercent": 60.0},
                "nodeStrategies": [
                    {
                        "nodeSelector": {
                            "matchLabels": {"pool": "gold", "zone": "z1"}
                        },
                        "cpuSuppressThresholdPercent": 25.0,
                    }
                ],
            }
        }
    )
    both = ctrl.render("a", {"pool": "gold", "zone": "z1"})
    partial = ctrl.render("b", {"pool": "gold", "zone": "z2"})
    assert both.threshold.cpu_suppress_threshold_percent == 25.0
    assert partial.threshold.cpu_suppress_threshold_percent == 60.0


def test_resource_qos_config_parses_per_class_blocks():
    from koordinator_tpu.api.types import QoSClass
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    ctrl = NodeSLOController()
    ctrl.apply_configmap(
        {
            "resource-qos-config": {
                "clusterStrategy": {
                    "beClass": {"memoryQoS": {"wmarkRatio": 95}},
                    "lsrClass": {"cpuQoS": {"groupIdentity": 2}},
                    "bogusClass": {"x": 1},
                }
            }
        }
    )
    slo = ctrl.render("n")
    assert slo.resource_qos[QoSClass.BE]["memoryQoS.wmarkRatio"] == 95.0
    assert slo.resource_qos[QoSClass.LSR]["cpuQoS.groupIdentity"] == 2.0


def test_rendered_resctrl_is_isolated_from_cluster_config():
    """Mutating one node's rendered resctrl dicts must not leak into the
    cluster default or other nodes (code-review r5: shallow replace
    shared the nested dicts)."""
    from koordinator_tpu.api.types import ResctrlStrategy
    from koordinator_tpu.manager.nodeslo import (
        NodeSLOController,
        SLOControllerConfig,
    )

    cfg = SLOControllerConfig(resctrl=ResctrlStrategy(enable=True))
    ctrl = NodeSLOController(cfg)
    a = ctrl.render("a")
    for attr in ("llc_percent", "mba_percent"):
        d = getattr(a.resctrl, attr, None)
        if isinstance(d, dict):
            d["poison"] = 1.0
    b = ctrl.render("b")
    for attr in ("llc_percent", "mba_percent"):
        d = getattr(b.resctrl, attr, None)
        if isinstance(d, dict):
            assert "poison" not in d


def test_configmap_via_yaml_loader_round_trip():
    from koordinator_tpu.api.yaml_loader import load_slo_controller_config
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    doc = {
        "kind": "ConfigMap",
        "metadata": {"name": "slo-controller-config"},
        "data": {
            "cpu-burst-config": '{"clusterStrategy": {"policy": "cpuBurstOnly"}}',
            "bogus": "not-json{{",
        },
    }
    parsed = load_slo_controller_config(doc)
    ctrl = NodeSLOController()
    ctrl.apply_configmap(parsed)
    assert ctrl.render("n").cpu_burst.policy == "cpuBurstOnly"


# ---------------------------------------------------------------------------
# arbitrator rate-limit / group edges (reference arbitrator/filter.go)
# ---------------------------------------------------------------------------


def _mk_job_pod(name, ns="default", owner="", prio=5000, qos=None):
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.descheduler.migration import PodMigrationJob

    labels = {}
    if qos is not None:
        labels[ext.LABEL_POD_QOS] = qos
    pod = Pod(
        meta=ObjectMeta(name=name, namespace=ns, labels=labels, owner_uid=owner),
        spec=PodSpec(requests={ext.RES_CPU: 1000}, priority=prio),
    )
    from koordinator_tpu.api.types import ObjectMeta as _OM

    job = PodMigrationJob(meta=_OM(name=f"mj-{name}"), pod_uid=pod.meta.uid)
    return job, pod


def _arbitrate(jobs_pods, args=None, **kw):
    from koordinator_tpu.descheduler.migration import Arbitrator

    jobs = [j for j, _p in jobs_pods]
    pods = {p.meta.uid: p for _j, p in jobs_pods}
    return [
        j.pod_uid for j in Arbitrator(args).arbitrate(jobs, pods, **kw)
    ]


def test_global_budget_counts_in_flight():
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [_mk_job_pod(f"p{i}", ns=f"ns{i}") for i in range(6)]
    args = ArbitratorArgs(max_migrating_global=5, max_migrating_per_namespace=9)
    assert len(_arbitrate(jp, args, in_flight=0)) == 5
    assert len(_arbitrate(jp, args, in_flight=3)) == 2
    assert len(_arbitrate(jp, args, in_flight=5)) == 0
    assert len(_arbitrate(jp, args, in_flight=99)) == 0   # over-budget clamps


def test_namespace_cap_counts_running_migrations():
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [_mk_job_pod(f"p{i}", ns="busy") for i in range(4)]
    args = ArbitratorArgs(max_migrating_global=10, max_migrating_per_namespace=2)
    assert len(_arbitrate(jp, args, in_flight=0)) == 2
    # one already running in the namespace eats into its cap
    assert (
        len(_arbitrate(jp, args, in_flight=1, running_per_ns={"busy": 1})) == 1
    )
    assert (
        len(_arbitrate(jp, args, in_flight=2, running_per_ns={"busy": 2})) == 0
    )


@pytest.mark.parametrize(
    "cap, replicas, expect",
    [
        (1, 10, 1),       # absolute int
        ("20%", 10, 2),   # percent rounds up against replicas
        ("25%", 10, 3),   # ceil(2.5) = 3
        ("10%", 3, 1),    # ceil(0.3) = 1
    ],
)
def test_workload_migrating_cap_int_or_percent(cap, replicas, expect):
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [_mk_job_pod(f"p{i}", owner="rs-1") for i in range(6)]
    args = ArbitratorArgs(
        max_migrating_global=10,
        max_migrating_per_namespace=10,
        max_migrating_per_workload=cap,
    )
    out = _arbitrate(
        jp, args, in_flight=0, replicas_by_owner={"rs-1": replicas}
    )
    assert len(out) == expect


def test_workload_unavailable_cap_counts_existing_unavailable():
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [_mk_job_pod(f"p{i}", owner="rs-1") for i in range(4)]
    args = ArbitratorArgs(
        max_migrating_global=10,
        max_migrating_per_namespace=10,
        max_unavailable_per_workload="30%",   # ceil(3) over 10 replicas
    )
    # 2 pods already unavailable → only 1 migration may start
    out = _arbitrate(
        jp,
        args,
        in_flight=0,
        replicas_by_owner={"rs-1": 10},
        unavailable_by_owner={"rs-1": 2},
    )
    assert len(out) == 1


def test_workload_without_replica_info_is_not_blocked():
    """No controller-finder data for the owner: limits are not evaluable
    and must NOT resolve to zero (the reference's nil-ownerRef early
    return) — blocking every owned pod forever would be a livelock."""
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [_mk_job_pod(f"p{i}", owner="unknown-rs") for i in range(3)]
    args = ArbitratorArgs(
        max_migrating_global=10,
        max_migrating_per_namespace=10,
        max_migrating_per_workload="10%",
    )
    assert len(_arbitrate(jp, args, in_flight=0)) == 3


def test_sort_order_be_and_low_band_first():
    """Eviction order: lowest priority band first, BE before LS within a
    band (arbitrator sort), so the cheapest workloads migrate first when
    the budget clamps."""
    from koordinator_tpu.descheduler.migration import ArbitratorArgs

    jp = [
        _mk_job_pod("prod", ns="a", prio=9500, qos="LS"),
        _mk_job_pod("mid", ns="b", prio=7500, qos="LS"),
        _mk_job_pod("batch-be", ns="c", prio=5500, qos="BE"),
        _mk_job_pod("batch-ls", ns="d", prio=5500, qos="LS"),
    ]
    args = ArbitratorArgs(max_migrating_global=2, max_migrating_per_namespace=9)
    picked = _arbitrate(jp, args, in_flight=0)
    assert picked == ["c/batch-be", "d/batch-ls"]


# ---------------------------------------------------------------------------
# runtimeproxy: hook-crash + Ignore-policy paths
# (reference pkg/runtimeproxy/dispatcher + config.go:27-43)
# ---------------------------------------------------------------------------


def _reg(name, handler, policy, hooks=None):
    from koordinator_tpu.runtimeproxy import (
        HookServerRegistration,
        RuntimeHookType,
    )

    return HookServerRegistration(
        name=name,
        hook_types=tuple(hooks or (RuntimeHookType.PRE_RUN_POD_SANDBOX,)),
        handler=handler,
        failure_policy=policy,
    )


def test_ignore_policy_swallows_crash_and_continues_chain():
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        RuntimeHookType,
    )

    d = Dispatcher()
    calls = []

    def crashing(hook, req):
        calls.append("crash")
        raise RuntimeError("hook server segfault analog")

    def healthy(hook, req):
        calls.append("healthy")
        return {"ok": True}

    d.register(_reg("crasher", crashing, FailurePolicy.IGNORE))
    d.register(_reg("healthy", healthy, FailurePolicy.FAIL))
    out = d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {"req": 1})
    # the crash was swallowed AND later servers still ran
    assert calls == ["crash", "healthy"]
    assert out == [{"ok": True}]


def test_none_policy_defaults_to_ignore():
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        RuntimeHookType,
        parse_failure_policy,
    )

    assert parse_failure_policy("") is FailurePolicy.NONE
    assert FailurePolicy.NONE.fails_open
    d = Dispatcher()
    d.register(
        _reg(
            "none-crasher",
            lambda h, r: (_ for _ in ()).throw(OSError("conn reset")),
            FailurePolicy.NONE,
        )
    )
    assert d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {}) == []


def test_fail_policy_aborts_with_hook_error_details():
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        HookError,
        RuntimeHookType,
    )

    d = Dispatcher()
    d.register(
        _reg(
            "strict",
            lambda h, r: (_ for _ in ()).throw(ValueError("bad patch")),
            FailurePolicy.FAIL,
        )
    )
    with pytest.raises(HookError) as ei:
        d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {})
    assert ei.value.server == "strict"
    assert ei.value.hook is RuntimeHookType.PRE_RUN_POD_SANDBOX
    assert isinstance(ei.value.cause, ValueError)


def test_fail_policy_crash_skips_later_servers():
    """A Fail-policy abort is an abort: servers later in registration
    order must NOT run (the CRI call is already doomed)."""
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        HookError,
        RuntimeHookType,
    )

    d = Dispatcher()
    calls = []
    d.register(
        _reg(
            "strict",
            lambda h, r: (_ for _ in ()).throw(RuntimeError("boom")),
            FailurePolicy.FAIL,
        )
    )
    d.register(
        _reg("later", lambda h, r: calls.append("later"), FailurePolicy.IGNORE)
    )
    with pytest.raises(HookError):
        d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {})
    assert calls == []


def test_unsubscribed_hook_not_called_even_when_crashing():
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        RuntimeHookType,
    )

    d = Dispatcher()
    d.register(
        _reg(
            "sandbox-only",
            lambda h, r: (_ for _ in ()).throw(RuntimeError("boom")),
            FailurePolicy.FAIL,
            hooks=(RuntimeHookType.PRE_RUN_POD_SANDBOX,),
        )
    )
    # a different lifecycle point never reaches the crashing server
    assert (
        d.dispatch(RuntimeHookType.PRE_CREATE_CONTAINER, {}) == []
    )


def test_reregistration_replaces_policy():
    """Re-registering a server name swaps its policy in place — a config
    reload flipping Fail→Ignore must take effect for the next dispatch."""
    from koordinator_tpu.runtimeproxy import (
        Dispatcher,
        FailurePolicy,
        HookError,
        RuntimeHookType,
    )

    d = Dispatcher()

    def crash(h, r):
        raise RuntimeError("boom")

    d.register(_reg("s", crash, FailurePolicy.FAIL))
    with pytest.raises(HookError):
        d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {})
    d.register(_reg("s", crash, FailurePolicy.IGNORE))
    assert d.dispatch(RuntimeHookType.PRE_RUN_POD_SANDBOX, {}) == []
    assert len(d.servers) == 1
