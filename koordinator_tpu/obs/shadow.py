"""Shadow-policy harness: alternate control policies that can never act.

The ROADMAP's learned-control-plane item requires any candidate policy
to be "always SHADOWED by the deterministic controllers and
decision-logged before it is allowed to act". This module is that
harness: a :class:`ShadowRegistry` attached to a
:class:`obs.decisions.DecisionLedger` holds at most one
:class:`ShadowPolicy` per controller name. Each time the acting
controller records a decision, the shadow is fed a deep COPY of the
SAME input snapshot, its proposal is recorded alongside the acting
decision (``shadow`` annotation on the ledger record,
``shadow_divergence_total{controller}`` on divergence) — and that is
ALL it can do. A shadow has no handle on the controller, receives no
mutable state, and an exception it raises is reported and dropped.
Bit-exactness of the acting decision trace with and without a shadow
attached is a soak assertion (see ``sim/longrun.py``).
"""

from __future__ import annotations

from typing import Dict, Optional

#: sentinel returned by ShadowRegistry.propose when no policy is
#: registered for the controller (distinct from a None proposal, which
#: would be a real — if degenerate — policy output)
NO_PROPOSAL = object()


class ShadowPolicy:
    """Base class for non-acting candidate policies.

    Subclasses implement :meth:`propose`, a PURE function of the
    recorded input snapshot — the same dict the acting controller
    decided from. The returned action dict uses the acting controller's
    action vocabulary so divergence is a plain ``!=``.
    """

    def propose(self, inputs: dict) -> Optional[dict]:
        raise NotImplementedError


class AlwaysDivergeShadow(ShadowPolicy):
    """Trivial always-diverging policy: proposes an action no real
    controller ever emits. Soaks attach it to prove the acting decision
    trace is bit-identical with a maximally-noisy shadow present."""

    def propose(self, inputs: dict) -> dict:
        return {"op": "__shadow_diverge__"}


class MirrorShadow(ShadowPolicy):
    """Replays a pure decide function — proposes exactly what the
    deterministic controller would. Divergence from the acting decision
    is therefore a determinism bug (the live sibling of
    ``tools/decision_replay.py``'s offline check)."""

    def __init__(self, decide):
        self._decide = decide

    def propose(self, inputs: dict) -> dict:
        action, _state = self._decide(inputs)
        return action


class ShadowRegistry:
    """At most one shadow policy per controller name."""

    def __init__(self):
        self._policies: Dict[str, ShadowPolicy] = {}

    def attach(self, controller: str, policy: ShadowPolicy) -> None:
        self._policies[str(controller)] = policy

    def detach(self, controller: str) -> None:
        self._policies.pop(str(controller), None)

    def policies(self) -> Dict[str, ShadowPolicy]:
        return dict(self._policies)

    def propose(self, controller: str, inputs: dict):
        """Proposal for one controller, or NO_PROPOSAL when no policy
        is registered. Exceptions propagate — the LEDGER is the layer
        that contains shadow failures (report_exception + drop), so the
        harness stays honest under test."""
        policy = self._policies.get(str(controller))
        if policy is None:
            return NO_PROPOSAL
        return policy.propose(inputs)
