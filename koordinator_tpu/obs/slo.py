"""Per-shard placement SLOs: targets, burn rate, and the ``/slo`` surface.

The ROADMAP's "serve millions of users" north star needs *objectives*,
not just timers: this module turns the lifecycle layer's per-pod signals
into per-shard SLO state a fleet operator (or the learned-policy reward
function) can read at a glance.

Three objectives per shard, mirroring what the partitioned control plane
can actually violate:

* ``p99_latency`` — p99 of per-pod placement latency (arrival→ack) over
  a rolling sample window must stay under the target;
* ``queue_age``   — the oldest queued pod's wait must stay under the
  target (backlog growth shows here before throughput numbers move);
* ``recovery``    — a takeover's time-to-recover (statehub resync +
  journal replay + re-lower) must stay under the target — the
  availability half of the failover story.

Accounting model: every ``observe_*`` call is one SLI sample, judged
against its target on arrival. Violations count into
``slo_violations_total{shard,slo}`` (long-run rate, survives window
eviction) and into the rolling window that yields the **burn rate** —
the fraction of recent samples violating divided by the objective's
error budget (burn > 1 means the budget is being spent faster than it
accrues; the standard multi-window alerting signal). ``/slo`` serves the
whole evaluation as JSON via the services engine.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple


@dataclass
class SloTarget:
    """One objective: violate when the SLI exceeds ``threshold_s``.
    ``budget`` is the tolerated violation fraction (error budget):
    burn rate = violating fraction of the window / budget."""

    name: str
    threshold_s: float
    budget: float = 0.01
    #: rolling sample window size (samples, not seconds: the control
    #: plane's cadence is cycles, and a cycle count is deterministic
    #: under the sim clock where a wall window is not)
    window: int = 512
    #: optional TIME horizon (tracker-clock units) on top of the count
    #: window (overload-control PR): samples older than this are
    #: excluded from the burn/p99 evaluation. Without it, an objective
    #: that stops receiving samples (e.g. placement latency once a
    #: browning fleet defers everything) freezes at its WORST window
    #: forever — and a burn-driven controller can never observe
    #: recovery. None keeps the pure count-window semantics.
    max_age_s: Optional[float] = None
    #: burn evidence floor: fewer fresh samples than this evaluate to
    #: burn 0 (a couple of stragglers in an otherwise-empty horizon
    #: must not swing a burn-driven controller to its extremes)
    min_samples: int = 1


def default_targets() -> Tuple[SloTarget, ...]:
    """Defaults sized for the latency_stream operating point: one-cycle
    placement at a few ms/cycle, sub-second backlog waits, and the
    ~150 ms warm takeover the recovery bench measures (10x headroom)."""
    return (
        SloTarget("p99_latency", threshold_s=1.0, budget=0.01),
        SloTarget("queue_age", threshold_s=5.0, budget=0.05),
        SloTarget("recovery", threshold_s=2.0, budget=0.10),
    )


@dataclass
class _Series:
    #: (value, violated, observed-at) on the tracker's clock
    samples: Deque[Tuple[float, bool, float]] = field(default_factory=deque)
    violations: int = 0
    total: int = 0
    worst: float = 0.0
    last: float = 0.0


class SloTracker:
    """Thread-safe per-(shard, slo) SLI accounting.

    ``registry`` receives ``slo_violations_total{shard,slo}``; pass the
    fleet registry so violations land in the merged scrape. ``clock``
    defaults to ``time.perf_counter`` — the SAME domain as the stream's
    arrival stamps and the tracer, because queue-age samples are
    DIFFERENCES against those stamps (a wall-clock default would make
    every default-wired queue-age sample ``time() - perf_counter()``,
    i.e. garbage); inject the sim clock for deterministic soaks."""

    def __init__(
        self,
        registry=None,
        targets: Optional[Tuple[SloTarget, ...]] = None,
        clock=time.perf_counter,
    ):
        self.clock = clock
        self.targets: Dict[str, SloTarget] = {
            t.name: t for t in (targets or default_targets())
        }
        self._series: Dict[Tuple[int, str], _Series] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.counter = None
        if registry is not None:
            self.counter = registry.counter(
                "slo_violations_total",
                "SLI samples that violated their per-shard objective",
                labels=("shard", "slo"),
            )

    # ---- sample ingestion ----

    def _observe(self, shard: int, slo: str, value_s: float) -> bool:
        tgt = self.targets.get(slo)
        if tgt is None:
            raise ValueError(f"unknown SLO {slo!r}")
        bad = value_s > tgt.threshold_s
        with self._lock:
            s = self._series.setdefault((int(shard), slo), _Series())
            s.samples.append((value_s, bad, self.clock()))
            while len(s.samples) > tgt.window:
                s.samples.popleft()
            s.total += 1
            s.last = value_s
            s.worst = max(s.worst, value_s)
            if bad:
                s.violations += 1
        if bad and self.counter is not None:
            self.counter.labels(shard=str(shard), slo=slo).inc()
        return bad

    def observe_latency(self, shard: int, seconds: float) -> bool:
        """One pod's placement latency (arrival→ack)."""
        return self._observe(shard, "p99_latency", seconds)

    def observe_queue_age(self, shard: int, seconds: float) -> bool:
        """Age of the OLDEST pod in the shard's queue at pump time."""
        return self._observe(shard, "queue_age", seconds)

    def observe_recovery(self, shard: int, seconds: float) -> bool:
        """One takeover's time-to-recover on the shard."""
        return self._observe(shard, "recovery", seconds)

    # ---- evaluation ----

    @staticmethod
    def _p99(values) -> float:
        if not values:
            return 0.0
        ordered = sorted(values)
        # nearest-rank p99: rank = ceil(0.99 * n), 1-based (no numpy
        # dependency in obs/). int(0.99*n) would be off by one whenever
        # n is a multiple of 100 — index n-1 IS the max, i.e. p100
        rank = -((-99 * len(ordered)) // 100)  # ceil without math
        return ordered[max(0, rank - 1)]

    def _fresh(self, samples, tgt: SloTarget, now: float):
        """The evaluable slice of a window: all of it, or — when the
        objective carries a time horizon — only samples young enough."""
        if tgt.max_age_s is None:
            return list(samples)
        horizon = now - tgt.max_age_s
        return [s for s in samples if s[2] >= horizon]

    def evaluate(self) -> Dict[str, Dict[str, dict]]:
        """Current state per shard per objective: target, window p99,
        last/worst sample, violation count, burn rate, ok flag."""
        now = self.clock()
        with self._lock:
            series = {
                k: (list(s.samples), s.violations, s.total, s.worst, s.last)
                for k, s in self._series.items()
            }
        out: Dict[str, Dict[str, dict]] = {}
        for (shard, slo), (samples, viol, total, worst, last) in sorted(
            series.items()
        ):
            tgt = self.targets[slo]
            samples = self._fresh(samples, tgt, now)
            window_bad = sum(1 for _v, bad, _t in samples if bad)
            frac = (
                window_bad / len(samples)
                if len(samples) >= tgt.min_samples
                else 0.0
            )
            burn = frac / tgt.budget if tgt.budget > 0 else 0.0
            out.setdefault(str(shard), {})[slo] = {
                "target_s": tgt.threshold_s,
                "budget": tgt.budget,
                "window_p99_s": round(
                    self._p99([v for v, _b, _t in samples]), 6
                ),
                "last_s": round(last, 6),
                "worst_s": round(worst, 6),
                "samples": total,
                "violations": viol,
                "burn_rate": round(burn, 4),
                "ok": burn <= 1.0,
            }
        return out

    def burn_rate(self, shard: int, slo: str) -> float:
        """One (shard, objective) burn rate without materializing the
        whole :meth:`evaluate` payload — the topology controller's
        per-tick read (elastic-topology PR): burn > 1 on a shard's
        placement objectives is the scale-out signal."""
        tgt = self.targets.get(slo)
        if tgt is None:
            raise ValueError(f"unknown SLO {slo!r}")
        now = self.clock()
        with self._lock:
            s = self._series.get((int(shard), slo))
            if s is None or not s.samples:
                return 0.0
            samples = self._fresh(s.samples, tgt, now)
            if len(samples) < tgt.min_samples or not samples:
                return 0.0
            frac = sum(1 for _v, bad, _t in samples if bad) / len(samples)
        return frac / tgt.budget if tgt.budget > 0 else 0.0

    def ok(self) -> bool:
        """True while every shard's every objective burns within budget."""
        return all(
            row["ok"]
            for shard in self.evaluate().values()
            for row in shard.values()
        )

    def render(self) -> str:
        ev = self.evaluate()
        return json.dumps(
            {
                "ok": all(
                    row["ok"] for sh in ev.values() for row in sh.values()
                ),
                "targets": {
                    n: {"threshold_s": t.threshold_s, "budget": t.budget}
                    for n, t in sorted(self.targets.items())
                },
                "shards": ev,
            },
            indent=1,
            sort_keys=True,
        )
