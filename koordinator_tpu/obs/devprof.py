"""Solver observatory: device/compiler-level profiling for the solve path.

Five bench rounds of ``stage_ms`` tables end at the dispatch boundary —
"solve" is an opaque residual with no device-op, compile/retrace or
memory attribution. This module is the evidence layer underneath it:

* **Compile/retrace ledger** (:class:`CompileLedger`) — every jitted
  solver entry point (``assign``, ``solve_stream``, ``solve_stream_full``,
  ``scatter_rows``, ``gather_rows``, the ``parallel.sharded`` paths)
  carries a trace-time hook (:func:`tracing`): the hook body runs ONLY
  while JAX is tracing the function, so an installed ledger sees every
  (re)trace with zero steady-state cost — the compiled program contains
  no trace of the hook. Call sites additionally wrap dispatches in
  :meth:`DevProf.watch`, which records the call's host signature (shapes,
  flags, gate-relevant statics); a trace firing inside a watched window
  is attributed to that signature, its wall time is billed as compile
  time (``solver_compiles_total{fn}`` / ``solver_compile_seconds{fn}``),
  and the signature DIFF against the function's previous call names the
  retrace cause (which shape/flag delta triggered it). Served at
  ``/debug/compiles``; the longrun soak asserts steady state is
  retrace-free.

* **Device timeline** (:class:`DevProf` capture window) — an on-demand
  window (``/debug/profile?cycles=N``) during which every watched
  dispatch is FENCED (``jax.block_until_ready``) and recorded as a
  device-lane event stamped with ``cycle_id``/stage, wrapped in a
  ``jax.profiler.TraceAnnotation`` so an external XLA profile aligns by
  the same names. The events merge into the tracer's Chrome trace as a
  dedicated ``device`` lane, so device ops line up under their host
  stage spans. Fencing serializes the dispatch pipeline — that is the
  point of an explicit, bounded capture window (it is never on by
  default).

* **Device-memory census** (:class:`DeviceMemoryCensus`) — per-cycle
  live-buffer accounting for the resident tables
  (``solver_device_bytes{table}``), process live-array totals, and a
  donation-effectiveness check (a donated buffer that survives the
  scatter is a donation MISS: the in-place update silently became a
  copy). :class:`LeakSentinel` turns the totals into the chaos soak's
  leak-detector arm: monotone live-array growth across incarnations
  fails.

Disabled mode is the PR 1/PR 7 standing contract: the scheduler holds
``devprof=None`` and every hot-path site is one attribute-is-None check;
the trace-time hooks cost nothing once compiled.

``jax`` is imported lazily — importing this module (or wiring the hooks
into ``ops.solver``) adds no import-time dependency.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Mapping, Optional, Tuple

# ---------------------------------------------------------------------------
# trace-time hook registry
# ---------------------------------------------------------------------------

#: ledgers currently installed process-wide. Appended by
#: CompileLedger.install(); read by tracing() at JAX trace time. A plain
#: list: mutation is rare (install/uninstall), reads are trace-time only.
_LEDGERS: List["CompileLedger"] = []

_TLS = threading.local()


def tracing(fn_name: str) -> None:
    """Called from INSIDE jitted solver function bodies. Executes only
    while JAX traces the function (a cache miss — first compile or a
    retrace); the compiled program never runs it. No-op (one truthiness
    check on a module global) when no ledger is installed."""
    if not _LEDGERS:
        return
    for led in tuple(_LEDGERS):
        led._note_trace(fn_name)


def _watch_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class _NullWatch:
    """Shared no-op watch for sites whose scheduler has no observatory."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None

    def result(self, _x) -> None:
        return None


NULL_WATCH = _NullWatch()


# ---------------------------------------------------------------------------
# compile/retrace ledger
# ---------------------------------------------------------------------------


class _FnStats:
    __slots__ = ("traces", "calls", "compile_s", "sigs", "last_sig")

    def __init__(self):
        self.traces = 0
        self.calls = 0
        self.compile_s = 0.0
        #: signature key -> call count (a host-side mirror of the jit
        #: cache's keyspace: shapes/dtypes/static flags)
        self.sigs: Dict[Tuple, int] = {}
        self.last_sig: Optional[Dict[str, object]] = None


def _sig_key(sig: Mapping[str, object]) -> Tuple:
    return tuple(sorted((k, repr(v)) for k, v in sig.items()))


def _sig_diff(
    old: Optional[Mapping[str, object]], new: Mapping[str, object]
) -> Dict[str, object]:
    if old is None:
        return {"first_call": True}
    out: Dict[str, object] = {}
    for k in sorted(set(old) | set(new)):
        a, b = old.get(k, "<absent>"), new.get(k, "<absent>")
        if repr(a) != repr(b):
            out[k] = [a, b]
    return out or {"identical_signature": True}


class _Watch:
    """One watched dispatch: signature + wall time + trace attribution.

    ``result()`` registers the dispatch's output; during an armed capture
    window the exit fences it (block_until_ready) and records a
    device-lane event."""

    __slots__ = (
        "dp", "fn", "sig", "cycle", "stage", "kind", "fired",
        "traced_fns", "_t0", "_out", "_ann",
    )

    def __init__(self, dp: "DevProf", fn: str, sig, cycle, stage, kind):
        self.dp = dp
        self.fn = fn
        self.sig = sig
        self.cycle = cycle
        self.stage = stage
        self.kind = kind
        self.fired = False
        self.traced_fns: List[str] = []
        self._out = None
        self._ann = None

    def result(self, x) -> None:
        self._out = x

    def __enter__(self) -> "_Watch":
        _watch_stack().append(self)
        if self.dp._capturing:
            self._ann = self.dp._annotation(self)
            if self._ann is not None:
                self._ann.__enter__()
        self._t0 = self.dp.clock()
        return self

    def __exit__(self, *exc) -> None:
        dp = self.dp
        fenced = False
        if dp._capturing and self._out is not None and exc[0] is None:
            try:
                import jax

                jax.block_until_ready(self._out)
                fenced = True
            except Exception as fence_exc:  # noqa: BLE001 — capture is
                # best-effort: a fencing failure must not become a
                # scheduling failure, but it is never swallowed silently
                from .errors import report_exception

                report_exception("devprof.fence", fence_exc)
        t1 = dp.clock()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        st = _watch_stack()
        if st and st[-1] is self:
            st.pop()
        dp.ledger._observe_call(self, t1 - self._t0)
        if dp._capturing and fenced:
            dp._record_device_event(self, self._t0, t1)
        self._out = None


class CompileLedger:
    """Traces/compiles per jitted solver entry point, per signature.

    One trace == one compile on the solver path (every entry point is a
    top-level jit), so the two counters share a stream. ``install()``
    registers the trace-time hook; symmetric ``uninstall()`` for tests.
    """

    def __init__(self, registry=None, clock=time.perf_counter,
                 max_causes: int = 64):
        self.clock = clock
        self._lock = threading.Lock()
        self._fns: Dict[str, _FnStats] = {}  # guarded-by: self._lock
        #: recent retrace-cause records (which delta triggered each trace)
        self._causes: deque = deque(maxlen=max_causes)  # guarded-by: self._lock
        self._steady_mark: Optional[Dict[str, int]] = None  # guarded-by: self._lock
        self._compiles_counter = None
        self._compile_seconds = None
        if registry is not None:
            self._compiles_counter = registry.counter(
                "solver_compiles_total",
                "jitted solver entry-point (re)traces/compiles",
                labels=("fn",),
            )
            self._compile_seconds = registry.counter(
                "solver_compile_seconds",
                "wall seconds of calls that (re)traced, per entry point "
                "(trace+compile+first execute)",
                labels=("fn",),
            )

    def install(self) -> "CompileLedger":
        if self not in _LEDGERS:
            _LEDGERS.append(self)
        return self

    def uninstall(self) -> None:
        try:
            _LEDGERS.remove(self)
        except ValueError:
            pass

    # -- recording --

    def _note_trace(self, fn: str) -> None:
        """Runs at JAX trace time, on the tracing thread."""
        st = _watch_stack()
        watch = st[-1] if st else None
        with self._lock:
            stats = self._fns.setdefault(fn, _FnStats())
            stats.traces += 1
            cause: Dict[str, object] = {"fn": fn, "t": self.clock()}
            if watch is not None:
                watch.fired = True
                watch.traced_fns.append(fn)
                cause["watched_fn"] = watch.fn
                cause["cycle"] = watch.cycle
                cause["stage"] = watch.stage
                if fn == watch.fn:
                    cause["delta"] = _sig_diff(stats.last_sig, watch.sig)
            else:
                cause["delta"] = {"unwatched": True}
            if self._steady_mark is not None:
                cause["steady_state"] = True
            self._causes.append(cause)
        if self._compiles_counter is not None:
            self._compiles_counter.labels(fn=fn).inc()

    def _observe_call(self, watch: "_Watch", wall_s: float) -> None:
        with self._lock:
            stats = self._fns.setdefault(watch.fn, _FnStats())
            stats.calls += 1
            key = _sig_key(watch.sig)
            stats.sigs[key] = stats.sigs.get(key, 0) + 1
            stats.last_sig = dict(watch.sig)
            if watch.fired:
                stats.compile_s += wall_s
                # the cause record was appended at trace time; bill the
                # wall retroactively (tracing cannot know its own wall)
                for cause in reversed(self._causes):
                    if (
                        cause.get("watched_fn") == watch.fn
                        and "wall_s" not in cause
                    ):
                        cause["wall_s"] = round(wall_s, 6)
                        break
        if watch.fired and self._compile_seconds is not None:
            self._compile_seconds.labels(fn=watch.fn).inc(wall_s)

    # -- steady state --

    def mark_steady(self) -> None:
        """Declare warmup over: traces from here on are RETRACES the
        steady-state contract forbids (longrun assertion)."""
        with self._lock:
            self._steady_mark = {
                fn: s.traces for fn, s in self._fns.items()
            }

    def steady_retraces(self) -> int:
        with self._lock:
            if self._steady_mark is None:
                return 0
            return sum(
                s.traces - self._steady_mark.get(fn, 0)
                for fn, s in self._fns.items()
            )

    def steady_causes(self) -> List[dict]:
        with self._lock:
            return [
                dict(c) for c in self._causes if c.get("steady_state")
            ]

    def total_traces(self) -> int:
        with self._lock:
            return sum(s.traces for s in self._fns.values())

    # -- inspection --

    def report(self) -> Dict[str, object]:
        with self._lock:
            fns = {
                fn: {
                    "traces": s.traces,
                    "compiles": s.traces,
                    "calls": s.calls,
                    "signatures": len(s.sigs),
                    "compile_seconds": round(s.compile_s, 6),
                }
                for fn, s in sorted(self._fns.items())
            }
            causes = [dict(c) for c in self._causes]
            steady = self._steady_mark is not None
        return {
            "functions": fns,
            "recent_causes": causes,
            "steady_marked": steady,
            "steady_retraces": self.steady_retraces(),
        }

    def render(self) -> str:
        return json.dumps(self.report(), indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# device-memory census + leak sentinel
# ---------------------------------------------------------------------------


def _tree_nbytes(tree) -> int:
    """Total device bytes of a pytree of jax arrays (None-tolerant)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def live_summary() -> Tuple[int, int]:
    """(count, bytes) over every live jax array in the process."""
    import jax

    count = 0
    total = 0
    for arr in jax.live_arrays():
        count += 1
        try:
            total += int(arr.nbytes)
        except (RuntimeError, ValueError):
            # an array deleted/donated between enumeration and the read
            continue
    return count, total


def donation_dead(tree) -> bool:
    """True when every array leaf of ``tree`` was consumed by donation
    (the in-place scatter really was in place). A live leaf means XLA
    silently copied instead — the donation-effectiveness check."""
    import jax

    for leaf in jax.tree.leaves(tree):
        is_deleted = getattr(leaf, "is_deleted", None)
        if is_deleted is not None and not is_deleted():
            return False
    return True


class DeviceMemoryCensus:
    """Per-cycle live-buffer accounting for the device-resident tables."""

    def __init__(self, registry=None):
        self.last: Dict[str, int] = {}
        self.last_live: Tuple[int, int] = (0, 0)
        self.donation_checks = 0
        self.donation_misses = 0
        self._bytes_gauge = None
        self._live_arrays_gauge = None
        self._live_bytes_gauge = None
        self._donation_missed = None
        if registry is not None:
            self._bytes_gauge = registry.gauge(
                "solver_device_bytes",
                "live device bytes held by each resident solver table",
                labels=("table",),
            )
            self._live_arrays_gauge = registry.gauge(
                "solver_live_arrays",
                "process-wide live jax array count at last census",
            )
            self._live_bytes_gauge = registry.gauge(
                "solver_live_bytes",
                "process-wide live jax array bytes at last census",
            )
            self._donation_missed = registry.counter(
                "solver_donation_missed_total",
                "donated resident buffers still alive after the scatter "
                "(the in-place update silently became a copy)",
            )

    def sample(self, tables: Mapping[str, object]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for table, tree in tables.items():
            if tree is None:
                continue
            n = _tree_nbytes(tree)
            out[table] = n
            if self._bytes_gauge is not None:
                self._bytes_gauge.set(float(n), table=table)
        self.last = out
        count, total = live_summary()
        self.last_live = (count, total)
        if self._live_arrays_gauge is not None:
            self._live_arrays_gauge.set(float(count))
            self._live_bytes_gauge.set(float(total))
        return out

    def check_donation(self, donated_tree) -> bool:
        """Record one donation-effectiveness observation; returns
        effective (True = the donated input died as promised)."""
        ok = donation_dead(donated_tree)
        self.donation_checks += 1
        if not ok:
            self.donation_misses += 1
            if self._donation_missed is not None:
                self._donation_missed.inc()
        return ok


class LeakSentinel:
    """Monotone live-array growth detector for the chaos soak: one
    sample per incarnation boundary; strictly increasing totals across
    every boundary (beyond ``tolerance_bytes``) is a leak."""

    def __init__(self, tolerance_bytes: int = 1 << 20):
        self.tolerance_bytes = int(tolerance_bytes)
        self.samples: List[Tuple[str, int, int]] = []

    def sample(self, tag: str) -> Tuple[int, int]:
        import gc

        gc.collect()  # drop python-held garbage before counting device refs
        count, total = live_summary()
        self.samples.append((tag, count, total))
        return count, total

    def problems(self, min_samples: int = 3) -> List[str]:
        if len(self.samples) < min_samples:
            return []
        byts = [b for _t, _c, b in self.samples]
        growth = byts[-1] - byts[0]
        monotone = all(b2 > b1 for b1, b2 in zip(byts, byts[1:]))
        if monotone and growth > self.tolerance_bytes:
            return [
                "monotone live-array growth across incarnations: "
                + " -> ".join(
                    f"{t}={b}B" for t, _c, b in self.samples
                )
                + f" (+{growth}B > {self.tolerance_bytes}B tolerance)"
            ]
        return []


# ---------------------------------------------------------------------------
# the observatory handle a scheduler carries
# ---------------------------------------------------------------------------


class DevProf:
    """Per-scheduler solver observatory: ledger + capture window + census.

    Attach with ``BatchScheduler.attach_devprof``; multiple schedulers
    may share one instance (the bench's stage pass attaches the same
    observatory to warmup and measured instances so cold compiles land
    in one ledger)."""

    #: bound on retained device-lane events (a capture window over a
    #: long drain must not grow without bound)
    MAX_DEVICE_EVENTS = 16384

    def __init__(self, registry=None, clock=time.perf_counter):
        self.clock = clock
        self.ledger = CompileLedger(registry=registry, clock=clock)
        self.census = DeviceMemoryCensus(registry=registry)
        # NOT lock-guarded by design: a bounded deque with GIL-atomic
        # appends — the capture hot path must not serialize on the
        # capture-control lock
        self.device_events: deque = deque(maxlen=self.MAX_DEVICE_EVENTS)
        self._capture_remaining = 0  # guarded-by: self._lock
        self._capturing = False  # guarded-by: self._lock
        self._cycle_id = 0  # guarded-by: self._lock
        self._lock = threading.Lock()

    # -- install / watch --

    def install(self) -> "DevProf":
        self.ledger.install()
        return self

    def uninstall(self) -> None:
        self.ledger.uninstall()

    def watch(
        self,
        fn: str,
        cycle: Optional[int] = None,
        stage: str = "solve",
        kind: str = "device-compute",
        **sig,
    ) -> _Watch:
        """Context manager around one jitted dispatch. ``sig`` is the
        host-visible signature (shapes/flags) retraces are attributed
        to; ``kind`` buckets the op for the solve-residual breakdown
        (``device-compute`` vs ``transfer``)."""
        return _Watch(
            self, fn, sig,
            self._cycle_id if cycle is None else cycle,
            stage, kind,
        )

    # -- capture window --

    def capture(self, cycles: int) -> Dict[str, object]:
        """Arm an on-demand capture window: the next ``cycles``
        scheduling cycles run with fenced, device-lane-recorded
        dispatches (``/debug/profile?cycles=N``)."""
        with self._lock:
            self._capture_remaining = max(0, int(cycles))
            if self._capture_remaining == 0:
                self._capturing = False
        return self.status()

    def status(self) -> Dict[str, object]:
        return {
            "capturing": self._capturing,
            "cycles_remaining": self._capture_remaining,
            "device_events": len(self.device_events),
        }

    def cycle_begin(self, cycle_id: int) -> None:
        # the cycle stamp moves WITH the capture arm-check (koordlint
        # guarded-by finding GB001: the write raced a concurrently
        # armed /debug/profile capture outside the lock)
        with self._lock:
            self._cycle_id = int(cycle_id)
            if self._capture_remaining > 0:
                self._capturing = True

    def cycle_end(self, sched=None) -> None:
        with self._lock:
            if self._capturing:
                self._capture_remaining -= 1
                if self._capture_remaining <= 0:
                    self._capturing = False
        if sched is not None:
            self.census.sample(self._resident_tables(sched))

    @staticmethod
    def _resident_tables(sched) -> Dict[str, object]:
        def cached(attr):
            entry = getattr(sched, attr, None)
            return entry[1] if entry is not None else None

        return {
            "nodes": getattr(sched, "_resident_nodes", None),
            "nodes_window": cached("_window_cache"),
            "quota": cached("_quota_dev_cache"),
            "numa": cached("_numa_dev_cache"),
            "devices": cached("_device_dev_cache"),
        }

    def _annotation(self, watch: "_Watch"):
        """A jax.profiler.TraceAnnotation naming this dispatch in any
        concurrently-running XLA profile (same vocabulary as the
        device-lane events). Best-effort: None when unavailable."""
        try:
            import jax

            return jax.profiler.TraceAnnotation(
                f"{watch.fn}:cycle={watch.cycle}:stage={watch.stage}"
            )
        except (ImportError, AttributeError, TypeError):
            return None  # profiler is optional on this backend/jax

    def _record_device_event(
        self, watch: "_Watch", t0: float, t1: float
    ) -> None:
        self.device_events.append(
            {
                "fn": watch.fn,
                "cycle": watch.cycle,
                "stage": watch.stage,
                "kind": watch.kind,
                "t0": t0,
                "t1": t1,
                "compiled": watch.fired,
            }
        )

    # -- chrome-trace merge --

    def chrome_device_events(
        self, epoch: float, pid: int = 1, tid: int = 10_000
    ) -> List[dict]:
        """Device-lane Chrome events, re-based onto ``epoch`` (the
        owning tracer's epoch, same monotonic clock) so device ops line
        up under their host stage spans."""
        events: List[dict] = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": "device"},
            }
        ]
        for ev in list(self.device_events):
            events.append(
                {
                    "name": ev["fn"],
                    "cat": "device",
                    "ph": "X",
                    "ts": round((ev["t0"] - epoch) * 1e6, 3),
                    "dur": round((ev["t1"] - ev["t0"]) * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": {
                        "cycle": ev["cycle"],
                        "stage": ev["stage"],
                        "kind": ev["kind"],
                        "compiled": ev["compiled"],
                    },
                }
            )
        return events

    def extend_chrome(self, doc: Dict[str, object], epoch: float) -> None:
        if self.device_events:
            doc["traceEvents"] = list(doc["traceEvents"]) + (
                self.chrome_device_events(epoch)
            )

    # -- the solve-residual breakdown --

    def breakdown_ms(self) -> Dict[str, object]:
        """Decompose the captured windows' solve residual: compile wall
        (from the ledger) vs fenced device-compute vs transfer, plus the
        device-compute total keyed by watch stage (``stage_ms``) so
        off-hot-path stages — e.g. the candidate-shortlist plan probe's
        ``shortlist`` stage — are visible separately from ``solve``."""
        compute = transfer = 0.0
        stages: Dict[str, float] = {}
        for ev in list(self.device_events):
            dur = (ev["t1"] - ev["t0"]) * 1e3
            if ev["kind"] == "transfer":
                transfer += dur
            else:
                compute += dur
                stages[ev["stage"]] = stages.get(ev["stage"], 0.0) + dur
        compile_s = sum(
            row["compile_seconds"]
            for row in self.ledger.report()["functions"].values()
        )
        return {
            "compile_ms": round(compile_s * 1e3, 3),
            "device_compute_ms": round(compute, 3),
            "transfer_ms": round(transfer, 3),
            "stage_ms": {
                k: round(v, 3) for k, v in sorted(stages.items())
            },
        }

    def render(self) -> str:
        return json.dumps(
            {
                "status": self.status(),
                "breakdown_ms": self.breakdown_ms(),
                "census": {
                    "tables_bytes": self.census.last,
                    "live_arrays": self.census.last_live[0],
                    "live_bytes": self.census.last_live[1],
                    "donation_checks": self.census.donation_checks,
                    "donation_misses": self.census.donation_misses,
                },
            },
            indent=1,
            sort_keys=True,
        )
