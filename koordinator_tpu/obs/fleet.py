"""Fleet aggregation: one scrape, one trace, one health view per fleet.

The partitioned control plane runs one registry/tracer/health instance
per shard runtime; an operator (and the bench harness) wants ONE
``/metrics`` scrape with a ``shard`` label, ONE Chrome trace with a
process lane per shard (handoffs linked by flow arrows), and ONE
``/healthz`` that says which shards this incarnation owns and at what
epoch. This module merges without touching the per-shard instances —
each shard's registry stays its own write path (no cross-shard lock
contention on the hot cycle), aggregation happens at read time.
"""

from __future__ import annotations

import json
from typing import Dict, List, Mapping, Optional, Sequence, Tuple


def expose_with_labels(registry, extra: Mapping[str, str]) -> List[str]:
    """Re-render a registry's text exposition with ``extra`` labels
    injected into every sample line (HELP/TYPE lines pass through;
    dedup happens in :func:`merged_metrics`)."""
    inject = ",".join(
        f'{k}="{v}"' for k, v in sorted(extra.items())
    )
    out: List[str] = []
    for line in registry.expose().splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name_part, _, value = line.rpartition(" ")
        if "{" in name_part:
            head, _, rest = name_part.partition("{")
            out.append(f"{head}{{{inject},{rest} {value}")
        else:
            out.append(f"{name_part}{{{inject}}} {value}")
    return out


def merged_metrics(registries: Mapping[int, object]) -> str:
    """One Prometheus exposition over per-shard registries: every sample
    gains ``shard="<s>"``. Families are emitted METRIC-major — HELP/TYPE
    once (first shard wins; the registries are homogeneous by
    construction), then that family's samples across every shard — so
    each family forms one contiguous group as the exposition format
    requires (a shard-major interleave is rejected by strict parsers)."""
    order: List[str] = []
    meta: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}
    seen_meta: set = set()
    for shard in sorted(registries):
        family = None
        for line in expose_with_labels(
            registries[shard], {"shard": str(shard)}
        ):
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(" ", 3)
                family = parts[2] if len(parts) > 2 else line
            else:
                # headerless sample (foreign registry): group by the
                # bare sample name so it still lands in ONE family
                if family is None:
                    family = line.split("{", 1)[0].split(" ", 1)[0]
            if family not in meta:
                order.append(family)
                meta[family] = []
                samples[family] = []
            if line.startswith("#"):
                key = tuple(line.split(" ", 3)[:3])
                if key not in seen_meta:
                    seen_meta.add(key)
                    meta[family].append(line)
            else:
                samples[family].append(line)
    out: List[str] = []
    for family in order:
        out.extend(meta[family])
        out.extend(samples[family])
    return "\n".join(out) + "\n"


def merge_chrome_traces(
    tracers: Mapping[int, object],
    handoffs: Sequence[Mapping[str, object]] = (),
    pod_flows: Mapping[str, Sequence[Mapping[str, object]]] = (),
) -> Dict[str, object]:
    """One Chrome ``trace_event`` document over per-shard tracers: each
    shard renders as its own PROCESS lane (``pid = shard + 1``, named
    ``shard-<s>``), thread lanes keep their per-shard identity, and each
    entry of ``handoffs`` — dicts with ``shard``, ``t_out``, ``t_in``
    (ABSOLUTE readings on the tracers' shared clock; ``t_in`` None for a
    drain whose successor has not been granted yet), ``from``/``to``
    incarnation names — becomes a linked flow arrow (``ph "s"``→``"f"``)
    from the donor's drain instant to the new owner's takeover on that
    shard's lane, so a pod queue's journey across owners reads as one
    arrow in Perfetto.

    ``pod_flows`` (uid → that pod's lifecycle events, dicts with
    ``stage``/``t``/``shard``) additionally links each INDIVIDUAL pod's
    journey — submit→route→dispatch→ack — as one flow chain across the
    shard lanes it crossed (``ph "s"``/``"t"``/``"f"`` sharing one id
    per pod). Events with no shard (submit) anchor on the pod's first
    shard-scoped lane. Timestamps are lifecycle-clock readings on the
    tracers' shared monotonic clock, re-based like everything else.

    Clock alignment: each tracer exports span ``ts`` relative to its OWN
    construction epoch, so lanes from tracers built at different times
    would drift apart. All lanes (and the handoff stamps) are re-based
    onto ONE fleet epoch — the earliest tracer epoch — which is valid
    because every per-shard tracer in an incarnation reads the same
    underlying monotonic clock."""
    epoch0 = min(
        (float(getattr(tr, "epoch", 0.0)) for tr in tracers.values()),
        default=0.0,
    )
    events: List[dict] = []
    for shard in sorted(tracers):
        pid = int(shard) + 1
        tr = tracers[shard]
        offset_us = (float(getattr(tr, "epoch", 0.0)) - epoch0) * 1e6
        doc = tr.to_chrome_trace()
        for ev in doc["traceEvents"]:
            ev = dict(ev, pid=pid)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev["args"] = {"name": f"shard-{shard}"}
            elif "ts" in ev:
                ev["ts"] = round(ev["ts"] + offset_us, 3)
            events.append(ev)
    for i, hand in enumerate(handoffs):
        shard = int(hand.get("shard", 0))
        pid = shard + 1
        t_out = (float(hand.get("t_out", epoch0)) - epoch0) * 1e6
        raw_in = hand.get("t_in")
        t_in = (
            t_out
            if raw_in is None
            else (float(raw_in) - epoch0) * 1e6
        )
        flow_id = i + 1
        common = {
            "name": "shard-handoff",
            "cat": "handoff",
            "id": flow_id,
            "pid": pid,
            "tid": 0,
        }
        events.append(
            dict(
                common,
                ph="s",
                ts=round(t_out, 3),
                args={"from": hand.get("from", "")},
            )
        )
        events.append(
            dict(
                common,
                ph="f",
                bp="e",
                ts=round(max(t_in, t_out + 1e-3), 3),
                args={"to": hand.get("to", "")},
            )
        )
    # per-pod flow chains (distributed-observability satellite): one
    # linked s→t→…→f arrow per pod across the shard lanes it crossed
    flow_base = len(handoffs) + 1
    for k, (uid, evs) in enumerate(sorted(dict(pod_flows or {}).items())):
        points = _pod_flow_points(evs)
        if len(points) < 2:
            continue
        flow_id = flow_base + k
        last = len(points) - 1
        t_prev = None
        for i, (shard, t, stage) in enumerate(points):
            ts = (float(t) - epoch0) * 1e6
            if t_prev is not None and ts <= t_prev:
                # Perfetto drops zero/negative-duration flow steps
                ts = t_prev + 1e-3
            t_prev = ts
            events.append(
                {
                    "name": "pod-flow",
                    "cat": "pod",
                    "id": flow_id,
                    "pid": int(shard) + 1,
                    "tid": 0,
                    "ph": "s" if i == 0 else ("f" if i == last else "t"),
                    **({"bp": "e"} if i == last else {}),
                    "ts": round(ts, 3),
                    "args": {"uid": uid, "stage": stage},
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: lifecycle stages a pod's flow chain links (submit→route→dispatch→ack;
#: resubmit/handoff ride along so cross-owner journeys stay connected)
_FLOW_STAGES = ("submit", "route", "enqueue", "resubmit", "handoff",
                "dispatch", "ack")


def _pod_flow_points(evs) -> List[tuple]:
    """(shard, t, stage) chain for one pod's flow arrows: flow-relevant
    stages in event order, shardless events anchored on the pod's first
    shard-scoped lane."""
    raw = []
    for ev in evs:
        if isinstance(ev, Mapping):
            stage, t, shard = ev.get("stage"), ev.get("t"), ev.get("shard", -1)
        else:
            stage, t, shard = ev.stage, ev.t, ev.shard
        if stage in _FLOW_STAGES:
            raw.append((int(shard), float(t), stage))
    first_shard = next((s for s, _t, _st in raw if s >= 0), None)
    if first_shard is None:
        return []
    return [
        (s if s >= 0 else first_shard, t, st) for s, t, st in raw
    ]


class FleetServices:
    """HTTP-shaped dispatch over a :class:`ShardedScheduler` incarnation:

      /metrics               — merged per-shard registries, shard label
      /healthz               — ownership/epoch rows per shard (200/503)
      /slo                   — the incarnation's SLO tracker state
      /trace                 — merged Chrome trace, one lane per shard
      /debug/flightrecorder  — every owned shard's recorder (recovered
                               records of dead incarnations included)
      /debug/decisions       — every owned shard's decision ledger
                               (controller inputs → action → state,
                               adopted tails included)
      /debug/pipeline        — per-shard speculation-gate verdicts
                               (forwarded to each runtime's engine)
      /debug/brownout        — the fleet's brownout-ladder state
                               (overload-control PR; one controller,
                               shared across shards)

    Built lazily by ``ShardedScheduler.fleet`` — read-only, no state of
    its own, so it is always consistent with live ownership."""

    def __init__(self, sharded):
        self.sharded = sharded

    # ---- views over live ownership ----

    def _registries(self) -> Dict[int, object]:
        return {
            s: rt.sched.extender.registry
            for s, rt in sorted(self.sharded._runtimes.items())
        }

    def _tracers(self) -> Dict[int, object]:
        return {
            s: rt.sched.extender.tracer
            for s, rt in sorted(self.sharded._runtimes.items())
        }

    def healthz(self) -> Tuple[bool, dict]:
        sh = self.sharded
        rows: Dict[str, dict] = {}
        ok = True
        # ACTIVE shards (ids are sparse once the elastic topology has
        # split/merged — a retired cell has no health to report)
        for s in sh.fabric.shard_map.active_shards():
            owned = sh.owns(s)
            rt = sh.runtime(s)
            row = {
                "owned": owned,
                "epoch": (
                    rt.sched._fence_epoch
                    if (owned and rt is not None)
                    else sh.fabric.fences[s].current()
                ),
                "backlog": sh.backlog(s),
            }
            if owned and rt is not None:
                sub_ok = rt.sched.extender.health.ok()
                row["health_ok"] = sub_ok
                ok = ok and sub_ok
            rows[str(s)] = row
        return ok, {
            "ok": ok,
            "incarnation": sh.name,
            "owned": sh.owned(),
            "shards": rows,
        }

    # ---- dispatch ----

    def dispatch(
        self, method: str, path: str, body: str = ""
    ) -> Tuple[int, str]:
        path, _, query = path.partition("?")
        if path == "/metrics":
            regs = self._registries()
            text = merged_metrics(regs) if regs else "\n"
            lc = self.sharded.lifecycle
            if lc is not None and lc.registry is not None:
                # the lifecycle tracker is incarnation-level and its
                # histogram already labels by shard — append verbatim
                # instead of routing through the shard-label injection
                text += lc.registry.expose()
            return 200, text
        if path == "/healthz":
            ok, doc = self.healthz()
            return (200 if ok else 503), json.dumps(
                doc, indent=1, sort_keys=True
            )
        if path == "/slo":
            slo = self.sharded.slo
            if slo is None:
                return 404, "no SLO tracker wired"
            return 200, slo.render()
        if path == "/trace":
            lc = self.sharded.lifecycle
            tracers = self._tracers()
            doc = merge_chrome_traces(
                tracers,
                self.sharded.handoff_log,
                pod_flows=(lc.flows() if lc is not None else {}),
            )
            # each shard's solver-observatory device lane rides in that
            # shard's process lane, re-based on the same fleet epoch the
            # merge used for the span lanes
            epoch0 = min(
                (
                    float(getattr(tr, "epoch", 0.0))
                    for tr in tracers.values()
                ),
                default=0.0,
            )
            for s, rt in sorted(self.sharded._runtimes.items()):
                dp = getattr(rt.sched, "devprof", None)
                if dp is not None and dp.device_events:
                    doc["traceEvents"] = list(doc["traceEvents"]) + (
                        dp.chrome_device_events(epoch0, pid=int(s) + 1)
                    )
            return 200, json.dumps(doc)
        if path in ("/debug/compiles", "/debug/profile"):
            # forwarded per owned shard (same shape as /debug/pipeline);
            # shards without an observatory report their 404 body
            shards = {}
            fwd = path + (f"?{query}" if query else "")
            for s, rt in sorted(self.sharded._runtimes.items()):
                code, text = rt.sched.extender.services.dispatch(
                    method, fwd, body
                )
                try:
                    shards[str(s)] = json.loads(text)
                except ValueError:
                    shards[str(s)] = {"status": code, "body": text}
            return 200, json.dumps(
                {"incarnation": self.sharded.name, "shards": shards},
                indent=1,
            )
        if path == "/debug/pipeline":
            shards = {
                str(s): json.loads(
                    rt.sched.extender.services.dispatch(
                        "GET", "/debug/pipeline"
                    )[1]
                )
                for s, rt in sorted(self.sharded._runtimes.items())
            }
            return 200, json.dumps(
                {"incarnation": self.sharded.name, "shards": shards},
                indent=1,
            )
        if path == "/debug/brownout":
            bo = self.sharded.brownout
            if bo is None:
                return 404, "no brownout controller wired"
            return 200, bo.render()
        if path == "/topology":
            # elastic-topology PR: the live shard-map generation — the
            # cell tree, the open transition (if a split/merge is in
            # flight), and the journaled transition history tail
            topo = self.sharded.fabric.topology
            m = topo.map
            return 200, json.dumps(
                {
                    "generation": topo.generation,
                    "base_shards": m.base,
                    "active": m.active_shards(),
                    "cells": {
                        "/".join(str(p) for p in path_): int(sid)
                        for path_, sid in sorted(m._cells.items())
                    },
                    "open_transition": topo.open_transition(),
                    "history": topo.history(limit=32),
                },
                indent=1,
                sort_keys=True,
            )
        if path == "/debug/flightrecorder":
            shards = {}
            for s, rt in sorted(self.sharded._runtimes.items()):
                fr = getattr(rt.sched, "flight_recorder", None)
                if fr is not None:
                    shards[str(s)] = json.loads(fr.render())
            return 200, json.dumps(
                {"incarnation": self.sharded.name, "shards": shards},
                indent=1,
            )
        if path == "/debug/decisions":
            shards = {}
            for s, rt in sorted(self.sharded._runtimes.items()):
                dl = getattr(rt.sched, "decision_ledger", None)
                if dl is not None:
                    shards[str(s)] = json.loads(dl.render())
            return 200, json.dumps(
                {"incarnation": self.sharded.name, "shards": shards},
                indent=1,
            )
        return 404, "not found"
