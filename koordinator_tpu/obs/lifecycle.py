"""Fleet-wide pod-lifecycle tracing (distributed-observability tentpole).

PR 1's Span/Tracer answers "where did CYCLE time go" inside one process;
this module answers "where did THIS POD's time go" across the partitioned
control plane: a per-pod trace context threaded from
``StreamScheduler.submit`` through ``ShardRouter`` route/fan-out, the
single-winner claim, queue wait, solve dispatch, commit and bind-ack —
with shard handoffs, crash orphaning and journal-replay recovery recorded
as first-class events, so a pod that crossed three incarnations still has
ONE contiguous timeline.

Two consumers drive the design:

* the ``placement_latency_seconds{shard,stage}`` histogram — the per-pod
  placement-latency SLO signal (arrival→ack end to end, decomposed into
  route/queue/claim/solve/commit), which the SLO layer (:mod:`.slo`) and
  the learned-policy roadmap item both read;
* the gap-free-timeline invariant the multi-shard chaos soak asserts:
  every placed pod's events are time-ordered, start at ``submit``, end at
  ``ack``, and every shard/incarnation transition is bracketed by
  handoff/orphan/recover events (:func:`validate_timeline`).

Crash survival: the tracker itself is in-memory, but the scheduler embeds
each pod's compact context (:meth:`PodLifecycle.context`) into the bind
journal's record, so a takeover's replay can emit a ``recover`` event
carrying the ORIGINAL submit stamp — the timeline bridges the dead
incarnation instead of restarting at the new one.

``lifecycle=None`` stays the default everywhere it is threaded: the
disabled path is one attribute-is-None check, same contract as the
tracer's no-op singleton.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: terminal stages: the pod's placement story is over. ``shed`` is
#: terminal-but-redeemable (overload-control PR): the story ends there
#: unless a driver redeems the resubmit ticket, which re-opens it with
#: a ``resubmit``/``enqueue`` bridge
_TERMINAL = frozenset({"ack", "gone", "shed"})

#: event stages a timeline may contain (validator vocabulary)
STAGES = frozenset(
    {
        "submit",      # arrival at the control plane (the SLO clock start)
        "route",       # ShardRouter picked the pod's primary shard
        "fanout",      # backlog spill: also enqueued on a spill shard
        "enqueue",     # landed in a shard owner's stream queue
        "resubmit",    # re-enqueued from a handoff with original stamps
        "claim",       # won the cross-shard single-winner claim
        "claim_lost",  # lost the claim (another shard schedules it)
        "dispatch",    # fed into a scheduling cycle's batch
        "decide",      # cycle produced a verdict (node or None)
        "handoff",     # surfaced from a donor's queue at shard handoff
        "orphan",      # owner died with the pod queued/in flight
        "shard_split", # re-homed by a live shard split (elastic topology)
        "shard_merge", # re-homed by a live shard merge (elastic topology)
        "recover",     # journal replay restored the acknowledged bind
        "shed",        # overload admission shed the pod (terminal unless
                       # a resubmit ticket is redeemed)
        "ack",         # bind acknowledged / published (terminal)
        "gone",        # pod deleted before placement (terminal)
    }
)

#: stages that DISPLACE a pod from its owner: until a bridge event
#: (resubmit/recover/enqueue) lands, any placement-path progress is a
#: timeline gap — the validator's cross-incarnation/cross-topology arm.
#: ``shed`` rides the same machinery (overload-control PR): placement
#: progress after a shed without a ticket-redemption bridge is a gap
_DISPLACING = frozenset({"orphan", "shard_split", "shard_merge", "shed"})

#: default histogram buckets (seconds): sub-ms in-process pumps up to the
#: multi-cycle waits a leaderless gap produces
LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


@dataclass
class LifecycleEvent:
    """One step of a pod's placement journey."""

    stage: str
    t: float
    #: shard the event happened on (-1 = not shard-scoped, e.g. submit)
    shard: int = -1
    #: free detail: node name on decide/ack, incarnation on orphan, …
    detail: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "stage": self.stage,
            "t": self.t,
            "shard": self.shard,
            "detail": self.detail,
        }


class _ShardBuffer:
    """One shard's event buffer: its own lock, its own uid→events map.
    The hot ``event()`` path touches ONLY this lock — per-shard pump
    threads sharing one tracker no longer serialize on a fleet-wide
    mutex (PR 7 queued follow-on)."""

    __slots__ = ("lock", "events")

    def __init__(self):
        self.lock = threading.Lock()
        #: uid -> [(global seq, event), ...] in this shard's append order
        self.events: Dict[str, List[Tuple[int, LifecycleEvent]]] = {}  # guarded-by: self.lock


class PodLifecycle:
    """Thread-safe per-pod event timeline + placement-latency histogram.

    ``clock`` supplies timestamps when an event's caller has none (the
    sharded soak injects its sim clock so timelines are deterministic);
    callers that DO know the instant (StreamScheduler's arrival stamps)
    pass ``t=`` explicitly so the latency math matches the stream's own.

    ``registry`` (a ``utils.metrics.Registry``) receives
    ``placement_latency_seconds{shard,stage}``; pass the fleet registry
    to fold the histogram into the merged scrape.

    Storage is PER-SHARD buffers merged on read: each shard's events
    append under that shard's own lock, so concurrent per-shard pump
    threads contend only on their own buffer (plus a rare structure
    lock at first sight of a uid and at terminal events). A global
    atomic sequence number (``itertools.count`` — C-level, effectively
    atomic under the GIL) preserves the fleet-wide arrival order a
    single buffer used to give for free: a merged timeline sorts by
    sequence, never by possibly-tied timestamps, so causal order across
    shards (orphan before resubmit at the same sim-clock tick) survives
    the split. Reads (timeline/validate/render) take every buffer lock —
    they are the cold path by design."""

    def __init__(
        self,
        registry=None,
        clock=time.perf_counter,
        max_pods: int = 200_000,
    ):
        self.clock = clock
        #: shard id (-1 = shardless submit lane) -> its buffer
        self._bufs: Dict[int, _ShardBuffer] = {}  # guarded-by: self._lock
        #: every known uid in FIRST-SIGHT order (dict-as-ordered-set);
        #: the max_pods bound is over this registry
        self._uids: Dict[str, None] = {}  # guarded-by: self._lock
        #: completed uids in COMPLETION order (dict-as-ordered-set), so
        #: eviction under the max_pods bound drops the oldest finished
        #: timelines first, deterministically
        self._done: Dict[str, None] = {}  # guarded-by: self._lock
        #: STRUCTURE lock: buffer creation, uid registry, done set,
        #: eviction. Never held while a caller holds a buffer lock
        #: (lock order is always structure → buffer).
        self._lock = threading.Lock()
        self._next_seq = itertools.count(1).__next__
        self.max_pods = max_pods
        #: kept so the fleet scrape can fold this incarnation-level
        #: registry into /metrics verbatim (its samples already carry
        #: their own shard label — no fleet-side injection)
        self.registry = registry
        self.histogram = None
        if registry is not None:
            self.histogram = registry.histogram(
                "placement_latency_seconds",
                "per-pod placement latency, arrival to bind-ack, "
                "decomposed by lifecycle stage (stage=e2e is the whole "
                "journey)",
                labels=("shard", "stage"),
                buckets=LATENCY_BUCKETS,
            )

    # ---- recording ----

    def event(
        self,
        uid: str,
        stage: str,
        shard: int = -1,
        t: Optional[float] = None,
        detail: str = "",
    ) -> None:
        shard = int(shard)
        ev = LifecycleEvent(
            stage=stage,
            t=self.clock() if t is None else t,
            shard=shard,
            detail=detail,
        )
        # first sight of a uid registers it (and maybe evicts) under the
        # STRUCTURE lock — the membership pre-check is GIL-safe and keeps
        # steady-state appends off that lock entirely
        if uid not in self._uids:
            with self._lock:
                if uid not in self._uids:
                    if len(self._uids) >= self.max_pods:
                        self._evict_locked()
                    self._uids[uid] = None
        buf = self._bufs.get(shard)
        if buf is None:
            with self._lock:
                buf = self._bufs.setdefault(shard, _ShardBuffer())
        seq = self._next_seq()
        with buf.lock:
            buf.events.setdefault(uid, []).append((seq, ev))
        # close the register→append race: a concurrent eviction may have
        # purged this uid between the fast-path check and the append,
        # leaving the fresh entry orphaned (in no registry, so no future
        # eviction could ever reclaim it). The racy membership re-check
        # is one GIL-atomic dict read; the slow path re-registers.
        if uid not in self._uids:
            with self._lock:
                if uid not in self._uids:
                    self._uids[uid] = None
        if stage in _TERMINAL:
            with self._lock:
                self._done[uid] = None
        elif uid in self._done:
            # a redeemed shed ticket (or any re-opened story) makes the
            # pod live again: it must leave the completed set so the
            # retention eviction prefers genuinely finished timelines.
            # The membership pre-check keeps the steady path lock-free.
            with self._lock:
                self._done.pop(uid, None)

    def _evict_locked(self) -> None:
        """Bounded retention: drop the oldest COMPLETED timelines first
        (an unbounded tracker would leak for the fleet's lifetime); if
        none are left — a fleet whose churn is dominated by never-placed
        pods, which have no terminal event — fall back to the oldest
        OPEN timelines so the bound still holds. Caller holds the
        structure lock; buffer locks nest inside it (lock order)."""
        victims = list(self._done)[: max(1, self.max_pods // 10)]
        if not victims:
            victims = [
                u for u in self._uids if u not in self._done
            ][: max(1, self.max_pods // 10)]
        victim_set = set(victims)
        for buf in self._bufs.values():
            with buf.lock:
                for old_uid in victim_set:
                    buf.events.pop(old_uid, None)
        for old_uid in victims:
            self._uids.pop(old_uid, None)
            self._done.pop(old_uid, None)

    # stage-specific helpers keep call sites short and the stage names
    # in ONE vocabulary (typos would silently break the validator)

    def submitted(self, uid: str, t: Optional[float] = None) -> None:
        self.event(uid, "submit", t=t)

    def routed(
        self, uid: str, shard: int, t: Optional[float] = None,
        detail: str = "",
    ) -> None:
        self.event(uid, "route", shard=shard, t=t, detail=detail)

    def acked(
        self,
        uid: str,
        shard: int,
        node: str,
        t: Optional[float] = None,
    ) -> Optional[float]:
        """Terminal acknowledgement: record the event AND observe the
        per-stage latency decomposition into the histogram. Returns the
        end-to-end latency (first submit → this ack, on the tracker's
        clock domain) so the caller can feed its SLO sample without
        mixing time domains, or None if the submit was never seen."""
        t = self.clock() if t is None else t
        self.event(uid, "ack", shard=shard, t=t, detail=node)
        self._observe(uid, shard, t)
        evs = self.timeline(uid)
        t0 = next((e.t for e in evs if e.stage == "submit"), None)
        # a redeemed shed ticket re-anchors the SLO clock (overload-
        # control PR): the shed run was terminally accounted by
        # overload_shed_total, so the redeemed run's latency story
        # starts at its bridge (resubmit/enqueue after the last shed) —
        # otherwise every redemption wave re-burns the latency budget
        # for debt the shed metric already paid
        last_shed = None
        for i, e in enumerate(evs):
            if e.stage == "shed":
                last_shed = i
        if last_shed is not None:
            t0 = next(
                (
                    e.t
                    for e in evs[last_shed + 1:]
                    if e.stage in ("resubmit", "enqueue", "submit")
                ),
                t0,
            )
        return None if t0 is None else max(0.0, t - t0)

    def seen(self, uid: str) -> bool:
        with self._lock:
            return uid in self._uids

    # ---- the histogram decomposition ----

    def _observe(self, uid: str, shard: int, t_ack: float) -> None:
        if self.histogram is None:
            return
        evs = self.timeline(uid)
        last: Dict[str, float] = {}
        first_submit: Optional[float] = None
        for ev in evs:
            last[ev.stage] = ev.t
            if first_submit is None and ev.stage == "submit":
                first_submit = ev.t
        if first_submit is None:
            return
        sh = str(shard)
        obs = self.histogram.observe
        obs(max(0.0, t_ack - first_submit), shard=sh, stage="e2e")
        # stage spans from LAST occurrences (retries/handoffs re-enter
        # earlier stages; the final successful pass is what the SLO sees)
        enq = last.get("enqueue", last.get("resubmit"))
        if enq is not None:
            obs(max(0.0, enq - first_submit), shard=sh, stage="route")
        claim = last.get("claim")
        disp = last.get("dispatch")
        # unsharded streams have no claim gate: queue wait then runs
        # enqueue→dispatch instead of enqueue→claim
        qref = claim if claim is not None else disp
        if qref is not None and enq is not None:
            obs(max(0.0, qref - enq), shard=sh, stage="queue")
        if disp is not None and claim is not None:
            obs(max(0.0, disp - claim), shard=sh, stage="claim")
        dec = last.get("decide", last.get("recover"))
        if dec is not None and disp is not None:
            obs(max(0.0, dec - disp), shard=sh, stage="solve")
        if dec is not None:
            obs(max(0.0, t_ack - dec), shard=sh, stage="commit")

    # ---- journal context (crash survival) ----

    def context(self, uid: str) -> Optional[Dict[str, object]]:
        """Compact context the scheduler embeds in the pod's bind-journal
        record: the ORIGINAL submit stamp and the shard-hop count. A
        takeover's replay hands it back to :meth:`recovered` so the
        bridged timeline keeps the true arrival time."""
        evs = self.timeline(uid)
        if not evs:
            return None
        t0 = next((e.t for e in evs if e.stage == "submit"), evs[0].t)
        hops = len({e.shard for e in evs if e.shard >= 0})
        return {"t0": t0, "hops": hops}

    def recovered(
        self,
        uid: str,
        shard: int,
        node: str,
        ctx: Optional[Dict[str, object]] = None,
        t: Optional[float] = None,
    ) -> None:
        """Journal replay restored this pod's acknowledged bind on a new
        incarnation. If the tracker never saw the pod submit (a genuinely
        fresh process), the journaled context re-seeds the timeline."""
        with self._lock:
            fresh = uid not in self._uids
            done = uid in self._done
        if done:
            return  # already terminal: replay of an old bind, no gap
        if fresh and ctx and "t0" in ctx:
            self.event(uid, "submit", t=float(ctx["t0"]))
        self.event(uid, "recover", shard=shard, t=t, detail=node)

    def is_done(self, uid: str) -> bool:
        with self._lock:
            return uid in self._done

    # ---- inspection ----

    def timeline(self, uid: str) -> List[LifecycleEvent]:
        """The pod's merged timeline: per-shard buffers joined and
        ordered by the global arrival sequence (true fleet-wide append
        order, not possibly-tied timestamps)."""
        with self._lock:
            bufs = list(self._bufs.values())
        merged: List[Tuple[int, LifecycleEvent]] = []
        for buf in bufs:
            with buf.lock:
                merged.extend(buf.events.get(uid, ()))
        merged.sort(key=lambda pair: pair[0])
        return [ev for _seq, ev in merged]

    def uids(self) -> List[str]:
        with self._lock:
            return list(self._uids)

    def flows(self, max_pods: int = 256) -> Dict[str, List[dict]]:
        """Per-pod flow-arrow feed for the merged Chrome trace
        (``obs.fleet.merge_chrome_traces(pod_flows=...)``): the most
        recently COMPLETED ``max_pods`` pods' timelines as event dicts."""
        with self._lock:
            done = list(self._done)[-max_pods:]
        return {
            uid: [e.to_dict() for e in self.timeline(uid)]
            for uid in done
        }

    def render(self, uid: str) -> str:
        return json.dumps(
            [e.to_dict() for e in self.timeline(uid)], indent=1
        )


def validate_timeline(
    events: Sequence[LifecycleEvent], require_terminal: bool = True
) -> List[str]:
    """Gap-free-timeline check (the chaos-soak invariant). Returns a
    list of problems (empty = valid):

    * non-empty, first event is ``submit``, timestamps non-decreasing;
    * every stage is in the known vocabulary;
    * ``dispatch`` only after the pod entered a queue (enqueue/resubmit)
      — a dispatch with no enqueue means a shard fed a pod it never
      admitted;
    * ``ack`` only after a ``decide``/``recover`` produced a node — an
      ack out of nowhere means the driver observed a bind the control
      plane never decided (the lost-ack gap);
    * after a DISPLACING event — ``orphan`` (owner died) or a topology
      bracket (``shard_split``/``shard_merge``: the pod's range moved
      under it) — the next placement-path event must be
      ``resubmit``/``recover``/``enqueue``: the bridge across the dead
      incarnation or the retired cell. The multi-shard soak fails on a
      gap across a split exactly here;
    * ``shed`` (overload-control PR) is terminal-but-redeemable: it may
      END the timeline, or be bridged by ``resubmit``/``enqueue`` (a
      redeemed resubmit ticket) — placement progress straight after a
      shed is a gap, and a shed AFTER the bind was acknowledged means an
      admission path dropped a pod the cluster already placed;
    * terminal: ends at ``ack``/``gone``/``shed`` when
      ``require_terminal``.
    """
    problems: List[str] = []
    if not events:
        return ["empty timeline"]
    if events[0].stage != "submit":
        problems.append(f"starts at {events[0].stage!r}, not submit")
    t_prev = events[0].t
    queued = False
    decided = False
    acked = False
    displaced = ""   # the displacing stage name, "" when bridged
    for i, ev in enumerate(events):
        if ev.stage not in STAGES:
            problems.append(f"[{i}] unknown stage {ev.stage!r}")
            continue
        if ev.t < t_prev - 1e-9:
            problems.append(
                f"[{i}] time went backwards: {ev.t} < {t_prev} "
                f"at {ev.stage}"
            )
        t_prev = max(t_prev, ev.t)
        if ev.stage in ("enqueue", "resubmit"):
            queued = True
            if displaced and ev.stage == "enqueue":
                displaced = ""  # driver re-routed the displaced pod
        if ev.stage in ("decide", "recover"):
            decided = True
        if ev.stage == "dispatch" and not queued:
            problems.append(f"[{i}] dispatch before any enqueue")
        if ev.stage == "ack" and not decided:
            problems.append(f"[{i}] ack without a decide/recover")
        if ev.stage == "ack":
            acked = True
        if ev.stage == "shed" and acked:
            problems.append(
                f"[{i}] shed after the bind was acknowledged — an "
                "admission path dropped an already-placed pod"
            )
        if displaced and ev.stage in ("dispatch", "decide", "ack"):
            problems.append(
                f"[{i}] {ev.stage} after {displaced} without "
                "resubmit/recover/enqueue bridge"
            )
        if ev.stage in _DISPLACING:
            displaced = ev.stage
            queued = False
        if ev.stage in ("resubmit", "recover"):
            displaced = ""
    if require_terminal and events[-1].stage not in _TERMINAL:
        problems.append(f"ends at {events[-1].stage!r}, not terminal")
    return problems
