"""Per-subsystem health aggregation behind the ``/healthz`` endpoint.

Each hardened subsystem (solver fallback ladder, cycle deadline, commit
journal, snapshot channel, informers, koordlet ticks) reports its
degraded/ok state here; the services engine serves the aggregate as
``/healthz`` — 200 when every subsystem is ok, 503 with the per-subsystem
detail when anything is degraded. Degraded is a *state*, not an event:
a subsystem sets it when it enters a fallback and clears it when the
recovery path re-promotes (so a scraper sees the current truth, not a
counter it has to rate()).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional


class HealthRegistry:
    """Thread-safe subsystem → (ok, detail, since) map."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._state: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def set(self, subsystem: str, ok: bool, detail: str = "") -> None:
        with self._lock:
            cur = self._state.get(subsystem)
            if cur is not None and cur["ok"] == ok and cur["detail"] == detail:
                return  # unchanged: keep the original transition time
            self._state[subsystem] = {
                "ok": bool(ok),
                "detail": detail,
                "since": self._clock(),
            }

    def get(self, subsystem: str) -> Optional[dict]:
        with self._lock:
            st = self._state.get(subsystem)
            return dict(st) if st is not None else None

    def ok(self) -> bool:
        with self._lock:
            return all(s["ok"] for s in self._state.values())

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._state.items()}

    def render(self) -> str:
        snap = self.snapshot()
        return json.dumps(
            {
                "ok": all(s["ok"] for s in snap.values()),
                "subsystems": snap,
            },
            indent=1,
            sort_keys=True,
        )
