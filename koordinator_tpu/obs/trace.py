"""Span/Tracer with ring-buffer retention and Chrome trace export.

The model is deliberately small: a :class:`Tracer` owns a bounded ring of
finished :class:`Span` records; ``tracer.span(name)`` is a context manager
that stamps monotonic start/duration and the per-thread nesting depth.
When the tracer is disabled, ``span()`` returns a shared no-op singleton —
no allocation, no lock, no ring write — so instrumentation can stay wired
in hot paths permanently (the disabled-mode guard is one attribute read).

Export is Chrome ``trace_event`` JSON ("X" complete events, microsecond
timestamps relative to the tracer's epoch), loadable in Perfetto or
chrome://tracing. Nesting renders from time containment per thread lane,
so no parent pointers are stored.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    """One finished span. ``t0``/``dur`` are seconds on the tracer's
    monotonic clock (``t0`` relative to the tracer epoch); ``depth`` is
    the per-thread nesting level at entry (0 = top-level)."""

    name: str
    t0: float
    dur: float
    cat: str = ""
    tid: int = 0
    depth: int = 0
    args: Dict[str, object] = field(default_factory=dict)


class _NullSpan:
    """Shared no-op span: context manager + arg sink. A single module
    instance serves every disabled-tracer call site."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **args) -> None:
        return None


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """In-flight span handle; appends a finished :class:`Span` to the
    tracer ring on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_depth", "_tid")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args) -> None:
        self.args.update(args)

    def __enter__(self) -> "_LiveSpan":
        tr = self._tracer
        local = tr._local
        self._depth = getattr(local, "depth", 0)
        local.depth = self._depth + 1
        self._tid = threading.get_ident()
        self._t0 = tr.clock()
        return self

    def __exit__(self, *exc) -> None:
        tr = self._tracer
        t1 = tr.clock()
        tr._local.depth = self._depth
        tr._append(
            Span(
                name=self.name,
                t0=self._t0 - tr.epoch,
                dur=t1 - self._t0,
                cat=self.cat,
                tid=self._tid,
                depth=self._depth,
                args=self.args,
            )
        )


class StageTimer:
    """Times one stage into BOTH a span and a metrics histogram.

    The histogram is observed unconditionally — metric continuity must
    not depend on whether tracing is sampled on — while the span follows
    the tracer's enabled state. Timing reads the tracer's injectable
    monotonic ``clock`` (the default is ``time.perf_counter``), so tests
    can pin stage durations with a fake clock instead of asserting
    against contention-sensitive wall time. ``last_dur`` holds the most
    recent stage duration for per-cycle consumers (flight recorder)."""

    __slots__ = ("_tracer", "_span", "_histogram", "_labels", "_t0",
                 "last_dur")

    def __init__(self, tracer: "Tracer", name: str, histogram=None,
                 cat: str = "", labels: Optional[Dict[str, str]] = None,
                 **args):
        self._tracer = tracer
        self._span = tracer.span(name, cat=cat, **args)
        self._histogram = histogram
        self._labels = labels or {}
        self.last_dur = 0.0

    def set(self, **args) -> None:
        self._span.set(**args)

    def __enter__(self) -> "StageTimer":
        self._t0 = self._tracer.clock()
        self._span.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._span.__exit__(*exc)
        self.last_dur = self._tracer.clock() - self._t0
        if self._histogram is not None:
            self._histogram.observe(self.last_dur, **self._labels)


class StageSequence:
    """Contiguous stage spans: ``enter(name)`` closes the previous stage
    and opens the next, so a cycle's stages tile its wall time (the
    ≥95%-coverage property the trace endpoint promises). Each stage also
    observes ``histogram`` with a ``stage`` label when one is given, and
    accumulates into ``totals`` (stage → seconds for THIS sequence) so a
    per-cycle consumer — the flight recorder — gets the cycle's own
    stage breakdown without scraping the cumulative histogram."""

    __slots__ = ("_tracer", "_histogram", "_cat", "_args", "_cur",
                 "_cur_name", "totals")

    def __init__(self, tracer: "Tracer", histogram=None, cat: str = "", **args):
        self._tracer = tracer
        self._histogram = histogram
        self._cat = cat
        self._args = args
        self._cur: Optional[StageTimer] = None
        self._cur_name: Optional[str] = None
        self.totals: Dict[str, float] = {}

    def enter(self, name: str) -> None:
        self.close()
        st = StageTimer(
            self._tracer,
            name,
            histogram=self._histogram,
            cat=self._cat,
            labels={"stage": name} if self._histogram is not None else None,
            **self._args,
        )
        st.__enter__()
        self._cur = st
        self._cur_name = name

    def set(self, **args) -> None:
        if self._cur is not None:
            self._cur.set(**args)

    def close(self) -> None:
        if self._cur is not None:
            self._cur.__exit__(None, None, None)
            self.totals[self._cur_name] = (
                self.totals.get(self._cur_name, 0.0) + self._cur.last_dur
            )
            self._cur = None
            self._cur_name = None


class Tracer:
    """Thread-safe span collector with bounded retention.

    ``enabled`` toggles sampling at runtime (the services engine's POST
    /trace flips it); the ring keeps the most recent ``capacity``
    finished spans. ``clock`` is the monotonic time source every span
    and :class:`StageTimer` reads (default ``time.perf_counter``;
    inject a fake for deterministic stage timing in tests). The epoch is
    the tracer's construction instant on that clock — every exported
    timestamp is relative to it.
    """

    def __init__(
        self,
        enabled: bool = False,
        capacity: int = 65536,
        clock=time.perf_counter,
    ):
        self.enabled = enabled
        self.clock = clock
        self.epoch = clock()
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._local = threading.local()

    def set_clock(self, clock) -> None:
        """Swap the time source (tests): re-anchors the epoch so exported
        timestamps stay non-negative, and clears spans recorded on the
        old clock — mixed-domain durations are meaningless."""
        self.clock = clock
        self.epoch = clock()
        self.clear()

    # -- recording --

    def span(self, name: str, cat: str = "", **args):
        """Context manager for one span; the no-op singleton when
        sampling is off (zero allocation on the disabled path when no
        kwargs are passed)."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, cat, args)

    def stage(self, name: str, histogram=None, cat: str = "", **args) -> StageTimer:
        """A :class:`StageTimer` feeding both this tracer and
        ``histogram`` (any object with ``observe(seconds)``)."""
        return StageTimer(self, name, histogram=histogram, cat=cat, **args)

    def _append(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- inspection / export --

    def records(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def stage_totals(self, max_depth: Optional[int] = None) -> Dict[str, float]:
        """Total seconds per span name (optionally only spans at or above
        ``max_depth`` nesting). Nested same-name spans double-count by
        design — filter by depth for exclusive totals."""
        totals: Dict[str, float] = {}
        for s in self.records():
            if max_depth is not None and s.depth > max_depth:
                continue
            totals[s.name] = totals.get(s.name, 0.0) + s.dur
        return totals

    def to_chrome_trace(self) -> Dict[str, object]:
        """Chrome ``trace_event`` JSON object (Perfetto /
        chrome://tracing compatible): "X" complete events in µs, one
        lane per recording thread."""
        events: List[Dict[str, object]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "koordinator-tpu"},
            }
        ]
        tids: Dict[int, int] = {}
        for s in self.records():
            lane = tids.setdefault(s.tid, len(tids) + 1)
            events.append(
                {
                    "name": s.name,
                    "cat": s.cat or "default",
                    "ph": "X",
                    "ts": round(s.t0 * 1e6, 3),
                    "dur": round(s.dur * 1e6, 3),
                    "pid": 1,
                    "tid": lane,
                    "args": dict(s.args, depth=s.depth),
                }
            )
        for tid, lane in tids.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": lane,
                    "args": {"name": f"thread-{tid}"},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_json(self) -> str:
        return json.dumps(self.to_chrome_trace())


#: shared always-disabled tracer for call sites with no tracer wired
NULL_TRACER = Tracer(enabled=False, capacity=1)
