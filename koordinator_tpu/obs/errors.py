"""Exception accounting: every swallowed exception is logged and counted.

The package had seven ``except Exception:`` sites that degraded silently
— correct policy (a collector failure must not kill the QoS loop), wrong
observability (nobody could see the failure rate). This module gives
them one shared discipline: :func:`report_exception` logs through the
``koordinator_tpu`` logger and increments ``exceptions_total{site}`` on
the caller's component registry (scheduler/koordlet) or, for call sites
with no registry wired, on a process-wide default registry exposed via
:func:`default_error_registry`.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..utils.metrics import Registry

_log = logging.getLogger("koordinator_tpu")

#: fallback registry for call sites without a component registry
_DEFAULT = Registry(namespace="koordinator")


def ensure_exceptions_counter(reg: Registry):
    """Get-or-create the ``exceptions_total{site}`` counter on ``reg``."""
    c = reg.get("exceptions_total")
    if c is None:
        c = reg.counter(
            "exceptions_total",
            "exceptions caught and degraded (not swallowed silently)",
            labels=("site",),
        )
    return c


def default_error_registry() -> Registry:
    return _DEFAULT


def report_exception(
    site: str, exc: BaseException, registry: Optional[Registry] = None
) -> None:
    """Log ``exc`` at WARNING with its site and count it into
    ``exceptions_total{site}`` — the mandatory companion of every
    degrade-don't-crash ``except`` in the package."""
    _log.warning("exception at %s: %r", site, exc)
    ensure_exceptions_counter(registry if registry is not None else _DEFAULT).labels(
        site=site
    ).inc()


def exception_count(site: str, registry: Optional[Registry] = None) -> float:
    """Test/diagnostic helper: current count for ``site``."""
    reg = registry if registry is not None else _DEFAULT
    c = reg.get("exceptions_total")
    return 0.0 if c is None else c.value(site=site)
