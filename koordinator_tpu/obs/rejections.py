"""Rejection-reason taxonomy and machine-readable rejection records.

Every pod a scheduling cycle fails to place gets one record naming the
*stage* that killed it (which phase of the decision path), the *plugin*
(which policy inside the stage) and a *reason* from a closed enum — the
per-decision attribution Gavel/Synergy-style tuning needs, and what the
reference only exposes as free-text ``FitError`` messages.

The log is a bounded ring (same retention shape as the error dispatcher's
failure log) plus a ``rejections_total`` Prometheus counter labeled
``stage, plugin, reason`` so rates survive ring eviction.
"""

from __future__ import annotations

import enum
import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional


class RejectStage(str, enum.Enum):
    """Where in the decision path the pod was rejected."""

    TRANSFORM = "transform"      # BeforePreFilter pod-transformer drop
    GATE = "gate"                # PreEnqueue / gang gating
    PREFILTER = "prefilter"      # reservation affinity pre-match
    FILTER = "filter"            # boolean-mask construction (solver masks)
    QUOTA = "quota"              # elastic-quota admission
    GANG = "gang"                # in-solver gang min-member enforcement
    SOLVE = "solve"              # feasible but lost the capacity rounds
    RESERVE = "reserve"          # host-side Reserve revalidation
    PERMIT = "permit"            # gang all-or-nothing permit rollback


class RejectReason(str, enum.Enum):
    POD_TRANSFORMER_DROPPED = "pod_transformer_dropped"
    GANG_NOT_READY = "gang_not_ready"
    RESERVATION_UNAVAILABLE = "reservation_unavailable"
    NO_MATCHING_NODE = "no_matching_node"
    INSUFFICIENT_RESOURCES = "insufficient_resources"
    USAGE_EXCEEDS_THRESHOLD = "usage_exceeds_threshold"
    QUOTA_EXHAUSTED = "quota_exhausted"
    GANG_INCOMPLETE = "gang_incomplete"
    NO_FEASIBLE_NODE = "no_feasible_node"
    NODE_CAPACITY_REVALIDATION = "node_capacity_revalidation_failed"
    NUMA_ALLOCATION_FAILED = "numa_allocation_failed"
    DEVICE_ALLOCATION_FAILED = "device_allocation_failed"
    NODE_VANISHED = "node_vanished"
    #: robustness hardening (fault-injection PR): non-finite request /
    #: estimate rows quarantined before they can poison the cost tensors
    NUMERIC_INVALID = "nan_inf_quarantined"
    #: the solver-result feeder queue stalled past its fetch deadline —
    #: the chunk's pods re-enter the next cycle instead of wedging it
    SOLVE_RESULT_STALLED = "solve_result_stalled"
    #: the per-cycle deadline expired with chunks left; the remainder is
    #: deferred and the batch degrades for the next cycle
    CYCLE_DEADLINE_EXCEEDED = "cycle_deadline_exceeded"
    #: a mid-commit failure rolled the chunk's Reserve journal back —
    #: every half-assumed pod was forgotten and retries next cycle
    COMMIT_ROLLED_BACK = "commit_rolled_back"
    #: HA fencing (failover PR): the committing scheduler's leadership
    #: epoch is no longer current — a deposed leader's in-flight commit
    #: (including pipelined speculative dispatches) is rejected instead
    #: of double-placing; the pods retry under the new leader
    STALE_LEADER_EPOCH = "stale_leader_epoch"
    #: the write-ahead bind journal could not append the chunk's intent/
    #: bind record — journal-before-mutate means the chunk is rejected
    #: un-mutated and retries once the journal recovers
    JOURNAL_WRITE_FAILED = "journal_write_failed"
    #: QoS-aware overload control (brownout PR): a BATCH/FREE pod shed at
    #: the admission boundary — its band's queue budget and age limit
    #: were both exceeded (or the brownout ladder reached its shed
    #: level). Terminal ``shed`` lifecycle event + resubmit ticket; the
    #: pod never reaches a solve
    OVERLOAD_SHED = "overload_shed"
    #: gray-failure containment PR: the pod is blamed on the poison
    #: quarantine ledger (its lowering deterministically crashed a cycle
    #: and bisection isolated it) — rejected at the cycle gate and shed
    #: with a REDEEMABLE ticket: a changed spec fingerprint lifts the
    #: blame and re-admits through the ordinary path
    POISON_QUARANTINED = "poison_quarantined"


@dataclass
class RejectionRecord:
    cycle_id: int
    pod: str
    uid: str
    stage: str
    plugin: str
    reason: str
    detail: str = ""
    ts: float = 0.0

    def to_dict(self) -> Dict[str, object]:
        return {
            "cycle": self.cycle_id,
            "pod": self.pod,
            "uid": self.uid,
            "stage": self.stage,
            "plugin": self.plugin,
            "reason": self.reason,
            "detail": self.detail,
            "ts": self.ts,
        }


class RejectionLog:
    """Bounded rejection-record ring + labeled counter.

    ``counter`` is an optional ``utils.metrics.Counter`` with label names
    ``(stage, plugin, reason)``; records always land in the ring, counts
    always land in the counter, so ``/debug/rejections`` gives the recent
    *who* and ``/metrics`` the long-run *how often*."""

    def __init__(self, counter=None, capacity: int = 4096):
        self.counter = counter
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def record(
        self,
        cycle_id: int,
        pod,
        stage: RejectStage,
        plugin: str,
        reason: RejectReason,
        detail: str = "",
    ) -> None:
        rec = RejectionRecord(
            cycle_id=cycle_id,
            pod=pod.meta.name,
            uid=pod.meta.uid,
            stage=str(stage.value),
            plugin=plugin,
            reason=str(reason.value),
            detail=detail,
            ts=time.time(),
        )
        with self._lock:
            self._ring.append(rec)
        if self.counter is not None:
            self.counter.labels(
                stage=rec.stage, plugin=rec.plugin, reason=rec.reason
            ).inc()

    def records(
        self, cycle_id: Optional[int] = None
    ) -> List[RejectionRecord]:
        with self._lock:
            recs = list(self._ring)
        if cycle_id is not None:
            recs = [r for r in recs if r.cycle_id == cycle_id]
        return recs

    def for_uid(self, uid: str) -> List[RejectionRecord]:
        return [r for r in self.records() if r.uid == uid]

    def stage_tally(self) -> Dict[str, int]:
        """stage → record count over the retained ring (feeds the debug
        filter dump's per-stage tally)."""
        tally: Dict[str, int] = {}
        for r in self.records():
            tally[r.stage] = tally.get(r.stage, 0) + 1
        return tally

    def render(self) -> str:
        recs = self.records()
        return json.dumps(
            {
                "tally": self.stage_tally(),
                "records": [r.to_dict() for r in recs],
            },
            indent=1,
        )
