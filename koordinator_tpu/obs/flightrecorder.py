"""Crash-surviving flight recorder: the last N cycles, readable post-mortem.

``/metrics`` answers "how often", ``/trace`` answers "where did time go
while sampling was on" — neither answers "what were the last 200 cycles
of the incarnation that just died DOING". This module is the black box:
an append-only ring of per-cycle summaries (stage_ms, pipeline gate
states, speculation outcome, the adaptive pipeline-depth decision and
its discard-rate input — ``depth``/``depth_max``/``discard_rate``, so
every depth choice is explainable post-hoc and a takeover inherits the
dead writer's churn evidence — fence rejections, queue depth, batch
sizes)
persisted **beside the bind journal** over the same pluggable store API
(``MemoryJournalStore`` in tests/sim, ``FileJournalStore`` for real
durability), so a new incarnation taking over a shard loads the dead
incarnation's tail and serves it at ``/debug/flightrecorder`` — the
post-mortem evidence a crash loop otherwise destroys.

Retention: every record is appended to the store; when the in-memory
ring wraps ``2 * capacity`` appends past the last rewrite, the store is
compacted to the ring's content (same tmp-file/atomic-rename discipline
the journal's checkpoint uses, via ``store.rewrite``). A reader never
sees more than ~2×capacity records, a crash never loses more than the
single in-flight append.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .errors import report_exception


class FlightRecorder:
    """Bounded per-cycle summary ring over a journal-style store.

    ``incarnation`` stamps every record with the writing process's
    identity; records loaded from the store that carry a DIFFERENT
    incarnation are the dead writer's — they stay in the ring (flagged
    ``recovered`` on render) so the takeover can serve them."""

    def __init__(
        self,
        store=None,
        capacity: int = 256,
        shard: Optional[int] = None,
        incarnation: str = "",
        clock=time.time,
    ):
        from ..core.journal import MemoryJournalStore

        self.store = store if store is not None else MemoryJournalStore()
        self.capacity = int(capacity)
        self.shard = shard
        self.incarnation = incarnation
        self.clock = clock
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: self._lock
        self._since_rewrite = 0  # guarded-by: self._lock
        # adopt the predecessor's tail: this IS the crash-survival story
        tail = sorted(self.store.load(), key=lambda r: r.get("seq", 0))
        for rec in tail[-capacity:]:
            self._ring.append(dict(rec))
        self._seq = max((r.get("seq", 0) for r in tail), default=0)

    def record(
        self,
        cycle: int,
        stage_ms: Optional[Dict[str, float]] = None,
        gates: Optional[Dict[str, bool]] = None,
        speculation: str = "",
        fenced: bool = False,
        queue_depth: int = 0,
        bound: int = 0,
        unschedulable: int = 0,
        **extra,
    ) -> dict:
        """Append one cycle summary. Never raises into the scheduling
        path: a storage failure degrades to in-memory-only retention
        (the ring keeps recording; the black box is best-effort durable,
        the journal is the correctness-bearing log)."""
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq,
                "t": self.clock(),
                "cycle": int(cycle),
                "incarnation": self.incarnation,
                "stage_ms": {
                    k: round(float(v), 3)
                    for k, v in (stage_ms or {}).items()
                },
                "gates": dict(gates or {}),
                "speculation": speculation,
                "fenced": bool(fenced),
                "queue_depth": int(queue_depth),
                "bound": int(bound),
                "unschedulable": int(unschedulable),
            }
            if self.shard is not None:
                rec["shard"] = int(self.shard)
            rec.update(extra)
            self._ring.append(rec)
            try:
                self.store.append(rec)
                self._since_rewrite += 1
                if self._since_rewrite >= 2 * self.capacity:
                    self.store.rewrite(list(self._ring))
                    self._since_rewrite = 0
            except Exception as exc:
                # best-effort durability; the ring still has it. Broad
                # on purpose: the docstring promises NEVER to raise
                # into the scheduling path, and a store json-encodes
                # (TypeError on an odd `extra` value, not just OSError)
                report_exception("flightrecorder.store", exc)
            return rec

    # ---- inspection ----

    def last(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            recs = list(self._ring)
        return recs if n is None else recs[-n:]

    def recovered_records(self) -> List[dict]:
        """Records written by a DIFFERENT incarnation (the dead writer's
        tail this recorder adopted from the shared store)."""
        return [
            r
            for r in self.last()
            if r.get("incarnation") != self.incarnation
        ]

    def render(self, n: Optional[int] = None) -> str:
        recs = self.last(n)
        return json.dumps(
            {
                "incarnation": self.incarnation,
                "shard": self.shard,
                "cycles": len(recs),
                "recovered": sum(
                    1
                    for r in recs
                    if r.get("incarnation") != self.incarnation
                ),
                "records": [
                    dict(
                        r,
                        recovered=(
                            r.get("incarnation") != self.incarnation
                        ),
                    )
                    for r in recs
                ],
            },
            indent=1,
        )
