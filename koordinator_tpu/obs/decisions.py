"""Crash-surviving decision ledger: every controller decision, explainable.

The repo runs five hand-tuned feedback controllers over the same
SLO-burn inputs (pipeline depth, brownout ladder, admission verdicts,
solver circuit breaker, topology split/merge). Their decisions used to
be observable only as scattered side effects — a gauge here, a
flight-recorder stamp there. This module is the unified substrate the
learned-control-plane roadmap item needs: every decision is recorded as
a structured, seq-stamped record

    {controller, shard, tick, inputs, action, state}

where ``inputs`` is the COMPLETE evidence the controller read (burn
rates with window ages, discard-rate window, band occupancy, breaker
failure counts, hysteresis counters) and ``state`` is the controller's
post-decision internal state. Because every controller decides purely
FROM its snapshot (no clocks, no randomness), a recorded ledger can be
replayed offline (``tools/decision_replay.py``) and an alternate policy
(:mod:`obs.shadow`) can be diffed live against the acting decision —
fed the same snapshot, never allowed to act.

Persistence mirrors :class:`obs.flightrecorder.FlightRecorder` exactly:
records ride the journal-store API (sealed/screened by the store codec,
so ``store-integrity`` koordlint and ``journal_fsck`` cover them), a
takeover adopts the dead writer's tail, and the store is compacted to
the ring every ``2 * capacity`` appends. ``/debug/decisions`` serves
the ring per shard; ``controller_decisions_total{controller,action}``
counts the stream.
"""

from __future__ import annotations

import copy
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .errors import report_exception
from .shadow import NO_PROPOSAL as _NO_PROPOSAL


def action_label(action) -> str:
    """Short metric-label projection of an action dict.

    Decision actions are dicts; the ``controller_decisions_total``
    counter needs a bounded label vocabulary. Ops and verdicts label as
    their value (``escalate``, ``SHED``); everything else labels as the
    first recognized ``key=value`` pair so depth choices stay bounded by
    the depth range.
    """
    if isinstance(action, dict):
        for key in ("op", "verdict"):
            if key in action:
                return str(action[key])
        for key in ("allow", "depth", "to", "state"):
            if key in action:
                return f"{key}={action[key]}"
        return "other"
    return str(action)


def decision_trace(records) -> List[dict]:
    """Canonical projection of ledger records for bit-exactness checks.

    Drops only the non-decision-bearing annotations: ``t`` (wall time —
    real clocks differ between same-seed runs), ``shadow`` (a shadow
    policy must NEVER perturb the acting trace, so the comparison that
    proves it has to ignore the shadow's own annotation), and ``crc``
    (the store codec's seal — it covers ``t`` and ``shadow``, so it
    inherits their run-to-run variance). Everything else — seq, cseq,
    controller, shard, tick, inputs, action, state, incarnation — must
    be bit-identical for same-seed runs.
    """
    return [
        {k: v for k, v in r.items() if k not in ("t", "shadow", "crc")}
        for r in records
    ]


def controller_gaps(records) -> Dict[str, List[int]]:
    """Per-controller ``cseq`` gaps in a record list; ``{}`` = gap-free.

    Retention only ever drops records at the HEAD of a controller's
    stream (ring eviction / store compaction), and a takeover adopts the
    dead writer's tail and continues its ``cseq`` — so the retained
    records of each controller must form one contiguous run. A hole in
    the middle means lost decisions.
    """
    by_controller: Dict[str, List[int]] = {}
    for rec in records:
        by_controller.setdefault(str(rec.get("controller")), []).append(
            int(rec.get("cseq", 0))
        )
    gaps: Dict[str, List[int]] = {}
    for controller, seqs in by_controller.items():
        unique = set(seqs)
        lo, hi = min(unique), max(unique)
        missing = [s for s in range(lo, hi + 1) if s not in unique]
        if missing or len(unique) != len(seqs):
            gaps[controller] = missing or sorted(seqs)
    return gaps


class DecisionLedger:
    """Bounded controller-decision ring over a journal-style store.

    ``incarnation`` stamps every record with the writing process's
    identity; records adopted from the store under a DIFFERENT
    incarnation are the dead writer's decision tail (flagged
    ``recovered`` on render), and each controller's ``cseq`` continues
    from the adopted maximum so per-controller sequences stay gap-free
    across a takeover.
    """

    def __init__(
        self,
        store=None,
        capacity: int = 512,
        shard: Optional[int] = None,
        incarnation: str = "",
        clock=time.time,
    ):
        from ..core.journal import MemoryJournalStore

        self.store = store if store is not None else MemoryJournalStore()
        self.capacity = int(capacity)
        self.shard = shard
        self.incarnation = incarnation
        self.clock = clock
        #: non-acting alternate policies (obs.shadow.ShadowRegistry);
        #: consulted per record with a deep COPY of the snapshot so a
        #: shadow can never reach the acting controller's evidence
        self.shadow = None
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: self._lock
        self._cseq: Dict[str, int] = {}  # guarded-by: self._lock
        self._since_rewrite = 0  # guarded-by: self._lock
        self._registry = None
        self._decisions_total = None
        self._divergence_total = None
        #: flight recorders mirrored by flight_record() — the ledger is
        #: the controllers' SINGLE attachment point, so takeover
        #: adoption of journaled controller evidence is one code path
        self._flights: List = []
        # adopt the predecessor's tail: this IS the crash-survival story
        tail = sorted(self.store.load(), key=lambda r: r.get("seq", 0))
        for rec in tail[-capacity:]:
            self._ring.append(dict(rec))
        self._seq = max((r.get("seq", 0) for r in tail), default=0)
        for rec in tail:
            c = str(rec.get("controller", ""))
            self._cseq[c] = max(
                self._cseq.get(c, 0), int(rec.get("cseq", 0))
            )

    # ---- wiring ----

    def bind_registry(self, registry) -> None:
        """First caller wins (mirrors BrownoutController.bind_registry):
        the ledger counts decisions into ONE metrics registry even when
        several engines share it."""
        if registry is None or self._registry is not None:
            return
        self._registry = registry
        self._decisions_total = registry.counter(
            "controller_decisions_total",
            "Control-plane decisions recorded on the decision ledger",
            labels=("controller", "action"),
        )
        self._divergence_total = registry.counter(
            "shadow_divergence_total",
            "Shadow-policy proposals that diverged from the acting "
            "controller's decision",
            labels=("controller",),
        )

    def attach_shadow(self, shadow) -> None:
        """Attach a ShadowRegistry. First caller wins."""
        if shadow is not None and self.shadow is None:
            self.shadow = shadow

    def attach_flight(self, recorder) -> None:
        """Attach a FlightRecorder mirrored by :meth:`flight_record`."""
        if recorder is not None and recorder not in self._flights:
            self._flights.append(recorder)

    def flight_record(self, **kw) -> None:
        """Mirror a byte-compatible journal entry to every attached
        flight recorder (the brownout transition stamps ride here so
        the pre-ledger ``/debug/flightrecorder`` fields stay stable)."""
        for fr in self._flights:
            fr.record(**kw)

    # ---- the write path ----

    def record(
        self,
        controller: str,
        tick: int,
        inputs: dict,
        action: dict,
        state: dict,
        shard: Optional[int] = None,
        outcome: Optional[dict] = None,
        **extra,
    ) -> dict:
        """Append one decision. Never raises into the control path: a
        storage failure degrades to in-memory-only retention and a
        shadow failure is reported and dropped (a shadow can NEVER
        perturb the acting controller)."""
        proposal = _NO_PROPOSAL
        sh = self.shadow
        if sh is not None:
            try:
                proposal = sh.propose(
                    controller, copy.deepcopy(inputs)
                )
            except Exception as exc:
                # broad on purpose: shadow policies are candidate code
                # under evaluation; their crash must not reach the
                # acting control path
                report_exception("decisions.shadow", exc)
                proposal = _NO_PROPOSAL
        with self._lock:
            self._seq += 1
            cseq = self._cseq.get(controller, 0) + 1
            self._cseq[controller] = cseq
            rec = {
                "seq": self._seq,
                "cseq": cseq,
                "t": self.clock(),
                "controller": str(controller),
                "tick": int(tick),
                "inputs": inputs,
                "action": action,
                "state": state,
                "incarnation": self.incarnation,
            }
            use_shard = shard if shard is not None else self.shard
            if use_shard is not None:
                rec["shard"] = int(use_shard)
            if outcome is not None:
                rec["outcome"] = outcome
            rec.update(extra)
            if proposal is not _NO_PROPOSAL:
                rec["shadow"] = {
                    "proposal": proposal,
                    "diverged": proposal != action,
                }
            self._ring.append(rec)
            try:
                self.store.append(rec)
                self._since_rewrite += 1
                if self._since_rewrite >= 2 * self.capacity:
                    self.store.rewrite(list(self._ring))
                    self._since_rewrite = 0
            except Exception as exc:
                # best-effort durability; the ring still has it (same
                # contract as the flight recorder)
                report_exception("decisions.store", exc)
        ct = self._decisions_total
        if ct is not None:
            ct.labels(
                controller=str(controller), action=action_label(action)
            ).inc()
        if proposal is not _NO_PROPOSAL and rec["shadow"]["diverged"]:
            dt = self._divergence_total
            if dt is not None:
                dt.labels(controller=str(controller)).inc()
        return rec

    # ---- inspection ----

    def last(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            recs = list(self._ring)
        return recs if n is None else recs[-n:]

    def recovered_records(self) -> List[dict]:
        """Records written by a DIFFERENT incarnation (the dead writer's
        decision tail this ledger adopted from the shared store)."""
        return [
            r
            for r in self.last()
            if r.get("incarnation") != self.incarnation
        ]

    def render(self, n: Optional[int] = None) -> str:
        recs = self.last(n)
        return json.dumps(
            {
                "incarnation": self.incarnation,
                "shard": self.shard,
                "decisions": len(recs),
                "recovered": sum(
                    1
                    for r in recs
                    if r.get("incarnation") != self.incarnation
                ),
                "records": [
                    dict(
                        r,
                        recovered=(
                            r.get("incarnation") != self.incarnation
                        ),
                    )
                    for r in recs
                ],
            },
            indent=1,
        )
