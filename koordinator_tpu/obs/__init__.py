"""Observability: scheduling-cycle tracing and rejection attribution.

A dependency-free tracing subsystem shared by the scheduler, koordlet,
descheduler and the simulators:

* :mod:`trace` — :class:`Span`/:class:`Tracer` (thread-safe, monotonic
  clock, nestable), ring-buffer retention, Chrome ``trace_event`` JSON
  export, and :class:`StageTimer` feeding a span and a
  ``utils.metrics.Histogram`` from one timing.
* :mod:`rejections` — first-class rejection-reason taxonomy
  (:class:`RejectStage`/:class:`RejectReason`) and the
  :class:`RejectionLog` ring buffer + ``rejections_total`` counter the
  scheduler threads from boolean-mask construction through commit
  revalidation.
* :mod:`devprof` — the solver observatory: compile/retrace ledger over
  the jitted solver entry points (``/debug/compiles``), on-demand
  device-timeline capture (``/debug/profile?cycles=N``) merged into the
  Chrome trace, and the device-memory census + leak sentinel.
"""

from .decisions import (
    DecisionLedger,
    action_label,
    controller_gaps,
    decision_trace,
)
from .devprof import (
    CompileLedger,
    DeviceMemoryCensus,
    DevProf,
    LeakSentinel,
)
from .errors import (
    default_error_registry,
    ensure_exceptions_counter,
    report_exception,
)
from .flightrecorder import FlightRecorder
from .health import HealthRegistry
from .lifecycle import LifecycleEvent, PodLifecycle, validate_timeline
from .rejections import (
    RejectionLog,
    RejectionRecord,
    RejectReason,
    RejectStage,
)
from .shadow import (
    NO_PROPOSAL,
    AlwaysDivergeShadow,
    MirrorShadow,
    ShadowPolicy,
    ShadowRegistry,
)
from .slo import SloTarget, SloTracker
from .trace import NULL_TRACER, Span, StageTimer, Tracer

__all__ = [
    "NO_PROPOSAL",
    "NULL_TRACER",
    "AlwaysDivergeShadow",
    "CompileLedger",
    "DecisionLedger",
    "DevProf",
    "DeviceMemoryCensus",
    "FlightRecorder",
    "MirrorShadow",
    "ShadowPolicy",
    "ShadowRegistry",
    "LeakSentinel",
    "HealthRegistry",
    "LifecycleEvent",
    "PodLifecycle",
    "RejectReason",
    "RejectStage",
    "RejectionLog",
    "RejectionRecord",
    "SloTarget",
    "SloTracker",
    "Span",
    "StageTimer",
    "Tracer",
    "action_label",
    "controller_gaps",
    "decision_trace",
    "default_error_registry",
    "ensure_exceptions_counter",
    "report_exception",
    "validate_timeline",
]
