"""Batched assignment solver: masked argmin with capacity-consuming commit.

This is the TPU replacement for the reference scheduler's hot loop — the
per-pod ``scheduleOne`` cycle (upstream kube-scheduler, wrapped by
``pkg/scheduler/frameworkext/framework_extender.go:222-315``) that runs
Filter over nodes with 16-way goroutine chunking and Score over feasible
nodes, then commits one pod at a time via Reserve.

Two solvers share the mask/cost kernels:

* :func:`assign_sequential` — ``lax.scan`` over pods in priority order with a
  fully vectorized inner step over nodes. Bit-exact to the reference's
  sequential Filter→Score→Reserve semantics (the golden contract), O(P)
  scan trips.

* :func:`assign` — the fast path: a small number of *rounds*, each fully
  vectorized over (P, N):
    1. masks   — feasibility (fit + LoadAware usage thresholds) for all
                 still-unassigned pods against current consumed capacity;
    2. costs   — LoadAware least-used weighted score, negated;
    3. argmin  — every pod nominates its best node;
    4. commit  — per-node acceptance in priority order under remaining
                 capacity (segmented prefix sums over pods sorted by node).
  A per-round *acceptance quantum* (fraction of node allocatable per round)
  reproduces the sequential greedy's load-spreading: without it, every pod
  sharing an argmin would pile onto one node before its score ever rose.
  Rejected pods retry next round against the updated state; rounds stop at
  a fixed point (no acceptance ⇒ no future acceptance).

The solver's output is a *nomination* (SURVEY §7 hard part (a)): the host
Reserve step revalidates against live state and returns rejects to the next
batch, preserving k8s semantics.

On hand-written kernels: a Pallas nomination kernel (fused cost + jitter +
streaming top-K over node tiles, flash-attention-style O(P·K) memory) was
built and measured against this module's ``approx_max_k`` path on v5e.
XLA fuses the masked cost directly into ``approx_max_k``'s reduction, so
the [P, N] intermediate never materializes in HBM even at 8192×262144
(a virtual 8 GiB block): the XLA path won at every shape tried
(131k nodes: ~30 vs ~50 ms; 262k: ~60 vs ~150 ms, fetch-excluded). The
kernel was removed rather than shipped as a slower alternative — the
multi-chip ``parallel.sharded.shard_map_nominate`` covers node tables
beyond one chip's HBM with the same O(P·K·tp) communication shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from . import costs as cost_ops
from . import masks as mask_ops
from ..obs import devprof as _devprof
from .masks import EPS


@struct.dataclass
class NodeState:
    """Device-side node block (see core.snapshot.NodeArrays)."""

    allocatable: jnp.ndarray      # [N, D]
    requested: jnp.ndarray        # [N, D]
    estimated_used: jnp.ndarray   # [N, D] usage percentile + assigned-pending
    prod_used: jnp.ndarray        # [N, D]
    metric_fresh: jnp.ndarray     # [N] bool
    schedulable: jnp.ndarray      # [N] bool
    #: CPU amplification ratio per node (reference
    #: ``apis/extension/node_resource_amplification.go``). allocatable is
    #: already amplified (node webhook); exclusive cpuset pods consume
    #: physical CPUs, so their requests count ×ratio against it
    #: (``nodenumaresource/plugin.go:408-443`` filterAmplifiedCPUs).
    cpu_amp: jnp.ndarray = None   # [N]
    #: per-node LoadAware threshold overrides from the usage-thresholds
    #: annotation (0 = plugin-args global; ``apis/extension/load_aware.go``)
    custom_thresholds: jnp.ndarray = None        # [N, D]
    custom_prod_thresholds: jnp.ndarray = None   # [N, D]

    @classmethod
    def create(
        cls,
        allocatable,
        requested=None,
        estimated_used=None,
        prod_used=None,
        metric_fresh=None,
        schedulable=None,
        cpu_amp=None,
        custom_thresholds=None,
        custom_prod_thresholds=None,
    ) -> "NodeState":
        allocatable = jnp.asarray(allocatable, jnp.float32)
        n = allocatable.shape[0]
        z = jnp.zeros_like(allocatable)
        return cls(
            allocatable=allocatable,
            requested=z if requested is None else jnp.asarray(requested, jnp.float32),
            estimated_used=(
                z if estimated_used is None else jnp.asarray(estimated_used, jnp.float32)
            ),
            prod_used=z if prod_used is None else jnp.asarray(prod_used, jnp.float32),
            metric_fresh=(
                jnp.ones(n, bool) if metric_fresh is None else jnp.asarray(metric_fresh)
            ),
            schedulable=(
                jnp.ones(n, bool) if schedulable is None else jnp.asarray(schedulable)
            ),
            cpu_amp=(
                jnp.ones(n, jnp.float32)
                if cpu_amp is None
                else jnp.asarray(cpu_amp, jnp.float32)
            ),
            custom_thresholds=(
                z
                if custom_thresholds is None
                else jnp.asarray(custom_thresholds, jnp.float32)
            ),
            custom_prod_thresholds=(
                z
                if custom_prod_thresholds is None
                else jnp.asarray(custom_prod_thresholds, jnp.float32)
            ),
        )


@struct.dataclass
class PodBatch:
    requests: jnp.ndarray         # [P, D]
    estimate: jnp.ndarray         # [P, D] estimator-scaled usage
    priority: jnp.ndarray         # [P] int32
    is_prod: jnp.ndarray          # [P] bool
    valid: jnp.ndarray            # [P] bool
    gang_id: jnp.ndarray          # [P] int32, -1 = no gang
    #: row g holds minMember of gang g (PodGroup.spec.minMember); rows
    #: beyond the number of gangs are 0. Indexed by gang_id, sized [P].
    gang_min: jnp.ndarray
    #: leaf-to-root quota index path per pod, [P, L] int32, -1 = none
    #: (ElasticQuota tree; level 0 is the leaf)
    quota_chain: jnp.ndarray
    #: koord QoS class (extension.QoSClass), [P] int8 — drives NUMA
    #: alignment need (LSR/LSE) and BE suppression semantics
    qos: jnp.ndarray
    #: whole GPUs requested (nvidia.com/gpu), [P] int32
    gpu_whole: jnp.ndarray
    #: fractional GPU requested in percent of one device
    #: (koordinator.sh/gpu-memory-ratio < 100), [P] float32
    gpu_share: jnp.ndarray
    #: whole RDMA devices requested (koordinator.sh/rdma / 100), [P] int32
    rdma: jnp.ndarray = None
    #: whole FPGAs requested (koordinator.sh/fpga / 100), [P] int32
    fpga: jnp.ndarray = None
    #: row g: True when gang g is NonStrict — its placed members survive
    #: an under-filled gang instead of rolling back (AnnotationGangMode,
    #: reference apis/extension/coscheduling.go:40-53). Indexed by
    #: gang_id like gang_min, sized [P].
    gang_nonstrict: jnp.ndarray = None
    #: pod requires single-NUMA placement via the numa-topology-spec
    #: annotation ([P] bool; ORed with the LSR/LSE cpu-bind predicate in
    #: the zone feasibility mask)
    numa_required: jnp.ndarray = None

    @classmethod
    def create(
        cls,
        requests,
        priority,
        estimate=None,
        is_prod=None,
        valid=None,
        gang_id=None,
        gang_min=None,
        quota_chain=None,
        qos=None,
        gpu_whole=None,
        gpu_share=None,
        rdma=None,
        fpga=None,
        gang_nonstrict=None,
        numa_required=None,
        quota_levels: int = 4,
    ) -> "PodBatch":
        requests = jnp.asarray(requests, jnp.float32)
        priority = jnp.asarray(priority, jnp.int32)
        p = requests.shape[0]
        return cls(
            requests=requests,
            estimate=(
                requests if estimate is None else jnp.asarray(estimate, jnp.float32)
            ),
            priority=priority,
            is_prod=(priority >= 9000) if is_prod is None else jnp.asarray(is_prod),
            valid=jnp.ones(p, bool) if valid is None else jnp.asarray(valid),
            gang_id=(
                jnp.full(p, -1, jnp.int32)
                if gang_id is None
                else jnp.asarray(gang_id, jnp.int32)
            ),
            gang_min=(
                jnp.zeros(p, jnp.int32)
                if gang_min is None
                else jnp.asarray(gang_min, jnp.int32)
            ),
            quota_chain=(
                jnp.full((p, quota_levels), -1, jnp.int32)
                if quota_chain is None
                else jnp.asarray(quota_chain, jnp.int32)
            ),
            qos=(
                jnp.zeros(p, jnp.int8)
                if qos is None
                else jnp.asarray(qos, jnp.int8)
            ),
            gpu_whole=(
                jnp.zeros(p, jnp.int32)
                if gpu_whole is None
                else jnp.asarray(gpu_whole, jnp.int32)
            ),
            gpu_share=(
                jnp.zeros(p, jnp.float32)
                if gpu_share is None
                else jnp.asarray(gpu_share, jnp.float32)
            ),
            rdma=(
                jnp.zeros(p, jnp.int32)
                if rdma is None
                else jnp.asarray(rdma, jnp.int32)
            ),
            fpga=(
                jnp.zeros(p, jnp.int32)
                if fpga is None
                else jnp.asarray(fpga, jnp.int32)
            ),
            gang_nonstrict=(
                jnp.zeros(p, bool)
                if gang_nonstrict is None
                else jnp.asarray(gang_nonstrict, bool)
            ),
            numa_required=(
                jnp.zeros(p, bool)
                if numa_required is None
                else jnp.asarray(numa_required, bool)
            ),
        )


@functools.partial(jax.jit, donate_argnums=0)
def scatter_rows(full, idx, rows):
    """Refresh a device-resident node-axis pytree in place of a full
    re-upload: ``full`` is any pytree of ``[N, ...]`` arrays (NodeState,
    NumaState, DeviceState), ``idx`` [K] int32 the node rows to replace
    and ``rows`` the matching pytree of ``[K, ...]`` row blocks. ``idx``
    may carry duplicate entries (callers pad to a stable K for jit-cache
    stability) as long as duplicates carry identical row data.

    ``full`` is DONATED: the steady-state refresh updates the resident
    buffers in place (zero fresh [N, ...] allocations — XLA writes the
    scattered rows into the donated input's memory). The caller's old
    reference is dead after the call; every call site replaces its
    resident handle with the return value and never re-reads the input
    (tests assert buffer-pointer stability on the refresh path)."""
    _devprof.tracing("scatter_rows")
    return jax.tree.map(lambda f, r: f.at[idx].set(r), full, rows)


@jax.jit
def gather_rows(full, idx, valid):
    """Sampled-window lowering ON DEVICE: gather ``idx`` [B] node rows out
    of a resident full-axis pytree, zeroing rows where ``valid`` [B] is
    False (padding rows then read schedulable=False and mask out, the same
    contract the host-side pad-and-upload path provided). ``full`` is NOT
    donated: the resident arrays are re-read by later refreshes/windows
    (donation audit, perf PR 4 — same reason ``assign`` never donates its
    node/quota inputs)."""
    _devprof.tracing("gather_rows")

    def take(f):
        out = f[idx]
        v = valid.reshape((-1,) + (1,) * (out.ndim - 1))
        return jnp.where(v, out, jnp.zeros_like(out))

    return jax.tree.map(take, full)


#: memoized sharded scatter/gather programs, keyed on (kind, treedef,
#: per-leaf NamedShardings, rows treedef) — sharding-polymorphic jit
#: would retrace per call otherwise, and a fresh ``jax.jit`` per call
#: would defeat the compile cache outright (retrace-hazard discipline).
_SHARDED_ROW_FNS: dict = {}


def _tree_shardings(tree):
    return jax.tree.map(lambda leaf: leaf.sharding, tree)


def scatter_rows_sharded(mesh, full, idx, rows, devprof=None, **sig):
    """Mesh-resident form of :func:`scatter_rows`: refresh a node-axis
    pytree that lives SHARDED on the ``tp`` axis of a (dp, tp) mesh.

    The donation contract is the hard part — a naive
    ``scatter_rows(full, ...)`` on sharded operands would let the jit
    re-infer output shardings and silently break buffer aliasing at the
    resharding boundary. Here the program is compiled with explicit
    ``in_shardings``/``out_shardings`` pinned EQUAL for the donated
    ``full`` argument (the dirty index vector and row blocks ride in
    replicated — they are K-row slivers, not [N, ...] tables), so XLA
    aliases the resident shards in place; the donation-effectiveness
    census verifies the input really died. Programs are memoized per
    (treedef, leaf shardings) so the steady-state refresh never
    re-lowers. ``devprof`` wraps the dispatch in a signature-carrying
    watch window (PR 8 standing rule); ``sig`` feeds it."""
    from jax.sharding import NamedSharding, PartitionSpec

    sh = _tree_shardings(full)
    leaves, treedef = jax.tree.flatten(full)
    rows_def = jax.tree.structure(rows)
    key = (
        "scatter",
        treedef,
        tuple(leaf.sharding for leaf in leaves),
        rows_def,
    )
    fn = _SHARDED_ROW_FNS.get(key)
    rep = NamedSharding(mesh, PartitionSpec())
    if fn is None:

        def _traced_scatter(full_, idx_, rows_):
            _devprof.tracing("scatter_rows_sharded")
            return jax.tree.map(
                lambda f, r: f.at[idx_].set(r), full_, rows_
            )

        fn = jax.jit(
            _traced_scatter,
            in_shardings=(sh, rep, jax.tree.map(lambda _: rep, rows)),
            out_shardings=sh,
            donate_argnums=0,
        )
        _SHARDED_ROW_FNS[key] = fn
    idx = jax.device_put(idx, rep)
    rows = jax.device_put(rows, jax.tree.map(lambda _: rep, rows))
    with (
        devprof.watch(
            "scatter_rows_sharded", stage="snapshot", kind="transfer",
            dp=mesh.shape["dp"], tp=mesh.shape["tp"], **sig,
        )
        if devprof is not None
        else _devprof.NULL_WATCH
    ) as w:
        out = fn(full, idx, rows)
        w.result(out)
    return out


def gather_rows_sharded(mesh, full, idx, valid, devprof=None, **sig):
    """Mesh-resident form of :func:`gather_rows`: window-gather out of a
    tp-sharded resident pytree, output pinned back onto the same tp
    sharding so the windowed solve runs SPMD too. ``full`` is NOT
    donated (same resident re-read contract as :func:`gather_rows`);
    programs are memoized per (treedef, leaf shardings). ``devprof``
    wraps the dispatch in a signature-carrying watch window."""
    from jax.sharding import NamedSharding, PartitionSpec

    sh = _tree_shardings(full)
    leaves, treedef = jax.tree.flatten(full)
    key = ("gather", treedef, tuple(leaf.sharding for leaf in leaves))
    fn = _SHARDED_ROW_FNS.get(key)
    rep = NamedSharding(mesh, PartitionSpec())
    if fn is None:

        def _traced_gather(full_, idx_, valid_):
            _devprof.tracing("gather_rows_sharded")

            def take(f):
                out = f[idx_]
                v = valid_.reshape((-1,) + (1,) * (out.ndim - 1))
                return jnp.where(v, out, jnp.zeros_like(out))

            return jax.tree.map(take, full_)

        fn = jax.jit(
            _traced_gather,
            in_shardings=(sh, rep, rep),
            out_shardings=sh,
        )
        _SHARDED_ROW_FNS[key] = fn
    idx = jax.device_put(idx, rep)
    valid = jax.device_put(valid, rep)
    with (
        devprof.watch(
            "gather_rows_sharded", stage="snapshot", kind="transfer",
            dp=mesh.shape["dp"], tp=mesh.shape["tp"], **sig,
        )
        if devprof is not None
        else _devprof.NULL_WATCH
    ) as w:
        out = fn(full, idx, valid)
        w.result(out)
    return out


@struct.dataclass
class QuotaState:
    """Device-side ElasticQuota accounting ([Q, D] each).

    ``runtime`` is the fair-share entitlement computed host-side by the
    GroupQuotaManager (reference ``core/runtime_quota_calculator.go``);
    ``used`` is the sum of admitted pod requests charged to each quota
    (admission rule used+request ≤ runtime along the whole chain,
    reference ``plugin_helper.go:281-317``).
    """

    runtime: jnp.ndarray
    used: jnp.ndarray

    @classmethod
    def disabled(cls, dims: int) -> "QuotaState":
        return cls(
            runtime=jnp.full((1, dims), jnp.inf, jnp.float32),
            used=jnp.zeros((1, dims), jnp.float32),
        )


@struct.dataclass
class SolverParams:
    """LoadAware thresholds/weights on the dense resource axis ([D] each).

    A threshold of 0 disables that dim's usage check (reference
    ``LoadAwareSchedulingArgs`` defaulting, ``pkg/scheduler/apis/config``).
    """

    usage_thresholds: jnp.ndarray
    prod_thresholds: jnp.ndarray
    score_weights: jnp.ndarray


@struct.dataclass
class SolveResult:
    """One solve's assignments plus its POST-COMMIT capacity tables.

    The post-commit tables are the chaining currency: ``solve_stream``/
    ``solve_stream_full`` thread them between chunks WITHIN a cycle, and
    the scheduler's ``ChainCarry`` (open-the-gates PR) threads the very
    same arrays ACROSS the cycle boundary into the next speculative
    dispatch — zero extra device work either way, because the solver
    outputs ARE the chained state. Consumers that keep a chained solve
    must validate the carried tables against host truth at commit time
    (``BatchScheduler._carry_consume_ok``)."""

    assignment: jnp.ndarray       # [P] int32 node index, -1 = unschedulable
    node_requested: jnp.ndarray   # [N, D] post-commit
    node_estimated_used: jnp.ndarray  # [N, D] post-commit
    node_prod_used: jnp.ndarray   # [N, D] post-commit
    #: [Q, D] post-commit quota-used table (the extended shadow-row
    #: layout when the caller lowered one) — chained across chunks by
    #: the streams and across CYCLES by the pipeline's quota carry; the
    #: quota RUNTIME stays host-computed (water-fill preview) and is
    #: re-validated bit-exact at consume
    quota_used: jnp.ndarray
    rounds_used: jnp.ndarray      # [] int32
    #: post-commit exact per-slot GPU table [N, G] (placeholder [N, 1]
    #: zeros when the solve had no DeviceState) plus free RDMA/FPGA counts
    #: [N]; feed back via ``assign(dev_carry=...)`` to chain device
    #: capacity across chunks — or across cycles — without a host
    #: round-trip
    node_dev_slots: jnp.ndarray = None
    node_rdma_free: jnp.ndarray = None
    node_fpga_free: jnp.ndarray = None
    #: post-commit exact NUMA zone table [N, Z, DN] (placeholder
    #: [N, 1, 1] when the solve had no NumaState); feed back via
    #: ``assign(numa_carry=...)``
    node_zone_free: jnp.ndarray = None
    #: per-pod zone picked on device ([P] int32, -1 = none) — the host
    #: allocator consumes it instead of re-deriving the pick — and the
    #: zone-scoped charge each zoned pod applied ([P, DN], for refunds)
    pod_zone: jnp.ndarray = None
    pod_zone_charge: jnp.ndarray = None
    #: [2] int32 — rounds in which the candidate-shortlist solve fell back
    #: to full-axis nomination, by cause: [0] exactness-bound violation
    #: (a chosen candidate's cost reached the best excluded node's
    #: build-time lower bound), [1] shortlist exhaustion (a still-active
    #: pod had zero feasible candidates while excluded nodes might fit).
    #: Zeros when shortlisting ran clean or was statically off; None on
    #: legacy construction sites.
    shortlist_fallbacks: jnp.ndarray = None


def _quota_headroom(
    requests: jnp.ndarray, chain: jnp.ndarray, quotas: QuotaState
) -> jnp.ndarray:
    """Per-pod admission mask: used + request ≤ runtime along the whole
    quota chain (reference ``plugin_helper.go:281-317``). [P] bool."""
    q_cap = quotas.runtime.shape[0]
    q = jnp.clip(chain, 0, q_cap - 1)                       # [P, L]
    valid = chain >= 0
    head = jnp.all(
        quotas.used[q] + requests[:, None, :] <= quotas.runtime[q] + EPS,
        axis=-1,
    )                                                        # [P, L]
    return jnp.all(head | ~valid, axis=-1)


def _quota_commit(
    accepted: jnp.ndarray,
    requests: jnp.ndarray,
    chain: jnp.ndarray,
    quotas: QuotaState,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Cumulative in-round quota admission, pods given in priority order.

    For each chain level, charge node-accepted pods against their quota in
    priority order via segmented prefix sums; a pod must clear every level.
    Rejections at a deeper level may leave shallower prefix sums
    conservative for this round — safe (under-admission), corrected next
    round. Returns (final_accept [P], new_used [Q, D])."""
    p, levels = chain.shape
    q_cap = quotas.runtime.shape[0]
    d = requests.shape[1]
    ok = jnp.ones((p,), bool)
    if q_cap * d <= 1024:
        # Dense one-hot prefix path (static branch on the quota-table
        # shape): a stable bitonic [P] argsort per level per round was the
        # quota solve's dominant device cost; for small tables the same
        # priority-ordered per-quota prefix is one [P, Q, D] cumsum —
        # pods are already in priority order along P.
        qids = jnp.arange(q_cap, dtype=chain.dtype)
        for level in range(levels):
            key_raw = chain[:, level]
            participating = accepted & (key_raw >= 0)
            onehot = participating[:, None] & (key_raw[:, None] == qids[None, :])
            contrib = onehot[:, :, None] * requests[:, None, :]   # [P, Q, D]
            prefix = jnp.cumsum(contrib, axis=0)                  # inclusive
            gq = jnp.clip(key_raw, 0, q_cap - 1).astype(jnp.int32)
            own = jnp.take_along_axis(
                prefix, jnp.broadcast_to(gq[:, None, None], (p, 1, d)), axis=1
            )[:, 0, :]                                            # [P, D]
            fits = jnp.all(
                quotas.used[gq] + own <= quotas.runtime[gq] + EPS, axis=-1
            )
            ok &= ~participating | fits
    else:
        for level in range(levels):
            key_raw = chain[:, level]
            participating = accepted & (key_raw >= 0)
            key = jnp.where(participating, key_raw, q_cap)
            sidx = jnp.argsort(key, stable=True).astype(jnp.int32)
            skey = key[sidx]
            sreq = jnp.where(participating[sidx][:, None], requests[sidx], 0.0)
            is_start = jnp.concatenate(
                [jnp.ones((1,), bool), skey[1:] != skey[:-1]]
            )
            seg = _segment_prefix_sums(sreq, is_start)
            gq = jnp.minimum(skey, q_cap - 1)
            fits = jnp.all(
                quotas.used[gq] + seg <= quotas.runtime[gq] + EPS, axis=-1
            )
            ok_sorted = (skey >= q_cap) | fits
            ok &= jnp.zeros((p,), bool).at[sidx].set(ok_sorted)
    final = accepted & ok
    new_used = quotas.used
    for level in range(levels):
        key_raw = chain[:, level]
        charge = final & (key_raw >= 0)
        seg_ids = jnp.where(charge, key_raw, q_cap - 1)
        new_used = new_used + jax.ops.segment_sum(
            jnp.where(charge[:, None], requests, 0.0),
            seg_ids,
            num_segments=q_cap,
        )
    return final, new_used


def _segment_prefix_sums(values: jnp.ndarray, seg_starts: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix sum of ``values`` [P, D] within runs delimited by
    ``seg_starts`` [P] bool (True at each run's first row)."""
    p = values.shape[0]
    cums = jnp.cumsum(values, axis=0)
    idx = jnp.arange(p, dtype=jnp.int32)
    start_idx = jax.lax.cummax(jnp.where(seg_starts, idx, 0))
    base = jnp.where(
        (start_idx > 0)[:, None], cums[jnp.maximum(start_idx - 1, 0)], 0.0
    )
    return cums - base


def _jitter_hash(pi: jnp.ndarray, ni: jnp.ndarray) -> jnp.ndarray:
    """Knuth multiplicative nomination-jitter hash, folded to 16 bits.

    ``pi``/``ni`` are uint32 pod- and node-index arrays (broadcastable).
    The hash is keyed on ORIGINAL node ids — the shortlist solve gathers
    candidate columns and must reproduce the full-axis tie-break band
    bit-exactly, so it feeds the gathered candidate ids (not shortlist
    positions) through this same function."""
    return (
        pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)
    ) & jnp.uint32(0xFFFF)


#: extension.QoSClass values used on device (LSR/LSE need exclusive CPUs)
QOS_LSR, QOS_LSE = 3, 4

#: zone-needing winners resolved per node per commit round: each rank's
#: strategy-ordered zone pick runs sequentially (a short fori_loop) so it
#: sees the previous ranks' charges — host-equivalent bookkeeping without
#: serializing a node's whole backlog onto one round. The spread quantum
#: bounds per-node acceptance near this in practice; overflow ranks
#: simply retry next round.
ZONE_WINNERS_PER_ROUND = 4


def _cpu_bind(pods: PodBatch) -> jnp.ndarray:
    """[P] bool — pod wants an exclusive cpuset (the host predicate
    ``nodenumaresource.wants_numa``: LSR/LSE QoS with a positive
    whole-core CPU request; reference ``plugin.go:251-313``
    requiredCPUBindPolicy resolution)."""
    cpu_req = pods.requests[:, 0]
    return (
        ((pods.qos == QOS_LSR) | (pods.qos == QOS_LSE))
        & (cpu_req > 0)
        & (jnp.mod(cpu_req, 1000.0) == 0)
    )


def _feasible(
    pods: PodBatch, nodes: NodeState, params: SolverParams, active: jnp.ndarray
) -> jnp.ndarray:
    free = nodes.allocatable - nodes.requested
    feas = mask_ops.fit_mask(pods.requests, free)
    # Amplified-CPU filter (nodenumaresource/plugin.go:408-443): on nodes
    # whose allocatable was amplified (ratio > 1), a cpuset-bound pod's
    # CPU request counts ×ratio — physical cores don't stretch. The
    # already-allocated exclusive CPUs' amplified surcharge is folded into
    # nodes.requested host-side (BatchScheduler.node_state).
    amp = jnp.maximum(nodes.cpu_amp, 1.0)
    eff_cpu = pods.requests[:, 0][:, None] * amp[None, :]
    feas &= ~_cpu_bind(pods)[:, None] | (eff_cpu <= free[:, 0][None, :] + EPS)
    feas &= mask_ops.usage_threshold_mask(
        pods.estimate,
        nodes.estimated_used,
        nodes.allocatable,
        params.usage_thresholds,
        nodes.metric_fresh,
        node_custom=nodes.custom_thresholds,
    )
    feas &= mask_ops.prod_usage_threshold_mask(
        pods.is_prod,
        pods.estimate,
        nodes.prod_used,
        nodes.allocatable,
        params.prod_thresholds,
        nodes.metric_fresh,
        node_custom=nodes.custom_prod_thresholds,
    )
    feas &= nodes.schedulable[None, :]
    feas &= active[:, None]
    return feas


def _priority_order(pods: PodBatch) -> jnp.ndarray:
    """Stable (-priority, arrival) order — the reference activeQ pop order
    (upstream PrioritySort over koord priority bands)."""
    return jnp.argsort(-pods.priority, stable=True).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_rounds",
        "topk",
        "cost_transform",
        "nomination_jitter",
        "approx_topk",
        "numa_scoring",
        "device_scoring",
        "shortlist_k",
    ),
)
def assign(
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    quotas: QuotaState | None = None,
    numa: "NumaState | None" = None,
    devices: "DeviceState | None" = None,
    max_rounds: int = 24,
    round_quantum: float = 0.35,
    topk: int = 4,
    cost_transform=None,
    nomination_jitter: float = 4.0,
    approx_topk: bool = False,
    node_mask: "jnp.ndarray | None" = None,
    dev_carry: "tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray] | None" = None,
    numa_carry: "jnp.ndarray | None" = None,
    numa_scoring: "str | None" = None,
    device_scoring: "str | None" = None,
    shortlist_k: "int | None" = None,
) -> SolveResult:
    """Round-based fast solver. ``round_quantum`` is the fraction of a node's
    allocatable (per dim, measured in estimated usage) it may accept per
    round; at least one pod per node per round is always eligible so the
    fixed point is reached regardless of pod size. ``topk`` is the nomination
    fan-out per pod per round (see round_body).

    ``nomination_jitter`` adds a deterministic per-(pod, node) perturbation
    (in score points, scores span 0-100) to the ranked cost. LoadAware
    scores are coarse — on a large cluster thousands of nodes tie within a
    point — so without it every pod nominates the same few argmin nodes
    and the per-node round quantum serializes the batch (measured: 8192
    pods → 8 distinct nominated nodes). It generalizes kube-scheduler's
    random tie-break among equal-scored hosts, with a deliberately wider
    band: each pod may land on any node within ``nomination_jitter`` score
    points of its true optimum (bounded deviation, massively better
    spread). ``nomination_jitter=0.0, topk=1`` restores strict per-pod
    argmin *nomination* (batched commit semantics are unchanged); the
    deviation-vs-throughput trade is these two knobs."""
    _devprof.tracing("assign")
    p = pods.requests.shape[0]
    n = nodes.allocatable.shape[0]
    # Static specialization: with no quota tree the per-level sort/prefix
    # passes are dead weight — trace them out entirely.
    quota_enabled = quotas is not None
    if quotas is None:
        quotas = QuotaState.disabled(pods.requests.shape[1])

    order = _priority_order(pods)
    spods = jax.tree.map(lambda a: a[order], pods)
    # per-pod node constraints (nodeSelector / required nodeAffinity /
    # spec.nodeName), host-built [P, N] bool, permuted with the pods
    smask = None if node_mask is None else node_mask[order]

    def add_jitter(cost: jnp.ndarray) -> jnp.ndarray:
        """Deterministic per-(pod, node) perturbation, Knuth multiplicative
        hash folded to [0, nomination_jitter) score points. Computed inside
        the round body so XLA fuses it into the cost elementwise op — a
        hoisted [P, N] buffer would hold ~P·N·4 bytes across every round."""
        if nomination_jitter <= 0.0:
            return cost
        pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
        ni = jnp.arange(n, dtype=jnp.uint32)[None, :]
        h = _jitter_hash(pi, ni)
        return cost + h.astype(jnp.float32) * (nomination_jitter / 65536.0)

    def add_jitter_cols(cost: jnp.ndarray, cand: jnp.ndarray) -> jnp.ndarray:
        """Gathered-column jitter: ``cand`` [P, K] carries ORIGINAL node
        ids, so each (pod, node) pair hashes to the same perturbation it
        gets on the full axis — the tie-break band is gather-invariant."""
        if nomination_jitter <= 0.0:
            return cost
        pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
        h = _jitter_hash(pi, cand.astype(jnp.uint32))
        return cost + h.astype(jnp.float32) * (nomination_jitter / 65536.0)

    # round-invariant: which pods bind exclusive CPUs (NUMA alignment +
    # amplified-CPU charging both key off it)
    bind_mask = _cpu_bind(spods)
    if numa is not None:
        from .numa import NumaState, numa_fit_mask, zone_pick

        # Alignment need mirrors the host predicate (nodenumaresource
        # wants_numa): LSR or LSE QoS with a positive whole-core request —
        # plus pods whose numa-topology-spec annotation requires
        # SingleNUMANode placement outright (numa_aware.go:29-31)
        wants = bind_mask
        s_required = (
            spods.numa_required
            if spods.numa_required is not None
            else jnp.zeros((p,), bool)
        )
        wants = wants | s_required
        # Zone selection is ON DEVICE (VERDICT r4 #4): zone_free is
        # carried exactly through the commit rounds (like the GPU slot
        # table) and each round's feasibility mask is recomputed from
        # it; the host allocator receives the picked zone and only
        # formats/bookkeeps. Round-invariant ingredients:
        zone_cap = numa.zone_cap
        n_zones = zone_cap.shape[1]
        dn = zone_cap.shape[-1]
        node_single = numa.policy == 3  # POLICY_SINGLE_NUMA_NODE
        node_has_zones = jnp.any(jnp.sum(zone_cap, axis=-1) > 0, axis=-1)
        zone_most = (
            numa.zone_most
            if numa.zone_most is not None
            else jnp.zeros((n,), bool)
        )
        amp_vec = jnp.maximum(nodes.cpu_amp, 1.0)
        zfree0 = numa.zone_free if numa_carry is None else numa_carry
        # The [P, N, Z] feasibility mask is computed ONCE from the
        # batch-start table (a per-round recompute is a rank-4 tensor per
        # round — measured 3× the whole solve); intra-batch exactness
        # comes from the commit-stage zone_pick check against the CARRIED
        # zone_free, which rejects a stale nomination before it commits.
        numa_mask = numa_fit_mask(
            spods.requests,
            wants,
            NumaState(
                zone_free=zfree0, zone_cap=zone_cap, policy=numa.policy
            ),
            cpu_amp=nodes.cpu_amp,
            pod_required=spods.numa_required,
        )
        if numa_scoring is not None:
            # NUMA-aligned Least/MostAllocated Score strategies
            # (nodenumaresource/scoring.go:66-120): a static [P, N] score
            # term over the zone the host allocator would pick
            numa_score_term = cost_ops.numa_aligned_cost(
                spods.requests,
                wants,
                zfree0,
                numa.zone_cap,
                params.score_weights,
                most_allocated=(numa_scoring == "MostAllocated"),
            )
        else:
            numa_score_term = None
    else:
        numa_score_term = None
        zfree0 = jnp.zeros((n, 1, 1), jnp.float32)
    if devices is not None:
        from .device import (
            device_consumption,
            device_fit_mask,
            device_fit_mask_cols,
            slot_commit,
            slot_stats,
        )

        slots0 = devices.slot_free
        rdma_tracked = devices.rdma_free is not None
        fpga_tracked = devices.fpga_free is not None
        rdma0 = (
            devices.rdma_free if rdma_tracked else jnp.zeros((n,), jnp.float32)
        )
        fpga0 = (
            devices.fpga_free if fpga_tracked else jnp.zeros((n,), jnp.float32)
        )
        if dev_carry is not None:
            # exact per-slot table (+ RDMA/FPGA counts) chained from a
            # previous chunk's SolveResult — no host round-trip between
            slots0, rdma0, fpga0 = dev_carry
        _, sdev_total = device_consumption(spods.gpu_whole, spods.gpu_share)
        sdev_rdma = spods.rdma.astype(jnp.float32)
        sdev_fpga = spods.fpga.astype(jnp.float32)
    else:
        slots0 = jnp.zeros((n, 1), jnp.float32)
        rdma0 = fpga0 = jnp.zeros((n,), jnp.float32)

    k = min(topk, n)
    # Candidate-shortlist solve (perf): prune the round loop's per-pod
    # node axis to each pod's top-K build-time candidates. Statically off
    # when K covers the axis anyway, when the nomination fan-out exceeds
    # K, or when a cost term breaks the exactness bound's monotonicity
    # premise (arbitrary cost_transform; MostAllocated device scoring
    # REWARDS usage, so an excluded node's cost can drop below its
    # build-time bound as other pods commit).
    shortlist_on = (
        shortlist_k is not None
        and 0 < shortlist_k < n
        and shortlist_k >= k
        and cost_transform is None
        and device_scoring != "MostAllocated"
    )

    def full_feas_cost(
        requested,
        est_used,
        prod_used,
        dev_stats,
        rdma_free,
        fpga_free,
        gate,
        clamp_device=False,
    ):
        """The round loop's full-axis masked+jittered cost [P, N] at the
        given carry state (``gate`` [P] = pod-level active/quota gates).
        Shared by the non-shortlist round body, the shortlist build
        (round-0 state, gates open) and the escape-hatch re-nomination,
        so all three price a (pod, node) pair identically.

        ``clamp_device=True`` (build only) clamps the DeviceShare
        LeastAllocated term at ≤ 0: its over-capacity score cutoff can
        lift a floor(-1) score back to 0 in a ~1e-6 float window, and the
        excluded-node bound must LOWER-bound every future round's cost."""
        work = NodeState(
            allocatable=nodes.allocatable,
            requested=requested,
            estimated_used=est_used,
            prod_used=prod_used,
            metric_fresh=nodes.metric_fresh,
            schedulable=nodes.schedulable,
            cpu_amp=nodes.cpu_amp,
            custom_thresholds=nodes.custom_thresholds,
            custom_prod_thresholds=nodes.custom_prod_thresholds,
        )
        feas = _feasible(spods, work, params, gate)
        if smask is not None:
            feas &= smask
        if numa is not None:
            feas &= numa_mask
        if devices is not None:
            dev_full, dev_partial, dev_smax, dev_total = dev_stats
            feas &= device_fit_mask(
                spods.gpu_whole,
                spods.gpu_share,
                dev_full,
                dev_partial,
                slot_max=dev_smax,
                rdma_req=spods.rdma,
                rdma_free=rdma_free if rdma_tracked else None,
                fpga_req=spods.fpga,
                fpga_free=fpga_free if fpga_tracked else None,
            )
            # an untracked resource axis (no node carries it) must still
            # reject pods REQUESTING it — tracing the carry out is a
            # compute optimization, not a feasibility change
            if not rdma_tracked:
                feas &= (spods.rdma == 0)[:, None]
            if not fpga_tracked:
                feas &= (spods.fpga == 0)[:, None]
        cost = cost_ops.load_aware_cost(
            spods.estimate,
            est_used,
            nodes.allocatable,
            params.score_weights,
            metric_fresh=nodes.metric_fresh,
        )
        if numa_score_term is not None:
            cost = cost + numa_score_term
        if devices is not None and device_scoring is not None:
            # DeviceShare Least/MostAllocated over GPU capacity
            # (deviceshare/scoring.go); dev_total is the round-carried
            # free total, so intra-batch commits steer later rounds
            dterm = cost_ops.device_cost(
                sdev_total,
                dev_stats[3],
                devices.cap_total,
                most_allocated=(device_scoring == "MostAllocated"),
            )
            if clamp_device:
                dterm = jnp.minimum(dterm, 0.0)
            cost = cost + dterm
        if cost_transform is not None:
            # BeforeScore transformer chain (frameworkext.interface.go:84-109):
            # a static, jit-traced rewrite of the cost tensor.
            cost = cost_transform(cost)
        cost = add_jitter(cost)
        return jnp.where(feas, cost, jnp.inf)

    if shortlist_on:
        # Shortlist build from round-0 state, pod-level gates OPEN (a
        # quota-blocked pod can free up mid-solve — its shortlist must
        # already be there). Node-wise feasibility is monotone
        # non-increasing across rounds and every cost term is monotone
        # non-decreasing (or constant), so the (K+1)-th best build cost
        # LOWER-bounds every excluded node's cost in every later round.
        # Candidates are sorted ASCENDING by node id: lax.top_k/argmin
        # break ties by lowest index, so positional tie-breaks over the
        # gathered columns equal node-id tie-breaks on the full axis.
        dev_stats0 = slot_stats(slots0) if devices is not None else None
        cost_b = full_feas_cost(
            nodes.requested,
            nodes.estimated_used,
            nodes.prod_used,
            dev_stats0,
            rdma0,
            fpga0,
            jnp.ones((p,), bool),
            clamp_device=True,
        )
        neg_b, idx_b = jax.lax.top_k(-cost_b, shortlist_k + 1)
        # Asymmetric slicing of top_k's two outputs ([:, :K] indices vs
        # [:, K] value) defeats XLA's TopkRewriter — the sort+slice
        # pattern stops matching and the build degrades to a full
        # O(N log N) row sort (measured 50× at 20k nodes). The barrier
        # pins the canonical sort+uniform-slice pattern so the rewrite to
        # the O(N log K) TopK custom call survives.
        neg_b, idx_b = jax.lax.optimization_barrier((neg_b, idx_b))
        plan_cand = jnp.sort(idx_b[:, :shortlist_k], axis=1).astype(jnp.int32)
        # +inf when fewer than K+1 nodes were feasible at build time: the
        # shortlist is COMPLETE (excluded nodes can never become feasible)
        plan_bound = -neg_b[:, shortlist_k]
        s_custom = (
            nodes.custom_thresholds[plan_cand]
            if nodes.custom_thresholds is not None
            else None
        )
        s_custom_prod = (
            nodes.custom_prod_thresholds[plan_cand]
            if nodes.custom_prod_thresholds is not None
            else None
        )
        cand_alloc = nodes.allocatable[plan_cand]        # [P, K, D]
        cand_fresh = nodes.metric_fresh[plan_cand]       # [P, K]
        cand_sched = nodes.schedulable[plan_cand]
        cand_amp = jnp.maximum(nodes.cpu_amp, 1.0)[plan_cand]
        cand_smask = (
            jnp.take_along_axis(smask, plan_cand, axis=1)
            if smask is not None
            else None
        )
        cand_numa = (
            jnp.take_along_axis(numa_mask, plan_cand, axis=1)
            if numa is not None
            else None
        )
        cand_numa_score = (
            jnp.take_along_axis(numa_score_term, plan_cand, axis=1)
            if numa_score_term is not None
            else None
        )
        cand_cap_total = (
            devices.cap_total[plan_cand]
            if devices is not None and device_scoring is not None
            else None
        )

    def shortlist_feas_cost(
        requested, est_used, prod_used, dev_stats, rdma_free, fpga_free, gate
    ):
        """Gathered-column round cost [P, K] over each pod's candidate
        columns — the same elementwise arithmetic as
        :func:`full_feas_cost` restricted to ``plan_cand``, so a
        candidate prices identically on both paths (decision identity)."""
        free_c = cand_alloc - requested[plan_cand]
        feas = mask_ops.fit_mask_cols(spods.requests, free_c)
        eff_cpu = spods.requests[:, 0][:, None] * cand_amp
        feas &= ~bind_mask[:, None] | (eff_cpu <= free_c[..., 0] + EPS)
        est_c = est_used[plan_cand]
        feas &= mask_ops.usage_threshold_mask_cols(
            spods.estimate,
            est_c,
            cand_alloc,
            params.usage_thresholds,
            cand_fresh,
            node_custom=s_custom,
        )
        feas &= mask_ops.prod_usage_threshold_mask_cols(
            spods.is_prod,
            spods.estimate,
            prod_used[plan_cand],
            cand_alloc,
            params.prod_thresholds,
            cand_fresh,
            node_custom=s_custom_prod,
        )
        feas &= cand_sched
        feas &= gate[:, None]
        if cand_smask is not None:
            feas &= cand_smask
        if cand_numa is not None:
            feas &= cand_numa
        if devices is not None:
            dev_full, dev_partial, dev_smax, dev_total = dev_stats
            feas &= device_fit_mask_cols(
                spods.gpu_whole,
                spods.gpu_share,
                dev_full[plan_cand],
                dev_partial[plan_cand],
                slot_max=dev_smax[plan_cand],
                rdma_req=spods.rdma,
                rdma_free=rdma_free[plan_cand] if rdma_tracked else None,
                fpga_req=spods.fpga,
                fpga_free=fpga_free[plan_cand] if fpga_tracked else None,
            )
            if not rdma_tracked:
                feas &= (spods.rdma == 0)[:, None]
            if not fpga_tracked:
                feas &= (spods.fpga == 0)[:, None]
        cost = cost_ops.load_aware_cost_cols(
            spods.estimate,
            est_c,
            cand_alloc,
            params.score_weights,
            metric_fresh=cand_fresh,
        )
        if cand_numa_score is not None:
            cost = cost + cand_numa_score
        if devices is not None and device_scoring is not None:
            cost = cost + cost_ops.device_cost_cols(
                sdev_total,
                dev_stats[3][plan_cand],
                cand_cap_total,
                most_allocated=False,
            )
        cost = add_jitter_cols(cost, plan_cand)
        return jnp.where(feas, cost, jnp.inf)

    def round_body(carry):
        (
            assigned,
            requested,
            est_used,
            prod_used,
            qused,
            dev_slots,
            rdma_free,
            fpga_free,
            zone_free,
            azone_s,
            fb,
            active,
            _progress,
            r,
        ) = carry
        round_quotas = QuotaState(runtime=quotas.runtime, used=qused)
        if quota_enabled:
            q_head = _quota_headroom(
                spods.requests, spods.quota_chain, round_quotas
            )
            gate = active & q_head
        else:
            gate = active
        if devices is not None:
            # exact round-start reductions over the carried slot table
            # (kept full-axis: O(N·G) and the commit needs them anyway)
            dev_stats = slot_stats(dev_slots)
            dev_full, dev_partial, dev_smax, dev_total = dev_stats
        else:
            dev_stats = None

        def _full_nominate(_):
            """Full-axis nomination — the only path when shortlisting is
            off, the escape hatch when a round's exactness check fails
            (then it recomputes ALL pods' nominations, so the round is
            decision-identical to the full solver by construction)."""
            cost = full_feas_cost(
                requested, est_used, prod_used, dev_stats,
                rdma_free, fpga_free, gate,
            )
            # Top-K nomination with rank-modular spreading: if every pod
            # nominated its single argmin, one node would absorb the whole
            # round (the sequential loop avoids this only by paying O(P)
            # steps). Pod with the r-th highest priority among active pods
            # nominates its (r mod K)-th best node, so a round fans out
            # over each pod's K best nodes while the best nodes still go
            # to the highest priorities.
            if approx_topk:
                # TPU-optimized partial reduction (avoids the full
                # variadic sort lax.top_k lowers to). approx_max_k's
                # recall < 1 could deterministically drop a pod's ONLY
                # feasible node(s) — a device/NUMA-constrained pod with a
                # handful of finite entries would then read as
                # unschedulable every round — so slot 0 is pinned to the
                # exact argmin (a cheap single reduction); the approximate
                # set only provides the spread fan-out, where recall loss
                # is covered by the nomination jitter.
                neg_ap, idx_ap = jax.lax.approx_max_k(-cost, k)  # [P, K]
                bidx = jnp.argmin(cost, axis=1).astype(idx_ap.dtype)
                bval = -jnp.take_along_axis(cost, bidx[:, None], axis=1)
                neg_top = jnp.concatenate([bval, neg_ap[:, : k - 1]], axis=1)
                top_idx = jnp.concatenate(
                    [bidx[:, None], idx_ap[:, : k - 1]], axis=1
                )
            else:
                neg_top, top_idx = jax.lax.top_k(-cost, k)      # [P, K]
            return neg_top, top_idx.astype(jnp.int32)

        if shortlist_on:
            cost_g = shortlist_feas_cost(
                requested, est_used, prod_used, dev_stats,
                rdma_free, fpga_free, gate,
            )                                                    # [P, K]
            neg_s, pos_s = jax.lax.top_k(-cost_g, k)
            idx_s = jnp.take_along_axis(plan_cand, pos_s, axis=1)
            if approx_topk:
                # replicate the full path's pinned-argmin construction
                # ([best, exact top k-1]): where approx_max_k is exact
                # (CPU lowers it to exact top_k) the nomination vectors
                # are bit-identical; where it is genuinely approximate
                # the fan-out band differs within the jitter window.
                neg_top_s = jnp.concatenate(
                    [neg_s[:, :1], neg_s[:, : k - 1]], axis=1
                )
                top_idx_s = jnp.concatenate(
                    [idx_s[:, :1], idx_s[:, : k - 1]], axis=1
                )
            else:
                neg_top_s, top_idx_s = neg_s, idx_s
            # Exactness check: every nomination this round must beat the
            # best EXCLUDED node's build-time lower bound, strictly (a tie
            # could hand the full axis a lower node id). Pods with pod-
            # level gates closed nominate nothing on either path — safe.
            kth = -neg_s[:, k - 1]
            safe = ~jnp.isfinite(plan_bound) | (
                jnp.isfinite(kth) & (kth < plan_bound)
            )
            unsafe = gate & ~safe
            trigger = jnp.any(unsafe)
            neg_top, top_idx = jax.lax.cond(
                trigger,
                _full_nominate,
                lambda _: (neg_top_s, top_idx_s.astype(jnp.int32)),
                None,
            )
            cand_any = jnp.any(jnp.isfinite(cost_g), axis=1)
            fb = fb + jnp.stack(
                [
                    jnp.any(unsafe & cand_any).astype(jnp.int32),
                    jnp.any(unsafe & ~cand_any).astype(jnp.int32),
                ]
            )
        else:
            neg_top, top_idx = _full_nominate(None)
        finite = jnp.isfinite(neg_top)
        n_feas = jnp.sum(finite, axis=1).astype(jnp.int32)  # [P]
        rank = jnp.cumsum(active.astype(jnp.int32)) - 1
        slot = jnp.where(
            n_feas > 0, rank % jnp.maximum(n_feas, 1), 0
        ).astype(jnp.int32)
        choice = jnp.take_along_axis(top_idx, slot[:, None], axis=1)[:, 0]
        choice = choice.astype(jnp.int32)
        has = jnp.take_along_axis(finite, slot[:, None], axis=1)[:, 0]
        node_key = jnp.where(has, choice, n)

        # Priority-ordered per-node commit via segmented prefix sums.
        sortidx = jnp.argsort(node_key, stable=True).astype(jnp.int32)
        snode = node_key[sortidx]
        gnode = jnp.minimum(snode, n - 1)
        sreq = spods.requests[sortidx]
        # cpuset-bound pods consume physical cores: charge CPU ×ratio on
        # amplified nodes so later rounds see true remaining capacity
        # (the reference reaches the same state one pod at a time through
        # Reserve → cpuset allocate → next GetAvailableCPUs).
        samp = jnp.where(
            bind_mask[sortidx], jnp.maximum(nodes.cpu_amp, 1.0)[gnode], 1.0
        )
        sreq = sreq.at[:, 0].multiply(samp)
        sest = spods.estimate[sortidx]
        sprod = spods.is_prod[sortidx]
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), snode[1:] != snode[:-1]]
        )
        seg_req = _segment_prefix_sums(sreq, is_start)
        seg_est = _segment_prefix_sums(sest, is_start)
        seg_prod = _segment_prefix_sums(
            jnp.where(sprod[:, None], sest, 0.0), is_start
        )

        alloc_g = nodes.allocatable[gnode]
        req0_g = requested[gnode]
        est0_g = est_used[gnode]
        fresh_g = nodes.metric_fresh[gnode]

        accept = snode < n
        accept &= jnp.all(req0_g + seg_req <= alloc_g + EPS, axis=-1)
        if devices is not None:
            # Exact intra-round GPU accounting over the slot table: whole
            # demand is prefix-checked against the fully-free slot count
            # (slots are interchangeable, so any K ≤ full_count commits
            # are simultaneously satisfiable); a fractional pod whose
            # share exceeds the node's best partial slot must open a full
            # one and is charged for it; and only the FIRST fractional
            # pod per node per round commits — its best-fit target is
            # then uncontended, so the post-round slot_commit reproduces
            # the host allocator's state exactly.
            swhole = spods.gpu_whole[sortidx].astype(jnp.float32)
            sshare = spods.gpu_share[sortidx]
            s_is_frac = sshare > EPS
            s_opens_full = s_is_frac & (sshare > dev_partial[gnode] + EPS)
            full_charge = swhole + s_opens_full.astype(jnp.float32)
            seg_full = _segment_prefix_sums(full_charge[:, None], is_start)[:, 0]
            seg_frac = _segment_prefix_sums(
                s_is_frac.astype(jnp.float32)[:, None], is_start
            )[:, 0]
            accept &= seg_full <= dev_full[gnode] + EPS
            accept &= ~s_is_frac | (seg_frac - s_is_frac.astype(jnp.float32) < 0.5)
            if rdma_tracked:
                s_rdma = sdev_rdma[sortidx]
                seg_rdma = _segment_prefix_sums(s_rdma[:, None], is_start)[:, 0]
                accept &= seg_rdma <= rdma_free[gnode] + EPS
            if fpga_tracked:
                s_fpga = sdev_fpga[sortidx]
                seg_fpga = _segment_prefix_sums(s_fpga[:, None], is_start)[:, 0]
                accept &= seg_fpga <= fpga_free[gnode] + EPS
        if numa is not None:
            # On-device zone selection (VERDICT r4 #4, mirrors the host
            # allocate_lowered pick): zone-needing pods are those on
            # strict-policy nodes, cpuset-bound pods, and
            # SingleNUMANode-required pods. Up to ZONE_WINNERS_PER_ROUND
            # zone winners per node per round are resolved SEQUENTIALLY
            # (a short fori_loop: rank j's strategy-ordered pick sees
            # ranks < j's charges), reproducing the host allocator's
            # one-at-a-time zone bookkeeping without serializing the
            # whole node onto one round.
            s_bind = bind_mask[sortidx]
            s_req_flag = s_required[sortidx]
            s_zone_cand = (
                node_single[gnode] | s_bind | s_req_flag
            ) & node_has_zones[gnode]
            cand_f = s_zone_cand.astype(jnp.float32)
            seg_zone = _segment_prefix_sums(cand_f[:, None], is_start)[:, 0]
            zrank = seg_zone - cand_f  # 0-based rank among the node's cands
            accept &= ~s_zone_cand | (zrank < ZONE_WINNERS_PER_ROUND - 0.5)
            s_reqz = spods.requests[sortidx, :dn]
            req_eff_z = s_reqz.at[:, 0].multiply(
                jnp.where(s_bind, amp_vec[gnode], 1.0)
            )
            # pods REQUIRING a zone (strict node policy / SingleNUMANode
            # spec) cannot commit without a fitting zone — the host
            # Reserve would reject them
            s_strict = node_single[gnode] | s_req_flag
            zone_ids = jnp.arange(n_zones, dtype=jnp.int32)
            zcap_g = zone_cap[gnode]
            zmost_g = zone_most[gnode]

            def zone_rank_step(j, zstate):
                zf_t, acc_t, zsel_t = zstate
                zpick_j, zfit_j = zone_pick(
                    zf_t[gnode], zcap_g, req_eff_z, zmost_g
                )
                sel = s_zone_cand & (jnp.abs(zrank - j) < 0.5) & acc_t
                acc_t = acc_t & ~(sel & s_strict & ~zfit_j)
                win = sel & zfit_j
                zsel_t = jnp.where(win, zpick_j, zsel_t)
                z_onehot = (
                    zone_ids[None, :] == zpick_j[:, None]
                ) & win[:, None]
                # non-winners scatter zero rows, so the n-1 dump is inert
                zf_t = zf_t - jax.ops.segment_sum(
                    z_onehot[:, :, None] * req_eff_z[:, None, :],
                    jnp.where(win, gnode, n - 1),
                    num_segments=n,
                )
                return (zf_t, acc_t, zsel_t)

            zone_free_t, accept, s_zone_sel = jax.lax.fori_loop(
                0,
                ZONE_WINNERS_PER_ROUND,
                zone_rank_step,
                (zone_free, accept, jnp.full((p,), -1, jnp.int32)),
            )
        # Intra-round cumulative usage-threshold check keeps the commit
        # faithful to sequential Filter semantics (load_aware.go:290-313,
        # rounded-percent comparison).
        thr = mask_ops.effective_thresholds(
            params.usage_thresholds, nodes.custom_thresholds
        )[gnode]
        over = (thr > 0.0) & (
            mask_ops.usage_percent(est0_g + seg_est, alloc_g) > thr
        )
        accept &= ~(fresh_g & jnp.any(over, axis=-1))
        pthr = mask_ops.effective_thresholds(
            params.prod_thresholds, nodes.custom_prod_thresholds
        )[gnode]
        pover = (pthr > 0.0) & (
            mask_ops.usage_percent(prod_used[gnode] + seg_prod, alloc_g) > pthr
        )
        accept &= ~(sprod & fresh_g & jnp.any(pover, axis=-1))
        # Spread quantum: prior intra-round acceptance on this node must stay
        # under quantum × allocatable (first pod of a segment always passes).
        # Dims the node doesn't provide (alloc 0, e.g. batch tiers before
        # the noderesource controller publishes them) are exempt — the
        # estimator's tier floors would otherwise serialize every batch-band
        # pod onto its own round.
        prior_est = seg_est - sest
        accept &= jnp.all(
            (alloc_g <= 0) | (prior_est <= round_quantum * alloc_g + EPS),
            axis=-1,
        )

        # Quota admission: cumulative along the chain in priority order;
        # a node-accepted pod must also clear every quota level.
        accepted_prio = jnp.zeros((p,), bool).at[sortidx].set(accept)
        if quota_enabled:
            final_prio, qused_new = _quota_commit(
                accepted_prio, spods.requests, spods.quota_chain, round_quotas
            )
        else:
            final_prio, qused_new = accepted_prio, qused
        final_node = final_prio[sortidx]
        assigned = jnp.where(final_prio, choice, assigned)

        seg_ids = jnp.where(final_node, snode, n - 1)
        zero = jnp.zeros_like(sreq)
        dreq = jax.ops.segment_sum(
            jnp.where(final_node[:, None], sreq, zero), seg_ids, num_segments=n
        )
        dest = jax.ops.segment_sum(
            jnp.where(final_node[:, None], sest, zero), seg_ids, num_segments=n
        )
        dprod = jax.ops.segment_sum(
            jnp.where((final_node & sprod)[:, None], sest, zero),
            seg_ids,
            num_segments=n,
        )
        if devices is not None:
            # per-node winner aggregates: total whole slots zeroed, the
            # (single) fractional winner's share + whether it opens a
            # full slot — then one vectorized [N, G] slot_commit
            whole_taken = jax.ops.segment_sum(
                jnp.where(final_node, swhole, 0.0), seg_ids, num_segments=n
            )
            frac_share = jax.ops.segment_sum(
                jnp.where(final_node & s_is_frac, sshare, 0.0),
                seg_ids,
                num_segments=n,
            )
            frac_opens = (
                jax.ops.segment_sum(
                    jnp.where(
                        final_node & s_opens_full, 1.0, 0.0
                    ),
                    seg_ids,
                    num_segments=n,
                )
                > 0.5
            )
            dev_slots = slot_commit(dev_slots, whole_taken, frac_share, frac_opens)
            if rdma_tracked:
                rdma_free = rdma_free - jax.ops.segment_sum(
                    jnp.where(final_node, s_rdma, 0.0), seg_ids, num_segments=n
                )
            if fpga_tracked:
                fpga_free = fpga_free - jax.ops.segment_sum(
                    jnp.where(final_node, s_fpga, 0.0), seg_ids, num_segments=n
                )
        if numa is not None:
            # charge the (single) zone winner's request against its zone
            # and record the pick (azone_s rides the carry in spods order)
            zwin = jnp.where(final_node, s_zone_sel, -1)
            z_onehot = (
                jnp.arange(n_zones, dtype=jnp.int32)[None, :]
                == jnp.clip(zwin, 0, n_zones - 1)[:, None]
            ) & (zwin >= 0)[:, None]                             # [P, Z]
            zdelta = (
                z_onehot[:, :, None] * req_eff_z[:, None, :]
            )                                                    # [P, Z, DN]
            zone_free = zone_free - jax.ops.segment_sum(
                zdelta, seg_ids, num_segments=n
            )
            upd = jnp.full((p,), -1, jnp.int32).at[sortidx].set(zwin)
            azone_s = jnp.where(upd >= 0, upd, azone_s)
        return (
            assigned,
            requested + dreq,
            est_used + dest,
            prod_used + dprod,
            qused_new,
            dev_slots,
            rdma_free,
            fpga_free,
            zone_free,
            azone_s,
            fb,
            active & (assigned < 0),
            jnp.any(final_prio),
            r + 1,
        )

    def round_cond(carry):
        active, progress, r = carry[-3:]
        return (r < max_rounds) & progress & jnp.any(active)

    init = (
        jnp.full((p,), -1, jnp.int32),
        nodes.requested,
        nodes.estimated_used,
        nodes.prod_used,
        quotas.used,
        slots0,
        rdma0,
        fpga0,
        zfree0,
        jnp.full((p,), -1, jnp.int32),
        jnp.zeros((2,), jnp.int32),
        pods.valid[order],
        jnp.array(True),
        jnp.array(0, jnp.int32),
    )
    (
        assigned_s,
        req_f,
        est_f,
        prod_f,
        qused_f,
        slots_f,
        rdma_f,
        fpga_f,
        zfree_f,
        azone_f,
        fb_f,
        _active,
        _prog,
        rounds,
    ) = jax.lax.while_loop(round_cond, round_body, init)

    # Back to original pod order. ``order`` is a permutation, so the
    # un-sort is the gather by its inverse — exactly equal to the
    # ``full(-1).at[order].set(...)`` scatter (every slot written once),
    # but partition-friendly: GSPMD mis-sizes the all-gather/slice pair
    # that 1-D permutation scatter lowers to on dp-sharded operands
    # (the toolchain defect the sharded suite's probe documents), while
    # the gather form partitions correctly everywhere.
    inv_order = jnp.argsort(order).astype(jnp.int32)
    assignment = assigned_s[inv_order]
    pod_zone = azone_f[inv_order]
    if numa is not None:
        # the zone charge each zoned pod applied (for gang refunds):
        # zone-scoped request, CPU amplified for cpuset-bound pods
        amp_assigned = jnp.maximum(nodes.cpu_amp, 1.0)[
            jnp.clip(assignment, 0, n - 1)
        ]
        bind_o = _cpu_bind(pods)
        zone_charge = pods.requests[:, :dn].at[:, 0].multiply(
            jnp.where(bind_o, amp_assigned, 1.0)
        )
        zone_charge = jnp.where((pod_zone >= 0)[:, None], zone_charge, 0.0)
    else:
        zone_charge = jnp.zeros((p, 1), jnp.float32)
    result = SolveResult(
        assignment=assignment,
        node_requested=req_f,
        node_estimated_used=est_f,
        node_prod_used=prod_f,
        quota_used=qused_f,
        rounds_used=rounds,
        node_dev_slots=slots_f,
        node_rdma_free=rdma_f,
        node_fpga_free=fpga_f,
        node_zone_free=zfree_f,
        pod_zone=pod_zone,
        pod_zone_charge=zone_charge,
        shortlist_fallbacks=fb_f,
    )
    if devices is not None and devices.cap_total is not None:
        # heterogeneous inventories pad the slot table with zero rows —
        # gang refunds must never water-fill onto a padding slot
        g_slots = slots0.shape[1]
        slot_exists = (
            jnp.arange(g_slots)[None, :]
            < (devices.cap_total / 100.0)[:, None]
        )
    else:
        slot_exists = None
    return enforce_gangs(result, pods, slot_exists)


@functools.partial(
    jax.jit,
    static_argnames=(
        "shortlist_k",
        "nomination_jitter",
        "numa_scoring",
        "device_scoring",
    ),
)
def shortlist_plan(
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    numa: "NumaState | None" = None,
    devices: "DeviceState | None" = None,
    node_mask: "jnp.ndarray | None" = None,
    shortlist_k: int = 64,
    nomination_jitter: float = 4.0,
    numa_scoring: "str | None" = None,
    device_scoring: "str | None" = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Standalone shortlist BUILD — the plan stage of the candidate-
    shortlist solve as its own jitted entry, so the devprof ledger can
    time it separately (the ``shortlist`` stage in ``solve_breakdown_ms``).

    Replays ``assign``'s build block: round-0 masked cost with pod-level
    gates OPEN and the DeviceShare LeastAllocated term clamped at ≤ 0,
    then per-pod top-(K+1) with the sort/slice pattern pinned (see the
    TopkRewriter note in ``assign``). Returns ``(plan_cand [P, K] int32,
    candidates ascending by node id in SOLVER pod order, plan_bound [P]
    — the (K+1)-th best build cost, +inf when the shortlist is
    complete)``. Diagnostics only: ``assign`` traces its own copy of
    this computation inside the solve jit (XLA fuses it with the round
    loop; a separate plan dispatch would cost a device round-trip per
    chunk on the hot path), so this entry never feeds decisions.
    """
    _devprof.tracing("shortlist_plan")
    p = pods.requests.shape[0]
    n = nodes.allocatable.shape[0]
    order = _priority_order(pods)
    spods = jax.tree.map(lambda a: a[order], pods)
    smask = None if node_mask is None else node_mask[order]
    feas = _feasible(spods, nodes, params, jnp.ones((p,), bool))
    if smask is not None:
        feas &= smask
    numa_score_term = None
    if numa is not None:
        from .numa import numa_fit_mask

        wants = _cpu_bind(spods)
        if spods.numa_required is not None:
            wants = wants | spods.numa_required
        feas &= numa_fit_mask(
            spods.requests,
            wants,
            numa,
            cpu_amp=nodes.cpu_amp,
            pod_required=spods.numa_required,
        )
        if numa_scoring is not None:
            numa_score_term = cost_ops.numa_aligned_cost(
                spods.requests,
                wants,
                numa.zone_free,
                numa.zone_cap,
                params.score_weights,
                most_allocated=(numa_scoring == "MostAllocated"),
            )
    if devices is not None:
        from .device import device_consumption, device_fit_mask, slot_stats

        rdma_tracked = devices.rdma_free is not None
        fpga_tracked = devices.fpga_free is not None
        dev_full, dev_partial, dev_smax, dev_total = slot_stats(
            devices.slot_free
        )
        feas &= device_fit_mask(
            spods.gpu_whole,
            spods.gpu_share,
            dev_full,
            dev_partial,
            slot_max=dev_smax,
            rdma_req=spods.rdma,
            rdma_free=devices.rdma_free if rdma_tracked else None,
            fpga_req=spods.fpga,
            fpga_free=devices.fpga_free if fpga_tracked else None,
        )
        if not rdma_tracked:
            feas &= (spods.rdma == 0)[:, None]
        if not fpga_tracked:
            feas &= (spods.fpga == 0)[:, None]
    cost = cost_ops.load_aware_cost(
        spods.estimate,
        nodes.estimated_used,
        nodes.allocatable,
        params.score_weights,
        metric_fresh=nodes.metric_fresh,
    )
    if numa_score_term is not None:
        cost = cost + numa_score_term
    if devices is not None and device_scoring is not None:
        _, sdev_total = device_consumption(spods.gpu_whole, spods.gpu_share)
        dterm = cost_ops.device_cost(
            sdev_total,
            dev_total,
            devices.cap_total,
            most_allocated=(device_scoring == "MostAllocated"),
        )
        cost = cost + jnp.minimum(dterm, 0.0)
    if nomination_jitter > 0.0:
        pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
        ni = jnp.arange(n, dtype=jnp.uint32)[None, :]
        h = _jitter_hash(pi, ni)
        cost = cost + h.astype(jnp.float32) * (nomination_jitter / 65536.0)
    cost_b = jnp.where(feas, cost, jnp.inf)
    neg_b, idx_b = jax.lax.top_k(-cost_b, shortlist_k + 1)
    neg_b, idx_b = jax.lax.optimization_barrier((neg_b, idx_b))
    plan_cand = jnp.sort(idx_b[:, :shortlist_k], axis=1).astype(jnp.int32)
    plan_bound = -neg_b[:, shortlist_k]
    return plan_cand, plan_bound


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_rounds",
        "topk",
        "cost_transform",
        "nomination_jitter",
        "approx_topk",
        "shortlist_k",
    ),
)
def solve_stream(
    pods_stacked: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    quotas: QuotaState | None = None,
    max_rounds: int = 24,
    round_quantum: float = 0.35,
    topk: int = 4,
    cost_transform=None,
    nomination_jitter: float = 4.0,
    approx_topk: bool = False,
    shortlist_k: "int | None" = None,
) -> tuple[jnp.ndarray, NodeState, jnp.ndarray, QuotaState]:
    """Pipelined multi-batch solve: ``lax.scan`` over a [B, P, ...] stacked
    ``PodBatch``, threading consumed node (and quota) capacity between
    batches entirely on device.

    This is the dispatch-latency answer to the reference's continuous
    ``scheduleOne`` loop: where the host round-trips once per *pod*
    (apiserver bind), the batched path round-trips once per *stream* —
    batch b+1's masks see batch b's commits without the host ever touching
    the arrays in between.

    Returns ``(assignments [B, P], final NodeState, placed-per-batch [B],
    final QuotaState)`` — the quota state must come back out so a second
    stream (next wave of pending pods) can thread consumption the same way
    it threads node capacity.
    """
    _devprof.tracing("solve_stream")
    quota_enabled = quotas is not None
    if quotas is None:
        quotas = QuotaState.disabled(pods_stacked.requests.shape[-1])

    def step(carry, pb):
        cur, qused = carry
        res = assign(
            pb,
            cur,
            params,
            quotas=QuotaState(runtime=quotas.runtime, used=qused)
            if quota_enabled
            else None,
            max_rounds=max_rounds,
            round_quantum=round_quantum,
            topk=topk,
            cost_transform=cost_transform,
            nomination_jitter=nomination_jitter,
            approx_topk=approx_topk,
            shortlist_k=shortlist_k,
        )
        nxt = cur.replace(
            requested=res.node_requested,
            estimated_used=res.node_estimated_used,
            prod_used=res.node_prod_used,
        )
        placed = jnp.sum(res.assignment >= 0).astype(jnp.int32)
        return (nxt, res.quota_used), (res.assignment, placed)

    (final_nodes, final_qused), (assignments, placed) = jax.lax.scan(
        step, (nodes, quotas.used), pods_stacked
    )
    final_quotas = QuotaState(runtime=quotas.runtime, used=final_qused)
    return assignments, final_nodes, placed, final_quotas


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_rounds",
        "topk",
        "nomination_jitter",
        "approx_topk",
        "numa_scoring",
        "device_scoring",
        "shortlist_k",
    ),
)
def solve_stream_full(
    pods_stacked: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    quotas: QuotaState | None = None,
    numa: "NumaState | None" = None,
    devices: "DeviceState | None" = None,
    max_rounds: int = 24,
    round_quantum: float = 0.35,
    topk: int = 4,
    nomination_jitter: float = 4.0,
    approx_topk: bool = False,
    numa_scoring: "str | None" = None,
    device_scoring: "str | None" = None,
    node_mask: "jnp.ndarray | None" = None,
    shortlist_k: "int | None" = None,
):
    """Pipelined multi-chunk solve with the FULL constraint set: a
    ``lax.scan`` over a [C, P, ...] stacked :class:`PodBatch` threading
    node capacity, the quota table, the exact GPU slot table and the
    exact NUMA zone table between chunks — ONE jitted program and one
    device→host transfer per drain. On tunneled backends every program
    launch and every fetch costs a fixed round trip, so the per-chunk
    dispatch pipeline pays C× that overhead where this pays it once
    (the per-chunk path remains for transformers/cost-transform cases).

    ``node_mask`` [C, P, N] bool (optional) carries per-chunk hard node
    constraints (nodeSelector / required nodeAffinity / spec.nodeName)
    through the scan — constrained chunks no longer force the per-chunk
    dispatch path. None traces the mask out entirely.

    Returns ``(assignments [C, P], pod_zones [C, P], rounds [C],
    shortlist_fallbacks [C, 2])`` — the fallback counts are all-zero when
    shortlisting is off (``assign`` emits a zeros sentinel so the scan's
    stacked outputs are shape-stable across configs).
    """
    _devprof.tracing("solve_stream_full")
    quota_enabled = quotas is not None
    if quotas is None:
        quotas = QuotaState.disabled(pods_stacked.requests.shape[-1])
    n = nodes.allocatable.shape[0]
    if devices is not None:
        rdma0 = (
            devices.rdma_free
            if devices.rdma_free is not None
            else jnp.zeros((n,), jnp.float32)
        )
        fpga0 = (
            devices.fpga_free
            if devices.fpga_free is not None
            else jnp.zeros((n,), jnp.float32)
        )
        dev_carry0 = (devices.slot_free, rdma0, fpga0)
    else:
        dev_carry0 = None
    numa_carry0 = numa.zone_free if numa is not None else None

    def step(carry, xs):
        pb, chunk_mask = xs if node_mask is not None else (xs, None)
        cur, qused, dev_carry, numa_carry = carry
        res = assign(
            pb,
            cur,
            params,
            quotas=(
                QuotaState(runtime=quotas.runtime, used=qused)
                if quota_enabled
                else None
            ),
            numa=numa,
            devices=devices,
            max_rounds=max_rounds,
            round_quantum=round_quantum,
            topk=topk,
            nomination_jitter=nomination_jitter,
            approx_topk=approx_topk,
            node_mask=chunk_mask,
            dev_carry=dev_carry,
            numa_carry=numa_carry,
            numa_scoring=numa_scoring,
            device_scoring=device_scoring,
            shortlist_k=shortlist_k,
        )
        nxt = cur.replace(
            requested=res.node_requested,
            estimated_used=res.node_estimated_used,
            prod_used=res.node_prod_used,
        )
        new_dev = (
            (res.node_dev_slots, res.node_rdma_free, res.node_fpga_free)
            if devices is not None
            else dev_carry
        )
        new_numa = res.node_zone_free if numa is not None else numa_carry
        return (nxt, res.quota_used, new_dev, new_numa), (
            res.assignment,
            res.pod_zone,
            res.rounds_used,
            res.shortlist_fallbacks,
        )

    xs = (
        pods_stacked if node_mask is None else (pods_stacked, node_mask)
    )
    _final, (assignments, zones, rounds, fallbacks) = jax.lax.scan(
        step, (nodes, quotas.used, dev_carry0, numa_carry0), xs
    )
    return assignments, zones, rounds, fallbacks


@jax.jit
def enforce_gangs(
    result: SolveResult,
    pods: PodBatch,
    slot_exists: "jnp.ndarray | None" = None,
) -> SolveResult:
    """All-or-nothing gang rollback (Coscheduling Permit semantics,
    reference ``pkg/scheduler/plugins/coscheduling/core/core.go:346-465``:
    bound-ready pods are held until the whole gang passes, otherwise the
    gang group is rejected and re-queued).

    Gangs whose placed-member count is below ``minMember`` have all their
    placements rolled back and their capacity returned, exactly like the
    reference rejecting a gang at Permit and cycling it back to the queue
    — unless the gang is **NonStrict** (AnnotationGangMode,
    ``apis/extension/coscheduling.go:40-53``): NonStrict gangs keep their
    successfully-placed members on partial placement
    (``coscheduling/core/core.go:333`` only rejects the group in Strict
    mode).
    """
    # no tracing hook on purpose: every call site is inside another
    # jitted entry point's trace, so a hook here would double-bill each
    # outer (re)trace in the CompileLedger — nested jits are sub-jaxprs
    # of the entry point whose hook already fired (koordlint's
    # retrace-hazard pass requires hooks on host-DISPATCHED jits only)
    p = pods.requests.shape[0]
    n = result.node_requested.shape[0]
    assignment = result.assignment
    placed = assignment >= 0
    has_gang = pods.gang_id >= 0
    gid = jnp.clip(pods.gang_id, 0, p - 1)
    counts = jax.ops.segment_sum(
        (placed & has_gang).astype(jnp.int32), gid, num_segments=p
    )
    gang_ok = (counts >= pods.gang_min) | pods.gang_nonstrict
    keep = placed & (~has_gang | gang_ok[gid])
    rollback = placed & ~keep

    node_of = jnp.clip(assignment, 0, n - 1)
    zero = jnp.zeros_like(pods.requests)
    dreq = jax.ops.segment_sum(
        jnp.where(rollback[:, None], pods.requests, zero),
        jnp.where(rollback, node_of, n - 1),
        num_segments=n,
    )
    dest = jax.ops.segment_sum(
        jnp.where(rollback[:, None], pods.estimate, zero),
        jnp.where(rollback, node_of, n - 1),
        num_segments=n,
    )
    dprod = jax.ops.segment_sum(
        jnp.where((rollback & pods.is_prod)[:, None], pods.estimate, zero),
        jnp.where(rollback & pods.is_prod, node_of, n - 1),
        num_segments=n,
    )
    # refund rolled-back pods' GPU/RDMA/FPGA consumption so the chained
    # per-slot table stays usable across chunks (water-fill: exact for
    # whole-GPU members, conservative for fractional — see slot_refund)
    node_dev_slots = result.node_dev_slots
    node_rdma_free = result.node_rdma_free
    node_fpga_free = result.node_fpga_free
    if node_dev_slots is not None:
        from .device import slot_refund

        seg = jnp.where(rollback, node_of, n - 1)
        whole = pods.gpu_whole.astype(jnp.float32)
        refund = jax.ops.segment_sum(
            jnp.where(rollback, whole * 100.0 + pods.gpu_share, 0.0),
            seg,
            num_segments=n,
        )
        node_dev_slots = slot_refund(node_dev_slots, refund, slot_exists)
        if node_rdma_free is not None:
            node_rdma_free = node_rdma_free + jax.ops.segment_sum(
                jnp.where(rollback, pods.rdma.astype(jnp.float32), 0.0),
                seg,
                num_segments=n,
            )
        if node_fpga_free is not None:
            node_fpga_free = node_fpga_free + jax.ops.segment_sum(
                jnp.where(rollback, pods.fpga.astype(jnp.float32), 0.0),
                seg,
                num_segments=n,
            )
    # refund rolled-back pods' exact zone charges and clear their picks
    node_zone_free = result.node_zone_free
    pod_zone = result.pod_zone
    pod_zone_charge = result.pod_zone_charge
    if node_zone_free is not None and pod_zone is not None:
        n_zones = node_zone_free.shape[1]
        dn_z = node_zone_free.shape[2]
        if pod_zone_charge is not None and pod_zone_charge.shape[1] == dn_z:
            zref = rollback & (pod_zone >= 0)
            seg_z = jnp.where(zref, node_of, n - 1)
            z_onehot = (
                jnp.arange(n_zones, dtype=jnp.int32)[None, :]
                == jnp.clip(pod_zone, 0, n_zones - 1)[:, None]
            ) & zref[:, None]
            zdelta = z_onehot[:, :, None] * pod_zone_charge[:, None, :]
            node_zone_free = node_zone_free + jax.ops.segment_sum(
                zdelta, seg_z, num_segments=n
            )
        pod_zone = jnp.where(rollback, -1, pod_zone)
    # Refund quota charges of rolled-back pods along their chains.
    # (Q == 1 is the disabled sentinel — real trees are padded to Q ≥ 2.)
    quota_used = result.quota_used
    q_cap = quota_used.shape[0]
    for level in range(pods.quota_chain.shape[1] if q_cap > 1 else 0):
        key_raw = pods.quota_chain[:, level]
        refund = rollback & (key_raw >= 0)
        quota_used = quota_used - jax.ops.segment_sum(
            jnp.where(refund[:, None], pods.requests, zero),
            jnp.where(refund, key_raw, q_cap - 1),
            num_segments=q_cap,
        )
    return SolveResult(
        assignment=jnp.where(keep, assignment, -1),
        node_requested=result.node_requested - dreq,
        node_estimated_used=result.node_estimated_used - dest,
        node_prod_used=result.node_prod_used - dprod,
        quota_used=quota_used,
        rounds_used=result.rounds_used,
        node_dev_slots=node_dev_slots,
        node_rdma_free=node_rdma_free,
        node_fpga_free=node_fpga_free,
        node_zone_free=node_zone_free,
        pod_zone=pod_zone,
        pod_zone_charge=pod_zone_charge,
        shortlist_fallbacks=result.shortlist_fallbacks,
    )


@functools.partial(jax.jit, static_argnames=("shortlist_k",))
def assign_sequential(
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    quotas: QuotaState | None = None,
    shortlist_k: "int | None" = None,
) -> SolveResult:
    """Exact sequential-commit solver: ``lax.scan`` over pods in priority
    order, vectorized over nodes inside each step. Bit-faithful to the
    reference's one-pod-at-a-time cycle (the golden contract; SURVEY §7
    step 2 "batched masked argmin with capacity-consuming sequential
    commit (scan)").

    ``shortlist_k`` prunes each step's node axis to the pod's top-K
    build-time candidates (the sequential analog of ``assign``'s
    candidate shortlist). The exactness bound here is on the SCORE side:
    usage only grows as pods commit, so an excluded node's build-time
    score UPPER-bounds its score at every later step — a step whose best
    shortlisted score strictly beats the (K+1)-th build score cannot
    have lost to any excluded node (strict ``>`` so an excluded node
    tying the winner, which could out-rank it by lower node id, forces
    the full-axis step instead). Decisions are identical either way."""
    _devprof.tracing("assign_sequential")
    p = pods.requests.shape[0]
    n = nodes.allocatable.shape[0]
    quota_enabled = quotas is not None
    if quotas is None:
        quotas = QuotaState.disabled(pods.requests.shape[1])
    q_cap = quotas.runtime.shape[0]
    order = _priority_order(pods)
    spods = jax.tree.map(lambda a: a[order], pods)

    amp = jnp.maximum(nodes.cpu_amp, 1.0)
    thr_full = mask_ops.effective_thresholds(
        params.usage_thresholds, nodes.custom_thresholds
    )
    pthr_full = mask_ops.effective_thresholds(
        params.prod_thresholds, nodes.custom_prod_thresholds
    )
    w_sum = jnp.sum(params.score_weights) + 1e-9

    def node_score(after, alloc, fresh):
        """The step's LeastAllocated score over any (gathered or full)
        node axis — elementwise, so gathering commutes with it."""
        frees = jnp.maximum(alloc - after, 0.0)
        per_dim = jnp.floor(
            jnp.where(alloc > 0, frees * 100.0 / (alloc + 1e-9), 0.0)
        )
        score = jnp.floor(
            jnp.sum(per_dim * params.score_weights, axis=-1) / w_sum
        )
        return jnp.where(fresh, score, 0.0)

    shortlist_on = shortlist_k is not None and 0 < shortlist_k < n
    if shortlist_on:
        # Build from the initial tables, pod-level gates open. Usage only
        # grows step over step, so every excluded node's build score is
        # an upper bound on its score at any later step, and build
        # infeasibility is permanent — the (K+1)-th best build score is
        # the escape-hatch bound. -inf ⇒ the shortlist is COMPLETE.
        free0 = nodes.allocatable - nodes.requested
        bind0 = _cpu_bind(spods)
        feas0 = mask_ops.fit_mask(spods.requests, free0)
        eff_cpu0 = spods.requests[:, 0][:, None] * amp[None, :]
        feas0 &= ~bind0[:, None] | (eff_cpu0 <= free0[:, 0][None, :] + EPS)
        after0 = nodes.estimated_used[None, :, :] + spods.estimate[:, None, :]
        over0 = (thr_full[None] > 0.0) & (
            mask_ops.usage_percent(after0, nodes.allocatable[None])
            > thr_full[None]
        )
        feas0 &= ~(nodes.metric_fresh[None, :] & jnp.any(over0, axis=-1))
        pafter0 = nodes.prod_used[None, :, :] + spods.estimate[:, None, :]
        pover0 = (pthr_full[None] > 0.0) & (
            mask_ops.usage_percent(pafter0, nodes.allocatable[None])
            > pthr_full[None]
        )
        feas0 &= (
            ~(
                spods.is_prod[:, None]
                & nodes.metric_fresh[None, :]
                & jnp.any(pover0, axis=-1)
            )
            | ~spods.is_prod[:, None]
        )
        feas0 &= nodes.schedulable[None, :]
        score0 = node_score(
            after0, nodes.allocatable[None], nodes.metric_fresh[None]
        )
        score0 = jnp.where(feas0, score0, -jnp.inf)
        top_s, idx_s = jax.lax.top_k(score0, shortlist_k + 1)
        # same TopkRewriter hazard as assign's build: asymmetric slicing
        # of the two outputs defeats the sort+slice→TopK rewrite
        top_s, idx_s = jax.lax.optimization_barrier((top_s, idx_s))
        plan_cand = jnp.sort(idx_s[:, :shortlist_k], axis=1).astype(jnp.int32)
        plan_bound = top_s[:, shortlist_k]

    def step(carry, xs):
        requested, est_used, prod_used, qused, fb = carry
        if shortlist_on:
            req, est, is_prod, valid, qchain, bind, cand, bound = xs
        else:
            req, est, is_prod, valid, qchain, bind = xs
        # quota admission along the chain (pod-level, node-independent)
        qidx = jnp.clip(qchain, 0, q_cap - 1)
        q_valid = qchain >= 0
        pod_gate = valid
        if quota_enabled:
            pod_gate &= jnp.all(
                jnp.all(
                    qused[qidx] + req[None, :] <= quotas.runtime[qidx] + EPS,
                    axis=-1,
                )
                | ~q_valid
            )

        def full_nominate(_):
            free = nodes.allocatable - requested
            # per-node effective request: cpuset-bound pods' CPU ×ratio
            # on amplified nodes (filterAmplifiedCPUs, plugin.go:408-443)
            req_eff = jnp.broadcast_to(req[None, :], free.shape)
            req_eff = req_eff.at[:, 0].multiply(jnp.where(bind, amp, 1.0))
            feas = jnp.all(req_eff <= free + EPS, axis=-1)
            over = (thr_full > 0.0) & (
                mask_ops.usage_percent(
                    est_used + est[None, :], nodes.allocatable
                )
                > thr_full
            )
            feas &= ~(nodes.metric_fresh & jnp.any(over, axis=-1))
            pover = (pthr_full > 0.0) & (
                mask_ops.usage_percent(
                    prod_used + est[None, :], nodes.allocatable
                )
                > pthr_full
            )
            feas &= (
                ~(is_prod & nodes.metric_fresh & jnp.any(pover, axis=-1))
                | ~is_prod
            )
            feas &= nodes.schedulable & pod_gate
            score = node_score(
                est_used + est[None, :], nodes.allocatable, nodes.metric_fresh
            )
            score = jnp.where(feas, score, -jnp.inf)
            best = jnp.argmax(score).astype(jnp.int32)
            return best, feas[best]

        if shortlist_on:
            # gathered-column step over the pod's K candidates — the
            # same elementwise arithmetic as full_nominate, so a
            # candidate scores identically on both paths
            alloc_c = nodes.allocatable[cand]
            fresh_c = nodes.metric_fresh[cand]
            free_c = alloc_c - requested[cand]
            feas_c = jnp.all(req[None, :] <= free_c + EPS, axis=-1)
            feas_c &= ~bind | (req[0] * amp[cand] <= free_c[:, 0] + EPS)
            est_c = est_used[cand] + est[None, :]
            thr_c = thr_full[cand]
            over_c = (thr_c > 0.0) & (
                mask_ops.usage_percent(est_c, alloc_c) > thr_c
            )
            feas_c &= ~(fresh_c & jnp.any(over_c, axis=-1))
            pthr_c = pthr_full[cand]
            pover_c = (pthr_c > 0.0) & (
                mask_ops.usage_percent(prod_used[cand] + est[None, :], alloc_c)
                > pthr_c
            )
            feas_c &= ~(is_prod & fresh_c & jnp.any(pover_c, axis=-1)) | ~is_prod
            feas_c &= nodes.schedulable[cand] & pod_gate
            score_c = jnp.where(
                feas_c, node_score(est_c, alloc_c, fresh_c), -jnp.inf
            )
            bpos = jnp.argmax(score_c).astype(jnp.int32)
            sc_best = score_c[bpos]
            cand_any = jnp.isfinite(sc_best)
            # safe ⇔ the shortlist provably contains the full-axis argmax:
            # complete shortlist, or strictly beating every excluded
            # node's score upper bound; a gated-out pod places nowhere on
            # either path. Candidates ascend by node id, so the
            # positional argmax tie-break equals the full-axis one.
            safe = (
                jnp.isneginf(bound) | (sc_best > bound) | ~pod_gate
            )
            unsafe = ~safe
            best, has = jax.lax.cond(
                unsafe,
                full_nominate,
                lambda _: (cand[bpos], feas_c[bpos]),
                None,
            )
            fb = fb + jnp.stack(
                [unsafe & cand_any, unsafe & ~cand_any]
            ).astype(jnp.int32)
        else:
            best, has = full_nominate(None)
        # commit row: the winner's effective request (amplified CPU for
        # cpuset-bound pods) scattered onto the full-axis tables
        req_commit = req.at[0].multiply(jnp.where(bind, amp[best], 1.0))
        onehot = (jnp.arange(n) == best)[:, None] & has
        requested = requested + jnp.where(onehot, req_commit[None, :], 0.0)
        est_used = est_used + jnp.where(onehot, est[None, :], 0.0)
        prod_used = prod_used + jnp.where(onehot & is_prod, est[None, :], 0.0)
        if quota_enabled:
            charge = (
                (jnp.arange(q_cap)[:, None] == qidx[None, :])
                & q_valid[None, :]
                & has
            )
            qused = qused + jnp.any(charge, axis=1)[:, None] * req[None, :]
        return (requested, est_used, prod_used, qused, fb), jnp.where(
            has, best, -1
        )

    xs = (
        spods.requests,
        spods.estimate,
        spods.is_prod,
        spods.valid,
        spods.quota_chain,
        _cpu_bind(spods),
    )
    if shortlist_on:
        xs = xs + (plan_cand, plan_bound)
    (req_f, est_f, prod_f, qused_f, fb_f), assigned_s = jax.lax.scan(
        step,
        (
            nodes.requested,
            nodes.estimated_used,
            nodes.prod_used,
            quotas.used,
            jnp.zeros((2,), jnp.int32),
        ),
        xs,
    )
    assignment = jnp.full((p,), -1, jnp.int32).at[order].set(assigned_s)
    result = SolveResult(
        assignment=assignment,
        node_requested=req_f,
        node_estimated_used=est_f,
        node_prod_used=prod_f,
        quota_used=qused_f,
        rounds_used=jnp.array(p, jnp.int32),
        node_dev_slots=jnp.zeros((n, 1), jnp.float32),
        node_rdma_free=jnp.zeros((n,), jnp.float32),
        node_fpga_free=jnp.zeros((n,), jnp.float32),
        node_zone_free=jnp.zeros((n, 1, 1), jnp.float32),
        pod_zone=jnp.full((p,), -1, jnp.int32),
        pod_zone_charge=jnp.zeros((p, 1), jnp.float32),
        shortlist_fallbacks=fb_f,
    )
    return enforce_gangs(result, pods)
