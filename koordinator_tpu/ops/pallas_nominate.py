"""Fused nomination kernel (Pallas/TPU): masked LoadAware cost + jitter +
streaming top-K in one pass over node tiles.

The XLA nomination path materializes the [P, N] cost block in HBM (several
times: cost, jitter-added, masked) before top-k reads it back. This kernel
streams node tiles through VMEM, carrying each pod row's running top-K
candidates in scratch — HBM traffic is the *inputs* ([P, D] pods, [N, D]
nodes) plus [P, K] outputs, independent of N·P. That's the same
flash-attention-style trade the pallas guide's double-buffering pattern
describes: recompute in VMEM instead of round-tripping the big intermediate.

Used for single-chip node tables big enough that the [P, N] intermediates
pressure HBM (the sharded shard_map path covers the multi-chip case; both
share this kernel's semantics). Interpret mode keeps the CPU test suite
honest; numerics match the XLA nomination bit-for-bit in f32.

Measured (v5e, P=16384, N=10240, K=4): 9.9 ms/iter vs ~5 ms for the
XLA fused cost+approx_max_k — the K selection sweeps cost K extra passes
over each tile, so on HBM-comfortable shapes the XLA path stays the
default (ops.solver uses it); this kernel is the O(P·K)-memory variant
for node tables whose [P, N] intermediates would not fit, and the
foundation for fusing the commit phase next.

Reference behavior being fused (see ops.solver.assign round_body):
  cost  = load_aware_cost(...)                 (costs.py / load_aware.go:387)
  cost += jitter hash (Knuth multiplicative)   (solver.add_jitter)
  cost  = inf where infeasible                 (masks.fit/usage/schedulable)
  top_k(-cost, K)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

TILE_P = 128
TILE_N = 128
_NEG_INF = -3.0e38


def _kernel(
    params_ref,       # SMEM [3, D]  (usage_thresholds, weights, _pad)
    pod_est_ref,      # [TILE_P, D]
    node_alloc_ref,   # [D, TILE_N]  — node tables arrive TRANSPOSED so a
    node_req_ref,     # [D, TILE_N]    dim slice is a natural lane vector;
    node_est_ref,     # [D, TILE_N]    [N, D] would make every per-dim read
    node_flags_ref,   # [2, TILE_N]    a sublane->lane transpose (measured:
    pod_req_ref,      # [TILE_P, D]    40M of scoped-VMEM spill)
    neg_out_ref,      # [K, TILE_P]  (K in sublanes: a [P, K] layout would
    idx_out_ref,      # [K, TILE_P]   pad K's 4 lanes to 128 — 32x VMEM)
    vals_scratch,     # VMEM [K, TILE_P] f32
    idx_scratch,      # VMEM [K, TILE_P] i32
    *,
    dims: int,
    k: int,
    jitter: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        vals_scratch[:] = jnp.full((k, TILE_P), _NEG_INF, jnp.float32)
        idx_scratch[:] = jnp.full((k, TILE_P), -1, jnp.int32)

    i = pl.program_id(0)
    g_pod = i * TILE_P + jax.lax.broadcasted_iota(jnp.int32, (TILE_P, TILE_N), 0)
    g_node = j * TILE_N + jax.lax.broadcasted_iota(jnp.int32, (TILE_P, TILE_N), 1)

    score = jnp.zeros((TILE_P, TILE_N), jnp.float32)
    wsum = jnp.float32(1e-9)
    feas = node_flags_ref[0:1, :] > 0.5                   # [1, TN] schedulable
    fresh = node_flags_ref[1:2, :] > 0.5
    over = jnp.zeros((TILE_P, TILE_N), dtype=jnp.bool_)
    for d in range(dims):
        alloc = node_alloc_ref[d : d + 1, :]              # [1, TN]
        req_free = alloc - node_req_ref[d : d + 1, :]
        pod_req = pod_req_ref[:, d : d + 1]               # [TP, 1]
        pod_est = pod_est_ref[:, d : d + 1]
        feas = feas & (pod_req <= req_free + 1e-3)  # masks.EPS slack
        after = node_est_ref[d : d + 1, :] + pod_est      # [TP, TN]
        thr = params_ref[0, d]
        # rounded-percent threshold check (masks.usage_percent semantics)
        pct = jnp.floor(
            jnp.where(alloc > 0, after * 100.0 / alloc, 0.0) + 0.5
        )
        over |= (thr > 0.0) & (pct > thr)
        w = params_ref[1, d]
        frac = jnp.floor(
            jnp.where(
                alloc > 0,
                jnp.maximum(alloc - after, 0.0) * 100.0 / (alloc + 1e-9),
                0.0,
            )
        )
        score = score + frac * w
        wsum = wsum + w
    feas = feas & ~(fresh & over)
    # reference integer-floor scoring; expired metric scores 0 (see
    # ops.costs.load_aware_cost)
    score = jnp.where(fresh, jnp.floor(score / wsum), 0.0)
    cost = -score
    if jitter > 0.0:
        # int32 wraparound arithmetic is bit-identical to the solver's
        # uint32 hash after the & 0xFFFF fold (two's complement low bits);
        # Mosaic has no uint32->f32 cast, int32->f32 lowers fine.
        h = (
            g_pod * jnp.int32(-1640531535) + g_node * jnp.int32(40503)
        ) & jnp.int32(0xFFFF)
        cost = cost + h.astype(jnp.float32) * (jitter / 65536.0)
    neg = jnp.where(feas, -cost, _NEG_INF)                # maximize -cost

    # two-stage streaming top-K: (1) K selection sweeps over the tile
    # block, (2) merge the tile's K-list with the carried K-list. The
    # K-lists live [K, TP] — K in sublanes, pods in lanes — so every
    # cross-list op is a cheap sublane reduction and nothing pads K to
    # 128 lanes.
    node_idx = g_node.astype(jnp.int32)
    col = jax.lax.broadcasted_iota(jnp.int32, neg.shape, 1)
    tile_vals = []
    tile_idxs = []
    blk = neg
    for _ in range(k):
        best = jnp.max(blk, axis=1)                                  # [TP]
        am = jnp.argmax(blk, axis=1).astype(jnp.int32)
        onehot = col == am[:, None]
        tile_vals.append(best)
        # gather via one-hot reduce (Mosaic has no arbitrary gather)
        tile_idxs.append(jnp.sum(jnp.where(onehot, node_idx, 0), axis=1))
        blk = jnp.where(onehot, _NEG_INF, blk)
    vals = jnp.concatenate(
        [vals_scratch[:], jnp.stack(tile_vals, axis=0)], axis=0
    )                                                                # [2K, TP]
    idxs = jnp.concatenate(
        [idx_scratch[:], jnp.stack(tile_idxs, axis=0)], axis=0
    )
    row = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 0)
    merged_vals = []
    merged_idxs = []
    for _ in range(k):
        best = jnp.max(vals, axis=0)                                 # [TP]
        am = jnp.argmax(vals, axis=0).astype(jnp.int32)
        onehot = row == am[None, :]
        merged_vals.append(best)
        merged_idxs.append(jnp.sum(jnp.where(onehot, idxs, 0), axis=0))
        vals = jnp.where(onehot, _NEG_INF, vals)
    vals_scratch[:] = jnp.stack(merged_vals, axis=0)
    idx_scratch[:] = jnp.stack(merged_idxs, axis=0)

    @pl.when(j == nj - 1)
    def _emit():
        neg_out_ref[:] = vals_scratch[:]
        idx_out_ref[:] = jnp.where(
            vals_scratch[:] <= _NEG_INF / 2, -1, idx_scratch[:]
        )


@functools.partial(
    jax.jit, static_argnames=("topk", "nomination_jitter", "interpret")
)
def nominate_fused(
    pod_requests: jnp.ndarray,     # [P, D]
    pod_estimate: jnp.ndarray,     # [P, D]
    node_allocatable: jnp.ndarray, # [N, D]
    node_requested: jnp.ndarray,   # [N, D]
    node_est_used: jnp.ndarray,    # [N, D]
    schedulable: jnp.ndarray,      # [N] bool
    metric_fresh: jnp.ndarray,     # [N] bool
    usage_thresholds: jnp.ndarray, # [D]
    score_weights: jnp.ndarray,    # [D]
    topk: int = 4,
    nomination_jitter: float = 4.0,
    interpret: bool = False,
):
    """Returns (neg_top [P, K] f32, node_idx [P, K] i32, -1 = no candidate).

    Pads P to TILE_P and N to TILE_N multiples; padded nodes are marked
    unschedulable so they can never be nominated.
    """
    p, d = pod_requests.shape
    n = node_allocatable.shape[0]
    pp = -(-p // TILE_P) * TILE_P
    nn = -(-n // TILE_N) * TILE_N

    def pad(a, rows, fill=0.0):
        return jnp.pad(a, ((0, rows - a.shape[0]),) + ((0, 0),) * (a.ndim - 1),
                       constant_values=fill)

    pod_req = pad(jnp.asarray(pod_requests, jnp.float32), pp)
    pod_est = pad(jnp.asarray(pod_estimate, jnp.float32), pp)
    alloc = pad(jnp.asarray(node_allocatable, jnp.float32), nn).T
    req = pad(jnp.asarray(node_requested, jnp.float32), nn).T
    est = pad(jnp.asarray(node_est_used, jnp.float32), nn).T
    flags = jnp.stack(
        [
            pad(jnp.asarray(schedulable, jnp.float32), nn),
            pad(jnp.asarray(metric_fresh, jnp.float32), nn),
        ],
        axis=0,
    )
    params = jnp.stack(
        [
            jnp.asarray(usage_thresholds, jnp.float32),
            jnp.asarray(score_weights, jnp.float32),
            jnp.zeros((d,), jnp.float32),
        ]
    )

    from jax.experimental.pallas import tpu as pltpu

    grid = (pp // TILE_P, nn // TILE_N)
    kernel = functools.partial(
        _kernel, dims=d, k=topk, jitter=nomination_jitter
    )
    neg, idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # params
            pl.BlockSpec((TILE_P, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, TILE_N), lambda i, j: (0, j)),
            pl.BlockSpec((d, TILE_N), lambda i, j: (0, j)),
            pl.BlockSpec((d, TILE_N), lambda i, j: (0, j)),
            pl.BlockSpec((2, TILE_N), lambda i, j: (0, j)),
            pl.BlockSpec((TILE_P, d), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((topk, TILE_P), lambda i, j: (0, i)),
            pl.BlockSpec((topk, TILE_P), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((topk, pp), jnp.float32),
            jax.ShapeDtypeStruct((topk, pp), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((topk, TILE_P), jnp.float32),
            pltpu.VMEM((topk, TILE_P), jnp.int32),
        ],
        interpret=interpret,
    )(params, pod_est, alloc, req, est, flags, pod_req)
    return neg.T[:p], idx.T[:p]
