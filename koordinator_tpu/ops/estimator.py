"""Pod usage estimation (reference ``pkg/scheduler/plugins/loadaware/estimator/
default_estimator.go:59-122``).

The reference's DefaultEstimator scales a pod's requests by per-resource
factors (CPU 85%, memory 70% by default) to estimate its post-bind usage;
priority bands below prod fall back to smaller defaults. Here it is a pure
vectorized function over the dense resource axis.
"""

from __future__ import annotations

from typing import Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from ..api import extension as ext

#: default scaling factors by resource name (DefaultMilliCPURequest /
#: DefaultMemoryRequest analogs use the same axis; unknown dims scale 1.0)
DEFAULT_SCALE_FACTORS: Mapping[str, float] = {
    ext.RES_CPU: 0.85,
    ext.RES_MEMORY: 0.70,
    ext.RES_BATCH_CPU: 0.85,
    ext.RES_BATCH_MEMORY: 0.70,
}


def scale_vector(
    resources: Tuple[str, ...],
    overrides: Mapping[str, float] | None = None,
) -> np.ndarray:
    """Build the [D] scale-factor vector for a snapshot's resource axis."""
    table = dict(DEFAULT_SCALE_FACTORS)
    if overrides:
        table.update(overrides)
    return np.array([table.get(r, 1.0) for r in resources], np.float32)


def estimate_pod_usage(requests: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Estimated usage of pending pods: ``requests * scale`` ([..., D])."""
    return requests * scale
