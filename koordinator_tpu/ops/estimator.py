"""Pod usage estimation (reference ``pkg/scheduler/plugins/loadaware/estimator/
default_estimator.go:59-122``).

The reference's DefaultEstimator scales a pod's requests by per-resource
factors (CPU 85%, memory 70% by default) to estimate its post-bind usage;
priority bands below prod fall back to smaller defaults. Here it is a pure
vectorized function over the dense resource axis.
"""

from __future__ import annotations

from typing import Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from ..api import extension as ext

#: default scaling factors by resource name (DefaultMilliCPURequest /
#: DefaultMemoryRequest analogs use the same axis; unknown dims scale 1.0)
DEFAULT_SCALE_FACTORS: Mapping[str, float] = {
    ext.RES_CPU: 0.85,
    ext.RES_MEMORY: 0.70,
    ext.RES_BATCH_CPU: 0.85,
    ext.RES_BATCH_MEMORY: 0.70,
}


def scale_vector(
    resources: Tuple[str, ...],
    overrides: Mapping[str, float] | None = None,
) -> np.ndarray:
    """Build the [D] scale-factor vector for a snapshot's resource axis."""
    table = dict(DEFAULT_SCALE_FACTORS)
    if overrides:
        table.update(overrides)
    return np.array([table.get(r, 1.0) for r in resources], np.float32)


def estimate_pod_usage(requests: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Estimated usage of pending pods: ``requests * scale`` ([..., D])."""
    return requests * scale


#: zero-request floors (default_estimator.go:35-39 DefaultMilliCPURequest /
#: DefaultMemoryRequest = 200*1024*1024 bytes ≡ 200 MiB in snapshot units)
DEFAULT_MILLI_CPU_REQUEST = 250.0
DEFAULT_MEMORY_REQUEST_MIB = 200.0
_DEFAULT_FLOORS: Mapping[str, float] = {
    ext.RES_CPU: DEFAULT_MILLI_CPU_REQUEST,
    ext.RES_BATCH_CPU: DEFAULT_MILLI_CPU_REQUEST,
    ext.RES_MEMORY: DEFAULT_MEMORY_REQUEST_MIB,
    ext.RES_BATCH_MEMORY: DEFAULT_MEMORY_REQUEST_MIB,
}


def estimate_pod(config, pod, scale: np.ndarray) -> np.ndarray:
    """Reference-exact single-pod estimate (``estimatedUsedByResource``,
    ``default_estimator.go:88-123``): base = max(request, limit), scaled
    and rounded, capped at the limit; a dim with neither request nor limit
    estimates at the default floor (250m cpu / 200Mi memory) — an
    unspecified pod is never free. A pod may override individual scaling
    factors via the load-estimated-scaling-factors annotation, in percent
    (``default_estimator.go:60-64``). [D] numpy."""
    custom = ext.parse_custom_estimated_scaling_factors(
        pod.meta.annotations
    )
    if custom:
        scale = np.array(scale, np.float32, copy=True)
        for name, pct in custom.items():
            if name in config.resources:
                scale[config.resources.index(name)] = pct / 100.0
    req = config.res_vector(pod.spec.requests)
    lim = config.res_vector(pod.spec.limits)
    base = np.maximum(req, lim)
    # floor(x+0.5) = Go math.Round for non-negative values (np.round would
    # round half to even — same convention note as masks.usage_percent)
    est = np.floor(base * scale + 0.5)
    est = np.where(lim > 0, np.minimum(est, lim), est)
    # The floor covers only the pod's own tier dims — the reference
    # iterates resourceWeights (cpu, memory) with the resource name
    # translated by priority class (TranslateResourceNameByPriorityClass),
    # so a batch pod floors batch-cpu/batch-memory, everyone else cpu/memory.
    if pod.priority_class == ext.PriorityClass.BATCH:
        tier = (ext.RES_BATCH_CPU, ext.RES_BATCH_MEMORY)
    else:
        tier = (ext.RES_CPU, ext.RES_MEMORY)
    floors = np.array(
        [_DEFAULT_FLOORS.get(r, 0.0) if r in tier else 0.0 for r in config.resources],
        np.float32,
    )
    return np.where(base > 0, est, floors).astype(np.float32)
