"""Filter predicates as boolean masks over (pods × nodes).

Each reference Filter plugin becomes a mask builder:
  * NodeResourcesFit            → :func:`fit_mask`
  * LoadAwareScheduling.Filter  → :func:`usage_threshold_mask`
    (reference ``pkg/scheduler/plugins/loadaware/load_aware.go:122-186,290-313``)

Masks compose by logical AND; `True` means feasible. All functions are pure
and jit-safe; the (P, N, D) intermediates are fused by XLA into the (P, N)
reduction so nothing of rank 3 is materialized in HBM.
"""

from __future__ import annotations

import jax.numpy as jnp

EPS = 1e-3  # float32 slack for large-magnitude resource dims (MiB, milli-cpu)


def usage_percent(used: jnp.ndarray, allocatable: jnp.ndarray) -> jnp.ndarray:
    """Utilization as the reference computes it for threshold checks:
    ``int64(math.Round(used/total*100))`` (``load_aware.go
    filterNodeUsage``) — a node at 65.4% passes a 65% threshold. Go's
    math.Round is half-away-from-zero; values are non-negative here so
    floor(x + 0.5) reproduces it (jnp.round would round half to even)."""
    pct = jnp.where(allocatable > 0, used * 100.0 / allocatable, 0.0)
    return jnp.floor(pct + 0.5)


def fit_mask(pod_req: jnp.ndarray, node_free: jnp.ndarray) -> jnp.ndarray:
    """NodeResourcesFit: every requested dim fits in node free capacity.

    pod_req:   [P, D]; node_free: [N, D] (allocatable - requested).
    Returns [P, N] bool.
    """
    return jnp.all(pod_req[:, None, :] <= node_free[None, :, :] + EPS, axis=-1)


def effective_thresholds(
    thresholds: jnp.ndarray,
    node_custom: jnp.ndarray | None,
) -> jnp.ndarray:
    """[N, D] effective per-node thresholds: a node carrying a non-empty
    usage-thresholds annotation replaces the plugin-args global map
    WHOLESALE — dims absent from the custom map (0 here) go unchecked on
    that node (reference ``load_aware.go`` GetCustomUsageThresholds /
    filterNodeUsage replace the whole map)."""
    if node_custom is None:
        return thresholds[None, :]
    has_custom = jnp.any(node_custom > 0.0, axis=-1, keepdims=True)  # [N, 1]
    return jnp.where(has_custom, node_custom, thresholds[None, :])


def usage_threshold_mask(
    pod_estimate: jnp.ndarray,
    node_estimated_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    thresholds: jnp.ndarray,
    metric_fresh: jnp.ndarray,
    node_custom: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """LoadAware Filter: reject nodes whose estimated utilization after
    placing the pod exceeds the per-resource threshold.

    Mirrors ``load_aware.go:290-313``: for each dim with threshold > 0,
    ``round((estimatedUsed + podEstimate)·100/allocatable) > threshold``
    ⇒ reject (the rounded-percent comparison is the reference's boundary
    semantics — see :func:`usage_percent`). Nodes with an expired
    NodeMetric skip the usage check (degraded mode,
    ``load_aware.go:143-149``) — the fit mask still applies.

    pod_estimate: [P, D]; node_estimated_used/allocatable: [N, D];
    thresholds: [D] in percent (0 disables the dim); metric_fresh: [N] bool.
    Returns [P, N] bool.
    """
    after = node_estimated_used[None, :, :] + pod_estimate[:, None, :]
    pct = usage_percent(after, node_allocatable[None, :, :])
    thr = effective_thresholds(thresholds, node_custom)[None, :, :]
    over = (thr > 0.0) & (pct > thr)
    ok = ~jnp.any(over, axis=-1)
    return ok | ~metric_fresh[None, :]


def prod_usage_threshold_mask(
    pod_is_prod: jnp.ndarray,
    pod_estimate: jnp.ndarray,
    node_prod_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    prod_thresholds: jnp.ndarray,
    metric_fresh: jnp.ndarray,
    node_custom: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """LoadAware prod-usage thresholds: only prod-band pods are checked
    against prod-tier utilization (``load_aware.go:163-179``).

    pod_is_prod: [P] bool. Returns [P, N] bool.
    """
    base = usage_threshold_mask(
        pod_estimate,
        node_prod_used,
        node_allocatable,
        prod_thresholds,
        metric_fresh,
        node_custom=node_custom,
    )
    return base | ~pod_is_prod[:, None]


def fit_mask_cols(pod_req: jnp.ndarray, node_free: jnp.ndarray) -> jnp.ndarray:
    """Gathered-column :func:`fit_mask`: ``node_free`` is [P, K, D] (each
    pod's K candidate node columns already gathered). Elementwise
    arithmetic is identical to the full-axis form — the shortlist solve's
    decision-identity contract requires bit-equal booleans per
    (pod, node) pair. Returns [P, K] bool."""
    return jnp.all(pod_req[:, None, :] <= node_free + EPS, axis=-1)


def effective_thresholds_cols(
    thresholds: jnp.ndarray,
    node_custom: jnp.ndarray | None,
) -> jnp.ndarray:
    """Gathered-column :func:`effective_thresholds`: ``node_custom`` is
    [P, K, D] (or None). Returns [P, K, D] (broadcastable [1, 1, D] when
    no custom table)."""
    if node_custom is None:
        return thresholds[None, None, :]
    has_custom = jnp.any(node_custom > 0.0, axis=-1, keepdims=True)  # [P, K, 1]
    return jnp.where(has_custom, node_custom, thresholds[None, None, :])


def usage_threshold_mask_cols(
    pod_estimate: jnp.ndarray,
    node_estimated_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    thresholds: jnp.ndarray,
    metric_fresh: jnp.ndarray,
    node_custom: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Gathered-column :func:`usage_threshold_mask`: node args are
    [P, K, D] / [P, K] candidate columns. Same elementwise arithmetic as
    the full-axis form (bit-equal per pair). Returns [P, K] bool."""
    after = node_estimated_used + pod_estimate[:, None, :]
    pct = usage_percent(after, node_allocatable)
    thr = effective_thresholds_cols(thresholds, node_custom)
    over = (thr > 0.0) & (pct > thr)
    ok = ~jnp.any(over, axis=-1)
    return ok | ~metric_fresh


def prod_usage_threshold_mask_cols(
    pod_is_prod: jnp.ndarray,
    pod_estimate: jnp.ndarray,
    node_prod_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    prod_thresholds: jnp.ndarray,
    metric_fresh: jnp.ndarray,
    node_custom: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Gathered-column :func:`prod_usage_threshold_mask`. Returns [P, K]."""
    base = usage_threshold_mask_cols(
        pod_estimate,
        node_prod_used,
        node_allocatable,
        prod_thresholds,
        metric_fresh,
        node_custom=node_custom,
    )
    return base | ~pod_is_prod[:, None]


def combine(*masks: jnp.ndarray) -> jnp.ndarray:
    """AND-compose masks, broadcasting [N]→[P,N] as needed."""
    out = None
    for m in masks:
        if m.ndim == 1:
            m = m[None, :]
        out = m if out is None else (out & m)
    return out
