"""NUMA topology feasibility/scoring masks + hint merge, vectorized.

Rebuild of NodeNUMAResource's data plane
(``pkg/scheduler/plugins/nodenumaresource/plugin.go:318-442`` Filter,
``scoring.go:66-120`` Score) and the scheduler-level topology manager
(``pkg/scheduler/frameworkext/topologymanager/policy_*.go``).

Zone convention: the zone resource axis is the *prefix* of the snapshot's
dense resource axis (dims 0..DN-1, i.e. cpu and memory), so pod zone
requests are a slice of the existing request tensor — no extra pod arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from .masks import EPS

# NUMAPolicy enum values (keep in sync with core.topology.NUMAPolicy)
POLICY_NONE = 0
POLICY_BEST_EFFORT = 1
POLICY_RESTRICTED = 2
POLICY_SINGLE_NUMA_NODE = 3


@struct.dataclass
class NumaState:
    """Device-side NUMA zone block.

    zone_free — remaining allocatable per zone        [N, Z, DN]
    zone_cap  — zone allocatable capacity             [N, Z, DN]
    policy    — node topology manager policy          [N] int8
    zone_most — per-node MostAllocated zone-pick strategy flag [N] bool
                (None → LeastAllocated everywhere); mirrors the host's
                ``_most_allocated`` label/default resolution so the
                solver's on-device zone selection matches the host
                allocator pick-for-pick (``util.go:33-47``)
    """

    zone_free: jnp.ndarray
    zone_cap: jnp.ndarray
    policy: jnp.ndarray
    zone_most: jnp.ndarray = None


def zone_pick(
    zone_free_g: jnp.ndarray,   # [P, Z, DN] carried free at each pod's node
    zone_cap_g: jnp.ndarray,    # [P, Z, DN]
    req_eff: jnp.ndarray,       # [P, DN] amplified zone-scoped request
    most_allocated: jnp.ndarray,  # [P] bool — node's pick strategy
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Strategy-ordered fitting-zone pick, the exact on-device mirror of
    the host allocator's per-winner loop
    (``NUMAManager.allocate_lowered``: both dims checked unconditionally,
    utilization keyed on the CPU dim, LeastAllocated spreads /
    MostAllocated packs). Returns ``(zone [P] int32, has_fit [P] bool)``;
    zone is only meaningful where has_fit."""
    fits = jnp.all(zone_free_g >= req_eff[:, None, :] - 1e-3, axis=-1)  # [P, Z]
    # padded/unregistered zones (zero capacity) must never be picked —
    # a near-zero request would otherwise "fit" them, and MostAllocated's
    # util=1.0 would actively prefer them (code-review r5)
    fits &= jnp.any(zone_cap_g > 0, axis=-1)
    used0 = zone_cap_g[:, :, 0] - zone_free_g[:, :, 0]
    util = (used0 + 1.0) / (zone_cap_g[:, :, 0] + 1.0)
    key = jnp.where(
        fits,
        jnp.where(most_allocated[:, None], -util, util),
        jnp.inf,
    )
    zone = jnp.argmin(key, axis=1).astype(jnp.int32)
    has_fit = jnp.isfinite(jnp.min(key, axis=1))
    return zone, has_fit


def numa_fit_mask(
    pod_requests: jnp.ndarray,   # [P, D] full resource axis
    pod_wants_numa: jnp.ndarray,  # [P] bool (LSR/LSE-style alignment need)
    numa: NumaState,
    cpu_amp: jnp.ndarray | None = None,  # [N] node CPU amplification ratio
    pod_required: jnp.ndarray | None = None,  # [P] bool single-NUMA REQUIRED
) -> jnp.ndarray:
    """[P, N] feasibility under each node's topology policy.

    single-numa-node: the pod's zone-scoped request must fit in ONE zone
    (``policy_single_numa_node.go``); restricted/best-effort/none: the sum
    across zones suffices (alignment is then a scoring preference). Pods
    not requesting alignment are always NUMA-feasible, as are nodes
    reporting no zones.

    ``cpu_amp`` mirrors the reference's ``AmplifyResourceList`` on the
    request side (``nodenumaresource/plugin.go:630-645``): zone capacities
    are expected already in *amplified* space (``amplifyNUMANodeResources``
    — the NUMAManager registers them that way), so cpuset-bound pods' CPU
    requests amplify ×ratio to match (net physical semantics for bound
    pods; stretched shared capacity for everyone else).
    """
    dn = numa.zone_free.shape[-1]
    n = numa.zone_free.shape[0]
    req = pod_requests[:, :dn]                                 # [P, DN]
    if cpu_amp is None:
        amp = jnp.ones((n,), jnp.float32)
    else:
        amp = jnp.maximum(cpu_amp, 1.0)
    # bound pods' CPU requests amplify with the capacity space; [P, N, DN]
    # (XLA fuses this into the zone_fit reduction — nothing rank-3/4
    # materializes in HBM)
    scale = jnp.ones((n, dn), jnp.float32).at[:, 0].set(amp)    # [N, DN]
    req_scale = 1.0 + pod_wants_numa[:, None, None].astype(jnp.float32) * (
        scale[None, :, :] - 1.0
    )                                                           # [P, N, DN]
    req_eff = req[:, None, :] * req_scale                       # [P, N, DN]
    # dims a node's zones don't report (zero capacity, e.g. memory left
    # unregistered) are not checked — like a disabled threshold
    dim_on = jnp.sum(numa.zone_cap, axis=1) > 0                 # [N, DN]
    zone_fit = jnp.all(
        (req_eff[:, :, None, :] <= numa.zone_free[None, :, :, :] + EPS)
        | ~dim_on[None, :, None, :],
        axis=-1,
    )                                                           # [P, N, Z]
    any_zone = jnp.any(zone_fit, axis=-1)                       # [P, N]
    total_free = jnp.sum(numa.zone_free, axis=1)                # [N, DN]
    total_fit = jnp.all(
        (req_eff <= total_free[None, :, :] + EPS) | ~dim_on[None, :, :],
        axis=-1,
    )                                                           # [P, N]
    # topology presence comes from capacity, not remaining free space — an
    # exhausted node must stay infeasible, not fall back to "no topology"
    has_zones = jnp.any(jnp.sum(numa.zone_cap, axis=-1) > 0, axis=-1)  # [N]
    strict = numa.policy == POLICY_SINGLE_NUMA_NODE
    # strict nodes align every pod (kubelet would reject otherwise); on
    # other nodes only alignment-requesting pods are zone-checked. A pod
    # whose numa-topology-spec REQUIRES SingleNUMANode needs a one-zone
    # fit on EVERY node regardless of the node's own policy
    # (numa_aware.go:29-31).
    strict_pn = strict[None, :]
    if pod_required is not None:
        strict_pn = strict_pn | pod_required[:, None]
    ok = jnp.where(
        strict_pn, any_zone, total_fit | ~pod_wants_numa[:, None]
    )
    return ok | ~has_zones[None, :]


def numa_alignment_cost(
    pod_requests: jnp.ndarray,
    numa: NumaState,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """[P, N] score→cost over the best-fitting zone.

    LeastAllocated (default): prefer the node whose best zone has the most
    headroom after placement; MostAllocated (bin-packing): the least
    (reference ``scoring.go`` + ``least_allocated.go``/``most_allocated.go``).
    Nodes where no single zone fits score worst-but-finite so strict
    feasibility stays the mask's job.
    """
    dn = numa.zone_free.shape[-1]
    req = pod_requests[:, :dn]
    after = numa.zone_free[None, :, :, :] - req[:, None, None, :]  # [P,N,Z,DN]
    fits = jnp.all(after >= -EPS, axis=-1)                          # [P,N,Z]
    total = jnp.maximum(jnp.max(numa.zone_free, axis=1), 1e-9)      # [N, DN]
    frac_free = jnp.clip(after / total[None, :, None, :], 0.0, 1.0)
    zone_score = jnp.mean(frac_free, axis=-1) * 100.0               # [P,N,Z]
    if most_allocated:
        zone_score = 100.0 - zone_score
    zone_score = jnp.where(fits, zone_score, -1.0)
    best = jnp.max(zone_score, axis=-1)                             # [P, N]
    return -best


def merge_hints(
    provider_masks: jnp.ndarray,   # [H, M] bool — per provider, allowed zone bitmask ids
    n_zones: int,
) -> jnp.ndarray:
    """Topology-manager hint merge over bitmask space (vectorized analog of
    ``policy.go`` mergePermutations): M = 2^Z candidate zone sets; a
    candidate is feasible iff every provider allows a superset of it; the
    *narrowest* feasible candidate (fewest zones, then lowest id) wins.

    Returns the winning bitmask id (int32), or -1 if none feasible.
    """
    m = 1 << n_zones
    ids = jnp.arange(m, dtype=jnp.int32)
    feasible = jnp.all(provider_masks, axis=0)                  # [M]
    bits = jnp.sum(
        (ids[:, None] >> jnp.arange(n_zones)[None, :]) & 1, axis=1
    )
    key = jnp.where(feasible & (ids > 0), bits * m + ids, jnp.iinfo(jnp.int32).max)
    best = jnp.argmin(key).astype(jnp.int32)
    return jnp.where(jnp.min(key) == jnp.iinfo(jnp.int32).max, -1, best)


# ---- host-side provider-hint merge (reference policy.go mergeFilteredHints
# / mergePermutation / iterateAllProviderTopologyHints) ----
#
# The vectorized merge_hints above serves the solver's zone feasibility;
# this mirror reproduces the reference's per-winner hint negotiation
# exactly (permutation AND-merge, preferred propagation, narrowest-wins
# with score tie-break) for the host Reserve path and parity tests.

import dataclasses as _dc
from itertools import product as _product
from typing import Optional as _Optional, Sequence as _Sequence


@_dc.dataclass
class TopologyHint:
    """One provider hint: ``affinity`` is a zone bitmask (None = no
    preference / any), ``preferred`` mirrors the reference flag."""

    affinity: _Optional[int] = None
    preferred: bool = True
    score: float = 0.0
    unsatisfied: bool = False


def _popcount(x: int) -> int:
    return bin(x).count("1")


def _narrower(a: int, b: int) -> bool:
    """bitmask.IsNarrowerThan: fewer bits set, or equal count and lower."""
    ca, cb = _popcount(a), _popcount(b)
    if ca != cb:
        return ca < cb
    return a < b


def filter_provider_hints(
    providers: _Sequence[_Optional[_Sequence[TopologyHint]]],
) -> list:
    """``filterProvidersHints``: a provider with no hints contributes a
    single preferred any-NUMA hint; an empty hint list (resource cannot be
    satisfied on any zone set) contributes an unsatisfied, unpreferred
    hint."""
    out = []
    for hints in providers:
        if hints is None:
            out.append([TopologyHint(affinity=None, preferred=True)])
        elif len(hints) == 0:
            out.append(
                [TopologyHint(affinity=None, preferred=False, unsatisfied=True)]
            )
        else:
            out.append(list(hints))
    return out


def merge_provider_hints(
    providers: _Sequence[_Optional[_Sequence[TopologyHint]]],
    n_zones: int,
) -> TopologyHint:
    """``mergeFilteredHints``: iterate every one-hint-per-provider
    permutation, AND the affinities, and keep the best merged hint —
    preferred beats non-preferred, then narrowest affinity, then highest
    accumulated score."""
    default_mask = (1 << n_zones) - 1
    filtered = filter_provider_hints(providers)
    best = TopologyHint(affinity=default_mask, preferred=False)
    for permutation in _product(*filtered):
        affs = [h.affinity for h in permutation if h.affinity is not None]
        preferred = all(h.preferred for h in permutation)
        if affs and any(a != affs[0] for a in affs):
            preferred = False
        merged = default_mask
        for a in affs:
            merged &= a
        if _popcount(merged) == 0:
            continue
        score = sum(
            h.score
            for h in permutation
            if h.affinity is not None and h.affinity == merged
        )
        cand = TopologyHint(affinity=merged, preferred=preferred, score=score)
        if cand.preferred and not best.preferred:
            best = cand
            continue
        if not cand.preferred and best.preferred:
            continue
        if not _narrower(cand.affinity, best.affinity):
            if (
                _popcount(cand.affinity) == _popcount(best.affinity)
                and cand.score > best.score
            ):
                best = cand
            continue
        best = cand
    return best


def policy_merge(
    providers: _Sequence[_Optional[_Sequence[TopologyHint]]],
    n_zones: int,
    policy: "NUMAPolicy | int",
) -> tuple:
    """Per-policy Merge + canAdmitPodResult (reference policy_*.go):

    - none:             no merge, always admit.
    - best-effort:      merged hint, always admit.
    - restricted:       merged hint, admit iff preferred.
    - single-numa-node: hints filtered to single-zone (or preferred
      don't-care) before the merge; an all-NUMA result degrades to a
      nil-affinity hint; admit iff preferred.

    Returns (TopologyHint, admit: bool).
    """
    from ..core.topology import NUMAPolicy as _NP

    policy = _NP(int(policy))
    if policy == _NP.NONE:
        return TopologyHint(affinity=None, preferred=True), True
    if policy == _NP.SINGLE_NUMA_NODE:
        filtered = []
        for hints in providers:
            if hints is None or len(hints) == 0:
                filtered.append(hints)
                continue
            kept = [
                h
                for h in hints
                if (h.affinity is None and h.preferred)
                or (h.affinity is not None and _popcount(h.affinity) == 1)
            ]
            filtered.append(kept)
        best = merge_provider_hints(filtered, n_zones)
        default_mask = (1 << n_zones) - 1
        if best.affinity == default_mask:
            best = TopologyHint(affinity=None, preferred=best.preferred)
        return best, best.preferred
    best = merge_provider_hints(providers, n_zones)
    if policy == _NP.RESTRICTED:
        return best, best.preferred
    return best, True   # BEST_EFFORT
