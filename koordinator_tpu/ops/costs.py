"""Score plugins as vectorized cost terms over (pods × nodes).

Lower cost = better node (the solver does masked argmin). Each reference
Score plugin maps to one term here:
  * LoadAwareScheduling.Score      → :func:`load_aware_cost`
    (reference ``pkg/scheduler/plugins/loadaware/load_aware.go:387-406``)
  * NodeResourcesFitPlus           → :func:`fit_plus_cost`
    (reference ``pkg/scheduler/plugins/noderesourcefitplus/plugin.go``)
  * ScarceResourceAvoidance        → :func:`scarce_resource_cost`
    (reference ``pkg/scheduler/plugins/scarceresourceavoidance/plugin.go``)
  * NUMA LeastAllocated/MostAllocated → :func:`least_allocated_cost` /
    :func:`most_allocated_cost` (reference ``nodenumaresource/least_allocated.go``)

Scores follow the reference's 0..100 convention, then negate into costs so
terms combine by weighted addition exactly like the framework's weighted sum.
"""

from __future__ import annotations

import jax.numpy as jnp

_SAFE = 1e-9


def _utilization_free_score(
    requested_like: jnp.ndarray, allocatable: jnp.ndarray, weights: jnp.ndarray
) -> jnp.ndarray:
    """score = ⌊Σ_d w_d · ⌊(alloc - used) · 100 / alloc⌋ / Σ_d w_d⌋, ≥ 0.

    Integer-floor semantics are part of the reference contract, not an
    implementation detail: ``leastUsedScore`` floors per resource and
    ``loadAwareSchedulingScorer`` floors the weighted mean (int64
    divisions, ``load_aware.go:387-406``) — its own test table
    (``load_aware_test.go`` TestScore: 52.5/93.67 → (52+93)/2 → 72)
    only reproduces under flooring.

    requested_like: [..., D] (estimated used or requested+req);
    allocatable: broadcastable [..., D]; weights: [D].
    """
    free = jnp.maximum(allocatable - requested_like, 0.0)
    per_dim = jnp.floor(
        jnp.where(allocatable > 0, free * 100.0 / (allocatable + _SAFE), 0.0)
    )
    wsum = jnp.sum(weights) + _SAFE
    # Elementwise multiply-reduce (not einsum/MXU): D is tiny and full f32
    # accumulation keeps scores bit-comparable with the scalar golden model.
    return jnp.floor(jnp.sum(per_dim * weights, axis=-1) / wsum)


def load_aware_cost(
    pod_estimate: jnp.ndarray,
    node_estimated_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    weights: jnp.ndarray,
    metric_fresh: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """LoadAware least-used score → cost ([P, N]).

    Mirrors ``load_aware.go:387-406`` (``loadAwareSchedulingScorer``): per-dim
    free-percentage after adding the pod's estimated usage, weighted-averaged
    with the reference's integer-floor semantics. A node whose NodeMetric is
    expired or missing scores 0 — still schedulable, ranked last
    (``TestScore`` "score node with expired nodeMetric" → 0).
    """
    after = node_estimated_used[None, :, :] + pod_estimate[:, None, :]  # [P,N,D]
    score = _utilization_free_score(after, node_allocatable[None, :, :], weights)
    if metric_fresh is not None:
        score = jnp.where(metric_fresh[None, :], score, 0.0)
    return -score


def load_aware_cost_cols(
    pod_estimate: jnp.ndarray,
    node_estimated_used: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    weights: jnp.ndarray,
    metric_fresh: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Gathered-column :func:`load_aware_cost`: node args are [P, K, D] /
    [P, K] candidate columns (the shortlist solve's per-pod sub-tensors).
    Elementwise arithmetic is identical to the full-axis form — decision
    identity requires bit-equal scores per (pod, node) pair. [P, K]."""
    after = node_estimated_used + pod_estimate[:, None, :]          # [P,K,D]
    score = _utilization_free_score(after, node_allocatable, weights)
    if metric_fresh is not None:
        score = jnp.where(metric_fresh, score, 0.0)
    return -score


def least_allocated_cost(
    pod_req: jnp.ndarray,
    node_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    weights: jnp.ndarray,
) -> jnp.ndarray:
    """Request-based least-allocated (NUMA scoring strategy LeastAllocated,
    reference ``nodenumaresource/least_allocated.go``)."""
    after = node_requested[None, :, :] + pod_req[:, None, :]
    return -_utilization_free_score(after, node_allocatable[None, :, :], weights)


def most_allocated_cost(
    pod_req: jnp.ndarray,
    node_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    weights: jnp.ndarray,
) -> jnp.ndarray:
    """MostAllocated (bin-packing): score = Σ w_d · used·100/alloc
    (reference ``nodenumaresource/most_allocated.go``)."""
    after = node_requested[None, :, :] + pod_req[:, None, :]
    free_score = _utilization_free_score(after, node_allocatable[None, :, :], weights)
    return -(100.0 - free_score)


def scarce_resource_cost(
    pod_req: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    scarce_dims: jnp.ndarray,
) -> jnp.ndarray:
    """ScarceResourceAvoidance: penalize nodes that carry a scarce resource
    (e.g. GPU) when the pod does not request it, so scarce capacity stays
    free for pods that need it.

    scarce_dims: [D] bool marking the scarce resource dims.
    Returns [P, N] cost in 0..100.
    """
    node_has = (node_allocatable > 0) & scarce_dims[None, :]          # [N, D]
    pod_wants = pod_req > 0                                           # [P, D]
    wasted = node_has[None, :, :] & ~pod_wants[:, None, :]            # [P, N, D]
    n_scarce = jnp.maximum(jnp.sum(scarce_dims), 1)
    return jnp.sum(wasted, axis=-1) * (100.0 / n_scarce)


def fit_plus_cost(
    pod_req: jnp.ndarray,
    node_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    dim_weights: jnp.ndarray,
    most_allocated_dims: jnp.ndarray,
) -> jnp.ndarray:
    """NodeResourcesFitPlus: per-resource-type choice of Least/MostAllocated
    strategy with per-resource weights (reference
    ``noderesourcefitplus/plugin.go``).

    most_allocated_dims: [D] bool — dims scored MostAllocated; others Least.
    """
    after = node_requested[None, :, :] + pod_req[:, None, :]
    frac_used = jnp.where(
        node_allocatable[None, :, :] > 0,
        jnp.clip(after / (node_allocatable[None, :, :] + _SAFE), 0.0, 1.0),
        0.0,
    )
    per_dim_score = jnp.where(
        most_allocated_dims[None, None, :], frac_used, 1.0 - frac_used
    ) * 100.0
    wants = (pod_req > 0).astype(per_dim_score.dtype)                 # [P, D]
    w = dim_weights[None, None, :] * wants[:, None, :]
    score = jnp.sum(per_dim_score * w, axis=-1) / (jnp.sum(w, axis=-1) + _SAFE)
    return -score


def device_cost(
    gpu_units: jnp.ndarray,
    dev_free_total: jnp.ndarray,
    dev_cap_total: jnp.ndarray,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """DeviceShare Score strategy over GPU capacity (reference
    ``deviceshare/scoring.go:45-110`` + ``resource_allocation.score`` —
    Least/MostAllocated over the node's device resources). Pods without a
    GPU request and nodes without GPUs contribute 0 (``state.skip`` /
    missing nodeDeviceInfo return 0 in the reference).

    gpu_units      [P] requested GPU percent-units (100 per whole GPU)
    dev_free_total [N] free percent-units (round-carried)
    dev_cap_total  [N] total percent-units
    Returns [P, N] cost (= -score, scores 0..100, integer-floored).
    """
    used_after = (
        (dev_cap_total[None, :] - dev_free_total[None, :]) + gpu_units[:, None]
    )
    cap = dev_cap_total[None, :]
    if most_allocated:
        raw = jnp.floor(used_after * 100.0 / (cap + _SAFE))
    else:
        raw = jnp.floor((cap - used_after) * 100.0 / (cap + _SAFE))
    score = jnp.where((cap > 0) & (used_after <= cap + 1e-6), raw, 0.0)
    score = jnp.where(gpu_units[:, None] > 0, score, 0.0)
    return -score


def device_cost_cols(
    gpu_units: jnp.ndarray,
    dev_free_total: jnp.ndarray,
    dev_cap_total: jnp.ndarray,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """Gathered-column :func:`device_cost`: ``dev_free_total`` /
    ``dev_cap_total`` are [P, K] candidate columns. Same elementwise
    arithmetic as the full-axis form. Returns [P, K] cost."""
    used_after = (dev_cap_total - dev_free_total) + gpu_units[:, None]
    cap = dev_cap_total
    if most_allocated:
        raw = jnp.floor(used_after * 100.0 / (cap + _SAFE))
    else:
        raw = jnp.floor((cap - used_after) * 100.0 / (cap + _SAFE))
    score = jnp.where((cap > 0) & (used_after <= cap + 1e-6), raw, 0.0)
    score = jnp.where(gpu_units[:, None] > 0, score, 0.0)
    return -score


def numa_aligned_cost(
    pod_req: jnp.ndarray,
    wants_numa: jnp.ndarray,
    zone_free: jnp.ndarray,
    zone_cap: jnp.ndarray,
    weights: jnp.ndarray,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """NUMA-aligned Least/MostAllocated scoring (reference
    ``nodenumaresource/scoring.go:66-120`` → ``calculateAllocatableAndRequested``
    + ``least_allocated.go``/``most_allocated.go``): for each (pod, node)
    the pod's hypothetical allocation is placed into the zone the host
    allocator would pick (the least-utilized zone that fits), and the node
    is scored on THAT zone's requested/allocatable — so a node whose
    aligned zone is tight scores poorly even when node totals look fine.

    pod_req      [P, D]  (only the first DN zone dims are used)
    wants_numa   [P] bool — pods without NUMA interest contribute 0
                 (reference preFilterState.skip)
    zone_free    [N, Z, DN], zone_cap [N, Z, DN]
    weights      [DN] scoring-strategy resource weights
    Returns [P, N] cost (= -score, reference scores are 0..100).
    """
    dn = zone_cap.shape[-1]
    req = pod_req[:, :dn]                                   # [P, DN]
    real = jnp.any(zone_cap > 0, axis=-1)                   # [N, Z]
    fits = jnp.all(
        req[:, None, None, :] <= zone_free[None, :, :, :] + 1e-6, axis=-1
    ) & real[None, :, :]                                    # [P, N, Z]
    used = zone_cap - zone_free                             # [N, Z, DN]
    # host zone pick: least (used_cpu+1)/(cap_cpu+1) among fitting zones
    util = (used[..., 0] + 1.0) / (zone_cap[..., 0] + 1.0)  # [N, Z]
    key = jnp.where(fits, util[None, :, :], jnp.inf)
    zstar = jnp.argmin(key, axis=-1)                        # [P, N]
    has_zone = jnp.any(fits, axis=-1)                       # [P, N]
    zoh = (
        jnp.arange(zone_cap.shape[1])[None, None, :] == zstar[:, :, None]
    )                                                       # [P, N, Z]
    used_z = jnp.sum(used[None] * zoh[..., None], axis=2)   # [P, N, DN]
    cap_z = jnp.sum(zone_cap[None] * zoh[..., None], axis=2)
    after = used_z + req[:, None, :]
    # integer-floor per-resource score, 0 when over capacity or cap==0
    # (leastRequestedScore / mostRequestedScore int64 semantics)
    if most_allocated:
        raw = jnp.floor(after * 100.0 / (cap_z + _SAFE))
    else:
        raw = jnp.floor((cap_z - after) * 100.0 / (cap_z + _SAFE))
    per_dim = jnp.where((cap_z > 0) & (after <= cap_z + 1e-6), raw, 0.0)
    wsum = jnp.sum(weights[:dn]) + _SAFE
    score = jnp.floor(jnp.sum(per_dim * weights[None, None, :dn], axis=-1) / wsum)
    score = jnp.where(wants_numa[:, None] & has_zone, score, 0.0)
    return -score
