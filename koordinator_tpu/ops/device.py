"""DeviceShare data plane: GPU slot feasibility masks, vectorized.

Rebuild of the reference DeviceShare plugin's accounting
(``pkg/scheduler/plugins/deviceshare/device_cache.go`` per-node slot
totals/allocations + ``allocator_gpu.go:1-451``): each node carries G GPU
slots in percent units (100 = one whole free GPU, matching the
``koordinator.sh/gpu-memory-ratio`` convention of
``apis/extension/device_share.go``). A pod requests either K whole GPUs
(``nvidia.com/gpu``) or a fraction of one (ratio < 100).

The solver masks feasibility from the exact per-slot state lowered at
batch start; intra-batch consumption uses conservative node aggregates
(whole-slot count + total percent) — the host DeviceManager revalidates
winners against exact slots, so approximation can only under-place within
one batch, never overcommit.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from .masks import EPS

FULL = 100.0  # one whole GPU in ratio units


@struct.dataclass
class DeviceState:
    """Per-node GPU slot state: slot_free [N, G] in percent units.

    Nodes without GPUs have all-zero rows; a row of 100s is an idle GPU.
    ``rdma_free`` [N] counts idle RDMA NICs per node (None when the
    deployment has no RDMA inventory) — feasibility only; exact NIC
    minors and PCIe co-location are the host DeviceManager's joint
    allocation at Reserve.
    """

    slot_free: jnp.ndarray
    rdma_free: jnp.ndarray = None
    fpga_free: jnp.ndarray = None
    #: total GPU percent-units per node ([N], 100 per installed GPU) —
    #: needed by the Score strategy (free alone can't distinguish a full
    #: node from a GPU-less one)
    cap_total: jnp.ndarray = None

    def aggregates(self):
        """(full_count [N], partial_max [N], total [N])."""
        full = jnp.sum(self.slot_free >= FULL - EPS, axis=1).astype(jnp.float32)
        partial = jnp.max(
            jnp.where(self.slot_free >= FULL - EPS, 0.0, self.slot_free), axis=1
        )
        total = jnp.sum(self.slot_free, axis=1)
        return full, partial, total


def device_fit_mask(
    gpu_whole: jnp.ndarray,    # [P] int32 — whole GPUs requested
    gpu_share: jnp.ndarray,    # [P] float32 — percent of one GPU (0 = none)
    full_count: jnp.ndarray,   # [N]
    partial_max: jnp.ndarray,  # [N]
    rdma_req: jnp.ndarray = None,   # [P] int32 — whole RDMA NICs
    rdma_free: jnp.ndarray = None,  # [N] free NIC count
    fpga_req: jnp.ndarray = None,   # [P] int32 — whole FPGAs
    fpga_free: jnp.ndarray = None,  # [N] free FPGA count
) -> jnp.ndarray:
    """[P, N] GPU feasibility (reference Filter, ``plugin.go:311``).

    Whole-GPU pods need that many fully-free slots; fractional pods need a
    partial slot with enough headroom or one fully-free slot to open.
    """
    whole_ok = gpu_whole[:, None].astype(jnp.float32) <= full_count[None, :] + EPS
    frac = gpu_share[:, None]
    frac_ok = (
        (frac <= partial_max[None, :] + EPS)
        | (full_count[None, :] >= 1.0 - EPS)
        | (frac <= EPS)
    )
    # pods requesting both whole + share (K GPUs and a remainder) need
    # whole_ok for K and frac capacity beyond those K slots; approximate
    # by requiring an extra full slot when both are present.
    both = (gpu_whole[:, None] > 0) & (frac > EPS)
    both_ok = (
        gpu_whole[:, None].astype(jnp.float32) + 1.0 <= full_count[None, :] + EPS
    ) | (frac <= partial_max[None, :] + EPS)
    ok = whole_ok & jnp.where(both, both_ok, frac_ok)
    if rdma_req is not None and rdma_free is not None:
        ok &= (
            rdma_req[:, None].astype(jnp.float32)
            <= rdma_free[None, :] + EPS
        )
    if fpga_req is not None and fpga_free is not None:
        ok &= (
            fpga_req[:, None].astype(jnp.float32)
            <= fpga_free[None, :] + EPS
        )
    return ok


def device_consumption(
    gpu_whole: jnp.ndarray, gpu_share: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pod in-round consumption: (full_slots [P], total_percent [P]).

    Fractional pods charge only the total-percent axis (optimistic about
    slot fragmentation): the cumulative total check bounds overcommit per
    node and the host DeviceManager revalidates winners against exact
    slots, so optimism costs at most a host-side reject, while pessimism
    would silently under-place whole batches.
    """
    full = gpu_whole.astype(jnp.float32)
    total = gpu_whole.astype(jnp.float32) * FULL + gpu_share
    return full, total
