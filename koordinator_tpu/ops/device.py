"""DeviceShare data plane: GPU slot feasibility masks, vectorized.

Rebuild of the reference DeviceShare plugin's accounting
(``pkg/scheduler/plugins/deviceshare/device_cache.go`` per-node slot
totals/allocations + ``allocator_gpu.go:1-451``): each node carries G GPU
slots in percent units (100 = one whole free GPU, matching the
``koordinator.sh/gpu-memory-ratio`` convention of
``apis/extension/device_share.go``). A pod requests either K whole GPUs
(``nvidia.com/gpu``) or a fraction of one (ratio < 100).

The solver carries the exact per-slot table ``slot_free`` [N, G] through
its commit rounds (the on-device analog of the reference's per-minor
``deviceResources`` map in ``device_cache.go``): whole-GPU winners zero
fully-free slots (interchangeable capacity — the host assigns concrete
minors), and one fractional winner per node per round takes a best-fit
bite matching the host allocator's tightest-partial-else-open-full rule
(``allocator_gpu.go:1-451``). Intra-batch state is therefore exact; the
host DeviceManager still revalidates winners at Reserve, but with
matching selection rules a reject implies a real inventory change, not
accounting drift.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from .masks import EPS

FULL = 100.0  # one whole GPU in ratio units


@struct.dataclass
class DeviceState:
    """Per-node GPU slot state: slot_free [N, G] in percent units.

    Nodes without GPUs have all-zero rows; a row of 100s is an idle GPU.
    ``rdma_free`` [N] counts idle RDMA NICs per node (None when the
    deployment has no RDMA inventory) — feasibility only; exact NIC
    minors and PCIe co-location are the host DeviceManager's joint
    allocation at Reserve.
    """

    slot_free: jnp.ndarray
    rdma_free: jnp.ndarray = None
    fpga_free: jnp.ndarray = None
    #: total GPU percent-units per node ([N], 100 per installed GPU) —
    #: needed by the Score strategy (free alone can't distinguish a full
    #: node from a GPU-less one)
    cap_total: jnp.ndarray = None

    def aggregates(self):
        """(full_count [N], partial_max [N], total [N])."""
        full, partial, _smax, total = slot_stats(self.slot_free)
        return full, partial, total


def slot_stats(slot_free: jnp.ndarray):
    """Round-start reductions over the slot table.

    Returns ``(full_count [N], partial_max [N], slot_max [N], total [N])``
    — the count of fully-free slots, the largest partially-free slot, the
    largest slot of any kind, and the summed free percent.
    """
    is_full = slot_free >= FULL - EPS
    full = jnp.sum(is_full, axis=1).astype(jnp.float32)
    partial = jnp.max(jnp.where(is_full, 0.0, slot_free), axis=1)
    smax = jnp.max(slot_free, axis=1)
    total = jnp.sum(slot_free, axis=1)
    return full, partial, smax, total


def device_fit_mask(
    gpu_whole: jnp.ndarray,    # [P] int32 — whole GPUs requested
    gpu_share: jnp.ndarray,    # [P] float32 — percent of one GPU (0 = none)
    full_count: jnp.ndarray,   # [N]
    partial_max: jnp.ndarray,  # [N]
    slot_max: jnp.ndarray = None,  # [N] largest slot of any kind
    rdma_req: jnp.ndarray = None,   # [P] int32 — whole RDMA NICs
    rdma_free: jnp.ndarray = None,  # [N] free NIC count
    fpga_req: jnp.ndarray = None,   # [P] int32 — whole FPGAs
    fpga_free: jnp.ndarray = None,  # [N] free FPGA count
) -> jnp.ndarray:
    """[P, N] GPU feasibility (reference Filter, ``plugin.go:311``), exact
    against the per-slot table's round-start reductions.

    Whole-GPU pods need that many fully-free slots. Fractional-only pods
    need any slot (partial or full) with enough headroom. Combined
    whole+share pods need K fully-free slots *plus* either a (K+1)-th full
    slot or a partial slot that fits the remainder.
    """
    if slot_max is None:
        slot_max = jnp.maximum(
            partial_max, jnp.where(full_count >= 1.0 - EPS, FULL, 0.0)
        )
    whole_ok = gpu_whole[:, None].astype(jnp.float32) <= full_count[None, :] + EPS
    frac = gpu_share[:, None]
    frac_ok = (frac <= slot_max[None, :] + EPS) | (frac <= EPS)
    both = (gpu_whole[:, None] > 0) & (frac > EPS)
    both_ok = (
        gpu_whole[:, None].astype(jnp.float32) + 1.0 <= full_count[None, :] + EPS
    ) | (frac <= partial_max[None, :] + EPS)
    ok = whole_ok & jnp.where(both, both_ok, frac_ok)
    if rdma_req is not None and rdma_free is not None:
        ok &= (
            rdma_req[:, None].astype(jnp.float32)
            <= rdma_free[None, :] + EPS
        )
    if fpga_req is not None and fpga_free is not None:
        ok &= (
            fpga_req[:, None].astype(jnp.float32)
            <= fpga_free[None, :] + EPS
        )
    return ok


def device_fit_mask_cols(
    gpu_whole: jnp.ndarray,        # [P] int32
    gpu_share: jnp.ndarray,        # [P] float32
    full_count: jnp.ndarray,       # [P, K] gathered candidate columns
    partial_max: jnp.ndarray,      # [P, K]
    slot_max: jnp.ndarray = None,  # [P, K]
    rdma_req: jnp.ndarray = None,
    rdma_free: jnp.ndarray = None,  # [P, K]
    fpga_req: jnp.ndarray = None,
    fpga_free: jnp.ndarray = None,  # [P, K]
) -> jnp.ndarray:
    """Gathered-column :func:`device_fit_mask`: the round-start slot
    reductions arrive pre-gathered per pod ([P, K] candidate columns).
    Same elementwise arithmetic as the full-axis form — the shortlist
    solve's decision identity requires bit-equal booleans. [P, K]."""
    if slot_max is None:
        slot_max = jnp.maximum(
            partial_max, jnp.where(full_count >= 1.0 - EPS, FULL, 0.0)
        )
    whole_ok = gpu_whole[:, None].astype(jnp.float32) <= full_count + EPS
    frac = gpu_share[:, None]
    frac_ok = (frac <= slot_max + EPS) | (frac <= EPS)
    both = (gpu_whole[:, None] > 0) & (frac > EPS)
    both_ok = (
        gpu_whole[:, None].astype(jnp.float32) + 1.0 <= full_count + EPS
    ) | (frac <= partial_max + EPS)
    ok = whole_ok & jnp.where(both, both_ok, frac_ok)
    if rdma_req is not None and rdma_free is not None:
        ok &= rdma_req[:, None].astype(jnp.float32) <= rdma_free + EPS
    if fpga_req is not None and fpga_free is not None:
        ok &= fpga_req[:, None].astype(jnp.float32) <= fpga_free + EPS
    return ok


def device_consumption(
    gpu_whole: jnp.ndarray, gpu_share: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pod total-percent demand: (full_slots [P], total_percent [P]).

    Used by the DeviceShare Score term (Least/MostAllocated over GPU
    capacity) — commit accounting is per-slot (:func:`slot_commit`).
    """
    full = gpu_whole.astype(jnp.float32)
    total = gpu_whole.astype(jnp.float32) * FULL + gpu_share
    return full, total


def slot_commit(
    slot_free: jnp.ndarray,       # [N, G]
    whole_taken: jnp.ndarray,     # [N] float — fully-free slots consumed
    frac_share: jnp.ndarray,      # [N] float — the node's single fractional winner's share
    frac_opens_full: jnp.ndarray,  # [N] bool — that winner bites a fully-free slot
) -> jnp.ndarray:
    """Apply one commit round's final winners to the slot table.

    Mirrors the host allocator (``allocator_gpu.go``): whole-GPU demand
    zeroes ``whole_taken`` fully-free slots (any — minors are
    interchangeable capacity; the host picks concrete ones at Reserve);
    the fractional winner either opens the next fully-free slot
    (``frac_opens_full``) or takes a best-fit bite from the tightest
    partial slot that still fits. At most one fractional winner per node
    per round is admitted by the solver, so the best-fit target is
    uncontended.
    """
    g = slot_free.shape[1]
    is_full = slot_free >= FULL - EPS
    # rank of each slot among the node's fully-free slots, by minor index
    full_rank = jnp.cumsum(is_full.astype(jnp.int32), axis=1) - 1
    w = whole_taken[:, None]
    consumed = is_full & (full_rank.astype(jnp.float32) < w - 0.5)
    opened = (
        is_full
        & (jnp.abs(full_rank.astype(jnp.float32) - w) < 0.5)
        & frac_opens_full[:, None]
    )
    # best-fit partial: tightest partially-free slot with enough headroom
    partial_free = jnp.where(is_full, jnp.inf, slot_free)
    cand = jnp.where(
        partial_free >= frac_share[:, None] - EPS, partial_free, jnp.inf
    )
    tgt = jnp.argmin(cand, axis=1)                                   # [N]
    has_cand = jnp.isfinite(jnp.min(cand, axis=1))
    take_partial = (frac_share > EPS) & ~frac_opens_full & has_cand
    partial_hit = take_partial[:, None] & (
        jnp.arange(g)[None, :] == tgt[:, None]
    )
    out = jnp.where(consumed, 0.0, slot_free)
    out = jnp.where(opened, FULL - frac_share[:, None], out)
    out = out - jnp.where(partial_hit, frac_share[:, None], 0.0)
    return out


def slot_refund(
    slot_free: jnp.ndarray,
    refund: jnp.ndarray,
    slot_exists: jnp.ndarray = None,
) -> jnp.ndarray:
    """Water-fill ``refund`` [N] percent back onto the slot table,
    emptiest slot first, each capped at FULL.

    Gang rollback returns capacity in aggregate (the rolled-back pods'
    concrete slots are not identifiable from carried state); filling the
    emptiest slots first reconstructs the pre-consumption table exactly in
    the common case (whole-GPU members zeroed slots that were fully free)
    and is conservative otherwise — a refunded slot never exceeds FULL, so
    the host revalidation at Reserve remains the overcommit backstop.

    ``slot_exists`` [N, G] bool marks REAL slots: heterogeneous
    inventories pad the table with zero rows (``slot_array``), and a
    refund landing on a padding slot would both fabricate capacity and
    strand the real slot's refund. Padding slots get zero headroom.
    """
    n, g = slot_free.shape
    order = jnp.argsort(slot_free, axis=1)
    s = jnp.take_along_axis(slot_free, order, axis=1)
    headroom = FULL - s
    if slot_exists is not None:
        exists = jnp.take_along_axis(slot_exists, order, axis=1)
        headroom = jnp.where(exists, headroom, 0.0)
    cum_prev = jnp.cumsum(headroom, axis=1) - headroom
    fill = jnp.clip(refund[:, None] - cum_prev, 0.0, headroom)
    filled = s + fill
    return jnp.zeros_like(slot_free).at[
        jnp.arange(n)[:, None], order
    ].set(filled)
