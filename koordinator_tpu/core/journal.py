"""Durable write-ahead bind journal + leader fencing epochs (HA tentpole).

The robustness PR made a *process* crash-safe within one commit (the
transactional ``_ReserveJournal`` rolls a half-applied chunk back); this
module makes the *scheduler role* crash-safe across processes:

* :class:`BindJournal` — an append-only write-ahead log of commit
  intents, acknowledged binds and forgets. The contract is **journal
  before mutate**: a chunk whose intent record cannot be written is
  rejected before any snapshot mutation, and a bind is *acknowledged*
  only once its record is durably appended — so a takeover can rebuild
  exactly the acknowledged world from the statehub resync plus a journal
  replay (``runtime/recovery.py``), with zero lost acknowledged bindings
  and zero duplicate placements.
* :class:`EpochFence` — the monotonic fencing authority (the lease
  record's epoch in a multi-process deployment; one shared object
  in-process). Every leadership grant carries an epoch; the commit and
  snapshot-channel boundaries check the caller's epoch against the
  current grant, so a deposed leader's in-flight commit raises
  :class:`StaleEpochError` instead of double-placing pods. The journal
  itself enforces the same monotonicity at the storage boundary — a
  write stamped with an epoch older than one already journaled is
  refused, the classic fencing-token-on-shared-store discipline.

Failure domain (ROADMAP rule): the named chaos point
``journal.write_fail`` fires inside :meth:`BindJournal._append`; callers
see :class:`JournalWriteError` and reject the chunk un-mutated. A second
point, ``journal.compact_crash``, fires inside :meth:`BindJournal.compact`
and simulates a process death mid-compaction: the live log stays intact
(the rewrite is tmp-file + atomic rename), only a torn temp file is left
behind, and a fresh store open repairs/ignores it.

Horizontal partitioning (PR 6): a :class:`BindJournal` can be scoped to
one **shard** (``shard=``) — every record is stamped with the shard id
and the journal's epoch monotonicity then *is* the shard's fencing
history, independent of every other shard's. :class:`ClaimTable` is the
cross-shard arbiter: before a shard's pump may schedule a pod that was
fanned out to several shards, it must win the pod's claim record —
first-writer-wins, epoch-fenced per shard — so two shards can never
bind the same pod.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..chaos import NULL_INJECTOR
from . import integrity


class FencingError(RuntimeError):
    """Base for leadership-fencing violations."""


class StaleEpochError(FencingError):
    """The caller's fencing epoch is no longer the current grant — its
    leadership was superseded (or locally revoked) and the guarded
    mutation must not proceed."""

    def __init__(self, epoch: int, current: int, what: str = "epoch"):
        super().__init__(
            f"stale leadership {what}: held {epoch}, current {current}"
        )
        self.epoch = epoch
        self.current = current


class JournalWriteError(RuntimeError):
    """A journal append failed (storage error or injected fault). The
    guarded mutation must not proceed — journal before mutate."""


class EpochFence:
    """Thread-safe monotonic fencing authority.

    ``advance()`` models a fresh leadership grant (the lease takeover
    bumping the record's epoch); ``adopt(epoch)`` mirrors an externally
    observed grant and refuses to move backwards; ``check(epoch)``
    raises :class:`StaleEpochError` when the caller's grant is no longer
    current (``epoch < 0`` is the locally-revoked sentinel a deposed
    scheduler stamps on itself — it always fails the check).
    """

    def __init__(self, start: int = 0):
        self._epoch = int(start)
        self._lock = threading.Lock()

    def advance(self) -> int:
        with self._lock:
            self._epoch += 1
            return self._epoch

    def adopt(self, epoch: int) -> int:
        with self._lock:
            if epoch < self._epoch:
                raise StaleEpochError(epoch, self._epoch, what="grant")
            self._epoch = int(epoch)
            return self._epoch

    def current(self) -> int:
        with self._lock:
            return self._epoch

    def check(self, epoch: int) -> None:
        with self._lock:
            if epoch < 0 or epoch != self._epoch:
                raise StaleEpochError(epoch, self._epoch)


# ---------------------------------------------------------------------------
# Journal stores: same record API over an in-memory list (tests, sim) and
# an append-only JSONL file (real durability across a process crash).
# ---------------------------------------------------------------------------


def _fold_integrity(store, rep, new_desc) -> None:
    """Fold one load's NEW findings into the store's cumulative report
    and its wired ``journal_corrupt_records_total{store}`` counter.
    ``new_desc`` lists descriptions of the NEWLY quarantined entries
    only. Findings that persist in the stream across loads (write
    holes, crash-retry duplicates) count via high-water deltas — one
    event, one increment, however many loads re-observe it."""
    newly_corrupt = len(new_desc)
    new_gaps = max(0, rep.seq_gaps - store._gap_high)
    store._gap_high = max(store._gap_high, rep.seq_gaps)
    new_dups = max(0, rep.dup_seq - store._dup_high)
    store._dup_high = max(store._dup_high, rep.dup_seq)
    store.last_integrity = rep
    total = store.integrity_total
    total.corrupt += newly_corrupt
    total.seq_gaps += new_gaps
    total.dup_seq += new_dups
    total.legacy = rep.legacy
    # kept/total mirror the LATEST load (cumulative counts above carry
    # the history; the size fields answer "what does the store hold now")
    total.kept = rep.kept
    total.total = rep.total
    total.torn_tail |= rep.torn_tail
    total.quarantined.extend(new_desc)
    fresh = newly_corrupt + new_gaps
    if fresh and store.corrupt_counter is not None:
        store.corrupt_counter.inc(float(fresh))


class MemoryJournalStore:
    """Record list in memory — survives a *simulated* crash (the store
    object outlives the scheduler it journals for), not a real one.

    ``lock`` serializes multi-writer access at the STORE: several
    BindJournal instances legitimately share one store (the standby-
    forget pattern journals through a fresh view of the owner's store),
    and each instance's own lock cannot order their writes against a
    compaction rewrite.

    State-integrity PR: every append/rewrite SEALS its record with the
    shared CRC codec (:mod:`..core.integrity`) and every load screens —
    an unverifiable record (the ``journal.corrupt_record`` chaos point's
    simulated media fault) is moved into :attr:`quarantined`, counted,
    and every verifiable record after it is kept."""

    def __init__(self, name: str = "memory") -> None:
        self.lock = threading.RLock()
        self.name = name
        self._records: List[dict] = []
        #: corrupt records screened out of the live stream, in detection
        #: order — the in-memory analog of the file store's sidecar
        self.quarantined: List[dict] = []
        #: optional ``journal_corrupt_records_total{store}`` child
        #: counter, incremented once per NEWLY detected corrupt record
        #: or write hole
        self.corrupt_counter = None
        #: last load's screening report / cumulative new findings
        self.last_integrity = integrity.IntegrityReport(store=name)
        self.integrity_total = integrity.IntegrityReport(store=name)
        self._gap_high = 0
        self._dup_high = 0
        #: seqs of quarantined records still relevant to the CURRENT
        #: stream's numbering (cleared on rewrite — a compaction
        #: renumbers, and a stale low anchor would fabricate holes)
        self._known_missing: set = set()

    def append(self, record: dict) -> None:
        self._records.append(integrity.seal(record))

    def load(self) -> List[dict]:
        with self.lock:
            kept, quarantine, rep = integrity.screen_records(
                [(dict(r), None) for r in self._records],
                store=self.name,
                # seqs of records already MOVED to the quarantine ledger
                # (this stream numbering's — see rewrite): their absence
                # is explained corruption, not a write hole
                known_missing_seqs=self._known_missing,
            )
            if quarantine:
                # quarantine is a MOVE: the corrupt record leaves the
                # live stream (so repeated loads do not re-count it) and
                # lands in the sidecar list for forensics/fsck
                bad = {pos for pos, _raw in quarantine}
                for pos in sorted(bad):
                    moved = self._records[pos]
                    self.quarantined.append(moved)
                    if isinstance(moved.get("seq"), int):
                        self._known_missing.add(moved["seq"])
                self._records = [
                    r
                    for pos, r in enumerate(self._records)
                    if pos not in bad
                ]
            _fold_integrity(self, rep, list(rep.quarantined))
            return kept

    def rewrite(self, records: Sequence[dict]) -> None:
        self._records = integrity.seal_records(records)
        # a rewrite renumbers the stream: stale gap/dup high-waters and
        # quarantined-seq anchors from the OLD numbering would fabricate
        # phantom write holes (and then mask real ones)
        self._gap_high = 0
        self._dup_high = 0
        self._known_missing.clear()

    def load_tail(self) -> Optional[List[dict]]:
        """Bounded-RTO read path: the verified records from the LAST
        checkpoint onward, or None when there is no usable checkpoint
        anchor OR the tail is not clean (caller falls back to
        :meth:`load`, which owns quarantine/counter/health accounting —
        the fast path must never swallow a corrupt acked record
        silently)."""
        with self.lock:
            start = None
            for i in range(len(self._records) - 1, -1, -1):
                if self._records[i].get("op") == "checkpoint":
                    start = i
                    break
            if start is None or start == 0:
                return None
            kept, quarantine, rep = integrity.screen_records(
                [(dict(r), None) for r in self._records[start:]],
                store=self.name,
            )
            if quarantine or not rep.ok:
                return None
            if kept and kept[0].get("op") == "checkpoint":
                return kept
            return None

    def corrupt_last_record(self) -> None:
        """Chaos helper (``journal.corrupt_record``): flip the payload of
        the most recent record WITHOUT re-sealing — the simulated media
        fault the load-time screen must quarantine."""
        if self._records:
            self._records[-1]["__bitrot__"] = 1


class FileJournalStore:
    """Append-only JSON-lines file. Each record is one line, flushed on
    append (``fsync=True`` additionally forces it to stable storage —
    the real durability point; default off because per-record fsync
    dominates commit latency and tests/benches exercise replay, not
    media failure). ``load`` tolerates a torn final line: a crash mid-
    append leaves a partial record, which is exactly an unacknowledged
    write and is discarded.

    State-integrity PR: appends/rewrites SEAL each record with the
    shared CRC codec and ``load`` screens — an unverifiable MID-FILE
    line (media corruption, not a torn tail) is QUARANTINED into the
    ``<path>.quarantine`` sidecar, counted
    (``journal_corrupt_records_total{store}``), and every verifiable
    line after it is kept instead of silently truncated. Records
    without a ``crc`` field (pre-codec journals) load read-only."""

    def __init__(self, path: str, fsync: bool = False,
                 name: Optional[str] = None):
        self.path = path
        self.fsync = fsync
        self.name = name if name is not None else os.path.basename(path)
        #: same multi-writer contract as MemoryJournalStore.lock
        self.lock = threading.RLock()
        #: same integrity surface as MemoryJournalStore
        self.corrupt_counter = None
        self.last_integrity = integrity.IntegrityReport(store=self.name)
        self.integrity_total = integrity.IntegrityReport(store=self.name)
        self._gap_high = 0
        self._dup_high = 0
        #: line positions already quarantined (the sidecar write and the
        #: counter must fire once per corrupt line, not once per load —
        #: the file is append-only between rewrites, so positions are
        #: stable; reset on rewrite/repair)
        self._quarantined_pos: set = set()
        # a crash mid-compaction leaves a stale (possibly torn) temp file
        # behind; the atomic-rename discipline means it was never the
        # journal — drop it so it cannot shadow a later rewrite
        try:
            os.unlink(path + ".tmp")
        except FileNotFoundError:
            pass
        self._repair_torn_tail()
        self._f = open(path, "a", encoding="utf-8")

    def _repair_torn_tail(self) -> None:
        """Truncate a partial final line left by a crash mid-append —
        BEFORE the append handle opens. Without this the next append
        would merge into the partial line, making one unparseable record
        that load() stops at, silently discarding every post-restart
        append behind it. The truncated bytes were never acknowledged."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size == 0:
                    return
                f.seek(size - 1)
                if f.read(1) == b"\n":
                    return
                f.seek(0)
                data = f.read(size)
                cut = data.rfind(b"\n") + 1  # 0 when no newline at all
                f.truncate(cut)
        except FileNotFoundError:
            pass

    def append(self, record: dict) -> None:
        self._f.write(
            json.dumps(integrity.seal(record), separators=(",", ":")) + "\n"
        )
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def load(self) -> List[dict]:
        with self.lock:
            entries: List[tuple] = []
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    for line in f:
                        stripped = line.strip()
                        if not stripped:
                            continue
                        try:
                            entries.append((json.loads(stripped), stripped))
                        except json.JSONDecodeError:
                            # screen_records decides: torn tail when
                            # final, quarantined corruption otherwise
                            entries.append((None, stripped))
            except FileNotFoundError:
                return []
            kept, quarantine, rep = integrity.screen_records(
                entries, store=self.name
            )
            # quarantine and rep.quarantined are parallel: select the
            # entries not seen before (positions are stable between
            # rewrites in an append-only file), so the sidecar write,
            # the counter and the cumulative descriptions each fire
            # once per corrupt line
            fresh_idx = [
                i
                for i, (pos, _raw) in enumerate(quarantine)
                if pos not in self._quarantined_pos
            ]
            if fresh_idx:
                with open(
                    self.path + ".quarantine", "a", encoding="utf-8"
                ) as q:
                    for i in fresh_idx:
                        q.write((quarantine[i][1] or "") + "\n")
                self._quarantined_pos.update(
                    quarantine[i][0] for i in fresh_idx
                )
            _fold_integrity(
                self, rep, [rep.quarantined[i] for i in fresh_idx]
            )
            return kept

    def rewrite(self, records: Sequence[dict]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for r in records:
                f.write(
                    json.dumps(integrity.seal(r), separators=(",", ":"))
                    + "\n"
                )
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")
        # a rewrite re-numbers the file: stale quarantine positions must
        # not mask corruption at re-used positions, and stale gap/dup
        # high-waters would fabricate (or absorb) write holes
        self._quarantined_pos.clear()
        self._gap_high = 0
        self._dup_high = 0

    def load_tail(self) -> Optional[List[dict]]:
        """Bounded-RTO read path (same contract as
        ``MemoryJournalStore.load_tail``): split lines cheaply, find the
        LAST line carrying a checkpoint marker by substring probe, and
        json-parse + CRC-verify only from there — recovery work scales
        with (live set + tail), not journal length. None when no usable
        anchor exists (caller falls back to the full :meth:`load`)."""
        with self.lock:
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    lines = [
                        ln.strip() for ln in f if ln.strip()
                    ]
            except FileNotFoundError:
                return None
            start = None
            for i in range(len(lines) - 1, -1, -1):
                if '"op":"checkpoint"' in lines[i]:
                    start = i
                    break
            if start is None or start == 0:
                return None
            entries: List[tuple] = []
            for raw in lines[start:]:
                try:
                    entries.append((json.loads(raw), raw))
                except json.JSONDecodeError:
                    entries.append((None, raw))
            kept, quarantine, rep = integrity.screen_records(
                entries, store=self.name
            )
            if quarantine or not rep.ok:
                # an unclean tail must go through the full load, which
                # owns quarantine/counter/health accounting — the fast
                # path never swallows a corrupt acked record silently
                return None
            if kept and kept[0].get("op") == "checkpoint":
                return kept
            return None

    def corrupt_last_record(self) -> None:
        """Chaos helper (``journal.corrupt_record``): flip one byte in
        the MIDDLE of the last line — a complete, newline-terminated,
        CRC-failing record (media corruption), distinct from a torn
        tail."""
        with self.lock, open(self.path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < 3:
                return
            f.seek(0)
            raw = f.read(size)
            cut = raw.rstrip(b"\n").rfind(b"\n") + 1
            line = raw[cut:].rstrip(b"\n")
            if not line:
                return
            mid = cut + len(line) // 2
            f.seek(mid)
            byte = raw[mid:mid + 1]
            f.write(b"#" if byte != b"#" else b"@")
            f.flush()

    def simulate_torn_rewrite(self, record: dict) -> None:
        """Chaos helper (``journal.compact_crash``): model a process
        death mid-rewrite — half of the checkpoint line reaches the temp
        file, the live log is untouched, and the dying process never got
        to the atomic rename. The next open must ignore the orphan."""
        line = json.dumps(record, separators=(",", ":"))
        with open(self.path + ".tmp", "w", encoding="utf-8") as f:
            f.write(line[: max(1, len(line) // 2)])
            f.flush()

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self) -> None:
        self._f.close()


# ---------------------------------------------------------------------------
# Replay view
# ---------------------------------------------------------------------------


@dataclass
class JournalReplay:
    """What a takeover rebuilds from the log: the acknowledged live set
    (binds minus forgets; an intent without a matching bind/abort —
    a crash mid-commit — contributes nothing, because the dying
    process's host mutations died with it)."""

    #: uid -> bind entry dict (node/req/est/prod/nom/conf), last write wins
    live: Dict[str, dict] = field(default_factory=dict)
    epoch_high: int = 0
    seq_high: int = 0
    binds: int = 0
    forgets: int = 0
    intents: int = 0
    aborts: int = 0
    #: intents never closed by a bind/abort (crash-mid-commit windows)
    open_intents: int = 0
    #: state-integrity PR: True when the replay fast-forwarded from a
    #: digest-verified checkpoint recovery image (bounded RTO — the
    #: pre-checkpoint history was neither parsed into the live set nor
    #: re-applied); the count of records actually APPLIED is
    #: ``applied`` (the RTO-bearing number the recovery bench sweeps)
    used_checkpoint: bool = False
    applied: int = 0
    #: checkpoint images REJECTED (image digest mismatch, or the
    #: ``checkpoint.digest_mismatch`` chaos point) — each rejection
    #: falls the replay back toward full history
    checkpoint_fallbacks: int = 0
    #: corrupt records the store quarantined across its lifetime (the
    #: zero-lost-ack soak reads it off the replay it already holds)
    corrupt_records: int = 0
    seq_gaps: int = 0


class BindJournal:
    """Write-ahead bind journal over a pluggable store.

    Record ops (one JSON object per record, ``seq`` strictly increasing):

    ``intent``      — a chunk commit is about to mutate host state:
                      ``planned`` carries the nominated (uid, node) pairs.
    ``bind``        — the chunk's Reserve+Permit held: ``binds`` carries
                      one entry per acknowledged pod with everything
                      ``restore_assumed`` needs to re-install the charge.
    ``abort``       — the chunk rolled back (the in-memory journal undid
                      the mutations); the preceding intent is void.
    ``forget``      — pods released (completion/eviction); replay drops
                      them from the live set.
    ``checkpoint``  — compaction marker carrying the full live set;
                      replay restarts from it.

    Every append is stamped with the writer's fencing epoch and refused
    (:class:`StaleEpochError`) when an append from a NEWER epoch has
    already landed — the journal is the fencing backstop at the storage
    boundary even when the in-process fence was bypassed.
    """

    def __init__(
        self,
        store=None,
        chaos=None,
        writes_counter=None,
        failures_counter=None,
        shard: Optional[int] = None,
        health=None,
    ):
        self.store = store if store is not None else MemoryJournalStore()
        self.chaos = chaos or NULL_INJECTOR
        #: optional ``journal_writes_total{op}`` / failure counters
        self.writes_counter = writes_counter
        self.failures_counter = failures_counter
        #: optional HealthRegistry: corruption detected at any load
        #: flips the ``journal_integrity`` row to degraded (a state, not
        #: an event — it stays degraded while quarantined records exist)
        self.health = health
        #: (corrupt, seq_gaps) high-water a successful verified recovery
        #: has absorbed: the journal_integrity row re-promotes to ok once
        #: a recovery proved the surviving records reconstruct a
        #: consistent world (degraded is a state, not a tombstone)
        self._integrity_resolved = (0, 0)
        #: shard this journal is scoped to (None = unsharded deployment);
        #: stamped on every record so a mixed-store forensic read can
        #: attribute writers, and epoch monotonicity is then per-shard
        #: by construction (one journal per shard)
        self.shard = shard
        self._lock = threading.Lock()
        tail, _bounded = self._load_for_replay()
        self._seq = max((r.get("seq", 0) for r in tail), default=0)
        self._epoch_high = max(
            (self._record_epoch_high(r) for r in tail), default=0
        )
        #: appends since the last checkpoint — drives maybe_compact
        #: without an O(records) store read per cycle
        self._since_checkpoint = sum(
            1
            for r in tail
            if r.get("op") not in ("checkpoint", "checkpoint_intent")
        )
        self._note_integrity()

    @staticmethod
    def _record_epoch_high(rec: dict) -> int:
        """A record's epoch evidence: its own stamp, plus — for a
        checkpoint recovery image — the journal epoch high it archived
        (the bounded tail load must not weaken fencing just because the
        pre-checkpoint history was never parsed)."""
        high = int(rec.get("epoch", 0))
        if rec.get("op") == "checkpoint":
            high = max(
                high, int((rec.get("extras") or {}).get("epoch_high", 0))
            )
        return high

    def _load_for_replay(self):
        """(records, bounded): the store's checkpoint-anchored tail when
        available — recovery work scales with (live set + tail), not
        journal length — else the full screened load."""
        tail_fn = getattr(self.store, "load_tail", None)
        if tail_fn is not None:
            tail = tail_fn()
            if tail:
                return tail, True
        return self.store.load(), False

    @property
    def epoch_high(self) -> int:
        with self._lock:
            return self._epoch_high

    # ---- integrity surface (state-integrity PR) ----

    def integrity_report(self):
        """The store's cumulative screening report (None for custom
        stores that predate the codec)."""
        return getattr(self.store, "integrity_total", None)

    def _note_integrity(self) -> None:
        """Reflect the store's cumulative integrity state onto the
        wired ``journal_integrity`` health row (called after every
        store load this journal performs)."""
        if self.health is None:
            return
        rep = self.integrity_report()
        if rep is None:
            return
        resolved = (
            rep.corrupt <= self._integrity_resolved[0]
            and rep.seq_gaps <= self._integrity_resolved[1]
        )
        if rep.ok:
            detail = f"store={rep.store} clean"
        elif resolved:
            detail = (
                f"store={rep.store} recovered past quarantine: "
                f"{rep.detail()}"
            )
        else:
            detail = f"store={rep.store} degraded: {rep.detail()}"
        self.health.set("journal_integrity", rep.ok or resolved, detail)

    def mark_integrity_recovered(self) -> None:
        """A verified recovery absorbed everything quarantined so far:
        re-promote the journal_integrity row (new corruption beyond this
        high-water degrades it again)."""
        rep = self.integrity_report()
        if rep is not None:
            self._integrity_resolved = (rep.corrupt, rep.seq_gaps)
        self._note_integrity()

    def _store_lock(self):
        """The store's multi-writer lock (stores without one — custom
        backends — fall back to no cross-instance ordering, same as
        before the lock existed)."""
        lock = getattr(self.store, "lock", None)
        return lock if lock is not None else contextlib.nullcontext()

    # ---- append side ----

    def _append(self, op: str, epoch: int, cycle: int, **fields) -> dict:
        try:
            if self.chaos.fire("journal.write_fail"):
                raise JournalWriteError(
                    f"injected journal write failure at op={op}"
                )
            with self._lock:
                if epoch is None:
                    # fence-exempt record (forgets): a release reflects
                    # an apiserver-observed deletion, authoritative
                    # regardless of who leads — stamp the current high
                    epoch = self._epoch_high
                if epoch < self._epoch_high:
                    raise StaleEpochError(
                        epoch, self._epoch_high, what="journal epoch"
                    )
                self._epoch_high = max(self._epoch_high, epoch)
                if self._seq > 0 and self.chaos.fire("journal.seq_gap"):
                    # corruption fault domain: a WRITE HOLE — a seq
                    # number consumed but its record never reaching the
                    # store (lost sector). Load-time screening counts
                    # the gap and degrades journal_integrity; no record
                    # (and no acknowledged state) is behind it. Guarded
                    # to an ESTABLISHED stream: a hole before the first
                    # record is indistinguishable from a compacted
                    # prefix, so injecting there would be undetectable
                    # by design.
                    self._seq += 1
                self._seq += 1
                rec = {
                    "seq": self._seq,
                    "epoch": int(epoch),
                    "cycle": int(cycle),
                    "op": op,
                    **fields,
                }
                if self.shard is not None:
                    rec["shard"] = int(self.shard)
                try:
                    with self._store_lock():
                        self.store.append(rec)
                except OSError as exc:
                    # roll the seq back: the record never landed, and a
                    # permanent hole here would read as a write hole at
                    # every future load (seq-gap screening is exact)
                    self._seq -= 1
                    raise JournalWriteError(
                        f"journal append failed: {exc!r}"
                    ) from exc
                if op == "intent" and self.chaos.fire(
                    "journal.corrupt_record"
                ):
                    # corruption fault domain: the record's bytes rot on
                    # media AFTER the append was acknowledged. Applied
                    # to the intent op (which contributes nothing to
                    # replay) so the soak can assert the quarantine
                    # machinery keeps every verifiable record AFTER the
                    # corrupt one — the silent-truncation bug this PR
                    # removes — while the zero-lost-ack ledger stays
                    # assertable.
                    bitrot = getattr(
                        self.store, "corrupt_last_record", None
                    )
                    if bitrot is not None:
                        with self._store_lock():
                            bitrot()
                self._since_checkpoint += 1
        except (JournalWriteError, StaleEpochError):
            if self.failures_counter is not None:
                self.failures_counter.inc()
            raise
        if self.writes_counter is not None:
            self.writes_counter.labels(op=op).inc()
        return rec

    def append_intent(
        self,
        epoch: int,
        cycle: int,
        planned: Sequence[Tuple[str, str]],
    ) -> dict:
        return self._append(
            "intent",
            epoch,
            cycle,
            planned=[[uid, node] for uid, node in planned],
        )

    def append_bind(
        self, epoch: int, cycle: int, entries: Sequence[dict]
    ) -> dict:
        """``entries``: per-pod dicts with keys ``uid``, ``node``,
        ``req`` (list), ``est`` (list), ``prod`` (bool), ``nom``
        (bind-nominal CPU milli), ``conf`` (confirmed flag); optionally
        ``numa``/``dev`` exact holds, ``quota`` leaf, and ``lc`` — the
        pod's compact lifecycle-trace context (original submit stamp +
        shard-hop count), carried durably so a takeover's replay can
        bridge the pod's timeline across a dead incarnation
        (fleet-tracing PR; consumed by ``runtime.recovery``)."""
        return self._append(
            "bind", epoch, cycle, binds=[dict(e) for e in entries]
        )

    def append_abort(self, epoch: int, cycle: int, reason: str = "") -> dict:
        return self._append("abort", epoch, cycle, reason=reason)

    def append_forget(
        self, epoch: Optional[int], cycle: int, uids: Sequence[str]
    ) -> dict:
        """``epoch=None`` marks the record fence-exempt: forgets mirror
        apiserver deletions, which a STANDBY must also journal (its
        informers keep observing completions during a leaderless gap —
        dropping them would let the next takeover's replay resurrect
        dead pods' charges)."""
        return self._append("forget", epoch, cycle, uids=list(uids))

    # ---- replay / compaction ----

    @staticmethod
    def _checkpoint_image_ok(rec: dict) -> bool:
        """A checkpoint record's recovery image is trustworthy when its
        content digest verifies (legacy checkpoints without one are
        trusted — the line-level CRC still covered them if sealed)."""
        stamped = rec.get("image_digest")
        if stamped is None:
            return True
        return stamped == integrity.payload_digest(
            {"live": rec.get("live", {}), "extras": rec.get("extras", {})}
        )

    def replay(self, use_checkpoint: bool = True) -> JournalReplay:
        """Rebuild the acknowledged live set.

        ``use_checkpoint=True`` (default) fast-forwards from the LAST
        digest-verified checkpoint recovery image and applies only the
        tail behind it — recovery work bounded by (live set + tail), not
        journal length. A checkpoint whose image digest fails is
        REJECTED (counted in ``checkpoint_fallbacks``) and the replay
        falls back to the next older verified image, or to full history.
        ``use_checkpoint=False`` forces the full-history walk (the
        recovery path's explicit fallback arm)."""
        rep = JournalReplay()
        records = None
        start = 0
        if use_checkpoint:
            tail_fn = getattr(self.store, "load_tail", None)
            if tail_fn is not None:
                tail = tail_fn()
                if tail and self._checkpoint_image_ok(tail[0]):
                    # bounded-RTO path: the pre-checkpoint prefix was
                    # never even parsed — recovery work is O(live+tail)
                    records = sorted(
                        tail, key=lambda r: r.get("seq", 0)
                    )
                    rep.used_checkpoint = True
        if records is None:
            records = sorted(
                self.store.load(), key=lambda r: r.get("seq", 0)
            )
            if use_checkpoint:
                for i in range(len(records) - 1, -1, -1):
                    if records[i].get("op") != "checkpoint":
                        continue
                    if self._checkpoint_image_ok(records[i]):
                        start = i
                        rep.used_checkpoint = True
                        break
                    # rejected images stay inside the applied window,
                    # where the walk below counts each exactly once
        # epoch/seq highs cover the WHOLE stream — fencing must not
        # weaken because a checkpoint bounded the applied window (a
        # checkpoint image archives the journal epoch high it covered)
        for rec in records:
            rep.epoch_high = max(
                rep.epoch_high, self._record_epoch_high(rec)
            )
            rep.seq_high = max(rep.seq_high, rec.get("seq", 0))
        open_intent = False
        for rec in records[start:]:
            op = rec.get("op")
            rep.applied += 1
            if op == "checkpoint":
                if not self._checkpoint_image_ok(rec):
                    # a rotted image inside the applied window: never
                    # reset the live set from untrusted bytes — skip it
                    # and keep folding the surrounding history
                    rep.checkpoint_fallbacks += 1
                    continue
                rep.live = {
                    uid: dict(e) for uid, e in rec.get("live", {}).items()
                }
                open_intent = False
            elif op == "intent":
                if open_intent:
                    rep.open_intents += 1
                rep.intents += 1
                open_intent = True
            elif op == "bind":
                rep.binds += 1
                open_intent = False
                for e in rec.get("binds", ()):
                    rep.live[e["uid"]] = dict(e)
            elif op == "abort":
                rep.aborts += 1
                open_intent = False
            elif op == "forget":
                rep.forgets += 1
                for uid in rec.get("uids", ()):
                    rep.live.pop(uid, None)
        if open_intent:
            rep.open_intents += 1
        integ = self.integrity_report()
        if integ is not None:
            rep.corrupt_records = integ.corrupt
            rep.seq_gaps = integ.seq_gaps
        self._note_integrity()
        return rep

    def _checkpoint_record(
        self, rep: JournalReplay, epoch: Optional[int], extras: dict
    ) -> dict:
        """One checkpoint RECOVERY IMAGE (state-integrity PR): the exact
        live set (bind entries already carry numa/dev holds, quota leaf
        and lc context), the journal's epoch high, caller extras (e.g.
        per-shard claim epoch-highs), and a content digest recovery
        verifies before trusting the image."""
        self._seq = max(self._seq, rep.seq_high) + 1
        live = {u: dict(e) for u, e in rep.live.items()}
        extras = dict(extras)
        extras.setdefault("epoch_high", int(self._epoch_high))
        checkpoint = {
            "seq": self._seq,
            "epoch": int(self._epoch_high if epoch is None else epoch),
            "cycle": -1,
            "op": "checkpoint",
            "live": live,
            "extras": extras,
        }
        checkpoint["image_digest"] = integrity.payload_digest(
            {"live": live, "extras": extras}
        )
        if self.shard is not None:
            checkpoint["shard"] = int(self.shard)
        return checkpoint

    def append_checkpoint(
        self, epoch: Optional[int] = None, extras: Optional[dict] = None
    ) -> JournalReplay:
        """Append a checkpoint recovery image WITHOUT dropping history
        (bounded-RTO acceleration): replay fast-forwards from it, but a
        digest mismatch can still fall back to the full journal — the
        belt :meth:`compact` cannot offer once it erased the prefix.
        Epoch-fenced like compaction."""
        with self._lock, self._store_lock():
            rep = self.replay()
            if epoch is not None and epoch < self._epoch_high:
                raise StaleEpochError(
                    epoch, self._epoch_high, what="checkpoint epoch"
                )
            checkpoint = self._checkpoint_record(rep, epoch, extras or {})
            try:
                self.store.append(checkpoint)
            except OSError as exc:
                self._seq -= 1
                raise JournalWriteError(
                    f"checkpoint append failed: {exc!r}"
                ) from exc
            self._since_checkpoint = 0
        if self.writes_counter is not None:
            self.writes_counter.labels(op="checkpoint").inc()
        return rep

    def compact(
        self, epoch: Optional[int] = None, extras: Optional[dict] = None
    ) -> JournalReplay:
        """Collapse the log to one checkpoint carrying the current live
        set (after a successful recovery, from the scheduler run loop via
        :meth:`maybe_compact`, or on a maintenance sweep so the log does
        not grow with cluster lifetime). A compaction stamped with an
        epoch older than one already journaled is refused — a deposed
        leader must not rewrite the log its successor is appending to.

        Intent-before-commit (state-integrity PR): a ``checkpoint_intent``
        record lands in the LIVE log before the rewrite, so a crash
        mid-rewrite leaves evidence of the attempt (replay treats the
        intent as a no-op); the checkpoint itself is a digest-stamped
        recovery image (:meth:`_checkpoint_record`).

        Failure domain: the ``journal.compact_crash`` chaos point models
        a process death mid-rewrite. The live log is untouched (the
        rewrite is tmp-file + atomic rename, so a crash before the
        rename loses only the unacknowledged checkpoint); callers see
        :class:`JournalWriteError` and the next open repairs/ignores the
        torn temp file."""
        with self._lock, self._store_lock():
            # replay INSIDE both locks: another BindJournal instance over
            # the same store (the standby-forget pattern) may append
            # between an outside-the-lock replay and the rewrite — the
            # rewrite would silently erase its acknowledged record. The
            # store lock orders this read-rewrite against those appends,
            # and the seq fixup keeps the checkpoint sorting after
            # records this instance never issued itself.
            rep = self.replay()
            if epoch is not None and epoch < self._epoch_high:
                raise StaleEpochError(
                    epoch, self._epoch_high, what="compaction epoch"
                )
            try:
                self.store.append(
                    {
                        "seq": max(self._seq, rep.seq_high) + 1,
                        "epoch": int(
                            self._epoch_high if epoch is None else epoch
                        ),
                        "cycle": -1,
                        "op": "checkpoint_intent",
                    }
                )
                self._seq = max(self._seq, rep.seq_high) + 1
            except OSError as exc:
                raise JournalWriteError(
                    f"checkpoint intent append failed: {exc!r}"
                ) from exc
            checkpoint = self._checkpoint_record(rep, epoch, extras or {})
            if self.chaos.fire("journal.compact_crash"):
                torn = getattr(self.store, "simulate_torn_rewrite", None)
                if torn is not None:
                    torn(checkpoint)
                raise JournalWriteError(
                    "injected crash mid-compaction (torn rewrite)"
                )
            try:
                self.store.rewrite([checkpoint])
            except OSError as exc:
                raise JournalWriteError(
                    f"journal compaction failed: {exc!r}"
                ) from exc
            self._since_checkpoint = 0
        return rep

    def maybe_compact(
        self,
        epoch: Optional[int] = None,
        min_records: int = 512,
        min_bytes: Optional[int] = None,
    ) -> Optional[JournalReplay]:
        """Threshold-gated :meth:`compact` for the scheduler run loop
        (ROADMAP queued follow-on): compacts when at least
        ``min_records`` records landed since the last checkpoint, or —
        for stores that report a size — when the log file exceeds
        ``min_bytes``. Returns the replay when compaction ran, None when
        below threshold."""
        with self._lock:
            due = self._since_checkpoint >= max(1, int(min_records))
            if not due and min_bytes is not None:
                size_fn = getattr(self.store, "size_bytes", None)
                due = size_fn is not None and size_fn() >= min_bytes
        if not due:
            return None
        return self.compact(epoch)

    def records(self) -> List[dict]:
        return self.store.load()


# ---------------------------------------------------------------------------
# Cross-shard single-winner claims
# ---------------------------------------------------------------------------


class ClaimConflictError(RuntimeError):
    """The pod's claim is already held by a different shard — the caller
    must not schedule it (the winner shard will)."""


class ClaimTable:
    """Single-winner pod→shard claim arbiter (horizontal partitioning).

    A pending pod whose feasible nodes span shards may be fanned out to
    several shards' queues; before a shard's pump feeds the pod it must
    :meth:`claim` it. The first claim wins and is durably recorded
    (``op="claim"`` over the same store API the journals use), every
    later claim from another shard loses (returns False), and a repeat
    claim by the winner is idempotent — so two shards can never bind the
    same pod. Claims are epoch-fenced **per shard**: a claim stamped
    with an epoch older than the shard's highest already-claimed epoch
    is refused outright (:class:`StaleEpochError`) — a deposed shard
    owner cannot grab new work on its way down.

    Cross-shard gangs (elastic-topology PR) add a TWO-PHASE protocol on
    top: :meth:`gang_prepare` takes all-or-nothing HOLDS on every member
    of a gang whose feasible nodes span shards (a hold makes rival
    claims lose like a claim does, but the holder shard's own feed-time
    :meth:`claim` still succeeds); :meth:`gang_commit` converts the
    holds into ordinary claims once every member bound, and
    :meth:`gang_abort` drops them entirely — no tombstone, because an
    aborted member was never placed and MUST stay claimable for the
    retry. Crash semantics: a ``gang_hold`` record with no matching
    ``gang_commit`` is discarded on reload — a claim phase that died
    mid-flight leaves ZERO holds behind.

    Elastic topology (same PR): :meth:`rehome` re-points claims across
    a shard split/merge — bound pods' claims follow their node to the
    child shard; claims won by a RETIRED shard with no known
    destination are voided (the pod re-claims at its next feed, which
    is safe: single-winner arbitration still decides exactly one
    feeder). Tombstones need no re-homing — they are shard-less by
    construction (a settled uid loses everywhere)."""

    def __init__(self, store=None, clock=_time.time, shard_live=None):
        self.store = store if store is not None else MemoryJournalStore()
        self.clock = clock
        #: optional predicate ``shard_id -> bool`` (the topology's
        #: ``is_active``): when wired, a claim held by a RETIRED shard
        #: self-heals to the live claimant — the closing stitch for the
        #: window between a topology commit and its claim re-home (a
        #: crash there would otherwise strand queued pods on a winner
        #: cell that can never schedule them). Safe because a retired
        #: cell is not electable and its fence was advanced: nothing
        #: can bind under it.
        self.shard_live = shard_live
        self._lock = threading.Lock()
        self._seq = 0
        #: uid -> winning shard
        self._winners: Dict[str, int] = {}
        #: uid -> (gang id, holder shard): two-phase gang HOLDS — not
        #: yet claims, but rival shards' claims lose against them
        self._holds: Dict[str, Tuple[str, int]] = {}  # guarded-by: self._lock
        #: gang id -> {uid: holder shard} for commit/abort bookkeeping
        self._gangs: Dict[str, Dict[str, int]] = {}  # guarded-by: self._lock
        #: released (GC'd) uid -> settle timestamp — tombstones, NOT
        #: free slots: a release happens at pod deletion, but a
        #: fanned-out copy of the pod can still sit in some backlogged
        #: shard's queue; letting that copy re-claim a freed uid would
        #: re-schedule a dead pod. :meth:`gc_tombstones` compacts
        #: tombstones OLDER than a retention window (rides the shard
        #: journal's run-loop compaction) — inside the window a
        #: post-release claim still loses.
        self._settled: Dict[str, float] = {}
        #: shard -> highest epoch ever used to claim
        self._epoch_high: Dict[int, int] = {}
        for rec in sorted(self.store.load(), key=lambda r: r.get("seq", 0)):
            op = rec.get("op")
            self._seq = max(self._seq, rec.get("seq", 0))
            if op == "claim":
                uid, shard = rec.get("uid"), int(rec.get("shard", -1))
                epoch = int(rec.get("epoch", 0))
                if uid not in self._settled:
                    held = self._winners.get(uid)
                    if held is None or (
                        self.shard_live is not None
                        and not self.shard_live(held)
                    ):
                        # first claim wins — unless the first winner's
                        # cell has since retired, in which case the
                        # later self-healed claim record is the truth
                        self._winners[uid] = shard
                self._epoch_high[shard] = max(
                    self._epoch_high.get(shard, 0), epoch
                )
            elif op == "claim_release":
                self._winners.pop(rec.get("uid"), None)
                self._settled[rec.get("uid")] = float(rec.get("ts", 0.0))
            elif op == "claim_epoch_high":
                # tombstone-GC checkpoint: per-shard epoch highs survive
                # even when every claim record of a shard was compacted
                # away (fencing must not weaken across a GC + reload)
                for shard_s, epoch in (rec.get("highs") or {}).items():
                    shard_i = int(shard_s)
                    self._epoch_high[shard_i] = max(
                        self._epoch_high.get(shard_i, 0), int(epoch)
                    )
            elif op == "gang_hold":
                gang = rec.get("gang")
                members = {
                    u: int(s) for u, s in (rec.get("members") or {}).items()
                }
                self._gangs[gang] = members
                for u, s in members.items():
                    self._holds[u] = (gang, s)
                for shard_s, epoch in (rec.get("epochs") or {}).items():
                    shard_i = int(shard_s)
                    self._epoch_high[shard_i] = max(
                        self._epoch_high.get(shard_i, 0), int(epoch)
                    )
            elif op == "gang_commit":
                members = self._gangs.pop(rec.get("gang"), {})
                for u, s in members.items():
                    self._holds.pop(u, None)
                    if u not in self._settled:
                        self._winners.setdefault(u, s)
            elif op == "gang_abort":
                for u in self._gangs.pop(rec.get("gang"), {}):
                    self._holds.pop(u, None)
            elif op == "claim_void":
                for u in rec.get("uids", ()):
                    self._winners.pop(u, None)
                    hold = self._holds.pop(u, None)
                    if hold is not None and hold[0] in self._gangs:
                        self._gangs[hold[0]].pop(u, None)
            elif op == "claim_rehome":
                moves = {
                    u: int(s) for u, s in (rec.get("moves") or {}).items()
                }
                void = {int(s) for s in rec.get("void", ())}
                self._apply_rehome_locked(moves, void)
        # crash semantics: a gang whose hold record was never closed by a
        # commit/abort belongs to a claim phase that DIED mid-flight —
        # its holds evaporate here, leaving every member claimable again
        for gang in list(self._gangs):
            for u in self._gangs.pop(gang):
                self._holds.pop(u, None)

    def claim(self, uid: str, shard: int, epoch: int) -> bool:
        """True when ``shard`` owns (or now wins) the pod's claim; False
        when another shard already won. Raises :class:`StaleEpochError`
        when ``epoch`` is older than the shard's claim-epoch high — the
        fencing check every claim flows through."""
        with self._lock:
            high = self._epoch_high.get(shard, 0)
            if epoch < 0 or epoch < high:
                raise StaleEpochError(epoch, high, what="claim epoch")
            if uid in self._settled:
                # the pod was decided AND GC'd — a claim now can only be
                # a stale fanned-out queue copy; losing it (False) makes
                # the caller drop the pod, which is correct: it is gone
                return False
            hold = self._holds.get(uid)
            if hold is not None:
                # a two-phase gang hold stands in for the claim until the
                # gang commits: the holder shard's own feed proceeds, any
                # rival loses (the gang decides the pod's fate, not the
                # fan-out race)
                return hold[1] == shard
            held = self._winners.get(uid)
            if held is not None:
                if held == shard:
                    return True
                if self.shard_live is None or self.shard_live(held):
                    return False
                # orphaned claim: its winner cell RETIRED (a crash
                # between a topology commit and the claim re-home
                # leaves exactly these) — self-heal to the live
                # claimant instead of dropping the pod forever
            self._seq += 1
            rec = {
                "seq": self._seq,
                "op": "claim",
                "uid": uid,
                "shard": int(shard),
                "epoch": int(epoch),
            }
            try:
                self.store.append(rec)
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"claim append failed: {exc!r}"
                ) from exc
            self._winners[uid] = int(shard)
            self._epoch_high[shard] = max(high, epoch)
            return True

    def winner(self, uid: str) -> Optional[int]:
        with self._lock:
            return self._winners.get(uid)

    def release(self, uid: str) -> None:
        """Settle a claim at pod GC: the winner mapping is dropped but
        the uid is TOMBSTONED, not freed — a stale fanned-out copy of
        the pod may still sit in a backlogged shard's queue, and letting
        it re-claim the uid would re-schedule a dead pod. A release is
        recorded so a reload keeps the tombstone. A uid that was never
        claimed needs no tombstone: fan-out copies must claim before
        binding, and only a bound pod can complete — so no stale copy of
        an unclaimed pod can exist."""
        with self._lock:
            if self._winners.pop(uid, None) is None:
                return
            ts = float(self.clock())
            self._settled[uid] = ts
            self._seq += 1
            try:
                self.store.append(
                    {
                        "seq": self._seq,
                        "op": "claim_release",
                        "uid": uid,
                        "ts": ts,
                    }
                )
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"claim release append failed: {exc!r}"
                ) from exc

    # ---- two-phase cross-shard gang claims (elastic-topology PR) ----

    def gang_prepare(
        self,
        gang: str,
        members: Dict[str, int],
        epochs: Dict[int, int],
        now: Optional[float] = None,
    ) -> bool:
        """Phase 1: take holds on EVERY member or none. ``members`` maps
        uid → the shard that will schedule it; ``epochs`` carries each
        involved shard's held fencing epoch (checked against the shard's
        claim-epoch high exactly like :meth:`claim` — a deposed owner
        cannot anchor a gang on its way down). Returns False — with zero
        holds taken — when any member is settled, already claimed by a
        shard other than its planned one, or held by another gang."""
        with self._lock:
            for shard in sorted(set(members.values())):
                epoch = int(epochs.get(shard, -1))
                high = self._epoch_high.get(shard, 0)
                if epoch < 0 or epoch < high:
                    raise StaleEpochError(
                        epoch, high, what="gang claim epoch"
                    )
            for uid, shard in members.items():
                if uid in self._settled:
                    return False
                hold = self._holds.get(uid)
                if hold is not None and hold != (gang, shard):
                    return False
                won = self._winners.get(uid)
                if won is not None and won != shard:
                    return False
            self._seq += 1
            rec = {
                "seq": self._seq,
                "op": "gang_hold",
                "gang": gang,
                "members": {u: int(s) for u, s in members.items()},
                "epochs": {str(s): int(e) for s, e in epochs.items()},
                "ts": float(self.clock() if now is None else now),
            }
            try:
                self.store.append(rec)
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"gang hold append failed: {exc!r}"
                ) from exc
            self._gangs[gang] = {u: int(s) for u, s in members.items()}
            for uid, shard in members.items():
                self._holds[uid] = (gang, int(shard))
            for shard, epoch in epochs.items():
                self._epoch_high[shard] = max(
                    self._epoch_high.get(int(shard), 0), int(epoch)
                )
            return True

    def gang_commit(self, gang: str) -> None:
        """Phase 2 success: every member bound — holds become ordinary
        claims (so pod-GC release/tombstone semantics apply from here)."""
        with self._lock:
            members = self._gangs.pop(gang, None)
            if members is None:
                return
            self._seq += 1
            try:
                self.store.append(
                    {"seq": self._seq, "op": "gang_commit", "gang": gang}
                )
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"gang commit append failed: {exc!r}"
                ) from exc
            for uid, shard in members.items():
                self._holds.pop(uid, None)
                if uid not in self._settled:
                    self._winners.setdefault(uid, shard)

    def gang_abort(self, gang: str) -> None:
        """Phase 2 failure: drop every hold ENTIRELY — no claim, no
        tombstone. The members were never placed, so they must stay
        claimable for whatever retry/re-route comes next; a tombstone
        here would brick them forever (zero-zombie-holds contract)."""
        with self._lock:
            members = self._gangs.pop(gang, None)
            if members is None:
                return
            self._seq += 1
            try:
                self.store.append(
                    {"seq": self._seq, "op": "gang_abort", "gang": gang}
                )
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"gang abort append failed: {exc!r}"
                ) from exc
            for uid in members:
                self._holds.pop(uid, None)

    def gang_holds(self, gang: Optional[str] = None) -> int:
        """Live (uncommitted, unaborted) hold count — the zero-zombie
        assertion surface."""
        with self._lock:
            if gang is not None:
                return len(self._gangs.get(gang, {}))
            return len(self._holds)

    # ---- topology re-home (shard split/merge) ----

    def _apply_rehome_locked(
        self, moves: Dict[str, int], void: set
    ) -> None:
        for uid, dest in moves.items():
            if uid in self._winners:
                self._winners[uid] = int(dest)
            if uid in self._holds:
                gang, _s = self._holds[uid]
                self._holds[uid] = (gang, int(dest))
                if gang in self._gangs and uid in self._gangs[gang]:
                    self._gangs[gang][uid] = int(dest)
        if void:
            for uid, shard in list(self._winners.items()):
                if shard in void and uid not in moves:
                    del self._winners[uid]
            for uid, (gang, shard) in list(self._holds.items()):
                if shard in void and uid not in moves:
                    del self._holds[uid]
                    if gang in self._gangs:
                        self._gangs[gang].pop(uid, None)

    def void_claims(self, uids: Sequence[str]) -> None:
        """Drop any claim/hold records for these uids WITHOUT a
        tombstone (overload-control PR, gang-abort hygiene): a topology
        transition between ``gang_prepare`` and the abort can VOID a
        queued member's hold (``rehome``), after which its feed
        re-claims as an ordinary winner — ``gang_abort`` only drops
        holds, so that re-established claim would otherwise pin the
        aborted member to one shard forever (its resubmitted copy,
        routed anywhere else, loses every claim and is never fed).
        No-op (and no journal record) when nothing is held."""
        with self._lock:
            hit = [
                u
                for u in uids
                if u in self._winners or u in self._holds
            ]
            if not hit:
                return
            self._seq += 1
            rec = {"seq": self._seq, "op": "claim_void", "uids": hit}
            try:
                self.store.append(rec)
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"claim void append failed: {exc!r}"
                ) from exc
            for u in hit:
                self._winners.pop(u, None)
                hold = self._holds.pop(u, None)
                if hold is not None and hold[0] in self._gangs:
                    self._gangs[hold[0]].pop(u, None)

    def rehome(
        self, moves: Dict[str, int], void_shards: Sequence[int] = ()
    ) -> None:
        """Shard split/merge commit: re-point claims to the shards that
        now own their pods. ``moves`` maps uid → destination shard (the
        child owning the pod's node, from the journal re-home);
        ``void_shards`` names the RETIRED shard ids — any remaining
        claim won by one of them (a queued, not-yet-bound pod) is voided
        so the pod can re-claim wherever the new topology routes it.
        One journaled record, so a reload replays the same state."""
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq,
                "op": "claim_rehome",
                "moves": {u: int(s) for u, s in moves.items()},
                "void": [int(s) for s in void_shards],
            }
            try:
                self.store.append(rec)
            except OSError as exc:
                self._seq -= 1  # no record landed: no write hole
                raise JournalWriteError(
                    f"claim rehome append failed: {exc!r}"
                ) from exc
            self._apply_rehome_locked(
                {u: int(s) for u, s in moves.items()},
                {int(s) for s in void_shards},
            )

    def tombstones_live(self) -> int:
        """Settled uids currently retained (the ``claim_tombstones_live``
        gauge's source)."""
        with self._lock:
            return len(self._settled)

    def gc_tombstones(
        self, retention_s: float, now: Optional[float] = None
    ) -> int:
        """Compact tombstones settled more than ``retention_s`` ago
        (queued PR 6 follow-on, driven by the shard journal's run-loop
        compaction). INSIDE the retention window a tombstone survives
        compaction — a post-GC claim on such a uid still loses — so the
        window must exceed the longest a fanned-out queue copy can
        plausibly outlive its pod's GC. The store is rewritten to the
        minimal equivalent log: one per-shard epoch-high checkpoint
        (fencing survives even when a shard's every claim record is
        dropped), the live claims, and the retained tombstones. Returns
        the number of tombstones still live."""
        if now is None:
            now = float(self.clock())
        cutoff = now - retention_s
        with self._lock:
            expired = [
                uid for uid, ts in self._settled.items() if ts <= cutoff
            ]
            if not expired:
                return len(self._settled)
            for uid in expired:
                del self._settled[uid]
            records: List[dict] = []
            self._seq += 1
            records.append(
                {
                    "seq": self._seq,
                    "op": "claim_epoch_high",
                    "highs": {
                        str(s): int(e) for s, e in self._epoch_high.items()
                    },
                }
            )
            for uid, shard in self._winners.items():
                self._seq += 1
                records.append(
                    {
                        "seq": self._seq,
                        "op": "claim",
                        "uid": uid,
                        "shard": int(shard),
                        "epoch": int(self._epoch_high.get(shard, 0)),
                    }
                )
            for uid, ts in self._settled.items():
                self._seq += 1
                records.append(
                    {
                        "seq": self._seq,
                        "op": "claim_release",
                        "uid": uid,
                        "ts": float(ts),
                    }
                )
            for gang, members in self._gangs.items():
                # live two-phase holds survive the rewrite — they are
                # open state, not history (an in-flight gang's claim
                # phase must not evaporate under a tombstone sweep)
                self._seq += 1
                records.append(
                    {
                        "seq": self._seq,
                        "op": "gang_hold",
                        "gang": gang,
                        "members": {
                            u: int(s) for u, s in members.items()
                        },
                        "epochs": {},
                        "ts": float(now),
                    }
                )
            try:
                self.store.rewrite(records)
            except OSError as exc:
                raise JournalWriteError(
                    f"claim tombstone GC failed: {exc!r}"
                ) from exc
            return len(self._settled)
