"""CPU topology model + cpuset accumulator.

Rebuild of the reference's CPU orchestration core
(``pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go:87-245,345-800``
and koordlet's NodeResourceTopology reporting): a node's CPUs form a
socket → NUMA-node → physical-core → logical-CPU hierarchy; exclusive
cpusets for LSR/LSE pods are taken greedily — whole sockets first, then
whole cores, then single threads — honoring the FullPCPUs / SpreadByPCPUs
bind policies.

Zone-level *feasibility* is decided on TPU (``ops.numa``); the exact CPU id
selection here is per-winner host work (SURVEY §7 step 6).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set, Tuple


class CPUBindPolicy(enum.Enum):
    """Pod-requested bind policy (reference ``apis/extension/numa_aware.go``
    CPUBindPolicy*)."""

    DEFAULT = "Default"
    FULL_PCPUS = "FullPCPUs"           # whole physical cores only
    SPREAD_BY_PCPUS = "SpreadByPCPUs"  # spread threads across cores
    CONSTRAINED_BURST = "ConstrainedBurst"


class NUMAPolicy(enum.IntEnum):
    """Node topology manager policy (reference
    ``frameworkext/topologymanager/policy_*.go``)."""

    NONE = 0
    BEST_EFFORT = 1
    RESTRICTED = 2
    SINGLE_NUMA_NODE = 3


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    cpu_id: int
    core_id: int
    numa_node: int
    socket: int


@dataclasses.dataclass
class CPUTopology:
    """Logical CPU inventory of one node."""

    cpus: List[CPUInfo]

    @classmethod
    def uniform(
        cls,
        sockets: int = 2,
        numa_per_socket: int = 1,
        cores_per_numa: int = 8,
        threads_per_core: int = 2,
    ) -> "CPUTopology":
        cpus: List[CPUInfo] = []
        cpu_id = 0
        core_id = 0
        for s in range(sockets):
            for n in range(numa_per_socket):
                numa = s * numa_per_socket + n
                for _ in range(cores_per_numa):
                    for _t in range(threads_per_core):
                        cpus.append(CPUInfo(cpu_id, core_id, numa, s))
                        cpu_id += 1
                    core_id += 1
        return cls(cpus=cpus)

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    @property
    def num_numa_nodes(self) -> int:
        return max((c.numa_node for c in self.cpus), default=-1) + 1

    def cpus_in_numa(self, numa: int) -> List[CPUInfo]:
        return [c for c in self.cpus if c.numa_node == numa]


class CPUAccumulator:
    """Greedy exclusive-cpuset allocator over one node's topology.

    Mirrors ``takeCPUs`` (``cpu_accumulator.go``): satisfy a request of
    ``n`` CPUs preferring (1) whole free sockets, (2) whole free cores,
    (3) single free threads; FullPCPUs requires the result to consist of
    whole physical cores; SpreadByPCPUs picks one thread per core across
    cores before doubling up.
    """

    def __init__(self, topology: CPUTopology):
        self.topology = topology
        self._allocated: Set[int] = set()
        #: pod uid -> cpu ids
        self._owners: Dict[str, Set[int]] = {}
        # static topology facts, computed once — recomputing them per
        # take() made the accumulator the host-path hot spot (O(cpus ×
        # cores) scans per winner)
        core_counts: Dict[int, int] = {}
        socket_counts: Dict[int, int] = {}
        for c in topology.cpus:
            core_counts[c.core_id] = core_counts.get(c.core_id, 0) + 1
            socket_counts[c.socket] = socket_counts.get(c.socket, 0) + 1
        self._threads_per_core = max(core_counts.values(), default=1)
        self._socket_size = max(socket_counts.values(), default=1)

    @property
    def available(self) -> List[CPUInfo]:
        return [c for c in self.topology.cpus if c.cpu_id not in self._allocated]

    def free_count(self, numa: Optional[int] = None) -> int:
        return sum(
            1
            for c in self.available
            if numa is None or c.numa_node == numa
        )

    def take(
        self,
        owner: str,
        n_cpus: int,
        policy: CPUBindPolicy = CPUBindPolicy.DEFAULT,
        numa: Optional[int] = None,
    ) -> Optional[Set[int]]:
        """Allocate ``n_cpus`` exclusive CPUs, optionally pinned to one NUMA
        node. Returns the cpu-id set or None if unsatisfiable."""
        avail = [
            c for c in self.available if numa is None or c.numa_node == numa
        ]
        if len(avail) < n_cpus:
            return None

        by_core: Dict[int, List[CPUInfo]] = {}
        for c in avail:
            by_core.setdefault(c.core_id, []).append(c)
        threads_per_core = self._threads_per_core
        full_cores = {
            cid: cs for cid, cs in by_core.items() if len(cs) == threads_per_core
        }

        taken: List[int] = []
        if policy == CPUBindPolicy.FULL_PCPUS:
            if n_cpus % threads_per_core != 0:
                return None
            need_cores = n_cpus // threads_per_core
            if len(full_cores) < need_cores:
                return None
            for cid in sorted(full_cores)[:need_cores]:
                taken.extend(c.cpu_id for c in full_cores[cid])
        elif policy == CPUBindPolicy.SPREAD_BY_PCPUS:
            # round-robin one thread per core, widest spread first
            cores_sorted = sorted(
                by_core.items(), key=lambda kv: (-len(kv[1]), kv[0])
            )
            ring = [sorted(cs, key=lambda c: c.cpu_id) for _, cs in cores_sorted]
            depth = 0
            while len(taken) < n_cpus:
                progressed = False
                for cs in ring:
                    if depth < len(cs) and len(taken) < n_cpus:
                        taken.append(cs[depth].cpu_id)
                        progressed = True
                if not progressed:
                    return None
                depth += 1
        else:
            # default: whole sockets, then whole cores, then loose threads
            by_socket: Dict[int, List[CPUInfo]] = {}
            for c in avail:
                by_socket.setdefault(c.socket, []).append(c)
            socket_size = self._socket_size
            for s in sorted(by_socket):
                cs = by_socket[s]
                if len(cs) == socket_size and n_cpus - len(taken) >= socket_size:
                    taken.extend(c.cpu_id for c in cs)
            remaining = n_cpus - len(taken)
            if remaining > 0:
                taken_set = set(taken)
                rem_cores = {
                    cid: [c for c in cs if c.cpu_id not in taken_set]
                    for cid, cs in by_core.items()
                }
                for cid in sorted(rem_cores):
                    cs = rem_cores[cid]
                    if len(cs) == threads_per_core and remaining >= threads_per_core:
                        taken.extend(c.cpu_id for c in cs)
                        remaining -= threads_per_core
                if remaining > 0:
                    taken_set = set(taken)
                    loose = [c.cpu_id for c in avail if c.cpu_id not in taken_set]
                    taken.extend(loose[:remaining])
                    remaining = 0
        if len(taken) < n_cpus:
            return None
        result = set(taken[:n_cpus])
        self._allocated |= result
        self._owners.setdefault(owner, set()).update(result)
        return result

    def release(self, owner: str) -> None:
        cpus = self._owners.pop(owner, set())
        self._allocated -= cpus

    def cpuset_of(self, owner: str) -> Optional[Set[int]]:
        return self._owners.get(owner)


def format_cpuset(cpus: Sequence[int]) -> str:
    """Render a cpuset in kernel list format (e.g. "0-3,8,10-11")."""
    ids = sorted(set(cpus))
    if not ids:
        return ""
    parts: List[str] = []
    start = prev = ids[0]
    for c in ids[1:]:
        if c == prev + 1:
            prev = c
            continue
        parts.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = c
    parts.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(parts)


def parse_cpuset(text: str) -> Set[int]:
    out: Set[int] = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            a, b = part.split("-", 1)
            out.update(range(int(a), int(b) + 1))
        else:
            out.add(int(part))
    return out
