"""CPU topology model + cpuset accumulator.

Rebuild of the reference's CPU orchestration core
(``pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go:87-245,345-800``
and koordlet's NodeResourceTopology reporting): a node's CPUs form a
socket → NUMA-node → physical-core → logical-CPU hierarchy; exclusive
cpusets for LSR/LSE pods are taken greedily — whole sockets first, then
whole cores, then single threads — honoring the FullPCPUs / SpreadByPCPUs
bind policies.

Zone-level *feasibility* is decided on TPU (``ops.numa``); the exact CPU id
selection here is per-winner host work (SURVEY §7 step 6).
"""

from __future__ import annotations

import dataclasses
import enum
import heapq

import numpy as np
from typing import Dict, List, Optional, Sequence, Set, Tuple


class CPUBindPolicy(enum.Enum):
    """Pod-requested bind policy (reference ``apis/extension/numa_aware.go``
    CPUBindPolicy*)."""

    DEFAULT = "Default"
    FULL_PCPUS = "FullPCPUs"           # whole physical cores only
    SPREAD_BY_PCPUS = "SpreadByPCPUs"  # spread threads across cores
    CONSTRAINED_BURST = "ConstrainedBurst"


class NUMAPolicy(enum.IntEnum):
    """Node topology manager policy (reference
    ``frameworkext/topologymanager/policy_*.go``)."""

    NONE = 0
    BEST_EFFORT = 1
    RESTRICTED = 2
    SINGLE_NUMA_NODE = 3


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    cpu_id: int
    core_id: int
    numa_node: int
    socket: int


@dataclasses.dataclass
class CPUTopology:
    """Logical CPU inventory of one node."""

    cpus: List[CPUInfo]

    @classmethod
    def uniform(
        cls,
        sockets: int = 2,
        numa_per_socket: int = 1,
        cores_per_numa: int = 8,
        threads_per_core: int = 2,
    ) -> "CPUTopology":
        cpus: List[CPUInfo] = []
        cpu_id = 0
        core_id = 0
        for s in range(sockets):
            for n in range(numa_per_socket):
                numa = s * numa_per_socket + n
                for _ in range(cores_per_numa):
                    for _t in range(threads_per_core):
                        cpus.append(CPUInfo(cpu_id, core_id, numa, s))
                        cpu_id += 1
                    core_id += 1
        return cls(cpus=cpus)

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    @property
    def num_numa_nodes(self) -> int:
        return max((c.numa_node for c in self.cpus), default=-1) + 1

    def cpus_in_numa(self, numa: int) -> List[CPUInfo]:
        return [c for c in self.cpus if c.numa_node == numa]


class CPUAccumulator:
    """Greedy exclusive-cpuset allocator over one node's topology.

    Mirrors ``takeCPUs`` (``cpu_accumulator.go``): satisfy a request of
    ``n`` CPUs preferring (1) whole free sockets, (2) whole free cores,
    (3) single free threads; FullPCPUs requires the result to consist of
    whole physical cores; SpreadByPCPUs picks one thread per core across
    cores before doubling up.

    The implementation works on a precomputed sorted view of the topology
    (positions ordered by (core, cpu id), cores contiguous) so each
    ``take`` is a handful of numpy reductions instead of per-CPU Python
    object scans — the exact per-winner assignment is the scheduler's
    host-path hot spot (VERDICT r1: the serial loop capped the NUMA
    scenario at ~3.3k pods/s).
    """

    def __init__(self, topology: CPUTopology):
        self.topology = topology
        self._allocated: Set[int] = set()
        #: pod uid -> cpu ids
        self._owners: Dict[str, Set[int]] = {}

        cpus = topology.cpus
        cpu_id = np.asarray([c.cpu_id for c in cpus], np.int64)
        core = np.asarray([c.core_id for c in cpus], np.int64)
        numa = np.asarray([c.numa_node for c in cpus], np.int64)
        socket = np.asarray([c.socket for c in cpus], np.int64)
        order = np.lexsort((cpu_id, core))
        self._cs_cpu = cpu_id[order]
        self._cs_core = core[order]
        self._cs_numa = numa[order]
        self._cs_socket = socket[order]
        self._pos = {int(c): i for i, c in enumerate(self._cs_cpu)}
        # core segmentation of the sorted view
        starts = np.r_[True, self._cs_core[1:] != self._cs_core[:-1]]
        self._core_starts = np.nonzero(starts)[0]
        self._core_index = np.cumsum(starts) - 1          # [C] -> core row
        self._core_id = self._cs_core[self._core_starts]   # [K]
        self._core_numa = self._cs_numa[self._core_starts]
        self._core_socket = self._cs_socket[self._core_starts]
        core_sizes = np.diff(np.r_[self._core_starts, len(cpus)])
        self._threads_per_core = int(core_sizes.max(initial=1))
        self._uniform_cores = bool(
            (core_sizes == self._threads_per_core).all()
        )
        self._n_numa = int(numa.max(initial=-1)) + 1
        self._n_sockets = int(socket.max(initial=-1)) + 1
        self._numa_socket = np.zeros(max(self._n_numa, 1), np.int64)
        self._numa_socket[self._core_numa] = self._core_socket
        counts_numa = np.bincount(numa, minlength=max(self._n_numa, 1))
        counts_socket = np.bincount(socket, minlength=max(self._n_sockets, 1))
        self._numa_cap = int(counts_numa.max(initial=0))
        self._socket_cap = int(counts_socket.max(initial=0))
        self._socket_size = self._socket_cap
        # free mask over sorted-view positions, maintained incrementally;
        # rebuilt if _allocated was mutated directly (test fixtures do).
        # The heap fast path defers its clears into _dirty_positions —
        # _free_mask flushes them before any vectorized read.
        self._free = np.ones(len(cpus), bool)
        self._free_alloc_count = 0
        self._dirty_positions: List[int] = []
        self._cpu_list = self._cs_cpu.tolist()
        self._core_starts_list = self._core_starts.tolist()
        # per-numa min-heaps of fully-free core rows (hot-path take);
        # maintained ONLY by the fast take path — any other mutation
        # (general-path take, release, direct _allocated edits)
        # invalidates them outright: a length-match heuristic alone is
        # ABA-unsafe (take +k then release -k restores the length while
        # the heap is stale). Built eagerly here: a fully-free topology's
        # heaps are just the ascending core rows per numa node (already
        # valid min-heaps), so the first commit never pays a lazy
        # numpy rebuild per node.
        self._heaps: Optional[List[List[int]]] = [
            np.nonzero(self._core_numa == d)[0].tolist()
            for d in range(max(self._n_numa, 1))
        ]
        self._heap_alloc_len = 0

    def _free_mask(self):
        if self._dirty_positions:
            self._free[self._dirty_positions] = False
            self._dirty_positions.clear()
        if len(self._allocated) != self._free_alloc_count:
            self._free = np.ones(len(self._cs_cpu), bool)
            for cpu in self._allocated:
                self._free[self._pos[cpu]] = False
            self._free_alloc_count = len(self._allocated)
        return self._free

    def _numa_heaps(self) -> List[List[int]]:
        """Min-heaps of fully-free core rows per numa node; rebuilt when
        invalidated (general-path take / release) or when ``_allocated``
        was mutated directly (length check — direct edits only add)."""
        if self._heaps is None or self._heap_alloc_len != len(self._allocated):
            free = self._free_mask()
            counts = np.add.reduceat(free, self._core_starts)
            full = counts == self._threads_per_core
            self._heaps = [
                np.nonzero(full & (self._core_numa == d))[0].tolist()
                for d in range(max(self._n_numa, 1))
            ]
            for h in self._heaps:
                heapq.heapify(h)
            self._heap_alloc_len = len(self._allocated)
        return self._heaps

    @property
    def available(self) -> List[CPUInfo]:
        return [c for c in self.topology.cpus if c.cpu_id not in self._allocated]

    def free_count(self, numa: Optional[int] = None) -> int:
        free = self._free_mask()
        if numa is not None:
            free = free & (self._cs_numa == numa)
        return int(free.sum())

    # ---- grouping helper (reference cpu_accumulator.go freeCoresInNode /
    # freeCoresInSocket / freeCPUsInNode: group free cpus by core, filter
    # full-free cores, order cores by (-free count, core id) (sortCores),
    # order domains by the NUMA allocate strategy — MostAllocated =
    # least-remaining first (bin-packing), the default) ----

    def _domain_cpu_lists(
        self,
        freev,
        domain: str,
        full_cores_only: bool,
        most_allocated: bool = True,
        with_cores: bool = False,
    ):
        """Ordered per-domain cpu-id arrays for the free cpus in ``freev``
        ([C] bool over the sorted view). ``domain`` is "numa" or "socket".
        With ``with_cores`` returns (cpu_ids, core_ids) pairs (spread path
        needs the core of each cpu)."""
        counts = np.add.reduceat(freev, self._core_starts)   # free per core
        if full_cores_only:
            core_ok = counts == self._threads_per_core
        else:
            core_ok = counts > 0
        if not core_ok.any():
            return []
        dom_of_core = self._core_numa if domain == "numa" else self._core_socket
        ndom = max(self._n_numa if domain == "numa" else self._n_sockets, 1)
        socket_free = np.bincount(
            self._cs_socket[freev], minlength=max(self._n_sockets, 1)
        )
        dom_total = np.bincount(
            dom_of_core[core_ok],
            weights=counts[core_ok].astype(np.float64),
            minlength=ndom,
        ).astype(np.int64)
        doms = np.nonzero(dom_total > 0)[0]
        dom_sock = self._numa_socket[doms] if domain == "numa" else doms
        sign = 1 if most_allocated else -1
        dorder = np.lexsort(
            (doms, sign * socket_free[dom_sock], sign * dom_total[doms])
        )
        doms_sorted = doms[dorder]
        dom_rank = np.full(ndom, ndom, np.int64)
        dom_rank[doms_sorted] = np.arange(len(doms_sorted))

        cpu_ok = freev & core_ok[self._core_index]
        idx = np.nonzero(cpu_ok)[0]
        cidx = self._core_index[idx]
        # (domain rank, cores with more free cpus first, core id, cpu id)
        skey = np.lexsort(
            (self._cs_cpu[idx], self._cs_core[idx], -counts[cidx],
             dom_rank[dom_of_core[cidx]])
        )
        sel = idx[skey]
        cpus_sorted = self._cs_cpu[sel]
        cores_sorted = self._cs_core[sel]
        dsorted = dom_rank[dom_of_core[self._core_index[sel]]]
        bounds = np.nonzero(np.diff(dsorted))[0] + 1
        cpu_lists = np.split(cpus_sorted, bounds)
        if not with_cores:
            return cpu_lists
        return list(zip(cpu_lists, np.split(cores_sorted, bounds)))

    @staticmethod
    def _spread(cpus, cores):
        """One thread per core across cores before doubling up
        (``spreadCPUs``): order by (depth within core, core id)."""
        o1 = np.lexsort((cpus, cores))
        c = cores[o1]
        starts = np.r_[True, c[1:] != c[:-1]]
        gidx = np.arange(len(c))
        start_of = np.maximum.accumulate(np.where(starts, gidx, 0))
        rank = gidx - start_of
        return cpus[o1][np.lexsort((c, rank))]

    def take(
        self,
        owner: str,
        n_cpus: int,
        policy: CPUBindPolicy = CPUBindPolicy.DEFAULT,
        numa: Optional[int] = None,
    ) -> Optional[Set[int]]:
        """Allocate ``n_cpus`` exclusive CPUs, optionally pinned to one NUMA
        node, with the reference ``takeCPUs`` flow (cpu_accumulator.go:87-230):
        FullPCPUs (or single-thread cores) tries whole-free-core cpus within
        one NUMA node, then one socket (strategy-ordered, MostAllocated =
        tightest fit first), then drains whole sockets largest-first and
        tops up core-by-core from the tightest remainder; other policies
        prefer one NUMA node / socket of free cpus with spread-by-core
        ordering. Returns the cpu-id set or None if unsatisfiable."""
        tpc = self._threads_per_core

        taken = None
        # DEFAULT resolves to the defaulted preferred policy FullPCPUs
        # (v1beta3/defaults.go defaultPreferredCPUBindPolicy) and may fall
        # back to the spread path when full cores can't satisfy; explicit
        # FULL_PCPUS is strict.
        full_pcpus = (
            policy in (CPUBindPolicy.FULL_PCPUS, CPUBindPolicy.DEFAULT)
            or tpc == 1
        )
        if full_pcpus:
            if policy == CPUBindPolicy.FULL_PCPUS and n_cpus % tpc != 0:
                return None
            if policy == CPUBindPolicy.DEFAULT and n_cpus % tpc != 0:
                full_pcpus = False
        if (
            full_pcpus
            and numa is not None
            and self._uniform_cores
            and n_cpus <= self._numa_cap
        ):
            # Hot path (zone-pinned FullPCPUs on a uniform topology — the
            # per-winner commit of SINGLE_NUMA_NODE LSR pods): the domain
            # ordering degenerates to "lowest fully-free core ids in the
            # zone", served O(k) from the per-numa core heap with no numpy
            # work at all (free-mask clears are deferred into the dirty
            # list). An under-full heap falls through to the general flow
            # (which may still satisfy via partial cores / spread).
            heaps = self._heaps
            if heaps is None or self._heap_alloc_len != len(self._allocated):
                heaps = self._numa_heaps()
            heap = heaps[numa]
            k = n_cpus // tpc
            if len(heap) >= k:
                starts = self._core_starts_list
                cpu_list = self._cpu_list
                dirty = self._dirty_positions
                result = set()
                pop = heapq.heappop
                for _ in range(k):
                    base = starts[pop(heap)]
                    for t in range(tpc):
                        dirty.append(base + t)
                        result.add(cpu_list[base + t])
                self._allocated |= result
                n_alloc = len(self._allocated)
                self._free_alloc_count = n_alloc
                self._heap_alloc_len = n_alloc
                o = self._owners.get(owner)
                if o is None:
                    self._owners[owner] = set(result)
                else:
                    o |= result
                return result

        freev = self._free_mask()
        if numa is not None:
            freev = freev & (self._cs_numa == numa)
        if int(freev.sum()) < n_cpus:
            return None
        if full_pcpus and taken is None:
            if n_cpus <= self._numa_cap:
                for cpus in self._domain_cpu_lists(freev, "numa", True):
                    if len(cpus) >= n_cpus:
                        taken = cpus[:n_cpus]
                        break
            if taken is None and n_cpus <= self._socket_cap:
                for cpus in self._domain_cpu_lists(freev, "socket", True):
                    if len(cpus) >= n_cpus:
                        taken = cpus[:n_cpus]
                        break
            if taken is None:
                # drain whole sockets largest-first, then the tightest
                # remainders core by core
                socket_lists = self._domain_cpu_lists(
                    freev, "socket", True, most_allocated=False
                )
                acc: List = []
                total = 0
                unsatisfied = []
                for cpus in socket_lists:
                    if n_cpus - total >= len(cpus):
                        acc.append(cpus)
                        total += len(cpus)
                    else:
                        unsatisfied.append(cpus)
                if total < n_cpus:
                    unsatisfied.sort(key=len)
                    for cpus in unsatisfied:
                        for i in range(0, len(cpus), tpc):
                            if (
                                n_cpus - total < tpc
                                and policy == CPUBindPolicy.FULL_PCPUS
                            ):
                                break
                            if total >= n_cpus:
                                break
                            chunk = cpus[i : i + tpc]
                            acc.append(chunk)
                            total += len(chunk)
                taken = (
                    np.concatenate(acc)[:n_cpus]
                    if acc
                    else np.empty(0, np.int64)
                )
            if len(taken) < n_cpus and policy != CPUBindPolicy.FULL_PCPUS:
                # preferred FullPCPUs unsatisfiable: fall back to spread
                full_pcpus = False
                taken = None
        if not full_pcpus:
            if n_cpus <= self._numa_cap:
                for cpus, cores in self._domain_cpu_lists(
                    freev, "numa", False, with_cores=True
                ):
                    if len(cpus) >= n_cpus:
                        taken = self._spread(cpus, cores)[:n_cpus]
                        break
            if taken is None and n_cpus <= self._socket_cap:
                for cpus, cores in self._domain_cpu_lists(
                    freev, "socket", False, with_cores=True
                ):
                    if len(cpus) >= n_cpus:
                        taken = self._spread(cpus, cores)[:n_cpus]
                        break
            if taken is None:
                idx = np.nonzero(freev)[0]
                taken = self._spread(self._cs_cpu[idx], self._cs_core[idx])[
                    :n_cpus
                ]
        if taken is None or len(taken) < n_cpus:
            return None
        result = {int(c) for c in taken}
        self._allocated |= result
        self._free[[self._pos[c] for c in result]] = False
        self._free_alloc_count = len(self._allocated)
        self._heaps = None
        self._owners.setdefault(owner, set()).update(result)
        return result

    def take_bulk(
        self,
        reqs: Sequence[Tuple[str, int, CPUBindPolicy, Optional[int]]],
    ) -> List[Optional[Set[int]]]:
        """Batched :meth:`take` for one node's winners in commit order —
        the per-winner cpuset assignment was the dominant host cost of
        the NUMA bench (VERDICT r3 #1). Identical pick semantics; the
        zone-pinned FullPCPUs hot path runs with every attribute lookup
        and heap-validity check hoisted OUT of the per-winner loop, and a
        winner that cannot use it falls back to :meth:`take` (after which
        the hoisted state is re-synced)."""
        out: List[Optional[Set[int]]] = []
        tpc = self._threads_per_core
        uniform = self._uniform_cores
        numa_cap = self._numa_cap
        heaps = self._numa_heaps()
        starts = self._core_starts_list
        cpu_list = self._cpu_list
        dirty = self._dirty_positions
        pop = heapq.heappop
        allocated = self._allocated
        owners = self._owners
        default_pol = CPUBindPolicy.DEFAULT
        full_pol = CPUBindPolicy.FULL_PCPUS
        for owner, n_cpus, policy, numa in reqs:
            if (
                uniform
                and numa is not None
                and n_cpus <= numa_cap
                and (policy is default_pol or policy is full_pol or tpc == 1)
                and n_cpus % tpc == 0
            ):
                heap = heaps[numa]
                k = n_cpus // tpc
                if len(heap) >= k:
                    result = set()
                    for _ in range(k):
                        base = starts[pop(heap)]
                        for t in range(tpc):
                            dirty.append(base + t)
                            result.add(cpu_list[base + t])
                    allocated |= result
                    o = owners.get(owner)
                    if o is None:
                        owners[owner] = set(result)
                    else:
                        o |= result
                    out.append(result)
                    continue
            # slow path: keep counters coherent for take(), then re-hoist
            n_alloc = len(allocated)
            self._free_alloc_count = n_alloc
            self._heap_alloc_len = n_alloc
            out.append(self.take(owner, n_cpus, policy=policy, numa=numa))
            heaps = self._numa_heaps()
            dirty = self._dirty_positions
        n_alloc = len(allocated)
        self._free_alloc_count = n_alloc
        self._heap_alloc_len = n_alloc
        return out

    def take_reserved(self, owner: str, cpu_ids: Set[int]) -> None:
        """Pre-allocate an exact cpu-id set (kubelet-reserved CPUs from
        the NodeResourceTopology report): unconditional — reserved CPUs
        are facts, not requests. Invalidates the fast-path heaps."""
        cpus = {int(c) for c in cpu_ids if int(c) in self._pos}
        if not cpus:
            return
        self._free_mask()  # flush deferred clears first
        self._allocated |= cpus
        self._free[[self._pos[c] for c in cpus]] = False
        self._free_alloc_count = len(self._allocated)
        self._heaps = None
        self._owners.setdefault(owner, set()).update(cpus)

    def release(self, owner: str) -> None:
        cpus = self._owners.pop(owner, set())
        if cpus:
            self._free_mask()  # sync first in case of direct mutations
            self._allocated -= cpus
            self._free[[self._pos[c] for c in cpus]] = True
            self._free_alloc_count = len(self._allocated)
            self._heaps = None

    def cpuset_of(self, owner: str) -> Optional[Set[int]]:
        return self._owners.get(owner)

    def allocated_count(self) -> int:
        """Number of exclusively-held CPUs (the reference's
        ``GetAvailableCPUs`` allocated set size, ``plugin.go:430-433``)."""
        return len(self._allocated)


def format_cpuset(cpus: Sequence[int]) -> str:
    """Render a cpuset in kernel list format (e.g. "0-3,8,10-11")."""
    ids = sorted(set(cpus))
    return format_cpuset_sorted(ids)


def format_cpuset_sorted(ids: Sequence[int]) -> str:
    """``format_cpuset`` for input already sorted+deduped (the commit hot
    path sorts once; a fully-contiguous set — the common FullPCPUs pick —
    renders without the scan)."""
    if not ids:
        return ""
    start, last = ids[0], ids[-1]
    n = len(ids)
    if last - start + 1 == n:
        return f"{start}-{last}" if n > 1 else str(start)
    parts: List[str] = []
    prev = start
    for c in ids[1:]:
        if c == prev + 1:
            prev = c
            continue
        parts.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = c
    parts.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(parts)


def parse_cpuset(text: str) -> Set[int]:
    out: Set[int] = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            a, b = part.split("-", 1)
            out.update(range(int(a), int(b) + 1))
        else:
            out.add(int(part))
    return out
