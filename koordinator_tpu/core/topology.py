"""CPU topology model + cpuset accumulator.

Rebuild of the reference's CPU orchestration core
(``pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go:87-245,345-800``
and koordlet's NodeResourceTopology reporting): a node's CPUs form a
socket → NUMA-node → physical-core → logical-CPU hierarchy; exclusive
cpusets for LSR/LSE pods are taken greedily — whole sockets first, then
whole cores, then single threads — honoring the FullPCPUs / SpreadByPCPUs
bind policies.

Zone-level *feasibility* is decided on TPU (``ops.numa``); the exact CPU id
selection here is per-winner host work (SURVEY §7 step 6).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set, Tuple


class CPUBindPolicy(enum.Enum):
    """Pod-requested bind policy (reference ``apis/extension/numa_aware.go``
    CPUBindPolicy*)."""

    DEFAULT = "Default"
    FULL_PCPUS = "FullPCPUs"           # whole physical cores only
    SPREAD_BY_PCPUS = "SpreadByPCPUs"  # spread threads across cores
    CONSTRAINED_BURST = "ConstrainedBurst"


class NUMAPolicy(enum.IntEnum):
    """Node topology manager policy (reference
    ``frameworkext/topologymanager/policy_*.go``)."""

    NONE = 0
    BEST_EFFORT = 1
    RESTRICTED = 2
    SINGLE_NUMA_NODE = 3


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    cpu_id: int
    core_id: int
    numa_node: int
    socket: int


@dataclasses.dataclass
class CPUTopology:
    """Logical CPU inventory of one node."""

    cpus: List[CPUInfo]

    @classmethod
    def uniform(
        cls,
        sockets: int = 2,
        numa_per_socket: int = 1,
        cores_per_numa: int = 8,
        threads_per_core: int = 2,
    ) -> "CPUTopology":
        cpus: List[CPUInfo] = []
        cpu_id = 0
        core_id = 0
        for s in range(sockets):
            for n in range(numa_per_socket):
                numa = s * numa_per_socket + n
                for _ in range(cores_per_numa):
                    for _t in range(threads_per_core):
                        cpus.append(CPUInfo(cpu_id, core_id, numa, s))
                        cpu_id += 1
                    core_id += 1
        return cls(cpus=cpus)

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    @property
    def num_numa_nodes(self) -> int:
        return max((c.numa_node for c in self.cpus), default=-1) + 1

    def cpus_in_numa(self, numa: int) -> List[CPUInfo]:
        return [c for c in self.cpus if c.numa_node == numa]


class CPUAccumulator:
    """Greedy exclusive-cpuset allocator over one node's topology.

    Mirrors ``takeCPUs`` (``cpu_accumulator.go``): satisfy a request of
    ``n`` CPUs preferring (1) whole free sockets, (2) whole free cores,
    (3) single free threads; FullPCPUs requires the result to consist of
    whole physical cores; SpreadByPCPUs picks one thread per core across
    cores before doubling up.
    """

    def __init__(self, topology: CPUTopology):
        self.topology = topology
        self._allocated: Set[int] = set()
        #: pod uid -> cpu ids
        self._owners: Dict[str, Set[int]] = {}
        # static topology facts, computed once — recomputing them per
        # take() made the accumulator the host-path hot spot (O(cpus ×
        # cores) scans per winner)
        core_counts: Dict[int, int] = {}
        socket_counts: Dict[int, int] = {}
        for c in topology.cpus:
            core_counts[c.core_id] = core_counts.get(c.core_id, 0) + 1
            socket_counts[c.socket] = socket_counts.get(c.socket, 0) + 1
        self._threads_per_core = max(core_counts.values(), default=1)
        self._socket_size = max(socket_counts.values(), default=1)

    @property
    def available(self) -> List[CPUInfo]:
        return [c for c in self.topology.cpus if c.cpu_id not in self._allocated]

    def free_count(self, numa: Optional[int] = None) -> int:
        return sum(
            1
            for c in self.available
            if numa is None or c.numa_node == numa
        )

    # ---- grouping helpers (reference cpu_accumulator.go freeCoresInNode /
    # freeCoresInSocket / freeCPUsInNode: group free cpus by core, filter
    # full-free cores, order domains by the NUMA allocate strategy —
    # MostAllocated = least-remaining first (bin-packing), the default) ----

    def _domain_cpu_lists(
        self,
        avail: List[CPUInfo],
        domain_of,
        full_cores_only: bool,
        most_allocated: bool = True,
    ) -> List[List[int]]:
        by_core: Dict[int, List[CPUInfo]] = {}
        for c in avail:
            by_core.setdefault(c.core_id, []).append(c)
        socket_free: Dict[int, int] = {}
        for c in avail:
            socket_free[c.socket] = socket_free.get(c.socket, 0) + 1
        domains: Dict[int, List[Tuple[int, List[int]]]] = {}
        dom_socket: Dict[int, int] = {}
        for cid, cs in by_core.items():
            if full_cores_only and len(cs) != self._threads_per_core:
                continue
            dom = domain_of(cs[0])
            domains.setdefault(dom, []).append(
                (cid, sorted(c.cpu_id for c in cs))
            )
            dom_socket[dom] = cs[0].socket
        out = []
        for dom, cores in domains.items():
            # cores with more free cpus first, then core id (sortCores)
            cores.sort(key=lambda kv: (-len(kv[1]), kv[0]))
            cpus = [cpu for _cid, cs in cores for cpu in cs]
            out.append((dom, cpus))
        sign = 1 if most_allocated else -1
        out.sort(
            key=lambda kv: (
                sign * len(kv[1]),
                sign * socket_free.get(dom_socket.get(kv[0], -1), 0),
                kv[0],
            )
        )
        return [cpus for _dom, cpus in out]

    def _spread(self, cpus: List[int]) -> List[int]:
        """One thread per core across cores before doubling up
        (``spreadCPUs``)."""
        core_of = {c.cpu_id: c.core_id for c in self.topology.cpus}
        by_core: Dict[int, List[int]] = {}
        for cpu in cpus:
            by_core.setdefault(core_of[cpu], []).append(cpu)
        ring = [sorted(cs) for _cid, cs in sorted(by_core.items())]
        out: List[int] = []
        depth = 0
        while len(out) < len(cpus):
            for cs in ring:
                if depth < len(cs):
                    out.append(cs[depth])
            depth += 1
        return out

    def take(
        self,
        owner: str,
        n_cpus: int,
        policy: CPUBindPolicy = CPUBindPolicy.DEFAULT,
        numa: Optional[int] = None,
    ) -> Optional[Set[int]]:
        """Allocate ``n_cpus`` exclusive CPUs, optionally pinned to one NUMA
        node, with the reference ``takeCPUs`` flow (cpu_accumulator.go:87-230):
        FullPCPUs (or single-thread cores) tries whole-free-core cpus within
        one NUMA node, then one socket (strategy-ordered, MostAllocated =
        tightest fit first), then drains whole sockets largest-first and
        tops up core-by-core from the tightest remainder; other policies
        prefer one NUMA node / socket of free cpus with spread-by-core
        ordering. Returns the cpu-id set or None if unsatisfiable."""
        avail = [
            c for c in self.available if numa is None or c.numa_node == numa
        ]
        if len(avail) < n_cpus:
            return None
        tpc = self._threads_per_core
        cpus_per_numa: Dict[int, int] = {}
        cpus_per_socket: Dict[int, int] = {}
        for c in self.topology.cpus:
            cpus_per_numa[c.numa_node] = cpus_per_numa.get(c.numa_node, 0) + 1
            cpus_per_socket[c.socket] = cpus_per_socket.get(c.socket, 0) + 1
        numa_cap = max(cpus_per_numa.values(), default=0)
        socket_cap = max(cpus_per_socket.values(), default=0)

        taken: List[int] = []
        # DEFAULT resolves to the defaulted preferred policy FullPCPUs
        # (v1beta3/defaults.go defaultPreferredCPUBindPolicy) and may fall
        # back to the spread path when full cores can't satisfy; explicit
        # FULL_PCPUS is strict.
        full_pcpus = (
            policy in (CPUBindPolicy.FULL_PCPUS, CPUBindPolicy.DEFAULT)
            or tpc == 1
        )
        if full_pcpus:
            if policy == CPUBindPolicy.FULL_PCPUS and n_cpus % tpc != 0:
                return None
            if policy == CPUBindPolicy.DEFAULT and n_cpus % tpc != 0:
                full_pcpus = False
        if full_pcpus:
            done = False
            if n_cpus <= numa_cap:
                for cpus in self._domain_cpu_lists(
                    avail, lambda c: c.numa_node, full_cores_only=True
                ):
                    if len(cpus) >= n_cpus:
                        taken = cpus[:n_cpus]
                        done = True
                        break
            if not done and n_cpus <= socket_cap:
                for cpus in self._domain_cpu_lists(
                    avail, lambda c: c.socket, full_cores_only=True
                ):
                    if len(cpus) >= n_cpus:
                        taken = cpus[:n_cpus]
                        done = True
                        break
            if not done:
                # drain whole sockets largest-first, then the tightest
                # remainders core by core
                socket_lists = self._domain_cpu_lists(
                    avail, lambda c: c.socket, full_cores_only=True,
                    most_allocated=False,
                )
                unsatisfied = []
                for cpus in socket_lists:
                    if n_cpus - len(taken) >= len(cpus):
                        taken.extend(cpus)
                    else:
                        unsatisfied.append(cpus)
                if len(taken) < n_cpus:
                    unsatisfied.sort(key=len)
                    for cpus in unsatisfied:
                        for i in range(0, len(cpus), tpc):
                            if n_cpus - len(taken) < tpc and policy == CPUBindPolicy.FULL_PCPUS:
                                break
                            if len(taken) >= n_cpus:
                                break
                            taken.extend(cpus[i : i + tpc])
                taken = taken[:n_cpus]
            if len(taken) < n_cpus and policy != CPUBindPolicy.FULL_PCPUS:
                # preferred FullPCPUs unsatisfiable: fall back to spread
                full_pcpus = False
                taken = []
        if not full_pcpus:
            done = False
            if n_cpus <= numa_cap:
                for cpus in self._domain_cpu_lists(
                    avail, lambda c: c.numa_node, full_cores_only=False
                ):
                    if len(cpus) >= n_cpus:
                        taken = self._spread(cpus)[:n_cpus]
                        done = True
                        break
            if not done and n_cpus <= socket_cap:
                for cpus in self._domain_cpu_lists(
                    avail, lambda c: c.socket, full_cores_only=False
                ):
                    if len(cpus) >= n_cpus:
                        taken = self._spread(cpus)[:n_cpus]
                        done = True
                        break
            if not done:
                taken = self._spread([c.cpu_id for c in avail])[:n_cpus]
        if len(taken) < n_cpus:
            return None
        result = set(taken)
        self._allocated |= result
        self._owners.setdefault(owner, set()).update(result)
        return result

    def release(self, owner: str) -> None:
        cpus = self._owners.pop(owner, set())
        self._allocated -= cpus

    def cpuset_of(self, owner: str) -> Optional[Set[int]]:
        return self._owners.get(owner)


def format_cpuset(cpus: Sequence[int]) -> str:
    """Render a cpuset in kernel list format (e.g. "0-3,8,10-11")."""
    ids = sorted(set(cpus))
    if not ids:
        return ""
    parts: List[str] = []
    start = prev = ids[0]
    for c in ids[1:]:
        if c == prev + 1:
            prev = c
            continue
        parts.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = c
    parts.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(parts)


def parse_cpuset(text: str) -> Set[int]:
    out: Set[int] = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            a, b = part.split("-", 1)
            out.update(range(int(a), int(b) + 1))
        else:
            out.add(int(part))
    return out
