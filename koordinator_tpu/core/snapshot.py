"""Tensor state snapshot: host objects → dense arrays.

The rebuild's analog of the reference scheduler's in-memory state (scheduler
cache NodeInfos + ``podAssignCache`` ``pkg/scheduler/plugins/loadaware/
pod_assign_cache.go`` + ``nodeDeviceCache`` + quota tree): one mutable
host-side store of numpy arrays, lowered to device arrays per solver batch.

Design notes (TPU-first):
  * All shapes are padded to buckets (next power of two, min 128) so that
    churn in pod/node counts does not recompile the jitted solver.
  * Resources live on a canonical D axis (``SnapshotConfig.resources``);
    cpu is milli-cores, memory is MiB, extended resources native units.
  * Incremental updates (assume/forget, metric refresh) mutate numpy in
    place — the device transfer happens once per solver batch, not per event.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..api import extension as ext
from ..api.types import Node, NodeMetric, Pod, ResourceList


#: fallback QoS per priority band (ext.qos_for_priority, vectorized)
_QOS_BY_BAND = np.array(
    [
        int(ext.qos_for_priority(ext.PriorityClass(b)))
        for b in range(len(ext.PriorityClass))
    ],
    np.int8,
)


def bucket_size(n: int, minimum: int = 128) -> int:
    """Round up to the next power of two (>= minimum) for stable jit shapes."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class SnapshotConfig:
    resources: Tuple[str, ...] = ext.DEFAULT_RESOURCES
    min_bucket: int = 128

    @property
    def dims(self) -> int:
        return len(self.resources)

    def res_vector(self, rl: Mapping[str, float]) -> np.ndarray:
        return np.array([float(rl.get(r, 0.0)) for r in self.resources], np.float32)


@dataclasses.dataclass
class NodeArrays:
    """Dense per-node state, padded to ``n_bucket`` rows.

    Mirrors what the reference spreads across NodeInfo + NodeMetric + the
    LoadAware ``podAssignCache``:
      allocatable      — Node.status.allocatable            [N, D]
      requested        — sum of assigned pod requests       [N, D]
      usage_avg        — NodeMetric avg node usage          [N, D]
      usage_agg        — NodeMetric aggregated percentile   [N, D]
      prod_usage       — NodeMetric prod-tier usage         [N, D]
      sys_usage        — NodeMetric system-tier usage (out-of-band
                         daemons; batchresource subtracts it)            [N, D]
      assigned_pending — estimated usage of assigned-but-unreported pods
                         (reference ``load_aware.go:315-358``)            [N, D]
      assigned_pending_prod — the prod-band slice of assigned_pending
                         (prod thresholds count only prod-tier pods)      [N, D]
      metric_fresh     — NodeMetric not expired             [N] bool
      has_metric       — a NodeMetric was EVER reported for the node
                         (filterExpiredNodeMetrics distinguishes
                         stale-metric nodes, which it may reject, from
                         never-reported ones, which the reference
                         Filter always admits)                [N] bool
      schedulable      — not cordoned, padded rows False    [N] bool
      cpu_amp          — CPU amplification ratio from the node annotation
                         (``apis/extension/node_resource_amplification.go``),
                         1.0 when unset                                   [N]
      custom_thresholds / custom_prod_thresholds — per-node LoadAware
                         threshold overrides from the usage-thresholds
                         annotation (``apis/extension/load_aware.go``);
                         0 = use the plugin-args global            [N, D]
      colo_reclaim     — per-node (cpu, memory) reclaim-ratio override
                         from the colocation-strategy annotation /
                         reclaim-ratio labels (``node_colocation.go``);
                         0 = use the cluster strategy              [N, 2]
      colo_enable      — per-node colocation enable override: -1 follow
                         the cluster strategy, 0 disable, 1 enable  [N]
    """

    allocatable: np.ndarray
    requested: np.ndarray
    usage_avg: np.ndarray
    usage_agg: np.ndarray
    prod_usage: np.ndarray
    sys_usage: np.ndarray
    assigned_pending: np.ndarray
    assigned_pending_prod: np.ndarray
    metric_fresh: np.ndarray
    has_metric: np.ndarray
    schedulable: np.ndarray
    cpu_amp: np.ndarray
    custom_thresholds: np.ndarray
    custom_prod_thresholds: np.ndarray
    colo_reclaim: np.ndarray
    colo_enable: np.ndarray
    n_real: int

    @classmethod
    def empty(cls, n_bucket: int, dims: int) -> "NodeArrays":
        z = lambda: np.zeros((n_bucket, dims), np.float32)
        return cls(
            allocatable=z(),
            requested=z(),
            usage_avg=z(),
            usage_agg=z(),
            prod_usage=z(),
            sys_usage=z(),
            assigned_pending=z(),
            assigned_pending_prod=z(),
            metric_fresh=np.zeros((n_bucket,), bool),
            has_metric=np.zeros((n_bucket,), bool),
            schedulable=np.zeros((n_bucket,), bool),
            cpu_amp=np.ones((n_bucket,), np.float32),
            custom_thresholds=z(),
            custom_prod_thresholds=z(),
            colo_reclaim=np.zeros((n_bucket, 2), np.float32),
            colo_enable=np.full((n_bucket,), -1, np.int8),
            n_real=0,
        )


@dataclasses.dataclass
class PodArrays:
    """Dense per-pending-pod state, padded to ``p_bucket`` rows.

    requests    — scheduling requests                      [P, D]
    priority    — raw k8s priority (sort key)              [P] int32
    prio_class  — koord band (extension.PriorityClass)     [P] int8
    qos         — koord QoS class                          [P] int8
    gang_id     — row-group id for coscheduling, -1 = none [P] int32
    quota_id    — leaf quota index, -1 = none              [P] int32
    valid       — padded rows False                        [P] bool
    """

    requests: np.ndarray
    priority: np.ndarray
    prio_class: np.ndarray
    qos: np.ndarray
    gang_id: np.ndarray
    quota_id: np.ndarray
    valid: np.ndarray
    #: row g: minMember of gang g (0 = unconstrained), indexed by gang_id
    gang_min: np.ndarray
    #: row g: True when gang g is NonStrict (placed members survive an
    #: under-filled gang instead of rolling back), indexed by gang_id
    gang_nonstrict: np.ndarray
    #: whole GPUs / fractional GPU percent per pod (DeviceShare)
    gpu_whole: np.ndarray
    gpu_share: np.ndarray
    #: whole RDMA NICs per pod (koordinator.sh/rdma, 100-unit instances)
    rdma: np.ndarray
    #: whole FPGAs per pod
    fpga: np.ndarray
    p_real: int
    #: gang id -> "namespace/name" key, parallel to gang_min rows
    gang_keys: List[str] = dataclasses.field(default_factory=list)
    #: pod uids in row order (collected in the single lowering pass so
    #: downstream consumers skip another per-pod walk)
    uids: List[str] = dataclasses.field(default_factory=list)
    #: leaf quota label per pod (None = unlabeled), row order
    quota_names: List[Optional[str]] = dataclasses.field(default_factory=list)
    #: rows whose estimate cannot use the vectorized request×scale path
    #: (explicit estimate / limits / custom scaling-factor annotation)
    est_override: Optional[np.ndarray] = None
    #: pod REQUIRES single-NUMA placement via the numa-topology-spec
    #: annotation (AnnotationNUMATopologySpec, ``numa_aware.go:29-31``) —
    #: independent of the LSR/LSE cpu-bind predicate
    numa_required: Optional[np.ndarray] = None
    #: quota non-preemptible pods (LabelPreemptible=false): admission
    #: additionally bounds them by quota MIN (``plugin.go:252-262``)
    non_preemptible: Optional[np.ndarray] = None
    #: rows served from the caller's interned-row cache this build
    intern_hits: int = 0

    @classmethod
    def empty(cls, p_bucket: int, dims: int) -> "PodArrays":
        return cls(
            requests=np.zeros((p_bucket, dims), np.float32),
            priority=np.zeros((p_bucket,), np.int32),
            prio_class=np.zeros((p_bucket,), np.int8),
            qos=np.zeros((p_bucket,), np.int8),
            gang_id=np.full((p_bucket,), -1, np.int32),
            quota_id=np.full((p_bucket,), -1, np.int32),
            valid=np.zeros((p_bucket,), bool),
            gang_min=np.zeros((p_bucket,), np.int32),
            gang_nonstrict=np.zeros((p_bucket,), bool),
            gpu_whole=np.zeros((p_bucket,), np.int32),
            gpu_share=np.zeros((p_bucket,), np.float32),
            rdma=np.zeros((p_bucket,), np.int32),
            fpga=np.zeros((p_bucket,), np.int32),
            p_real=0,
        )


@dataclasses.dataclass(slots=True)
class InternedPodRow:
    """Lowered row data for one pending pod, cached across cycles keyed on
    (uid, spec fingerprint) — ROADMAP item (c): a retry-heavy stream
    re-lowers the same still-pending pod every cycle, and the per-pod
    parse chain (requests walk, device-resource split, QoS/gang/quota
    label+annotation reads) was the measurable slice. The fingerprint is
    three tuple-hashes (requests, labels, annotations — far cheaper than
    the parse chain it replaces) so an in-place spec edit self-invalidates
    the entry rather than resurrecting stale rows."""

    fp: tuple
    req: np.ndarray          # [D] request row (owned copy)
    priority: int
    qos_explicit: int        # -1 = no explicit label
    gang: Optional[str]      # raw gang name (annotation/label), not ns-key
    gang_min: Optional[str]  # raw min-available label value
    gang_nonstrict: bool
    gpu_whole: int
    gpu_share: float
    rdma: float
    fpga: float
    quota_name: Optional[str]
    est_override: bool
    numa_required: bool
    non_preemptible: bool


def pod_fingerprint(pod: Pod) -> tuple:
    """Cheap content fingerprint of the spec fields ``build_pods`` reads."""
    spec = pod.spec
    meta = pod.meta
    return (
        spec.priority,
        hash(tuple(spec.requests.items())),
        hash(tuple(meta.labels.items())),
        hash(tuple(meta.annotations.items())),
        bool(spec.estimated),
        bool(spec.limits),
    )


@dataclasses.dataclass(slots=True)
class _AssumedPod:
    """Bookkeeping for one assumed/bound pod (the reference's
    ``podAssignCache`` entry). ``slots=True``: tens of thousands of these
    are constructed per bulk commit — attribute-dict allocation was a
    measurable slice of ``assume_pods_bulk``."""

    node_idx: int
    request: np.ndarray
    estimate: np.ndarray
    is_prod: bool
    assume_time: float
    absorbed: bool = False  # estimate already reflected in reported usage
    #: False while the assume is an optimistic scheduler-side charge not
    #: yet confirmed by the control plane (bind / pod_assumed sync). The
    #: reference scheduler cache expires such pods (kube-scheduler
    #: durationToExpireAssumedPod) so a rejected-then-deleted nomination
    #: can't leak capacity forever; see expire_assumed().
    confirmed: bool = True
    #: nominal (physical) CPU milli of a cpuset-bound pod whose charge was
    #: amplified; 0 for shared pods. Lets an amplification-ratio change
    #: re-base the live charge (upsert_node).
    bind_nominal_cpu: float = 0.0


class ClusterSnapshot:
    """Mutable host-side mirror of cluster state with index maps.

    The write path (informer events in the reference) is `upsert_node`,
    `set_node_metric`, `assume_pod`, `forget_pod`; the read path is
    `node_arrays` / `build_pods`, which hand padded numpy blocks to the
    jitted solver.
    """

    def __init__(
        self,
        config: Optional[SnapshotConfig] = None,
        agg_type: str = "p95",
        metric_expiry_s: float = 180.0,
    ):
        self.config = config or SnapshotConfig()
        #: coarse serialization between writers (informer handler threads)
        #: and the scheduling cycle — the reference scheduler cache's lock
        #: at batch granularity. Re-entrant: the cycle itself both reads
        #: and writes under it.
        import threading as _threading

        self.lock = _threading.RLock()
        res = self.config.resources
        self._cpu_dim = res.index(ext.RES_CPU) if ext.RES_CPU in res else 0
        self._res_index = {r: j for j, r in enumerate(res)}
        #: QoS label string → int, memoized across build_pods calls
        self._qos_label_cache: Dict[str, int] = {}
        #: NodeMetric aggregation percentile / expiry used at ingest
        #: (wired from LoadAwareSchedulingArgs by BatchScheduler)
        self.agg_type = agg_type
        self.metric_expiry_s = metric_expiry_s
        self._node_index: Dict[str, int] = {}
        self._node_names: List[str] = []
        self._free_node_slots: List[int] = []
        #: bumped on any node add/remove — cheap staleness check for
        #: consumers caching node-derived views (reservation candidates)
        self.node_epoch = 0
        self.nodes = NodeArrays.empty(self.config.min_bucket, self.config.dims)
        #: bumped on EVERY node-block mutation (upsert/remove, metric
        #: ingest, assume/forget). Device-resident consumers key their
        #: caches off it and pull the touched rows via drain_dirty().
        self.version = 0
        #: node rows touched since the last drain; _dirty_all marks a
        #: structural change (bucket growth / reset) that invalidates any
        #: resident mirror wholesale
        self._dirty_rows: set = set()
        self._dirty_all = True
        self._drain_owner: Optional[int] = None
        #: [n_bucket] bool — rows currently holding a real node (freed
        #: slots keep a stale name in _node_names; masks must not match it)
        self._present = np.zeros((self.config.min_bucket,), bool)
        #: inverted label index: (key, value) -> [n_bucket] bool rows.
        #: Built lazily per queried pair, then maintained eagerly on node
        #: upsert/remove — the vectorized node-constraint mask ANDs these
        #: bitmaps instead of walking per-node label dicts (P×N loop).
        self._label_rows: Dict[Tuple[str, str], np.ndarray] = {}
        #: pod uid -> _AssumedPod for assumed/bound pods
        self._assumed: Dict[str, "_AssumedPod"] = {}
        #: node name -> labels (nodeSelector/affinity masks read these)
        self._node_labels: Dict[str, Dict[str, str]] = {}
        #: node name -> annotations (per-node strategy overrides read these)
        self._node_annotations: Dict[str, Dict[str, str]] = {}

    def reset(self) -> None:
        """Clear all state in place (full-resync path: the snapshot object
        stays shared with the scheduler, so identity must survive)."""
        self._node_index.clear()
        self._node_names.clear()
        self._free_node_slots.clear()
        self.nodes = NodeArrays.empty(self.config.min_bucket, self.config.dims)
        self._assumed.clear()
        self._node_labels.clear()
        self._node_annotations.clear()
        self._present = np.zeros((self.config.min_bucket,), bool)
        self._label_rows.clear()
        self.node_epoch += 1
        self.touch_all()

    # ---- dirty-row tracking (device-resident consumers) ----

    def _touch(self, idx: int) -> None:
        self.version += 1
        if not self._dirty_all:
            self._dirty_rows.add(int(idx))

    def touch_rows(self, idxs: Iterable[int]) -> None:
        """Mark node rows as mutated (for the rare external writers that
        poke the node arrays directly instead of going through
        upsert/assume/metric APIs)."""
        self.version += 1
        if not self._dirty_all:
            self._dirty_rows.update(int(i) for i in idxs)

    def touch_all(self) -> None:
        """Invalidate any device-resident mirror wholesale (bucket growth,
        reset, or a writer that cannot enumerate the rows it touched)."""
        self.version += 1
        self._dirty_all = True
        self._dirty_rows.clear()

    def drain_dirty(self, owner: Optional[int] = None) -> Optional[np.ndarray]:
        """Consume the dirty-row marks: returns the sorted row indices
        touched since the last drain, or None when the resident mirror
        must be rebuilt from scratch (structural change). SINGLE-CONSUMER:
        the marks are cleared on return, so exactly one resident mirror
        may incrementally maintain itself per snapshot — pass a stable
        ``owner`` token and a second drainer degrades both to full
        re-lowers instead of silently missing rows."""
        if owner is not None:
            if self._drain_owner is None:
                self._drain_owner = owner
            elif self._drain_owner != owner:
                # contested drain: neither consumer can trust partial marks
                self._drain_owner = owner
                self._dirty_all = False
                self._dirty_rows.clear()
                return None
        if self._dirty_all:
            self._dirty_all = False
            self._dirty_rows.clear()
            return None
        rows = np.fromiter(
            self._dirty_rows, np.int32, count=len(self._dirty_rows)
        )
        rows.sort()
        self._dirty_rows.clear()
        return rows

    # ---- node-constraint inverted index ----

    #: cap on cached label-pair bitmaps: high-cardinality selectors
    #: (kubernetes.io/hostname=nodeX pins — one distinct value per node)
    #: would otherwise grow the index O(N²); pairs beyond the cap are
    #: built per query without caching (the pre-index cost, paid only by
    #: the overflow tail)
    _LABEL_INDEX_CAP = 8192

    def label_rows(self, key: str, value: str) -> np.ndarray:
        """[n_bucket] bool of nodes carrying ``key=value``. Built lazily
        per queried pair (one O(N) scan), maintained eagerly afterwards.
        Callers must treat the bitmap as read-only."""
        bm = self._label_rows.get((key, value))
        if bm is None:
            bm = np.zeros((self.nodes.allocatable.shape[0],), bool)
            for name, idx in self._node_index.items():
                if self._node_labels.get(name, {}).get(key) == value:
                    bm[idx] = True
            if len(self._label_rows) < self._LABEL_INDEX_CAP:
                self._label_rows[(key, value)] = bm
        return bm

    def constraint_row(
        self,
        node_name: Optional[str] = None,
        affinity_names: Optional[Sequence[str]] = None,
        selector: Optional[Mapping[str, str]] = None,
    ) -> np.ndarray:
        """[n_bucket] bool of nodes a pod's hard node constraints admit
        (spec.nodeName / required node-affinity names / nodeSelector — the
        upstream NodeName+NodeAffinity Filter semantics), built from the
        inverted index instead of a per-node label walk. Returns a fresh
        array the caller owns."""
        if node_name:
            row = np.zeros((self.nodes.allocatable.shape[0],), bool)
            idx = self._node_index.get(node_name)
            if idx is not None:
                row[idx] = True
        elif affinity_names is not None:
            row = np.zeros((self.nodes.allocatable.shape[0],), bool)
            for nm in affinity_names:
                idx = self._node_index.get(nm)
                if idx is not None:
                    row[idx] = True
        else:
            row = self._present.copy()
        if selector:
            for k, v in selector.items():
                row = row & self.label_rows(k, v)
        return row

    # ---- node side ----

    def _grow_nodes(self, need: int) -> None:
        cur = self.nodes.allocatable.shape[0]
        if need <= cur:
            return
        new = bucket_size(need, self.config.min_bucket)
        old = self.nodes

        def pad(a: np.ndarray) -> np.ndarray:
            width = [(0, new - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width)

        self.nodes = NodeArrays(
            allocatable=pad(old.allocatable),
            requested=pad(old.requested),
            usage_avg=pad(old.usage_avg),
            usage_agg=pad(old.usage_agg),
            prod_usage=pad(old.prod_usage),
            sys_usage=pad(old.sys_usage),
            assigned_pending=pad(old.assigned_pending),
            assigned_pending_prod=pad(old.assigned_pending_prod),
            metric_fresh=pad(old.metric_fresh),
            has_metric=pad(old.has_metric),
            schedulable=pad(old.schedulable),
            cpu_amp=np.pad(
                old.cpu_amp, (0, new - old.cpu_amp.shape[0]), constant_values=1.0
            ),
            custom_thresholds=pad(old.custom_thresholds),
            custom_prod_thresholds=pad(old.custom_prod_thresholds),
            colo_reclaim=pad(old.colo_reclaim),
            colo_enable=np.pad(
                old.colo_enable,
                (0, new - old.colo_enable.shape[0]),
                constant_values=-1,
            ),
            n_real=old.n_real,
        )
        self._present = np.pad(self._present, (0, new - self._present.shape[0]))
        for pair, bm in self._label_rows.items():
            self._label_rows[pair] = np.pad(bm, (0, new - bm.shape[0]))
        # bucket growth changes every resident-mirror shape
        self.touch_all()

    def upsert_node(self, node: Node) -> int:
        idx = self._node_index.get(node.meta.name)
        if idx is None:
            if self._free_node_slots:
                idx = self._free_node_slots.pop()
                self._node_names[idx] = node.meta.name
            else:
                idx = len(self._node_names)
                self._node_names.append(node.meta.name)
                self._grow_nodes(idx + 1)
            self._node_index[node.meta.name] = idx
            self.nodes.n_real = max(self.nodes.n_real, idx + 1)
            self.node_epoch += 1
        alloc = self.config.res_vector(node.status.allocatable)
        resv = ext.parse_node_reservation(node.meta.annotations)
        if resv is not None and resv.get("applyPolicy") in (
            None,
            "",
            ext.NODE_RESERVATION_POLICY_DEFAULT,
        ):
            # trim allocatable by the node-level reservation
            # (util.TrimNodeAllocatableByNodeReservation): reservedCPUs
            # overrides the cpu quantity; batch tiers already account the
            # reservation at the koord-manager and keep their values
            resources = dict(resv.get("resources") or {})
            cpus_str = resv.get("reservedCPUs") or ""
            if cpus_str:
                from .topology import parse_cpuset

                try:
                    resources[ext.RES_CPU] = len(parse_cpuset(cpus_str)) * 1000.0
                except ValueError:
                    pass
            reserved = self.config.res_vector(resources)
            for batch_res in (ext.RES_BATCH_CPU, ext.RES_BATCH_MEMORY):
                if batch_res in self._res_index:
                    reserved[self._res_index[batch_res]] = 0.0
            alloc = np.maximum(alloc - reserved, 0.0)
        self.nodes.allocatable[idx] = alloc
        custom = ext.parse_custom_usage_thresholds(node.meta.annotations)
        self.nodes.custom_thresholds[idx] = 0.0
        self.nodes.custom_prod_thresholds[idx] = 0.0
        if custom is not None:
            for field, arr in (
                ("usageThresholds", self.nodes.custom_thresholds),
                ("prodUsageThresholds", self.nodes.custom_prod_thresholds),
            ):
                table = custom.get(field)
                if isinstance(table, dict):
                    arr[idx] = self.config.res_vector(
                        {
                            k: v
                            for k, v in table.items()
                            if isinstance(v, (int, float))
                            and not isinstance(v, bool)
                        }
                    )
        # per-node colocation overrides (node_colocation.go), parsed once
        # here so the manager's reconcile loop reads plain arrays
        self.nodes.colo_reclaim[idx] = 0.0
        self.nodes.colo_enable[idx] = -1
        colo = ext.parse_node_colocation_strategy(node.meta.annotations)
        if colo is not None:
            if isinstance(colo.get("enable"), bool):
                self.nodes.colo_enable[idx] = int(colo["enable"])
            rr = colo.get("reserveRatio")
            if (
                isinstance(rr, (int, float))
                and not isinstance(rr, bool)
                and 0.0 <= rr < 1.0
            ):
                self.nodes.colo_reclaim[idx] = 1.0 - float(rr)
        for col, key in (
            (0, ext.LABEL_CPU_RECLAIM_RATIO),
            (1, ext.LABEL_MEMORY_RECLAIM_RATIO),
        ):
            ratio = ext.parse_reclaim_ratio(node.meta.labels, key)
            if ratio is not None:
                self.nodes.colo_reclaim[idx, col] = ratio
        self.nodes.schedulable[idx] = not node.unschedulable
        amp = ext.parse_node_amplification(node.meta.annotations)
        new_amp = max(float(amp.get(ext.RES_CPU, 1.0)), 1.0)
        old_amp = float(self.nodes.cpu_amp[idx])
        self.nodes.cpu_amp[idx] = new_amp
        if new_amp != old_amp:
            # re-base live bound pods' amplified charges onto the new ratio
            # (NUMAManager._sync_amp does the same for zone accounting) —
            # without this the node-level requested array drifts for as
            # long as the pods live
            for ap in self._assumed.values():
                if ap.node_idx != idx or ap.bind_nominal_cpu <= 0:
                    continue
                new_charge = ap.bind_nominal_cpu * new_amp
                self.nodes.requested[idx, self._cpu_dim] += (
                    new_charge - ap.request[self._cpu_dim]
                )
                ap.request = ap.request.copy()
                ap.request[self._cpu_dim] = new_charge
        new_labels = dict(node.meta.labels)
        old_labels = self._node_labels.get(node.meta.name)
        if old_labels != new_labels:
            # keep only bitmaps that already exist current — absent pairs
            # rebuild lazily on first query
            if self._label_rows:
                for k, v in (old_labels or {}).items():
                    if new_labels.get(k) != v:
                        bm = self._label_rows.get((k, v))
                        if bm is not None:
                            bm[idx] = False
                for k, v in new_labels.items():
                    if old_labels is None or old_labels.get(k) != v:
                        bm = self._label_rows.get((k, v))
                        if bm is not None:
                            bm[idx] = True
        self._present[idx] = True
        self._node_labels[node.meta.name] = new_labels
        self._node_annotations[node.meta.name] = dict(node.meta.annotations)
        self._touch(idx)
        return idx

    def node_labels(self, name: str) -> Mapping[str, str]:
        return self._node_labels.get(name, {})

    def node_annotations(self, name: str) -> Mapping[str, str]:
        return self._node_annotations.get(name, {})

    def remove_node(self, name: str) -> None:
        idx = self._node_index.pop(name, None)
        old_labels = self._node_labels.pop(name, None)
        self._node_annotations.pop(name, None)
        if idx is None:
            return
        if old_labels and self._label_rows:
            for k, v in old_labels.items():
                bm = self._label_rows.get((k, v))
                if bm is not None:
                    bm[idx] = False
        self._present[idx] = False
        self._touch(idx)
        self.node_epoch += 1
        for arr in (
            self.nodes.allocatable,
            self.nodes.requested,
            self.nodes.usage_avg,
            self.nodes.usage_agg,
            self.nodes.prod_usage,
            self.nodes.assigned_pending,
            self.nodes.assigned_pending_prod,
        ):
            arr[idx] = 0
        self.nodes.metric_fresh[idx] = False
        self.nodes.has_metric[idx] = False
        self.nodes.schedulable[idx] = False
        self.nodes.cpu_amp[idx] = 1.0
        self.nodes.custom_thresholds[idx] = 0.0
        self.nodes.custom_prod_thresholds[idx] = 0.0
        self.nodes.colo_reclaim[idx] = 0.0
        self.nodes.colo_enable[idx] = -1
        # Drop assumed-pod bookkeeping for the dead node so a later
        # forget_pod cannot corrupt whichever node reuses this slot.
        self._assumed = {
            uid: ap for uid, ap in self._assumed.items() if ap.node_idx != idx
        }
        self._free_node_slots.append(idx)

    def node_id(self, name: str) -> Optional[int]:
        return self._node_index.get(name)

    def node_name(self, idx: int) -> str:
        return self._node_names[idx]

    @property
    def node_count(self) -> int:
        return len(self._node_index)

    def set_node_metric(
        self,
        metric: NodeMetric,
        now: Optional[float] = None,
        agg_type: Optional[str] = None,
        expiry_s: Optional[float] = None,
    ) -> None:
        """Ingest a NodeMetric report (reference LoadAware reads the CRD at
        Filter/Score time, ``load_aware.go:163-179``; we fold it into the
        node block at informer time instead).

        Pods assumed *before* the report's update_time are considered
        reflected in the reported usage and their pending estimates are
        absorbed; pods assumed after keep contributing (reference
        ``load_aware.go:315-358`` compares assign time vs metric time).
        """
        idx = self._node_index.get(metric.meta.name)
        if idx is None:
            return
        cfg = self.config
        self.nodes.usage_avg[idx] = cfg.res_vector(metric.node_usage.usage)
        agg = metric.aggregated.get(agg_type or self.agg_type)
        self.nodes.usage_agg[idx] = cfg.res_vector(
            agg.usage if agg is not None else metric.node_usage.usage
        )
        self.nodes.prod_usage[idx] = cfg.res_vector(metric.prod_usage.usage)
        self.nodes.sys_usage[idx] = cfg.res_vector(metric.sys_usage.usage)
        import time as _t

        now = now if now is not None else _t.time()
        fresh = not metric.expired(
            now, expiry_s if expiry_s is not None else self.metric_expiry_s
        )
        self.nodes.metric_fresh[idx] = fresh
        self.nodes.has_metric[idx] = True
        self._touch(idx)
        if fresh:
            for ap in self._assumed.values():
                if (
                    ap.node_idx == idx
                    and not ap.absorbed
                    and ap.assume_time <= metric.update_time
                ):
                    self.nodes.assigned_pending[idx] -= ap.estimate
                    if ap.is_prod:
                        self.nodes.assigned_pending_prod[idx] -= ap.estimate
                    ap.absorbed = True

    # ---- assume / forget (reference scheduler cache + podAssignCache) ----

    def assume_pod(
        self,
        pod: Pod,
        node_name: str,
        estimated: Optional[np.ndarray] = None,
        now: Optional[float] = None,
        confirmed: bool = True,
        request: Optional[np.ndarray] = None,
        bind_nominal_cpu: Optional[float] = None,
    ) -> bool:
        """Charge ``pod`` against ``node_name``; returns False (no-op) when
        the node is absent — an assume racing a node delete is a
        reconciliation matter for the caller, not an invariant violation
        (the reference cache tolerates AssumePod on a deleted node the same
        way: the informer's next sync repairs it)."""
        import time as _t

        idx = self._node_index.get(node_name)
        if idx is None:
            return False
        # idempotent re-assume: a commit for a pod the solver already
        # assumed (or a move to another node) replaces, never double-counts.
        # A same-node re-assume of an absorbed pod stays absorbed — its load
        # already lives in the reported usage baseline, not in pending.
        prev = self._assumed.get(pod.meta.uid)
        absorbed = prev is not None and prev.absorbed and prev.node_idx == idx
        if prev is not None:
            self.forget_pod(pod.meta.uid)
        # callers that already lowered the request vector pass it in
        req = (
            np.asarray(request, np.float32)
            if request is not None
            else self.config.res_vector(pod.spec.requests)
        )
        # the usage estimate defaults to the *physical* request — a bound
        # pod on an amplified node still only burns its physical cores
        est = np.asarray(
            estimated if estimated is not None else req, np.float32
        )
        # cpuset-bound pods consume physical cores: on an amplified node
        # their CPU charge counts ×ratio (nodenumaresource/plugin.go:430-438
        # — requested − allocated + amplify(allocated)). Charging here keeps
        # every assume/forget path symmetric, with or without a registered
        # NUMA topology.
        amp = float(self.nodes.cpu_amp[idx])
        # callers that lowered the bind predicate already (BatchScheduler's
        # per-chunk arrays) pass bind_nominal_cpu to skip the recompute
        if bind_nominal_cpu is not None:
            bind_nominal = bind_nominal_cpu
        else:
            bind_nominal = (
                float(req[self._cpu_dim]) if ext.wants_cpu_bind(pod) else 0.0
            )
        if bind_nominal > 0 and amp > 1.0:
            req = req.copy()
            req[self._cpu_dim] *= amp
        self.nodes.requested[idx] += req
        is_prod = pod.priority_class == ext.PriorityClass.PROD
        if not absorbed:
            self.nodes.assigned_pending[idx] += est
            if is_prod:
                self.nodes.assigned_pending_prod[idx] += est
        self._assumed[pod.meta.uid] = _AssumedPod(
            node_idx=idx,
            request=req,
            estimate=est,
            is_prod=is_prod,
            assume_time=now if now is not None else _t.time(),
            absorbed=absorbed,
            confirmed=confirmed,
            bind_nominal_cpu=bind_nominal,
        )
        self._touch(idx)
        return True

    def assume_pods_bulk(
        self,
        pods: Sequence[Pod],
        node_idxs: np.ndarray,
        charged_rows: np.ndarray,
        est_rows: np.ndarray,
        is_prod: np.ndarray,
        bind_nominals: np.ndarray,
        now: Optional[float] = None,
        confirmed: bool = False,
    ) -> None:
        """Vectorized assume for a batch of fresh winners (the per-winner
        ``assume_pod`` was the commit loop's hot spot). ``charged_rows``
        are the rows to charge verbatim — the caller has already applied
        the amplified-CPU surcharge for bound pods (``bind_nominals``
        records their physical CPU for ratio re-basing). Callers must
        route pods that may already be assumed through ``assume_pod``
        (this path skips the idempotent-replace check)."""
        import time as _t

        if now is None:
            now = _t.time()

        def _scatter_add(target: np.ndarray, idxs: np.ndarray, rows: np.ndarray):
            # np.add.at is an order of magnitude slower than sort+reduceat
            # for duplicate indices (the common many-pods-per-node case)
            if idxs.size == 0:
                return
            perm = np.argsort(idxs, kind="stable")
            si = idxs[perm]
            sr = rows[perm]
            starts = np.nonzero(np.r_[True, si[1:] != si[:-1]])[0]
            target[si[starts]] += np.add.reduceat(sr, starts, axis=0)

        _scatter_add(self.nodes.requested, node_idxs, charged_rows)
        _scatter_add(self.nodes.assigned_pending, node_idxs, est_rows)
        if is_prod.any():
            _scatter_add(
                self.nodes.assigned_pending_prod,
                node_idxs[is_prod],
                est_rows[is_prod],
            )
        self.touch_rows(np.unique(node_idxs))
        assumed = self._assumed
        # one tolist per column: per-element numpy scalar indexing in a
        # 10k+ iteration loop costs ~1µs each; list(matrix) materializes
        # all row views in C, and the positional ctor skips kwarg parsing
        idx_l = node_idxs.tolist()
        prod_l = is_prod.tolist()
        nom_l = np.asarray(bind_nominals, np.float64).tolist()
        req_l = list(charged_rows)
        est_l = list(est_rows)
        ctor = _AssumedPod
        for k, pod in enumerate(pods):
            assumed[pod.meta.uid] = ctor(
                idx_l[k], req_l[k], est_l[k], prod_l[k], now,
                False, confirmed, nom_l[k],
            )

    def is_assumed(self, pod_uid: str) -> bool:
        """Whether a pod currently holds an assume/bound charge — the
        liveness signal external reconcilers (reservation owner drift) key
        off, without reaching into the internal store."""
        return pod_uid in self._assumed

    def expire_assumed(self, now: float, ttl: float) -> int:
        """Forget optimistic (unconfirmed) assumes older than ``ttl``
        seconds — the reference scheduler cache's assumed-pod expiration.
        A confirmed assume (bind observed / pod_assumed sync) never
        expires; its lifecycle belongs to pod_forgotten/delete events.
        Returns the number of pods expired."""
        stale = [
            uid
            for uid, ap in self._assumed.items()
            if not ap.confirmed and now - ap.assume_time > ttl
        ]
        for uid in stale:
            self.forget_pod(uid)
        return len(stale)

    def confirm_pod(self, pod_uid: str) -> bool:
        """Promote an optimistic assume to confirmed (bind observed /
        pod_assumed sync, or a ghost hold whose lifecycle is owned by the
        ReservationManager) so ``expire_assumed`` never drops it."""
        ap = self._assumed.get(pod_uid)
        if ap is None:
            return False
        ap.confirmed = True
        return True

    def forget_pod(self, pod_uid: str) -> None:
        ap = self._assumed.pop(pod_uid, None)
        if ap is None:
            return
        self.nodes.requested[ap.node_idx] -= ap.request
        if not ap.absorbed:
            self.nodes.assigned_pending[ap.node_idx] -= ap.estimate
            if ap.is_prod:
                self.nodes.assigned_pending_prod[ap.node_idx] -= ap.estimate
        self._touch(ap.node_idx)

    def restore_assumed(self, pod_uid: str, entry: "_AssumedPod") -> None:
        """Re-install a previously captured assume entry verbatim —
        transactional-rollback support for the Reserve journal: a
        re-assumed pod whose chunk commit failed mid-flight gets its
        PRIOR charge (node, request, estimate, absorbed state) back
        bit-exactly. Any current charge for the uid is removed first;
        both paths touch the dirty-row ledger so the device-resident
        mirror reconverges on the next refresh."""
        if pod_uid in self._assumed:
            self.forget_pod(pod_uid)
        self.nodes.requested[entry.node_idx] += entry.request
        if not entry.absorbed:
            self.nodes.assigned_pending[entry.node_idx] += entry.estimate
            if entry.is_prod:
                self.nodes.assigned_pending_prod[entry.node_idx] += entry.estimate
        self._assumed[pod_uid] = entry
        self._touch(entry.node_idx)

    # ---- pod batch build ----

    def build_pods(
        self,
        pods: Sequence[Pod],
        min_member_by_gang: Optional[Mapping[str, int]] = None,
        nonstrict_by_gang: Optional[Mapping[str, bool]] = None,
        bucket: Optional[int] = None,
        row_cache: Optional[Dict[str, "InternedPodRow"]] = None,
    ) -> PodArrays:
        """Lower pending pods to dense arrays. ``bucket`` overrides the
        padded row count (the scanned multi-chunk dispatch needs every
        chunk on ONE shape); it must be ≥ the natural bucket.

        Gang minMember resolution order (reference: PodGroup CRD or the
        ``pod-group.scheduling.sigs.k8s.io/min-available`` annotation,
        ``pkg/scheduler/plugins/coscheduling/core/core.go``):
        explicit mapping > pod label > member count in this batch. The gang
        mode resolves the same way (``nonstrict_by_gang`` from the
        PodGroupManager, else the first member's mode annotation —
        gang.go:128-132 parses once at gang creation).
        """
        p_bucket = bucket_size(len(pods), self.config.min_bucket)
        if bucket is not None:
            if bucket < len(pods):
                raise ValueError(
                    f"bucket override {bucket} smaller than pod count "
                    f"{len(pods)}"
                )
            p_bucket = max(p_bucket, bucket)
        out = PodArrays.empty(p_bucket, self.config.dims)
        gang_ids: Dict[str, int] = {}
        gang_members: Dict[int, int] = {}
        gang_label_min: Dict[int, int] = {}
        gang_pod_mode: Dict[int, bool] = {}
        # Tight single-pass lowering: the per-pod res_vector / property /
        # parse_* calls were a measurable slice of the per-batch host time
        # (one dict walk over requests replaces 5 separate parses;
        # priority-band and fallback-QoS resolution vectorize after).
        res_index = self._res_index
        req_rows = out.requests
        priority = out.priority
        n = len(pods)
        explicit_qos: List[Tuple[int, int]] = []
        qos_cache: Dict[str, int] = self._qos_label_cache
        uids: List[str] = []
        quota_names: List[Optional[str]] = []
        est_override = np.zeros(p_bucket, bool)
        numa_required = np.zeros(p_bucket, bool)
        non_preemptible = np.zeros(p_bucket, bool)
        preemptible_key = ext.LABEL_PREEMPTIBLE
        disable_key = ext.LABEL_DISABLE_PREEMPTIBLE
        quota_key = ext.LABEL_QUOTA_NAME
        custom_est_key = ext.ANNOTATION_CUSTOM_ESTIMATED_SCALING_FACTORS
        numa_spec_key = ext.ANNOTATION_NUMA_TOPOLOGY_SPEC
        intern_hits = 0
        for i, pod in enumerate(pods):
            spec = pod.spec
            meta = pod.meta
            labels = meta.labels
            uids.append(meta.uid)
            ent = fp = None
            if row_cache is not None:
                fp = pod_fingerprint(pod)
                ent = row_cache.get(meta.uid)
                if ent is not None and ent.fp != fp:
                    # spec changed under the same uid: stale rows must
                    # never resurrect — fall through to a fresh parse
                    ent = None
            if ent is not None:
                # interned fast path (ROADMAP item c): restore the
                # lowered row verbatim; gang GROUPING below still runs
                # per batch (gang ids are batch-local)
                intern_hits += 1
                quota_names.append(ent.quota_name)
                non_preemptible[i] = ent.non_preemptible
                est_override[i] = ent.est_override
                numa_required[i] = ent.numa_required
                priority[i] = ent.priority
                req_rows[i] = ent.req
                out.gpu_whole[i] = ent.gpu_whole
                out.gpu_share[i] = ent.gpu_share
                out.rdma[i] = ent.rdma
                out.fpga[i] = ent.fpga
                if ent.qos_explicit >= 0:
                    explicit_qos.append((i, ent.qos_explicit))
                gang = ent.gang
                label_min = ent.gang_min
                gang_pod_nonstrict = ent.gang_nonstrict
            else:
                quota_names.append(labels.get(quota_key))
                if (
                    labels.get(preemptible_key) == "false"
                    or labels.get(disable_key) == "true"
                ):
                    non_preemptible[i] = True
                if spec.estimated or spec.limits or custom_est_key in meta.annotations:
                    est_override[i] = True
                if numa_spec_key in meta.annotations:
                    # pod-level NUMA requirement API (numa_aware.go:29-31):
                    # SingleNUMANode requires a single-zone fit for THIS pod
                    # regardless of the node's own policy label
                    numa_spec = ext.parse_numa_topology_spec(meta.annotations)
                    if (
                        numa_spec
                        and numa_spec.get("numaTopologyPolicy") == "SingleNUMANode"
                    ):
                        numa_required[i] = True
                priority[i] = spec.priority or 0
                whole = 0
                ratio_mem: Optional[float] = None
                core = 0.0
                for k, v in spec.requests.items():
                    j = res_index.get(k)
                    if j is not None:
                        req_rows[i, j] = v
                    # device parsing is NOT exclusive with the dense axis: a
                    # deployment may append device resources to
                    # SnapshotConfig.resources (DEFAULT_RESOURCES invites it)
                    # and the device manager must still see the request
                    if k == ext.RES_GPU:
                        whole = int(v)
                    elif k == ext.RES_GPU_MEMORY_RATIO:
                        ratio_mem = float(v)
                    elif k == ext.RES_GPU_CORE:
                        core = float(v)
                    elif k == ext.RES_RDMA:
                        out.rdma[i] = ext._count_request(spec.requests, k)
                    elif k == ext.RES_FPGA:
                        out.fpga[i] = ext._count_request(spec.requests, k)
                ratio = ratio_mem if ratio_mem is not None else core
                if ratio >= 100.0:
                    whole += int(ratio // 100.0)
                    ratio = ratio % 100.0
                if whole or ratio:
                    out.gpu_whole[i] = whole
                    out.gpu_share[i] = ratio
                qv = -1
                qos_label = labels.get(ext.LABEL_POD_QOS)
                if qos_label:
                    qv = qos_cache.get(qos_label)
                    if qv is None:
                        qv = int(ext.QoSClass.parse(qos_label))
                        qos_cache[qos_label] = qv
                    if qv != int(ext.QoSClass.NONE):
                        explicit_qos.append((i, qv))
                    else:
                        qv = -1
                gang = meta.annotations.get(
                    ext.ANNOTATION_GANG_NAME
                ) or labels.get(ext.LABEL_GANG_NAME)
                label_min = None
                gang_pod_nonstrict = False
                if gang:
                    label_min = meta.annotations.get(
                        ext.ANNOTATION_GANG_MIN_AVAILABLE
                    ) or labels.get(ext.LABEL_GANG_MIN_AVAILABLE)
                    gang_pod_nonstrict = (
                        meta.annotations.get(ext.ANNOTATION_GANG_MODE)
                        == ext.GANG_MODE_NONSTRICT
                    )
                if row_cache is not None:
                    row_cache[meta.uid] = InternedPodRow(
                        fp=fp,
                        req=req_rows[i].copy(),
                        priority=int(priority[i]),
                        qos_explicit=qv,
                        gang=gang,
                        gang_min=label_min,
                        gang_nonstrict=gang_pod_nonstrict,
                        gpu_whole=int(out.gpu_whole[i]),
                        gpu_share=float(out.gpu_share[i]),
                        rdma=float(out.rdma[i]),
                        fpga=float(out.fpga[i]),
                        quota_name=quota_names[-1],
                        est_override=bool(est_override[i]),
                        numa_required=bool(numa_required[i]),
                        non_preemptible=bool(non_preemptible[i]),
                    )
            if gang:
                key = f"{meta.namespace}/{gang}"
                gid = gang_ids.setdefault(key, len(gang_ids))
                out.gang_id[i] = gid
                gang_members[gid] = gang_members.get(gid, 0) + 1
                if label_min is not None:
                    try:
                        gang_label_min[gid] = int(label_min)
                    except ValueError:
                        pass
                if gid not in gang_pod_mode:
                    gang_pod_mode[gid] = gang_pod_nonstrict
        out.valid[:n] = True
        # vectorized priority-band resolution from the canonical band
        # table (priority.go:29-48; same source as from_priority)
        prio_n = priority[:n]
        out.prio_class[:n] = np.select(
            [
                (prio_n >= lo) & (prio_n <= hi)
                for lo, hi in ext.PRIORITY_BANDS.values()
            ],
            [int(band) for band in ext.PRIORITY_BANDS],
            default=int(ext.PriorityClass.NONE),
        ).astype(np.int8)
        # fallback QoS by band (qos_for_priority), explicit labels override
        out.qos[:n] = _QOS_BY_BAND[out.prio_class[:n]]
        for i, qv in explicit_qos:
            out.qos[i] = qv
        out.gang_keys = [k for k, _ in sorted(gang_ids.items(), key=lambda kv: kv[1])]
        for key, gid in gang_ids.items():
            explicit = (min_member_by_gang or {}).get(key)
            if explicit is not None:
                out.gang_min[gid] = explicit
            elif gid in gang_label_min:
                out.gang_min[gid] = gang_label_min[gid]
            else:
                out.gang_min[gid] = gang_members[gid]
            declared = (nonstrict_by_gang or {}).get(key)
            out.gang_nonstrict[gid] = (
                declared
                if declared is not None
                else gang_pod_mode.get(gid, False)
            )
        out.p_real = len(pods)
        out.uids = uids
        out.quota_names = quota_names
        out.est_override = est_override
        out.numa_required = numa_required
        out.non_preemptible = non_preemptible
        out.intern_hits = intern_hits
        return out
